# Runtime image for kata-tpu-device-plugin.
# The reference uses a 2-stage CUDA ubi8 build for a Go binary
# (Dockerfile:31-70); a Python daemon needs only a slim base. The binary
# name/image tag mismatches of the reference (SURVEY §Quirks 1) are avoided
# by installing one console script from one source of truth (pyproject).
FROM python:3.12-slim

RUN pip install --no-cache-dir grpcio protobuf PyYAML prometheus_client

WORKDIR /opt/kata-tpu-device-plugin
COPY pyproject.toml ./
COPY kata_xpu_device_plugin_tpu ./kata_xpu_device_plugin_tpu
RUN pip install --no-cache-dir .

ENTRYPOINT ["kata-tpu-device-plugin"]
CMD ["run"]
