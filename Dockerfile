# Runtime image for kata-tpu-device-plugin.
# The reference uses a 2-stage CUDA ubi8 build for a Go binary
# (Dockerfile:31-70); a Python daemon needs only a slim base. The binary
# name/image tag mismatches of the reference (SURVEY §Quirks 1) are avoided
# by installing one console script from one source of truth (pyproject).
FROM python:3.12-slim

# Full pci.ids database at the discovery ladder's first system path
# (discovery/pciids.py:SYSTEM_PCIIDS_PATHS — same location the reference
# installs it, its Dockerfile:66), so VFIO resource naming covers arbitrary
# non-TPU devices without --pci-ids-path. The repo itself ships only the
# 24-line authored TPU table (data/pci.ids) as the committed fallback —
# vendoring the full 38k-line DB in git buys nothing over fetching it here.
# For a REPRODUCIBLE build, pin an immutable snapshot and its digest, e.g.:
#   docker build \
#     --build-arg PCI_IDS_URL=https://raw.githubusercontent.com/pciutils/pciids/<commit>/pci.ids \
#     --build-arg PCI_IDS_SHA256=<sha256> .
# The default rolling URL keeps offline/air-gapped builds possible via
# PCI_IDS_FETCH=0 (the in-package authored table then serves as fallback).
ARG PCI_IDS_FETCH=1
ARG PCI_IDS_URL=https://pci-ids.ucw.cz/v2.2/pci.ids
ARG PCI_IDS_SHA256=""
RUN if [ "$PCI_IDS_FETCH" = "1" ]; then \
      python -c "import urllib.request; urllib.request.urlretrieve('$PCI_IDS_URL', '/usr/pci.ids')" && \
      if [ -n "$PCI_IDS_SHA256" ]; then \
        echo "$PCI_IDS_SHA256  /usr/pci.ids" | sha256sum -c -; \
      fi && \
      grep -q "^1ae0" /usr/pci.ids; \
    fi

RUN pip install --no-cache-dir grpcio protobuf PyYAML prometheus_client

WORKDIR /opt/kata-tpu-device-plugin
COPY pyproject.toml ./
COPY kata_xpu_device_plugin_tpu ./kata_xpu_device_plugin_tpu
RUN pip install --no-cache-dir .

ENTRYPOINT ["kata-tpu-device-plugin"]
CMD ["run"]
