"""Paged KV arena: one block pool shared by every in-flight request.

The fixed-slot serving arena (``[L, max_batch, max_len, KV, D]``) charges
every admitted request the FULL ``max_len`` KV footprint up front, so
concurrency is capped by slot count and long-tail requests strand device
memory — the opposite of elastic accelerator allocation. vLLM's
PagedAttention showed the fix, and FlexNPU (PAPERS.md) showed why it
matters on accelerators: carve the KV memory into fixed-size token BLOCKS,
give each request a block table mapping logical positions to physical
blocks, and admit by token budget instead of slot count. This module is
that capability for the :mod:`.serving` arena model:

- :class:`KVPool` — the device-resident block pool (one ``[L, 1,
  num_blocks * block_size, KV, D]`` cache pytree, bf16 or int8
  :class:`~..ops.quant.QTensor` — the same leaf layout as a one-slot
  serving cache, so every existing cache op tree-maps over it) plus the
  host-side free list and per-block refcounts. Two blocks are reserved:
  ZERO — never written, so when the paged view gathers an unmapped
  table entry it reads the zeros a fresh dense arena would hold — and
  SCRATCH, the block-table filler, which absorbs writes that must not
  land anywhere real (decode writes from lanes with no live request,
  admission-scatter chunks covering tier-shared blocks); the view
  remaps SCRATCH entries to ZERO before gathering, so SCRATCH contents
  never surface on the read side.
- Device ops — jitted D2D scatter/gather between contiguous per-request
  caches (what ``prefill``/``prefill_suffix`` produce) and pool blocks,
  plus the spill/restore pair preemption uses.
- :class:`PagedPrefixTier` — the shared-prefix radix store of
  :mod:`.prefix_cache` re-homed INSIDE the pool: segments are block
  lists, hits share full blocks with the admitted request's table
  (refcounted, read-only; a partially-covered boundary block is
  copied-on-write into a private block), and LRU eviction returns
  unreferenced segments' blocks to the same free list decode allocates
  from.

**Bit-identity.** The paged decode path (``models.transformer`` paged
branch) gathers each row's block-table view back into the same
``[B, max_len]`` operand the dense arena presents: mapped positions hold
the verbatim rows the dense path would hold (the scatters copy prefill
caches unchanged, decode writes the same computed k/v), unmapped
positions read the never-written ZERO block (the zeros a fresh dense
arena holds), and every position ``> pos`` is replaced by the attention
mask before softmax anyway (the same argument the dense path makes for
its pad/stale rows). Every
position ``<= pos`` sits inside the lane's allocation by construction,
so greedy tokens are bit-identical to the fixed-slot path (tested in
``tests/test_kv_arena.py``).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.transformer import (
    PAGED_SCRATCH_BLOCK,
    PAGED_ZERO_BLOCK,
    DecoderConfig,
    init_kv_caches,
)
from .prefix_cache import RadixIndex

# The reserved physical blocks (the layout contract lives next to the
# paged ops in models.transformer; re-exported here for the pool's
# clients). SCRATCH absorbs writes that must not land anywhere real —
# decode writes from lanes with no live request, overrun writes of a
# finished lane, admission-scatter chunks covering tier-shared blocks —
# and is what unmapped block-table entries hold; the paged view remaps
# SCRATCH to ZERO (never written) before gathering, so unmapped reads
# see the zeros a fresh dense arena would hold (see the module header's
# bit-identity note).
ZERO_BLOCK = PAGED_ZERO_BLOCK
SCRATCH_BLOCK = PAGED_SCRATCH_BLOCK
RESERVED_BLOCKS = 2


# ----- device ops ----------------------------------------------------------
#
# All D2D copies inside jit (no host sync; strict mode's transfer guard
# leaves device-to-device moves free). Executable counts are bounded: the
# traced block-table length is a SHAPE, so pool_write_seq compiles one
# executable per admission width (ceil(bucket / block_size) — bounded by
# the prefill bucket ladder), and the spill/restore pair always runs at
# the full table width (exactly one executable each).


@partial(jax.jit, donate_argnums=(0,), static_argnames=("block_size",))
def pool_write_seq(pool, caches, row, table, block_size: int):
    """Scatter row ``row`` of a contiguous cache pytree (leaves
    ``[L, N, S, ...]``) into pool blocks: rows ``[j*bs, (j+1)*bs)`` of the
    cache land in pool block ``table[j]``. ``SCRATCH_BLOCK`` entries mask
    chunks that must not land (tier-shared blocks a hit admission reads
    but must not overwrite). The pool is donated — an admission must not
    copy the whole arena. Rows past the cache's length pad with zeros
    (they sit beyond ``max_len`` and are never gathered)."""
    bs = block_size
    nb = table.shape[0]
    dest = (table[:, None] * bs + jnp.arange(bs)[None, :]).reshape(-1)

    def put(p, c):
        seq = jax.lax.dynamic_index_in_dim(c, row, axis=1, keepdims=False)
        if seq.shape[1] < nb * bs:  # jaxguard: allow(JG104) bounded — one executable per admission width (ceil(bucket/bs), the prefill bucket ladder)
            pad = [(0, 0)] * seq.ndim
            pad[1] = (0, nb * bs - seq.shape[1])
            seq = jnp.pad(seq, pad)
        return p.at[:, 0, dest].set(seq[:, : nb * bs])

    return jax.tree.map(put, pool, caches)


@partial(jax.jit, donate_argnums=(0,), static_argnames=("block_size",))
def pool_write_batch(pool, caches, tables, block_size: int):
    """Batched :func:`pool_write_seq`: cache row ``i`` lands in blocks
    ``tables[i]`` — ONE donated scatter dispatch for a whole batched
    admission (N same-bucket requests) instead of N sequential ones.
    SCRATCH entries mask per-row chunks exactly as in the single-row
    form; distinct requests' real blocks are disjoint, and rows
    colliding on SCRATCH are don't-care (the paged view remaps SCRATCH
    to ZERO, so nothing live ever reads them)."""
    bs = block_size
    n, nb = tables.shape
    dest = (
        tables[:, :, None] * bs + jnp.arange(bs)[None, None, :]
    ).reshape(-1)

    def put(p, c):
        seq = c[:, :n]
        if seq.shape[2] < nb * bs:  # jaxguard: allow(JG104) bounded — one executable per (group size, admission width), both ladder-bounded
            pad = [(0, 0)] * seq.ndim
            pad[2] = (0, nb * bs - seq.shape[2])
            seq = jnp.pad(seq, pad)
        seq = seq[:, :, : nb * bs].reshape(
            (seq.shape[0], n * nb * bs) + seq.shape[3:]
        )
        return p.at[:, 0, dest].set(seq)

    return jax.tree.map(put, pool, caches)


@partial(jax.jit, static_argnames=("block_size",))
def pool_gather_rows(pool, table, block_size: int):
    """Gather the token rows of blocks ``table`` out of the pool into a
    contiguous ``[L, len(table)*bs, ...]`` pytree — the preemption SPILL
    read (the caller copies it to host). Always called at the full table
    width (SCRATCH-padded), so there is exactly one executable."""
    bs = block_size
    src = (table[:, None] * bs + jnp.arange(bs)[None, :]).reshape(-1)
    return jax.tree.map(lambda p: p[:, 0, src], pool)


@partial(jax.jit, donate_argnums=(0,), static_argnames=("block_size",))
def pool_scatter_rows(pool, rows, table, block_size: int):
    """Inverse of :func:`pool_gather_rows`: land contiguous token rows
    (leaves ``[L, len(table)*bs, ...]``) into blocks ``table`` — the
    preemption RESTORE write (rows arrive as an explicit host upload).
    SCRATCH entries absorb the padding tail."""
    bs = block_size
    dest = (table[:, None] * bs + jnp.arange(bs)[None, :]).reshape(-1)
    return jax.tree.map(lambda p, r: p.at[:, 0, dest].set(r), pool, rows)


@partial(jax.jit,
         static_argnames=("length", "cfg", "max_len", "quantized", "dtype",
                          "n", "block_size"))
def pool_materialize(pool, table, length: int, cfg: DecoderConfig,
                     max_len: int, quantized: bool, dtype, n: int,
                     block_size: int):
    """Build a fresh ``[L, n, max_len, ...]`` cache pytree with the pool
    rows of blocks ``table`` landed in EVERY row at positions
    ``[0, length)`` — the pre-populated caches
    :func:`..models.transformer.prefill_suffix` resumes from (``n > 1``:
    one shared prefix fanned out to n same-match admissions). The paged
    sibling of ``prefix_cache._materialize``; one executable per
    (bucket length, n)."""
    caches = init_kv_caches(cfg, n, max_len, dtype=dtype, quantized=quantized)
    bs = block_size
    src = (table[:, None] * bs + jnp.arange(bs)[None, :]).reshape(-1)[:length]

    def cp(c, p):
        seg = p[:, 0, src]  # [L, length, ...]
        seg = jnp.broadcast_to(
            seg[:, None], (seg.shape[0], n) + seg.shape[1:]
        )
        return jax.lax.dynamic_update_slice(c, seg, (0,) * c.ndim)

    return jax.tree.map(cp, caches, pool)


# ----- the pool ------------------------------------------------------------


class KVPool:
    """Device-resident paged KV pool + host-side block accounting.

    ``pool_tokens`` sizes the arena (rounded down to whole blocks; two
    blocks are reserved — see the module header). Blocks are refcounted:
    :meth:`try_alloc` hands out blocks at refcount 1, :meth:`ref` adds a
    holder (a lane's table sharing a prefix-tier block), and
    :meth:`unref` returns a block to the free list when its last holder
    lets go — so a tier segment and three lanes can all reference one
    physical block and it is recycled exactly once.

    **Kernel alignment contract (ISSUE 12).** Physical block ``t``
    occupies pool rows ``t * block_size .. (t+1) * block_size`` — the
    layout the paged-native decode kernel's index maps ride: its KV tile
    IS one pool block, DMA'd straight from the block table
    (:func:`..ops.decode_attn.pallas_paged_decode_attention`). On TPU
    hardware the tile must satisfy the sublane quantum
    (:func:`..ops.decode_attn.supports_paged_decode`: ``block_size`` a
    multiple of 8 and ``head_dim`` lane-aligned); an unaligned pool
    still serves correctly — the server's backend resolution falls back
    to the XLA gather path with an ``unsupported_shape`` reason on its
    ``decode_attn_backend`` event — it just forfeits the kernel.

    **Block-sharded placement (ISSUE 14).** Under the ``blocks`` pool
    layout (``shards = tp``) the pool's TOKEN axis shards across the
    serving mesh: ``num_blocks`` rounds down to a multiple of ``shards``
    so every physical block lives WHOLE on exactly one shard —
    ``shard_of(t) = t // shard_blocks``, local id ``t % shard_blocks``
    (the ``lane → (shard, physical block)`` mapping the block table
    implies). The free list splits per shard and :meth:`try_alloc`
    draws from the emptiest shards first, keeping per-shard occupancy
    balanced; both reserved blocks (ZERO, SCRATCH) land on shard 0.
    Per-chip pool bytes are ``~logical/shards`` for EVERY model — the
    GQA divide-or-replicate cliff of the ``heads`` layout does not
    exist here. ``shards=1`` (the default, and every ``heads``-layout
    pool) is the historical single-free-list behavior unchanged.
    """

    def __init__(self, cfg: DecoderConfig, pool_tokens: int,
                 block_size: int = 16, *, kv_quant: bool = False,
                 dtype=None, label: str = "", shards: int = 1) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        num_blocks = int(pool_tokens) // int(block_size)
        # Whole blocks per shard: the token axis must divide the mesh so
        # every physical block is shard-local (the kernel's shard-local
        # DMA form and the table's shard mapping both rest on this).
        num_blocks = (num_blocks // shards) * shards
        if num_blocks - RESERVED_BLOCKS < 1:
            raise ValueError(
                f"pool_tokens={pool_tokens} holds {num_blocks} blocks of "
                f"{block_size} across {shards} shard(s) — need at least "
                f"{RESERVED_BLOCKS + 1} (two reserved + one usable)"
            )
        self.cfg = cfg
        self.block_size = int(block_size)
        self.num_blocks = num_blocks
        self.shards = int(shards)
        self.shard_blocks = num_blocks // self.shards
        self.kv_quant = bool(kv_quant)
        self.dtype = dtype or cfg.dtype
        self.label = label
        self.arena = init_kv_caches(
            cfg, 1, num_blocks * self.block_size, dtype=self.dtype,
            quantized=kv_quant,
        )
        self._free: list[deque[int]] = [
            deque(
                b for b in range(s * self.shard_blocks,
                                 (s + 1) * self.shard_blocks)
                if b >= RESERVED_BLOCKS
            )
            for s in range(self.shards)
        ]
        self._refs = np.zeros(num_blocks, np.int64)

    # -- block accounting ----------------------------------------------------

    @property
    def blocks_total(self) -> int:
        """Usable (non-reserved) blocks."""
        return self.num_blocks - RESERVED_BLOCKS

    @property
    def blocks_free(self) -> int:
        return sum(len(f) for f in self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.blocks_total - self.blocks_free

    @property
    def capacity_tokens(self) -> int:
        return self.blocks_total * self.block_size

    def occupancy(self) -> float:
        return round(self.blocks_in_use / max(1, self.blocks_total), 4)

    def shard_of(self, block: int) -> int:
        """Which mesh shard physically holds ``block`` (always 0 on an
        unsharded pool)."""
        return block // self.shard_blocks

    def shard_occupancy(self) -> list[float]:
        """Per-shard fill: blocks in use over each shard's usable blocks
        (shard 0 carries the two reserved blocks, so its usable count is
        smaller). Length ``shards``."""
        out = []
        for s, free in enumerate(self._free):
            usable = self.shard_blocks - (RESERVED_BLOCKS if s == 0 else 0)
            out.append(
                round((usable - len(free)) / max(1, usable), 4)
            )
        return out

    def try_alloc(self, n: int) -> Optional[list[int]]:
        """``n`` blocks at refcount 1, or None (all-or-nothing — a partial
        grant would deadlock two growing lanes against each other). On a
        sharded pool, blocks come from the emptiest shards first so the
        per-shard sub-pools fill evenly (a lane's table freely mixes
        shards — the decode kernel's merge recombines them)."""
        if n < 0:
            raise ValueError(f"try_alloc({n})")
        if self.blocks_free < n:
            return None
        out: list[int] = []
        for _ in range(n):
            free = max(self._free, key=len)
            out.append(free.popleft())
        self._refs[out] += 1
        return out

    def ref(self, blocks) -> None:
        """Add a holder to already-allocated blocks (tier-shared prefix
        blocks entering a lane's table)."""
        for b in blocks:
            assert self._refs[b] > 0, f"ref of unallocated block {b}"
            self._refs[b] += 1

    def unref(self, blocks) -> None:
        """Drop one holder per block; blocks at refcount 0 return to their
        shard's free list."""
        for b in blocks:
            assert b >= RESERVED_BLOCKS, f"unref of reserved block {b}"
            self._refs[b] -= 1
            assert self._refs[b] >= 0, f"block {b} over-released"
            if self._refs[b] == 0:
                self._free[self.shard_of(b)].append(b)

    def stats(self) -> dict:
        return {
            "block_size": self.block_size,
            "blocks_total": self.blocks_total,
            "blocks_in_use": self.blocks_in_use,
            "blocks_free": self.blocks_free,
            "capacity_tokens": self.capacity_tokens,
            "occupancy": self.occupancy(),
            "shards": self.shards,
            "shard_occupancy": self.shard_occupancy(),
        }


# ----- the host-RAM offload tier (ISSUE 14) ---------------------------------


@dataclass
class _HostEntry:
    """One host-resident KV parcel: ``rows`` is the spilled pytree (None
    for accounting-only entries whose payload lives elsewhere — the
    preempted-session spills the serving loop already holds), ``tokens``
    its length, ``pinned`` marks in-flight session state that must not
    LRU out (and is allowed to overflow the capacity — correctness
    outranks the budget; the budget bounds the *cache* tier)."""

    tokens: int
    rows: Any = None
    tick: int = 0
    pinned: bool = False


class HostKVTier:
    """Bounded host-RAM store below the device KV pool (ISSUE 14,
    ROADMAP item 5b): cold KV — demoted prefix segments, preempted idle
    sessions' spills — parks here instead of occupying HBM, and rides
    the proven spill/restore upload path back on access. This class is
    the ACCOUNTING + payload store only; placement policy (what demotes,
    when to prefetch) lives with its clients
    (:class:`PagedPrefixTier` demotion/promotion,
    ``serving.GenerationServer`` preemption spills), so the tier itself
    never touches the device.

    ``capacity_tokens`` bounds the unpinned (cache) population; callers
    make room via :meth:`room` before :meth:`put` and evict their own
    LRU entries (they own the index state a drop must also clean up —
    radix nodes for prefix segments)."""

    def __init__(self, capacity_tokens: int, block_size: int,
                 *, label: str = "") -> None:
        if capacity_tokens < 1:
            raise ValueError(
                f"host tier capacity must be >= 1 token, got "
                f"{capacity_tokens}"
            )
        self.capacity_tokens = int(capacity_tokens)
        self.block_size = int(block_size)
        self.label = label
        self._entries: dict[Any, _HostEntry] = {}
        self._tick = 0

    def _next_tick(self) -> int:
        self._tick += 1
        return self._tick

    def room(self, tokens: int) -> bool:
        return self.tokens_used + int(tokens) <= self.capacity_tokens

    def put(self, key, tokens: int, rows: Any = None, *,
            pinned: bool = False) -> bool:
        """Store (or re-account) one parcel. Unpinned puts respect the
        capacity (False = no room — the caller evicts its own LRU first
        or falls back to dropping); pinned puts always land."""
        if not pinned and not self.room(tokens):
            return False
        self._entries[key] = _HostEntry(
            tokens=int(tokens), rows=rows, tick=self._next_tick(),
            pinned=pinned,
        )
        return True

    def get(self, key) -> Optional[_HostEntry]:
        entry = self._entries.get(key)
        if entry is not None:
            entry.tick = self._next_tick()
        return entry

    def pop(self, key) -> Optional[_HostEntry]:
        return self._entries.pop(key, None)

    def drop_unpinned(self) -> int:
        """Drop every unpinned entry (a prefix-tier rebuild orphans its
        demoted segments — their radix index died with the tier). Pinned
        session spills survive. Returns the count dropped."""
        dead = [k for k, e in self._entries.items() if not e.pinned]
        for k in dead:
            del self._entries[k]
        return len(dead)

    def lru_unpinned(self) -> Optional[Any]:
        """The least-recently-used unpinned key (the caller's eviction
        candidate), or None."""
        victims = [
            (e.tick, k) for k, e in self._entries.items() if not e.pinned
        ]
        return min(victims)[1] if victims else None

    @property
    def tokens_used(self) -> int:
        return sum(e.tokens for e in self._entries.values())

    @property
    def blocks_used(self) -> int:
        return sum(
            -(-e.tokens // self.block_size) for e in self._entries.values()
        )

    @property
    def entries(self) -> int:
        return len(self._entries)

    def occupancy(self) -> float:
        """Tier fill, tokens resident over capacity — the host-RAM twin
        of ``KVPool.occupancy()`` (the serving heartbeat reports the two
        side by side as the per-tier memory picture, ISSUE 15)."""
        return round(self.tokens_used / self.capacity_tokens, 4)

    def stats(self) -> dict:
        return {
            "capacity_tokens": self.capacity_tokens,
            "tokens_used": self.tokens_used,
            "blocks_used": self.blocks_used,
            "entries": self.entries,
            "occupancy": self.occupancy(),
        }


# ----- the shared-prefix tier ----------------------------------------------


@dataclass(eq=False)  # identity semantics: segments key the host tier
class _TierSegment:
    """One cached prefix: rows ``[0, length)`` live in ``blocks`` (the
    last block may be partially covered). ``refs`` counts in-flight hit
    pins; ``tick`` is the LRU clock; ``nodes`` are the radix entries (one
    per bucket boundary) pointing here. ``host=True`` marks a segment
    DEMOTED to the host-RAM tier (ISSUE 14): its rows live in the
    :class:`HostKVTier`, ``blocks`` is empty, the radix entries stay so
    a later hit can prefetch it back."""

    blocks: list
    length: int
    refs: int = 0
    tick: int = 0
    nodes: list = field(default_factory=list)
    host: bool = False


@dataclass(frozen=True)
class TierHit:
    """A pinned tier lookup: ``length`` prefix tokens live in
    ``segment.blocks``. Hold for the request's lifetime; release exactly
    once. Duck-types :class:`.prefix_cache.PrefixHit` (``.segment``,
    ``.length``) so the serving admission paths are shared."""

    segment: _TierSegment
    length: int


class PagedPrefixTier:
    """The radix shared-prefix store of :mod:`.prefix_cache`, re-homed as
    a TIER of one :class:`KVPool` instead of a separate arena: segments
    are pool block lists, hit admissions SHARE the fully-covered blocks
    with the request's own block table (pool refcounts; the partially
    covered boundary block is copied-on-write by the admission scatter),
    and eviction returns blocks to the same free list decode grows from —
    so prefix reuse and decode KV compete for, and elastically split, one
    memory budget.

    API-compatible with :class:`.prefix_cache.PrefixStore` where the
    serving loop touches it (``lookup``/``release``/``cancel``/``insert``
    /``materialize``/counters/``stats``), plus :meth:`shared_blocks` and
    :meth:`evict_one` for the pool's allocation pressure path. Inserts
    copy rows into tier-owned blocks (one jitted D2D scatter, exactly like
    the standalone store) and SKIP under pool pressure rather than evict
    live decode state — decode always outranks the cache.

    With a :class:`HostKVTier` attached (ISSUE 14), pool pressure
    DEMOTES the LRU unpinned segment to host RAM instead of dropping it
    (one D2D block gather + one sanctioned D2H copy — the PR 6 spill
    machinery; its radix entries survive), and a later hit on a demoted
    segment PREFETCHES it back: pool blocks allocate, the H2D upload
    starts asynchronously during admission — overlapping the in-flight
    decode dispatch under pipelined rounds — and the restore scatter
    re-lands the rows verbatim, so greedy outputs are bit-identical to
    a never-demoted run. Demotion always runs BEFORE the serving loop
    resorts to youngest-first preemption (``_alloc_blocks`` drains this
    tier first), converting "evict the cache" into "park it in a larger,
    slower tier"."""

    def __init__(self, pool: KVPool, cfg: DecoderConfig, buckets: tuple,
                 *, label: str = "",
                 host_tier: Optional[HostKVTier] = None,
                 on_demote=None, on_prefetch=None) -> None:
        buckets = tuple(sorted(buckets))
        if not buckets:
            raise ValueError(
                "PagedPrefixTier needs a prefill_buckets ladder — bucket-"
                "aligned match boundaries bound the executable count"
            )
        self.pool = pool
        self.cfg, self.buckets = cfg, buckets
        self.kv_quant = pool.kv_quant
        self.dtype = pool.dtype
        self.label = label
        self.host_tier = host_tier
        # Counter hooks (the server's kv_demotions_total /
        # kv_prefetches_total prometheus children — bound per label, so
        # the tier cannot resolve them itself).
        self._on_demote = on_demote
        self._on_prefetch = on_prefetch
        self._index = RadixIndex()
        self._segments: list[_TierSegment] = []
        self._tick = 0
        # Cumulative counters (stats()-style snapshot semantics), matching
        # the standalone PrefixStore's schema.
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.evictions = 0
        self.inserts = 0
        self.insert_skips = 0
        self.demotions = 0
        self.prefetches = 0
        self.host_evictions = 0
        self.prefetch_stalls = 0

    # -- host-side index operations -----------------------------------------

    def _next_tick(self) -> int:
        self._tick += 1
        return self._tick

    def lookup(self, prompt: np.ndarray) -> Optional[TierHit]:
        """Longest bucket-aligned cached prefix of ``prompt``, pinned
        (same contract as ``PrefixStore.lookup``: capped at
        ``len(prompt) - 1`` so at least one suffix token remains). A hit
        on a HOST-resident (demoted) segment prefetches it back into
        pool blocks first — when the pool cannot hold it right now the
        lookup degrades to a miss (the segment stays parked; cold
        admission is always correct)."""
        prompt = np.asarray(prompt)
        depth, seg = self._index.longest_match(prompt[: len(prompt) - 1])
        if seg is None:
            self.misses += 1
            return None
        if seg.host and not self._promote(seg):
            self.prefetch_stalls += 1
            self.misses += 1
            return None
        seg.refs += 1
        seg.tick = self._next_tick()
        self.hits += 1
        self.tokens_reused += depth
        return TierHit(seg, depth)

    def release(self, hit: TierHit) -> None:
        hit.segment.refs -= 1
        assert hit.segment.refs >= 0, "TierHit released twice"

    def cancel(self, hit: TierHit) -> None:
        """Release an unused hit and reverse the lookup's counters (the
        caller fell back to cold admission — e.g. no pool blocks for the
        suffix right now)."""
        self.release(hit)
        self.hits -= 1
        self.tokens_reused -= hit.length
        self.misses += 1

    def unlookup(self, hit: Optional[TierHit]) -> None:
        """Reverse one :meth:`lookup` entirely — counters AND pin — as if
        it never happened (same contract as ``PrefixStore.unlookup``):
        the caller's head-of-line block reservation failed, the request
        stays queued and will be looked up again when it re-offers, so
        neither a hit nor a miss must stick for this pass."""
        if hit is not None:
            self.cancel(hit)
        self.misses -= 1

    def shared_blocks(self, hit: TierHit) -> list:
        """The segment blocks FULLY covered by the match — the blocks an
        admitted request's table may reference directly (read-only,
        refcounted by the caller via ``pool.ref``). A partially covered
        boundary block is never shared: the admission scatter writes its
        private copy (the copy-on-write)."""
        return list(hit.segment.blocks[: hit.length // self.pool.block_size])

    def insert(self, prompt: np.ndarray, caches: Any, row) -> bool:
        """Store ``prompt``'s longest bucket-aligned proper prefix from a
        freshly prefilled cache pytree into tier-owned pool blocks.
        Registers a radix entry at every bucket boundary of the stored
        range (one shared segment). Under pool pressure, unreferenced
        tier segments evict LRU-first; if live state leaves no room the
        insert is SKIPPED (never an error, never a preemption)."""
        prompt = np.asarray(prompt, np.int32)
        bound = next(
            (b for b in reversed(self.buckets) if b <= len(prompt) - 1), None
        )
        if bound is None:
            return False
        have, have_seg = self._index.longest_match(prompt[:bound])
        if have >= bound:
            # Already stored to this depth — repair any shallow boundary
            # entry lost to eviction (see PrefixStore.insert).
            self._register_boundaries(prompt, have_seg, bound)
            return False
        bs = self.pool.block_size
        nb = -(-bound // bs)
        blocks = self.pool.try_alloc(nb)
        while blocks is None:
            if not self.evict_one():
                self.insert_skips += 1
                return False
            blocks = self.pool.try_alloc(nb)
        self.pool.arena = pool_write_seq(
            self.pool.arena, caches, jnp.int32(row),
            jnp.asarray(np.asarray(blocks, np.int32)), block_size=bs,
        )
        seg = _TierSegment(blocks, bound, tick=self._next_tick())
        self._register_boundaries(prompt, seg, bound)
        self._segments.append(seg)
        self.inserts += 1
        return True

    def _register_boundaries(self, prompt: np.ndarray, seg: _TierSegment,
                             upto: int) -> None:
        for b in self.buckets:
            if b > upto or b > seg.length:
                break
            depth, _ = self._index.longest_match(prompt[:b])
            if depth >= b:
                continue
            seg.nodes.append(self._index.insert(prompt[:b], seg))

    def evict_one(self) -> bool:
        """Relieve pool pressure by one segment: with a host tier
        attached, DEMOTE the least-recently-used unreferenced
        device-resident segment to host RAM (data survives — a later hit
        prefetches it back); without one — or when the host budget
        cannot absorb it even after dropping ITS least-recent entries —
        drop the segment outright. False when every device-resident
        segment is pinned by an in-flight hit (the caller falls through
        to preemption — demotion-before-preemption by construction)."""
        victims = [s for s in self._segments if s.refs == 0 and not s.host]
        if not victims:
            return False
        seg = min(victims, key=lambda s: s.tick)
        if self.host_tier is not None and self._demote(seg):
            return True
        for node in seg.nodes:
            self._index.remove(node)
        self.pool.unref(seg.blocks)
        self._segments.remove(seg)
        self.evictions += 1
        obs.emit(
            "serving", "prefix_evict",
            store=self.label, tokens=seg.length, blocks=len(seg.blocks),
            segments_left=len(self._segments), tier="kv_pool",
        )
        return True

    # -- host-RAM offload (ISSUE 14) -----------------------------------------

    def _demote(self, seg: _TierSegment) -> bool:
        """Park ``seg`` in the host tier: make room there (dropping ITS
        LRU host-resident segments first), gather the segment's block
        rows device-side, copy them down through the sanctioned
        spill path, and free the pool blocks. The radix entries stay —
        the segment is still indexed, just one tier colder."""
        from ..compat import jaxapi

        while not self.host_tier.room(seg.length):
            if not self._evict_host_one():
                return False  # budget cannot absorb it: caller drops
        nb = len(seg.blocks)
        with jaxapi.allow_transfer(
                "kv host tier demotion (D2H spill of cold prefix blocks)"):
            rows = jax.tree.map(
                np.asarray,  # demotion spill — sanctioned slow-path sync under pool pressure (guarded by allow_transfer)
                pool_gather_rows(
                    self.pool.arena,
                    jnp.asarray(np.asarray(seg.blocks, np.int32)),
                    block_size=self.pool.block_size,
                ),
            )
        self.host_tier.put(seg, seg.length, rows=rows)
        self.pool.unref(seg.blocks)
        seg.blocks = []
        seg.host = True
        seg.tick = self._next_tick()
        self.demotions += 1
        if self._on_demote is not None:
            self._on_demote()
        obs.emit(
            "serving", "kv_demote",
            store=self.label, tokens=seg.length, blocks=nb,
            host_tokens=self.host_tier.tokens_used,
            host_entries=self.host_tier.entries,
        )
        return True

    def _promote(self, seg: _TierSegment) -> bool:
        """Prefetch a demoted segment back into pool blocks: allocate
        (draining colder tier state under pressure), start the H2D
        upload — asynchronous, so under pipelined serving it overlaps
        the decode dispatch already in flight — and re-land the rows
        verbatim with the standard restore scatter. False when the pool
        cannot hold it right now (the segment stays parked)."""
        from ..compat import jaxapi

        entry = self.host_tier.get(seg)
        if entry is None or entry.rows is None:
            # Inconsistent (host flag without a host entry): drop the
            # segment from the index — a miss, never a crash.
            for node in seg.nodes:
                self._index.remove(node)
            if seg in self._segments:
                self._segments.remove(seg)
            return False
        bs = self.pool.block_size
        nb = -(-seg.length // bs)
        # Pin the promotion target for the duration: the allocation
        # pressure loop below can DEMOTE other segments, and the room-
        # making host eviction inside that demotion must not select the
        # very entry being promoted (it is unpinned and LRU-cold).
        entry.pinned = True
        try:
            blocks = self.pool.try_alloc(nb)
            while blocks is None:
                if not self.evict_one():
                    return False
                blocks = self.pool.try_alloc(nb)
        finally:
            entry.pinned = False
        self.host_tier.pop(seg)
        with jaxapi.allow_transfer(
                "kv host tier prefetch (H2D upload of a demoted prefix)"):
            rows = jax.tree.map(jnp.asarray, entry.rows)
            self.pool.arena = pool_scatter_rows(
                self.pool.arena, rows,
                jnp.asarray(np.asarray(blocks, np.int32)), block_size=bs,
            )
        seg.blocks = blocks
        seg.host = False
        seg.tick = self._next_tick()
        self.prefetches += 1
        if self._on_prefetch is not None:
            self._on_prefetch()
        obs.emit(
            "serving", "kv_prefetch",
            store=self.label, tokens=seg.length, blocks=nb,
            host_tokens=self.host_tier.tokens_used,
        )
        return True

    def _evict_host_one(self) -> bool:
        """Drop the host tier's LRU unpinned entry THAT IS OURS (a
        demoted segment — the serving loop's pinned session spills never
        LRU out), removing its radix entries with it."""
        key = self.host_tier.lru_unpinned()
        if not isinstance(key, _TierSegment):
            return False
        self.host_tier.pop(key)
        for node in key.nodes:
            self._index.remove(node)
        self._segments.remove(key)
        self.host_evictions += 1
        obs.emit(
            "serving", "prefix_evict",
            store=self.label, tokens=key.length, blocks=0,
            segments_left=len(self._segments), tier="kv_host",
        )
        return True

    # -- device-side copies --------------------------------------------------

    def materialize(self, hit: TierHit, max_len: int, n: int = 1):
        """A fresh ``[L, n, max_len, ...]`` cache pytree with the hit's
        prefix rows in every row at ``[0, hit.length)`` — what
        ``prefill_suffix`` resumes from. Pure device gather."""
        bs = self.pool.block_size
        nb = -(-hit.length // bs)
        return pool_materialize(
            self.pool.arena,
            jnp.asarray(np.asarray(hit.segment.blocks[:nb], np.int32)),
            hit.length, self.cfg, max_len, self.kv_quant, self.dtype,
            n, bs,
        )

    # -- reporting -----------------------------------------------------------

    @property
    def tokens_used(self) -> int:
        """DEVICE-resident tier tokens (host-demoted segments park their
        rows in the host tier's own accounting, not the pool's)."""
        return sum(s.length for s in self._segments if not s.host)

    @property
    def blocks_used(self) -> int:
        """Pool blocks the tier's segments hold a reference on (some may
        also be shared into lane tables; host-demoted segments hold
        none)."""
        return sum(len(s.blocks) for s in self._segments)

    def occupancy(self) -> float:
        """Tier fill as a fraction of the WHOLE pool — the tier is a
        tenant of the shared budget, not an arena of its own."""
        return round(self.tokens_used / max(1, self.pool.capacity_tokens), 4)

    def stats(self) -> dict:
        return {
            "capacity_tokens": self.pool.capacity_tokens,
            "tokens_used": self.tokens_used,
            "occupancy": self.occupancy(),
            "segments": len(self._segments),
            "entries": len(self._index),
            "hits": self.hits,
            "misses": self.misses,
            "tokens_reused": self.tokens_reused,
            "inserts": self.inserts,
            "insert_skips": self.insert_skips,
            "evictions": self.evictions,
            "demotions": self.demotions,
            "prefetches": self.prefetches,
            "host_evictions": self.host_evictions,
            "prefetch_stalls": self.prefetch_stalls,
            "host_segments": sum(1 for s in self._segments if s.host),
        }
