"""In-guest workload layer: the BASELINE config ladder (device probe,
compute check, all-reduce smoke) run inside the Kata guest the plugin
provisioned, plus the continuous-batching generation server."""
from .distributed import initialize_from_env, resolve
from .kv_arena import KVPool, PagedPrefixTier
from .prefix_cache import PrefixStore, RadixIndex
from .probe import probe_all_reduce, probe_compute, probe_devices, run_ladder
from .scheduler import Scheduler, SLOChunkedScheduler, make_scheduler
from .serving import GenerationServer, serve_batch
from .tp_serving import serving_mesh, shrink_ladder, tp_from_env

__all__ = [
    "GenerationServer",
    "serve_batch",
    "Scheduler",
    "SLOChunkedScheduler",
    "make_scheduler",
    "KVPool",
    "PagedPrefixTier",
    "PrefixStore",
    "RadixIndex",
    "initialize_from_env",
    "resolve",
    "probe_all_reduce",
    "probe_compute",
    "probe_devices",
    "run_ladder",
    "serving_mesh",
    "shrink_ladder",
    "tp_from_env",
]
