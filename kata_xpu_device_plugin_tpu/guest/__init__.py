"""In-guest validation: the BASELINE config ladder (device probe, compute
check, all-reduce smoke) run inside the Kata guest the plugin provisioned."""
from .distributed import initialize_from_env, resolve
from .probe import probe_all_reduce, probe_compute, probe_devices, run_ladder

__all__ = [
    "initialize_from_env",
    "resolve",
    "probe_all_reduce",
    "probe_compute",
    "probe_devices",
    "run_ladder",
]
