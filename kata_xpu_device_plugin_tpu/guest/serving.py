"""Continuous-batching generation server (in-guest serving loop).

The reference is host infrastructure and ships no serving stack (SURVEY §2:
zero ML code); this is the guest-side capability its users actually run on
the chips the plugin hands out. TPU-first design:

- A fixed-shape KV arena — ``[L, max_batch, max_len, KV, D]``, or with
  ``ring_kv`` a per-slot ring of ``window`` slots (window cycles: a tuple
  of per-position stacks, local layers at their window, global layers at
  max_len) — and one compiled ragged-decode scan (``transformer.decode``
  with [B] per-slot positions) serve every request mix — no shape churn,
  no recompiles as requests come and go.
- Admission is slot-based: a finished slot is refilled from the queue by
  prefilling the new prompt into fresh caches and writing them into the
  slot (one ``dynamic_update_slice``); all other slots keep decoding.
- The host loop only inspects tokens every ``chunk`` decode steps, so
  device dispatch stays one fused scan per chunk, and per-request python
  cost is amortized 1/chunk.

Greedy decoding matches :func:`..models.transformer.generate` token-for-
token per request (tested), independent of batching order and slot
assignment — continuous batching is a scheduling optimization, not a
numerics change. Sampling (temperature/top_k) is supported per-server; its
stream differs from single-request ``generate`` (different key schedule).

By default each distinct prompt length compiles its own prefill executable;
``prefill_buckets=(64, 256, 1024)``-style bucketing right-pads prompts to
the smallest fitting bucket — exact, not approximate (causal masking plus
``true_len`` logits indexing; see ``transformer.prefill``). The executable
count is bounded by ``len(buckets)`` only for prompts that fit a bucket;
longer prompts fall back to exact-length prefill (one executable per
distinct length), so the largest bucket should be sized to the longest
expected prompt.

PIPELINED rounds (``overlap=True``, the default): the round loop keeps one
decode chunk in flight — chunk N+1 is dispatched from chunk N's on-device
``last``/``pos`` outputs BEFORE chunk N's tokens are inspected, and chunk
N's token transfer rides an async ``DeviceFence`` copy started at
dispatch. Host-side scheduling (finish detection, queue refill, telemetry)
then runs concurrently with device compute instead of serializing with it.
Greedy output is token-identical to the lock-step loop (tested): each
request's tokens depend only on its own prefill state and per-slot
positions, and an admission decided after chunk N simply starts decoding at
chunk N+2 — a one-round scheduling lag, never a numerics change. Admission
itself batches: queued requests padding to the same prefill bucket run one
``[N, bucket]`` forward (``transformer.prefill_batch``) and scatter into
their slots in one vectorized write, instead of N sequential weight
streams — the dominant TTFT cost under burst arrival.

SHARED-PREFIX KV CACHE (``prefix_cache_tokens`` / ``prefix_store``): most
production prompts share a long common prefix (system prompt, few-shot
template). With a :class:`.prefix_cache.PrefixStore` attached, cold
admissions deposit each prompt's bucket-aligned prefix KV into a dedicated
device arena (radix-indexed by token ids), and later admissions that match
copy the prefix rows into their slot on device and prefill ONLY the suffix
(``transformer.prefill_suffix`` — RoPE positions shifted, causal mask over
``offset + suffix``). Greedy tokens are identical to cold admission
(tested); TTFT and prefill FLOPs drop by the shared fraction. Match
boundaries are ``prefill_buckets`` values, so the executable-count bound
survives. ``ring_kv`` and draft-model servers fall back to cold admission
(the ring/cycle folds re-layout prefix rows per slot and the draft arena
would miss its own prefix — explicitly unsupported for now).

TENSOR-PARALLEL SERVING (:mod:`.tp_serving`, ``tp=N``): one server runs
this whole loop — overlap, paged arena, prefix cache, scheduler, crash
recovery — over a 1×N ICI mesh built from the daemon-injected topology
env (``KATA_TPU_TP`` override → ``TPU_VISIBLE_CHIPS`` →
``TPU_ACCELERATOR_TYPE``). Params shard by the serving regex rules
(``parallel.sharding.SERVING_RULES`` — embeddings replicated, attention
heads and MLP column/row split over the ``model`` axis), the KV arena /
paged pool / prefix store shard their head axis, and GSPMD inserts the
tp collectives inside the SAME jitted prefill/decode executables — the
host scheduling loop is untouched, its ``last``/``pos``/block-table
inputs replicate into each dispatch with no resharding step on the
decode hot path. Greedy outputs are BIT-IDENTICAL to ``tp=1`` (tested
across paged/slotted × overlap × strict × prefix-hit and under seeded
fault schedules): sharding a matmul's non-contraction axis computes the
identical values, and the one psum per layer pair is the same fp32 sum
— exact wherever the backend's matmul accumulation is tiling-invariant
(the fp32 CI matrix; bf16 on XLA CPU retiles the accumulation per
output width, which can flip greedy near-ties in the last rounding bit
— see "Tensor-parallel serving" in docs/guest_guide.md).

CRASH-TOLERANT SERVING (:mod:`.resilience`): a recovery SUPERVISOR wraps
every scheduler round. A recoverable dispatch failure (injected fault,
watchdog stall, transient XLA status — :func:`.resilience.recoverable`)
no longer unwinds ``run()`` and drops the queue: the supervisor rebuilds
the pool/arena from scratch (the failed round may have poisoned donated
buffers), restores every lane that has a host checkpoint (taken every
``KATA_TPU_CHECKPOINT_ROUNDS`` rounds through the PR 6 spill machinery —
sanctioned ``allow_transfer``, off the overlapped critical path), requeues
the rest strict-FIFO for a from-the-prompt replay, and retries with
bounded exponential backoff. Greedy determinism makes recovery invisible
in the output: replaying a suffix (or a whole prompt) reproduces the same
tokens bit-for-bit, so recovered results equal a fault-free run (the
tested matrix: fault-kind × paged/slotted × overlap × strict). A request
implicated in ``KATA_TPU_QUARANTINE_K`` consecutive failed rounds is
QUARANTINED — failed individually into :meth:`GenerationServer.failures`
with a ``request_failed`` event — so one poison request cannot wedge
retries forever. :meth:`GenerationServer.drain` (wired to SIGTERM and a
maintenance-notice file by :func:`.resilience.wire_drain`) stops
admission, finishes in-flight work, fails still-queued requests loudly,
and emits a final checkpoint event. With every knob at its default the
hot path is untouched: the injector is disarmed, the watchdog inline,
and no new host syncs exist (jaxguard-clean).
"""
from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..compat import jaxapi
from ..models.transformer import (
    DecoderConfig,
    _decode_scan,
    _decode_while,
    _next_token,
    _sampling_args,
    cycle_ring_caches_from_prefill,
    init_cycle_kv_caches,
    init_kv_caches,
    prefill,
    prefill_batch,
    prefill_suffix,
    ring_caches_from_prefill,
)
from ..ops import attention
from . import resilience, tp_serving
from .kv_arena import (
    RESERVED_BLOCKS,
    SCRATCH_BLOCK,
    HostKVTier,
    KVPool,
    PagedPrefixTier,
    pool_gather_rows,
    pool_scatter_rows,
    pool_write_batch,
    pool_write_seq,
)
from .tp_serving import KV_LAYOUT_BLOCKS, KV_LAYOUT_HEADS, KV_LAYOUTS
from .prefix_cache import PrefixHit, PrefixStore
from .resilience import DeviceStallError, FaultInjector
from .scheduler import (
    DEFAULT_ITL_SLO_MS,
    DEFAULT_PREFILL_CHUNK,
    ENV_ITL_SLO_MS,
    ENV_PREFILL_CHUNK,
    ENV_SCHED_POLICY,
    POLICIES,
    POLICY_FIFO,
    POLICY_SLO,
    make_scheduler,
)

# Speculative serving opt-in (ISSUE 8 satellite): the measured spec A/B is
# a net LOSS today (BENCH_TPU_20260731T140338Z: 64.8 tok/s at 0.178 draft
# acceptance vs 206 tok/s plain), so ``speculative_k`` alone no longer
# arms it — the caller must also opt in (``spec_opt_in=True`` or this env)
# or the server degrades to plain decoding with a ``spec_disabled`` event.
ENV_SPEC_OPT_IN = "KATA_TPU_SPEC"

# Per-allocation trace context (ISSUE 11): the daemon's Allocate handler
# stamps its span's trace id into this env (cdi.constants.ENV_TRACE_CTX,
# config.trace_context — the same constants → allocators → manager path
# as every other knob). A server ADOPTS it as its trace id, so every
# serving span/event — request lifecycle traces, recovery/degraded
# events, flight-recorder dumps — joins the daemon's allocation trace
# end to end. Unset (direct runs, tests): the server mints its own, so
# a process's workloads still share one join key per server.
ENV_TRACE_CTX = "KATA_TPU_TRACE_CTX"

# int8 KV by default (ISSUE 12): the measured-1.7×-faster int8 KV cache
# is the server default, gated by the tools/eval_quality.py quality check
# (greedy-token match + logit drift vs bf16 — `make eval-kv` must pass
# before a release flips or keeps this). KATA_TPU_KV_QUANT is the
# daemon-injectable opt-out (cdi.constants.ENV_KV_QUANT, config.kv_quant
# — the standard constants → allocators → manager path): "bf16" restores
# the unquantized arena node-wide, "int8" pins the default explicitly,
# anything else degrades to the default with a kv_quant_invalid event.
# An explicit kv_quant= argument always wins.
ENV_KV_QUANT = "KATA_TPU_KV_QUANT"
DEFAULT_KV_QUANT = "int8"

# Multi-step decode (ISSUE 13): ``decode_steps=K`` multiplies the decode
# scan each host dispatch runs — one dispatch delivers ``chunk × K``
# tokens per lane, with ON-DEVICE EOS/budget masking (a lane that hits
# its budget or the eos token FREEZES inside the scan: token and
# position pin, so its cache rewrites are value-identical no-ops — see
# transformer._decode_scan) so host scheduling, the fence, and obs
# bookkeeping amortize over K× more tokens without a lane overrunning
# its block reservation. Daemon-injectable through the standard
# constants → allocators → manager path (cdi.constants.ENV_DECODE_STEPS,
# config.decode_steps, --decode-steps); malformed env values degrade to
# K=1 with a ``decode_steps_invalid`` event, an explicit argument
# raises. Greedy outputs are bit-identical to K=1 (tested).
ENV_DECODE_STEPS = "KATA_TPU_DECODE_STEPS"

# Fused prefill+decode dispatch (ISSUE 13): under ``slo_chunked``, a
# deferred admission chunk RIDES the decode dispatch — one jitted
# executable carries the N decode lanes' scan AND the admission lane's
# ``prefill_chunk``-wide suffix slice, so chunked admission stops
# alternating slice-round / decode-round (the head-of-line theft the
# scheduler exists to remove pays one dispatch + one fence instead of
# two). Default ON whenever ``slo_chunked`` is active; ``KATA_TPU_FUSED=0``
# is the guest-side kill switch, malformed values degrade with a
# ``fused_disabled`` event, and an explicit ``fused=True`` on a server
# whose policy cannot chunk raises.
ENV_FUSED = "KATA_TPU_FUSED"

# Persistent on-device decode rounds (ISSUE 20): ``persistent=True`` /
# ``KATA_TPU_PERSISTENT=1`` replaces the fixed ``chunk × decode_steps``
# scan with a ``lax.while_loop`` executable
# (transformer._decode_while) that keeps decoding ON DEVICE — greedy
# sampling, per-lane EOS/budget freezing, block-table positions bumped
# against a pre-reserved window — until the heartbeat-cadence step cap
# is hit, a lane freezes (needs host service), or a live lane's window
# is exhausted. The host is touched only at fence boundaries; ITL,
# scheduler, ledger, and heartbeat accounting divide by the DELIVERED
# step count read from the loop carry at the fence. Guest-side env-only
# knob (like KATA_TPU_FUSED/KATA_TPU_DEGRADED — no daemon injection
# surface): malformed values degrade with a ``persistent_disabled``
# event; explicit ``persistent=True`` on an incompatible server
# (speculative, ring_kv, sampling — the loop is greedy-only) raises,
# the env degrades. Greedy outputs stay bit-identical to lock-step K=1
# (tested across tp/paged/strict in tests/test_persistent_decode.py).
ENV_PERSISTENT = "KATA_TPU_PERSISTENT"

# Paged-pool placement layout + host-RAM KV offload tier (ISSUE 14):
# KATA_TPU_KV_LAYOUT selects "heads" (the historical divide-or-replicate
# head-axis sharding) or "blocks" (the paged pool's token axis shards
# across the tp mesh — per-shard pool bytes ~logical/tp for EVERY model,
# GQA included; the kv_replicated cliff does not exist). The layout is
# purely a PLACEMENT decision: every jitted pool op computes the same
# values over the same logical array, and the decode kernel's blocks
# form recombines shard-local split-K partials with the online-softmax
# merge — greedy outputs are bit-identical across layouts (tested).
# KATA_TPU_KV_HOST_TOKENS arms the host-RAM tier below the device pool:
# under pool pressure, cold KV (unpinned prefix segments; preempted idle
# sessions already spill there) DEMOTES to host RAM before any lane is
# preempted, and a prefix hit / session resume PREFETCHES it back with
# the H2D upload overlapping the in-flight decode dispatch. Standard
# knob contract: explicit args raise on conflict, the daemon-injected
# env degrades with kv_layout_invalid / kv_layout_disabled /
# kv_host_invalid / kv_host_disabled events.
ENV_KV_LAYOUT = tp_serving.ENV_KV_LAYOUT
ENV_KV_HOST_TOKENS = "KATA_TPU_KV_HOST_TOKENS"

# Serving heartbeat cadence (ISSUE 15): every K rounds the loop rolls
# its per-dispatch accounting into ONE ``serving_heartbeat`` event —
# tokens/s, rolling ITL/TTFT quantiles, per-tier occupancy, host-tier
# hit/prefetch rates, queue depth and admission wait, and the loop-phase
# time breakdown — from data the loop already holds, so the hot path
# pays ~one dict per heartbeat (the bench serving_obs_* A/B pins the
# cost <= 1% tok/s). The SLO-burn watchdog (obs/watchdog.py) consumes
# each heartbeat in-process. Daemon-injectable through the standard
# constants → allocators → manager path (cdi.constants
# ENV_HEARTBEAT_ROUNDS, config.heartbeat_rounds); malformed env values
# degrade to the default with a ``heartbeat_invalid`` event, an explicit
# negative argument raises. 0 disables heartbeat, watchdog, AND the
# phase clock — the fully uninstrumented loop.
ENV_HEARTBEAT_ROUNDS = "KATA_TPU_HEARTBEAT_ROUNDS"
DEFAULT_HEARTBEAT_ROUNDS = 32

# Loop-phase buckets of the heartbeat's time breakdown: where one
# heartbeat interval's host wall clock went. ``admit`` — admission
# passes (prefill forwards included); ``dispatch`` — building/enqueueing
# decode executables; ``retire`` — fence waits + token landing;
# ``host_transfer`` — checkpoint gathers, preemption spills, resume
# prefetch/restores (the D2H/H2D tier traffic); ``other`` — everything
# between (scheduling bookkeeping, queue work).
LOOP_PHASE_ADMIT = "admit"
LOOP_PHASE_DISPATCH = "dispatch"
LOOP_PHASE_RETIRE = "retire"
LOOP_PHASE_HOST = "host_transfer"
LOOP_PHASE_OTHER = "other"
LOOP_PHASES = (
    LOOP_PHASE_ADMIT, LOOP_PHASE_DISPATCH, LOOP_PHASE_RETIRE,
    LOOP_PHASE_HOST, LOOP_PHASE_OTHER,
)


class _PhaseClock:
    """Exclusive loop-phase wall-time accounting (ISSUE 15): the serving
    loop brackets its admission / dispatch / retire / host-transfer
    sections with :meth:`push`/:meth:`pop`, and elapsed time is charged
    to the INNERMOST open phase — a checkpoint gather inside a retire
    window lands in ``host_transfer``, not twice. Disarmed
    (``heartbeat_rounds=0``) both calls are one attribute test, so the
    uninstrumented loop stays uninstrumented. Host-only arithmetic:
    never fences or touches device state (the phase boundaries sit at
    calls the loop already makes)."""

    __slots__ = ("armed", "acc", "_stack", "_mark")

    def __init__(self, armed: bool):
        self.armed = armed
        self.acc = {p: 0.0 for p in LOOP_PHASES[:-1]}
        self._stack: list = []
        self._mark = 0.0

    def push(self, phase: str) -> None:
        if not self.armed:
            return
        now = time.perf_counter()
        if self._stack:
            top = self._stack[-1]
            self.acc[top] = self.acc.get(top, 0.0) + (now - self._mark)
        self._stack.append(phase)
        self._mark = now

    def pop(self) -> None:
        if not self.armed or not self._stack:
            return
        now = time.perf_counter()
        phase = self._stack.pop()
        self.acc[phase] = self.acc.get(phase, 0.0) + (now - self._mark)
        self._mark = now

    def snapshot(self) -> dict:
        return dict(self.acc)


def resolve_kv_quant(kv_quant, emit=None) -> bool:
    """The ONE int8-by-default resolution (ISSUE 12): explicit argument >
    ``KATA_TPU_KV_QUANT`` env ("int8" | "bf16") > the int8 default. Both
    :class:`GenerationServer` and a default-constructed
    :class:`.prefix_cache.PrefixStore` route through this, so an
    injected store and its server resolve the SAME dtype by default
    (their mismatch check stays for explicitly divergent pairs). A
    malformed env value degrades to the default; ``emit`` (the server's
    ``_emit``) reports it once as ``kv_quant_invalid`` — store-side
    resolution passes no emitter, so one server emits one event."""
    if kv_quant is not None:
        return bool(kv_quant)
    raw = os.environ.get(ENV_KV_QUANT, "").strip().lower()
    if raw and raw not in ("int8", "bf16"):
        if emit is not None:
            emit("kv_quant_invalid", reason=f"bad_env:{raw[:32]}")
        raw = ""
    return (raw or DEFAULT_KV_QUANT) == "int8"

# Decode-attention backend override (ISSUE 12): the serving decode step
# runs the paged-native split-K pallas kernel on TPU
# (ops/decode_attn.pallas_paged_decode_attention — block tables walked in
# place, int8 dequant fused in-kernel, shard_map'd over the tp mesh) and
# the XLA gather path elsewhere. This env forces either side
# ("pallas_paged" runs the kernel in interpret mode off-TPU — the CPU
# serving-matrix harness); malformed values degrade to the automatic
# choice with a decode_attn_invalid event. The resolved backend is
# emitted once per server (decode_attn_backend event), always present in
# stats()["decode_backend"], and scraped as a labeled gauge. The name
# constants live with the dispatch decision (ops/attention.py) so label
# and dispatch cannot drift.
ENV_DECODE_ATTN = attention.DECODE_ATTN_ENV
BACKEND_PAGED = attention.BACKEND_PAGED
BACKEND_REFERENCE = attention.BACKEND_REFERENCE

# Request lifecycle phases (ISSUE 11): every submitted request is in
# exactly ONE of these states at any moment, and the per-request ledger
# accrues wall time into the current phase at each state transition —
# so the emitted ``request_trace`` event's phases sum to the request's
# wall clock by construction (transitions are stamped at the same
# honest fence points the latency metrics already use: the first-token
# fence, the retire cadence, the spill/restore completions).
PHASE_QUEUE = "queue"                # submit → admission grant
PHASE_PREFILL = "prefill"            # admission grant → first-token fence
#                                      (chunked slices + their deferrals)
PHASE_DECODE = "decode"              # decoding rounds at full tp
PHASE_DECODE_DEGRADED = "decode_degraded"  # decoding on a shrunken mesh
PHASE_PREEMPTED = "preempted"        # KV spilled, waiting FIFO for the pool
PHASE_RECOVERY = "recovery"          # crash recovery: restore wait + replay
PHASES = (
    PHASE_QUEUE, PHASE_PREFILL, PHASE_DECODE, PHASE_DECODE_DEGRADED,
    PHASE_PREEMPTED, PHASE_RECOVERY,
)


# Serving-stat gauges, created through obs.metrics' idempotent factory
# (a reload or second import path returns the SAME collectors instead of
# raising Duplicated timeseries); instances distinguish themselves by the
# "server" label (see GenerationServer.export_metrics).
_PROM_STATS = (
    ("rounds", "Device rounds dispatched"),
    ("prefills", "Prompt prefills performed"),
    ("tokens_emitted", "Tokens emitted (pre-trim, incl. prefill tokens)"),
    ("tokens_per_round", "Mean decoded tokens per device round"),
    ("slots_busy", "Arena slots currently serving a request"),
    ("queued", "Requests waiting for a slot"),
    ("batch_occupancy", "Busy fraction of the arena's slots"),
    ("kv_slot_utilization", "Mean busy-slot cache fill (pos / arena len)"),
    ("arena_bytes", "KV arena HBM footprint (addressable shards summed)"),
    ("draft_acceptance", "Speculative draft acceptance rate"),
    ("prefill_batches", "Multi-request admission prefill forwards"),
    ("prefix_hit_ratio", "Prefix-cache hit ratio (hits / lookups)"),
    ("prefix_store_occupancy", "Prefix store fill (tokens used / capacity)"),
    ("kv_pool_occupancy", "Paged KV pool fill (blocks in use / usable)"),
    ("kv_blocks_in_use", "Paged KV pool blocks currently referenced"),
    ("kv_host_blocks", "Host-RAM KV tier blocks resident (demoted prefix "
                       "segments + preempted session spills)"),
    ("preemptions", "Requests preempted (KV spilled, requeued FIFO)"),
    ("cow_copies", "Prefix-tier boundary blocks privatized copy-on-write"),
    ("recoveries", "Supervisor recoveries from a failed scheduler round"),
    ("quarantined", "Requests failed after K consecutive implicated rounds"),
    ("device_stalls", "Watchdog fence deadlines exceeded (real or injected)"),
    ("checkpoints", "Host KV checkpoints taken for crash recovery"),
    ("sched_chunks", "Chunked-prefill slices run by the admission scheduler"),
    ("sched_defers", "Admission passes deferred to decode under SLO pressure"),
    ("slo_violations", "Decode rounds whose cadence exceeded the ITL SLO"),
    ("tp_degree", "Tensor-parallel degree of the serving mesh (1 = unsharded)"),
    ("tp_degraded", "Serving below the configured tensor-parallel degree "
                    "after a permanent chip fault (0/1)"),
    ("tp_shrinks", "Elastic mesh-shrink recoveries performed (chip loss / "
                   "ICI failure survived degraded)"),
    ("request_traces", "Request lifecycle traces emitted (one request_trace "
                       "event per retired/failed request)"),
    ("decode_steps", "Multi-step decode multiplier K (tokens per dispatch = "
                     "chunk × K; 1 = one chunk per dispatch)"),
    ("heartbeats", "Serving heartbeats emitted (one serving_heartbeat "
                   "event every heartbeat_rounds rounds)"),
    ("heartbeat_tokens_per_s", "Decoded tokens/s over the last heartbeat "
                               "interval (0.0 before the first heartbeat)"),
    ("watchdog_alerts", "SLO-burn watchdog alerts fired (sustained "
                        "breaches; each dumped the flight ring)"),
    ("watchdog_active", "Watchdog alert kinds currently active (0 = "
                        "healthy)"),
    # Device ledger (ISSUE 17): utilization over the last heartbeat
    # interval — always-present stats() numbers (0.0 before the first
    # heartbeat / with the ledger disarmed), so they ride the scrape
    # loop; the memory side exports through the dedicated
    # hbm_headroom_bytes gauge (NaN when the backend has no
    # memory_stats — a missing poll must never scrape as 0 bytes free).
    ("mfu", "Model FLOP/s utilization over the last heartbeat interval "
            "(dispatched executable FLOPs / interval wall / public "
            "per-chip peak x tp)"),
    ("device_busy_frac", "Fraction of the last heartbeat interval covered "
                         "by in-flight decode rounds (dispatch->retire)"),
    ("dispatch_gap_ms", "Mean retire-fence -> next-dispatch host gap over "
                        "the last heartbeat interval (ms; the "
                        "device-idle signal)"),
    # fused_admissions is stats()-only here: its prometheus surface is
    # the TRUE counter kata_tpu_serving_fused_admissions_total (the
    # factory stores counters under their _total-stripped stem, so a
    # same-stem scrape gauge would collide — the sched_chunks /
    # prefill_chunks_total pair makes the same split).
)


# Per-request lifecycle phase times (ISSUE 11): observed once per retired
# or failed request, one labeled child per phase — the aggregate a fleet
# router can load-balance on (where does THIS server's latency go).
def _hist_phase():
    return obs.histogram(
        "kata_tpu_serving_request_phase_seconds",
        "Per-request lifecycle phase time attributed at retire "
        "(queue/prefill/decode/decode_degraded/preempted/recovery)",
        ["server", "phase"],
    )


# Loop-phase time per heartbeat interval (ISSUE 15): where the serving
# loop's host wall clock goes — one labeled child per LOOP_PHASES entry,
# observed once per heartbeat, so rate() over the histogram sum answers
# "what fraction of this replica's time is admission vs dispatch vs
# fence waits vs tier traffic".
def _hist_loop_phase():
    return obs.histogram(
        "kata_tpu_serving_loop_phase_seconds",
        "Serving-loop phase time per heartbeat interval "
        "(admit/dispatch/retire/host_transfer/other)",
        ["server", "phase"],
    )


# Per-shard paged-pool occupancy (ISSUE 9): one gauge per mesh shard so
# dashboards see the sharded pool without a schema branch (shard 0 reports
# 0.0 on tp=1 / slotted servers — same always-present contract as the
# stats() field it mirrors).
def _gauge_shard_occupancy():
    return obs.gauge(
        "kata_tpu_serving_kv_pool_shard_occupancy",
        "Paged KV pool fill per tensor-parallel mesh shard "
        "(0.0 at tp=1 or on slotted servers)",
        ["server", "shard"],
    )


# Decode-attention backend (ISSUE 12): a labeled 0/1 gauge rather than a
# _PROM_STATS entry — the backend is a NAME, and the always-present
# stats()["decode_backend"] string cannot ride the numeric scrape loop.
# One child per known backend, 1 on the active one, so dashboards can
# alert on "fleet fraction running the kernel" without schema branches.
def _gauge_decode_backend():
    return obs.gauge(
        "kata_tpu_serving_decode_attn_backend",
        "Active decode-attention backend (1 on the server's backend "
        "label, 0 on the others; pallas_paged | xla_reference)",
        ["server", "backend"],
    )


# Device-memory headroom (ISSUE 17): a dedicated gauge rather than a
# _PROM_STATS entry — the scrape loop's stats().get(name, 0.0) default
# would fake "0 bytes free" on backends without memory_stats (CPU),
# where the ledger's contract is omission. The set_function reads the
# ledger directly and exports NaN for "unknown".
def _gauge_hbm_headroom():
    return obs.gauge(
        "kata_tpu_serving_hbm_headroom_bytes",
        "Device memory headroom (limit - used) at the last heartbeat "
        "poll; NaN where the backend exposes no memory_stats",
        ["server"],
    )


# Prefix-cache traffic counters (ISSUE 5): true Prometheus counters (the
# scrape-time gauges above mirror stats(); these are incremented at the
# moment of the lookup so rate() works even between scrapes).
def _ctr_prefix_hits():
    return obs.counter(
        "kata_tpu_serving_prefix_hits",
        "Admissions served from the prefix KV store (suffix-only prefill)",
        ["server"],
    )


def _ctr_prefix_misses():
    return obs.counter(
        "kata_tpu_serving_prefix_misses",
        "Admissions with no usable cached prefix (cold prefill)",
        ["server"],
    )


def _ctr_prefix_tokens_reused():
    return obs.counter(
        "kata_tpu_serving_prefix_tokens_reused",
        "Prompt tokens whose KV was copied from the prefix store "
        "instead of re-prefilled",
        ["server"],
    )


# Paged-pool traffic counters (ISSUE 6): incremented at the moment of the
# event so rate() works even between scrapes. The ``_total`` suffix keeps
# them distinct from the same-named scrape-time stats() gauges above.
def _ctr_preemptions():
    return obs.counter(
        "kata_tpu_serving_kv_preemptions_total",
        "Requests preempted under KV pool pressure (spilled + requeued)",
        ["server"],
    )


def _ctr_cow_copies():
    return obs.counter(
        "kata_tpu_serving_kv_cow_copies_total",
        "Prefix-tier boundary blocks privatized copy-on-write at admission",
        ["server"],
    )


# Host-RAM KV tier traffic counters (ISSUE 14): incremented at the moment
# of the D2H demotion / H2D prefetch so rate() works between scrapes; the
# kv_host_blocks scrape gauge mirrors the resident population.
def _ctr_kv_demotions():
    return obs.counter(
        "kata_tpu_serving_kv_demotions_total",
        "Cold KV demoted from the device pool to the host-RAM tier "
        "(prefix segments under pool pressure + preempted session spills)",
        ["server"],
    )


def _ctr_kv_prefetches():
    return obs.counter(
        "kata_tpu_serving_kv_prefetches_total",
        "Host-tier KV prefetched back to the device pool (prefix hits on "
        "demoted segments + preempted session resumes)",
        ["server"],
    )


# Resilience traffic counters (ISSUE 7): incremented at the moment of the
# event so rate() works between scrapes, like the pool counters above.
def _ctr_recoveries():
    return obs.counter(
        "kata_tpu_serving_crash_recoveries_total",
        "Supervisor recoveries from a failed scheduler round",
        ["server"],
    )


def _ctr_quarantined():
    return obs.counter(
        "kata_tpu_serving_requests_quarantined_total",
        "Requests failed individually after K consecutive implicated rounds",
        ["server"],
    )


def _ctr_stalls():
    return obs.counter(
        "kata_tpu_serving_fence_stalls_total",
        "Watchdog fence deadlines exceeded (real or injected)",
        ["server"],
    )


# Scheduler traffic counters (ISSUE 8): incremented at the moment of the
# decision so rate() works between scrapes, like the pool/resilience ones.
def _ctr_sched_chunks():
    return obs.counter(
        "kata_tpu_serving_prefill_chunks_total",
        "Chunked-prefill slices run by the admission scheduler",
        ["server"],
    )


def _ctr_sched_defers():
    return obs.counter(
        "kata_tpu_serving_admission_defers_total",
        "Admission passes deferred to decode under projected-ITL pressure",
        ["server"],
    )


def _ctr_slo_violations():
    return obs.counter(
        "kata_tpu_serving_itl_slo_violations_total",
        "Decode rounds whose retire cadence exceeded the ITL SLO",
        ["server"],
    )


# Fused-admission traffic counter (ISSUE 13): incremented when a chunked
# admission COMPLETES having ridden at least one fused dispatch (its
# slices were batched into decode rounds), so rate() works between
# scrapes like the other _total counters; the same-named scrape gauge
# mirrors stats().
def _ctr_fused_admissions():
    return obs.counter(
        "kata_tpu_serving_fused_admissions_total",
        "Chunked admissions whose slices rode fused prefill+decode "
        "dispatches",
        ["server"],
    )


def _prom_gauges() -> dict:
    return {
        name: obs.gauge(f"kata_tpu_serving_{name}", desc, ["server"])
        for name, desc in _PROM_STATS
    }


# Latency histograms (ISSUE 2): TTFT (submit → first token, includes
# queueing) and per-token decode latency (chunk wall time / chunk steps).
def _hist_ttft():
    return obs.histogram(
        "kata_tpu_serving_ttft_seconds",
        "Time to first token: submit → prefill token sampled",
        ["server"],
    )


def _hist_decode_token():
    return obs.histogram(
        "kata_tpu_serving_decode_token_seconds",
        "Per-token decode latency (fenced chunk time / steps)",
        ["server"],
    )


def _hbm_bytes(leaf) -> int:
    """Total device memory a (possibly sharded or replicated) array holds
    across all addressable devices — shard sizes summed, so a replicated
    array costs devices × nbytes and a sharded one its logical nbytes."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        return sum(
            int(np.prod(s.data.shape)) * leaf.dtype.itemsize for s in shards
        )
    return leaf.nbytes


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray  # [S] int32
    max_new_tokens: int
    t_submit: float = 0.0  # monotonic clock at submit() — TTFT anchor
    out: list = field(default_factory=list)
    done: bool = False
    # Consecutive failed rounds this request was implicated in (reset on
    # any round it survives); at the quarantine threshold the supervisor
    # fails it individually instead of retrying forever (ISSUE 7).
    fails: int = 0
    # Times this request was requeued for a from-the-prompt replay by
    # crash recovery — its re-admission ttft event is labeled with it.
    replays: int = 0
    # Lifecycle ledger (ISSUE 11): accrued seconds per PHASES entry, the
    # current phase, and the monotonic stamp it was entered at. ``state``
    # is None once the ledger closed (request_trace emitted) — a second
    # finish/fail can never double-emit or double-accrue.
    phases: dict = field(default_factory=dict)
    state: Optional[str] = PHASE_QUEUE
    t_state: float = 0.0


@dataclass
class _CkptEntry:
    """One live lane's recovery checkpoint: the request, its emitted
    tokens AS OF the snapshot (a copy — ``req.out`` keeps growing), the
    host scheduling state, and the lane's KV rows on host (full-table
    width for paged servers — the ``_Preempted`` layout — or the
    ``[L, 1, arena_len, ...]`` slot slice for slotted ones). Restore +
    greedy determinism replays the post-checkpoint suffix bit-identically
    to a fault-free run."""

    req: "_Request"
    out: list
    pos: int
    last: int
    kv: Any  # host pytree


@dataclass
class _Preempted:
    """One preempted request waiting FIFO for the pool to drain: its KV
    rows spilled to host (full-table-width pytree, block-granular), plus
    the host scheduling state (``pos``/``last``) a restore needs. The
    emitted tokens so far stay on ``req.out`` — restore resumes decode
    exactly where the spill cut it, so greedy output is unchanged."""

    req: "_Request"
    kv: Any  # host pytree, leaves [L, nb_max * block_size, ...]
    pos: int
    last: int


@dataclass
class _LanePlan:
    """A paged admission's block reservation, made BEFORE the prefill
    forward runs (allocation failure must requeue the request, not waste
    a forward). ``table[:n_shared]`` are prefix-tier blocks the lane
    references read-only (pool-refcounted); the admission scatter masks
    them with SCRATCH so shared rows are never rewritten — the partially
    covered boundary block, when the match is not block-aligned, is the
    first PRIVATE entry and receives its copy-on-write fill from the
    materialized cache."""

    table: list
    n_shared: int


@dataclass
class _PartialPrefill:
    """One CHUNKED admission in progress (ISSUE 8): the queue head's
    prompt being prefilled in ``prefill_chunk``-token slices interleaved
    with decode rounds. ``caches`` is the request's own standalone
    ``[L, 1, max_len, ...]`` cache pytree (prefix-hit rows materialized
    up front, each chunk's ``prefill_suffix`` resuming at ``offset``);
    the admission commits to a lane — arena write, store insert, first
    token — only when the final slice lands, so every shared invariant
    (TTFT stamping, FIFO, none-vanish) goes through the same
    ``_finish_admission`` epilogue as the unchunked paths. Strictly
    head-of-line: while a partial exists nothing else admits or resumes,
    and its request rides ``_admitting`` so a mid-chunk crash replays it
    from the prompt (PR 7 strict-FIFO requeue)."""

    req: _Request
    hit: Optional[PrefixHit]
    caches: Any
    offset: int  # prompt rows already resident (prefix reuse + chunks)
    reused: int  # prefix rows copied from the store (event bookkeeping)
    chunks: int = 0  # chunk forwards run so far
    fused: int = 0  # chunks that RODE a decode dispatch (ISSUE 13)


@dataclass
class _FusedChunk:
    """One admission slice riding a decode dispatch (ISSUE 13): the
    partial it belongs to, the slice geometry consumed AT DISPATCH
    (``p.offset`` advanced there — overlapped rounds pipeline one slice
    per dispatch, so the next dispatch's slice must not re-read it), and
    the slice's last-position logits future. ``last=True``: this was the
    final slice — retire samples the first token from ``logits`` and
    lands the admission through the shared ``_finish_admission``
    epilogue, exactly like the inline chunk path."""

    partial: _PartialPrefill
    take: int   # real suffix tokens this slice carried
    width: int  # padded executable width
    last: bool  # final slice → retire commits the admission
    logits: Any  # [1, vocab] device future from the fused executable


@dataclass
class _Inflight:
    """One dispatched-but-unretired decode chunk (the pipeline's depth-1
    slot). ``last``/``pos`` are the chunk's ON-DEVICE outputs — the next
    chunk dispatches from them directly, no host round-trip; ``fence`` is
    the async D2H copy of the tokens (and last/pos) started at dispatch.
    ``slots`` pins (slot, request) pairs at dispatch time: a slot refilled
    while the chunk was in flight fails the identity check at retire and
    its stale tokens are discarded. ``fused`` carries the admission slice
    that rode this dispatch, when one did (ISSUE 13) — applied at
    retire."""
    fence: obs.DeviceFence
    last: Any  # [B] device int32 — next chunk's tok input
    pos: Any  # [B] device int32
    slots: list  # [(slot_index, _Request)] host-known-busy at dispatch
    span: obs.Span  # detached; ends (fences + emits) at retire
    t_dispatch: float  # perf_counter at dispatch — round-cadence anchor
    fused: Optional[_FusedChunk] = None  # admission slice riding the chunk


@partial(jax.jit, donate_argnums=(0,))
def _write_slot(arena, slot_caches, slot: jax.Array):
    """Copy a freshly prefilled single-sequence cache pair into arena slot
    ``slot`` (traced scalar — one executable serves every slot). Tree-maps
    over the cache pytree, so bf16 arrays and int8 QTensor caches (q +
    scale leaves) both work."""
    s = jnp.asarray(slot, jnp.int32)

    def write(a, c):
        at = (jnp.int32(0), s) + (jnp.int32(0),) * (a.ndim - 2)
        return jax.lax.dynamic_update_slice(a, c, at)

    return jax.tree.map(write, arena, slot_caches)


@partial(jax.jit, donate_argnums=(0,))
def _write_slots(arena, batch_caches, slots: jax.Array):
    """Vectorized :func:`_write_slot`: scatter the N cache rows of one
    batched admission prefill (``prefill_batch`` — leaves ``[L, N, len,
    ...]``) into arena slots ``slots`` ([N] int32, traced) in ONE
    executable, instead of N sequential whole-arena update_slices. Same
    tree-map shape tolerance (bf16 and int8 QTensor q/scale leaves)."""
    def write(a, c):
        return a.at[:, slots].set(c)

    return jax.tree.map(write, arena, batch_caches)


@jax.jit
def _merge_rows(dev_vals, host_vals, fresh):
    """Overlapped dispatch input: the in-flight chunk's on-device
    ``last``/``pos`` rows, with rows the host refilled since the last
    dispatch (``fresh`` mask) overridden by their prefill values — the
    one-round scheduling lag's merge point."""
    return jnp.where(fresh, host_vals, dev_vals)


@partial(jax.jit, static_argnames=("cfg", "steps", "do_sample", "top_k",
                                   "top_p", "ring", "block_size",
                                   "paged_len", "decode_kernel_fn",
                                   "eos_id", "reduce_fn"),
         donate_argnums=(1,))
def _serve_decode(params, caches, tok, pos, cfg, steps: int, do_sample: bool,
                  top_k: int, temperature, key, top_p: float = 0.0,
                  ring: bool = False, block_tables=None,
                  block_size: int = 0, paged_len: int = 0,
                  decode_kernel_fn=None, eos_id=None, budget=None,
                  reduce_fn=None):
    """The server's one decode executable: a fixed-``steps`` ragged chunk
    with the KV arena DONATED — without donation XLA must copy every arena
    tensor each chunk (the first in-scan cache write would otherwise alias
    a live buffer), pure HBM traffic charged against the bandwidth decode
    is bound by. ``ring``: the arena is a per-slot ring buffer — one
    ``window``-slot pair, or the window-cycle tuple layout (see
    ``GenerationServer(ring_kv=True)``). ``block_tables`` (+ static
    ``block_size``/``paged_len``): the arena is the shared paged block
    pool and each row decodes through its table (``kv_pool_tokens``).
    ``decode_kernel_fn`` (STATIC, resolved once per server — ISSUE 12):
    the paged-native pallas decode-attention callable the transformer's
    ragged branches dispatch through; None keeps the XLA gather path.
    Its identity is part of the executable cache key, so a backend
    change can never reuse a stale executable. ``budget`` (+ static
    ``eos_id`` — ISSUE 13): the per-lane remaining-token upper bounds
    arming the on-device EOS/budget mask for multi-step dispatches
    (``decode_steps > 1``); None keeps the legacy unmasked scan."""
    return _decode_scan(params, caches, tok, pos, cfg, steps, None,
                        do_sample, top_k, temperature, key,
                        return_state=True, top_p=top_p, ring=ring,
                        block_tables=block_tables, block_size=block_size,
                        paged_len=paged_len,
                        decode_kernel_fn=decode_kernel_fn, eos_id=eos_id,
                        budget=budget, reduce_fn=reduce_fn)


@partial(jax.jit, static_argnames=("cfg", "steps", "do_sample", "top_k",
                                   "top_p", "block_size", "paged_len",
                                   "decode_kernel_fn", "eos_id",
                                   "reduce_fn"),
         donate_argnums=(1, 5))
def _fused_serve_decode(params, caches, tok, pos, budget, p_caches, suffix,
                        offset, true_len, cfg, steps: int, do_sample: bool,
                        top_k: int, temperature, key, top_p: float = 0.0,
                        block_tables=None, block_size: int = 0,
                        paged_len: int = 0, decode_kernel_fn=None,
                        eos_id=None, reduce_fn=None):
    """The FUSED prefill+decode executable (ISSUE 13): ONE dispatch
    carries the decode lanes' ``steps``-token scan over the (donated)
    arena AND the pending admission's ``prefill_suffix`` slice over its
    own (donated) standalone caches. The two subgraphs share ``params``
    but no data flows between them, so XLA is free to interleave the
    chunk's compute with the scan's — and the host pays one dispatch and
    one fence where the alternating slice-round/decode-round schedule
    paid two. Numerics are the composed functions' numerics exactly
    (``_decode_scan`` + ``prefill_suffix`` — the same jit-inlined
    callees the unfused paths run), which is the bit-identity argument
    the fused-vs-sequential test matrix pins."""
    toks, caches, last, new_pos = _decode_scan(
        params, caches, tok, pos, cfg, steps, None, do_sample, top_k,
        temperature, key, return_state=True, top_p=top_p, ring=False,
        block_tables=block_tables, block_size=block_size,
        paged_len=paged_len, decode_kernel_fn=decode_kernel_fn,
        eos_id=eos_id, budget=budget, reduce_fn=reduce_fn,
    )
    p_caches, p_logits, _pos = prefill_suffix(
        params, suffix, cfg, p_caches, offset, return_logits=True,
        true_len=true_len,
    )
    return toks, caches, last, new_pos, p_caches, p_logits


@partial(jax.jit, static_argnames=("cfg", "max_steps", "block_size",
                                   "paged_len", "decode_kernel_fn",
                                   "eos_id", "reduce_fn"),
         donate_argnums=(1,))
def _persistent_serve_decode(params, caches, tok, pos, budget, window_end,
                             cfg, max_steps: int, block_tables=None,
                             block_size: int = 0, paged_len: int = 0,
                             decode_kernel_fn=None, eos_id=None,
                             reduce_fn=None):
    """The PERSISTENT decode executable (ISSUE 20): one
    ``lax.while_loop`` round over the (donated) arena —
    :func:`..models.transformer._decode_while` — that decodes greedily
    on device until the static ``max_steps`` heartbeat-cadence cap, a
    lane freeze (eos/budget — the lane needs host service), or a live
    lane's pre-reserved ``window_end``. Statics mirror
    :func:`_serve_decode` (minus the sampling knobs — the loop is
    greedy-only) plus the cap; all are per-server constants, so the
    persistent form is ONE dispatch signature in the JG401 census and
    the steady-state compile tripwire stays zero across persistent
    rounds. Returns ``(out [B, max_steps], caches, tok, pos,
    delivered)`` — the caller slices and accounts by ``delivered``."""
    return _decode_while(params, caches, tok, pos, budget, window_end,
                         cfg, max_steps, None, ring=False,
                         block_tables=block_tables, block_size=block_size,
                         paged_len=paged_len,
                         decode_kernel_fn=decode_kernel_fn, eos_id=eos_id,
                         reduce_fn=reduce_fn)


class GenerationServer:
    """Slot-based continuous batching over one decode arena.

    >>> srv = GenerationServer(params, cfg, max_batch=4, max_len=512)
    >>> rid = srv.submit(prompt_tokens, max_new_tokens=64)
    >>> results = srv.run()          # {rid: np.ndarray of generated tokens}

    ``params`` may be the bf16 pytree or the int8-quantized one
    (``ops.quant.quantize_decoder_params``) — the decode path is shared.

    ``ring_kv=True`` prefills each admission into a PROMPT-LENGTH
    transient cache before folding the live window into the slot's ring,
    so without ``prefill_buckets`` every distinct prompt length compiles
    its own prefill executable — pair ring_kv with a bucket ladder (e.g.
    ``prefill_buckets=(256, 1024, 4096)``) to keep the
    one-executable-per-bucket property the module header promises.

    ``overlap=True`` (default) pipelines the round loop: one decode chunk
    stays in flight, the next chunk dispatches from its on-device state,
    and token transfers ride async copies — host scheduling overlaps
    device compute (see the module header for the token-identity
    argument). ``overlap=False`` restores the lock-step loop (the A/B
    baseline ``bench.py --no-overlap`` measures). Speculative serving
    (``speculative_k``) always runs lock-step: a verify round's inputs are
    the host-side accept decision of the previous round, so there is no
    schedule slack to hide transfers in.

    ``prefix_cache_tokens > 0`` attaches a shared-prefix KV store of that
    capacity (see the module header and :mod:`.prefix_cache`); it requires
    ``prefill_buckets`` (bucket-aligned match boundaries are what bound
    the executable count). ``None`` (default) reads the
    ``KATA_TPU_PREFIX_CACHE_TOKENS`` env the device plugin can inject
    (``config.prefix_cache_tokens``); ``0`` disables. ``prefix_store``
    injects an existing :class:`.prefix_cache.PrefixStore` instead — e.g.
    shared across servers in one process so a common system prompt warms
    once — and must match this server's config/buckets/kv_quant. Under
    ``ring_kv`` or a draft model the store is DISABLED (cold-admission
    fallback, documented as unsupported) rather than refused.

    RESILIENCE (ISSUE 7, ``docs/resilience.md``): ``checkpoint_rounds``
    (default ``KATA_TPU_CHECKPOINT_ROUNDS`` env, 0 = off) sets the
    host-KV recovery checkpoint cadence; ``fault_injector`` overrides the
    ``KATA_TPU_FAULTS``-driven default injector; ``fence_timeout_s``
    (``KATA_TPU_FENCE_TIMEOUT_S``) arms the watchdog fence;
    ``quarantine_after`` (``KATA_TPU_QUARANTINE_K``, default 3) is the
    consecutive-implicated-failure threshold before a request fails
    individually into :meth:`failures`; ``recovery_backoff_s``
    (``KATA_TPU_RECOVERY_BACKOFF_S``) seeds the bounded exponential
    retry backoff. ``KATA_TPU_RECOVERY=0`` disables supervision entirely
    (every exception unwinds, the pre-ISSUE-7 behavior).

    SCHEDULING (ISSUE 8, ``docs/guest_guide.md`` "Scheduling & SLOs"):
    ``sched_policy`` selects the admission policy object
    (:mod:`.scheduler`) — ``"fifo_batch"`` (default; admit the whole FIFO
    prefix every pass, today's behavior) or ``"slo_chunked"`` (slice
    admission prefills into ``prefill_chunk``-token chunks resumed via
    ``transformer.prefill_suffix`` and interleave at most one per decode
    round whenever in-flight requests' projected inter-token latency
    would exceed ``itl_slo_ms``). ``None`` reads the daemon-injectable
    envs (``KATA_TPU_SCHED_POLICY`` / ``KATA_TPU_PREFILL_CHUNK`` /
    ``KATA_TPU_ITL_SLO_MS``); malformed or incompatible env values
    degrade to ``fifo_batch`` with a ``sched_disabled`` event while
    explicit arguments raise. Greedy outputs under ``slo_chunked`` are
    BIT-IDENTICAL to ``fifo_batch`` (chunking changes when prefill work
    runs, never what it computes — tested across paged/slotted × overlap
    × strict × prefix-hit), and chunked admissions are head-of-line so
    FIFO and the crash-replay guarantees are preserved.

    FUSED SCHEDULING & MULTI-STEP DECODE (ISSUE 13,
    ``docs/guest_guide.md`` "Fused scheduling & multi-step decode"):
    ``decode_steps=K`` multiplies the per-dispatch decode scan — one
    host dispatch delivers ``chunk × K`` tokens per lane, with ON-DEVICE
    EOS/budget masking freezing finished lanes inside the scan (their
    token/position pin, so the frozen rewrites are value-identical
    no-ops and a lane never outruns its block reservation) — so host
    scheduling, the fence, and obs bookkeeping amortize over K× more
    tokens. ``None`` reads the daemon-injectable
    ``KATA_TPU_DECODE_STEPS`` (malformed values degrade to 1 with a
    ``decode_steps_invalid`` event; explicit nonsense raises). Under
    ``slo_chunked``, ``fused`` (default on; ``KATA_TPU_FUSED=0`` kills,
    malformed degrades with ``fused_disabled``) batches each deferred
    admission slice INTO the decode dispatch — one executable carries
    the decode lanes' scan and the chunk's ``prefill_suffix`` forward,
    so chunked admission stops alternating slice-round/decode-round and
    decode lanes stop stalling behind it. Greedy outputs are
    BIT-IDENTICAL to K=1 unfused across the serving matrix (tested);
    recovery stays dispatch-boundary-granular and strict-FIFO replay is
    unchanged (a fault mid-fused-dispatch discards the partial and
    replays it from the prompt).

    ``spec_opt_in`` (``KATA_TPU_SPEC=1``): speculative serving is opt-in
    — ``speculative_k`` alone degrades to plain decoding with a
    ``spec_disabled`` event (the measured A/B is a net loss at 0.178
    draft acceptance; see the module constant).

    TENSOR PARALLELISM (ISSUE 9, ``docs/guest_guide.md`` "Tensor-parallel
    serving"): ``tp=N`` serves over a 1×N ICI mesh
    (:mod:`.tp_serving`) — params by the serving regex rules, KV
    arena/pool/prefix-store head-sharded. ``None`` (default) resolves the
    daemon-injected topology env (``KATA_TPU_TP`` → ``TPU_VISIBLE_CHIPS``
    → ``TPU_ACCELERATOR_TYPE`` → 1); env-derived conflicts (``ring_kv``,
    speculative, more chips than devices) DEGRADE to single-chip serving
    with a ``tp_disabled`` event, while an explicit ``tp=`` argument
    raises. Mutually exclusive with ``mesh=`` (which keeps its
    training-layout sharding). Greedy outputs are bit-identical to
    ``tp=1``.

    KV QUANTIZATION (ISSUE 12): ``kv_quant=None`` (default) resolves
    int8 KV — the measured-1.7×-faster arena, quality-gated by
    ``tools/eval_quality.py`` (``make eval-kv``) — unless the
    daemon-injected ``KATA_TPU_KV_QUANT`` env says ``bf16`` (the
    node-wide opt-out; malformed values degrade to the default with a
    ``kv_quant_invalid`` event). An explicit ``True``/``False`` always
    wins.

    DECODE-ATTENTION BACKEND (ISSUE 12, ``docs/guest_guide.md`` "Decode
    attention backends"): the decode step's attention runs the
    paged-native split-K pallas kernel
    (:func:`..ops.decode_attn.pallas_paged_decode_attention`) on TPU —
    block tables walked in place (no ``_paged_view`` gather), int8
    dequant fused in-kernel, ``shard_map``'d over the tp mesh — and the
    XLA gather path elsewhere. ``decode_attn`` forces either side
    (``"pallas_paged"`` off-TPU runs interpret mode — the CPU test
    harness); ``None`` reads ``KATA_TPU_DECODE_ATTN`` then picks
    automatically. Explicit incompatible choices raise; env-injected
    ones degrade with the reason on the once-per-server
    ``decode_attn_backend`` event. Greedy outputs are bit-identical to
    the XLA path across the serving matrix (tested).

    DEGRADED MODE (ISSUE 10, ``docs/resilience.md`` "Degraded mode"):
    chip loss is a survivable event at ``tp > 1``. A PERMANENT fault
    (``chip_loss:<device>`` / ``ici_error`` schedule kinds, or an XLA
    error carrying a permanent-device marker) makes the supervisor
    SHRINK the mesh instead of retrying: halve the degree over the
    surviving chips (tp=4 → 2 → 1, floored at ``tp_min`` /
    ``KATA_TPU_TP_MIN``), re-shard params from a host donor copy
    retained at construction, rebuild the KV state on the smaller mesh,
    restore checkpointed lanes under the new sharding, and replay the
    rest strict-FIFO — recovered greedy outputs stay bit-identical to a
    fault-free run (tp-invariance). ``degraded=False`` /
    ``KATA_TPU_DEGRADED=0`` kills the path (and skips the donor copy);
    with no feasible rung left the load fails loudly into
    :meth:`failures` (reason ``chip_lost``) — none vanish.

    HEARTBEAT & WATCHDOG (ISSUE 15, ``docs/observability.md`` "Serving
    heartbeat"): every ``heartbeat_rounds`` rounds (default 32,
    ``KATA_TPU_HEARTBEAT_ROUNDS``; 0 disables) the loop emits ONE
    ``serving_heartbeat`` event rolled up from data it already holds —
    interval tokens/s, rolling ITL/TTFT p50/p99, batch and per-tier pool
    occupancy (device shards / host-RAM / prefix), host-tier
    demotion/prefetch and prefix hit rates, queue depth + admission
    wait, and the loop-phase time breakdown
    (admit/dispatch/retire/host_transfer) — and feeds it to the SLO-burn
    watchdog (:class:`..obs.watchdog.SLOBurnWatchdog`; ``watchdog=``
    injects a configured one, ``False`` disarms,
    ``KATA_TPU_WATCHDOG=0`` node-wide). On a sustained breach the
    watchdog dumps the always-armed flight ring with the breach as the
    reason and can open a bounded profiler window — zero operator
    action. Pure host arithmetic at existing boundaries: greedy outputs
    are bit-identical with heartbeat+watchdog on (tested), and
    ``heartbeat_rounds=0`` restores the fully uninstrumented loop.
    """

    def __init__(self, params: Any, cfg: DecoderConfig, max_batch: int = 4,
                 max_len: int = 512, eos_id: Optional[int] = None,
                 chunk: int = 8, temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0, mesh: Any = None,
                 kv_quant: Optional[bool] = None,
                 prefill_buckets: tuple = (),
                 speculative_k: int = 0, ring_kv: bool = False,
                 draft: Optional[tuple] = None, overlap: bool = True,
                 strict: Optional[bool] = None,
                 tripwire: Optional[bool] = None,
                 prefix_cache_tokens: Optional[int] = None,
                 prefix_store: Optional[PrefixStore] = None,
                 kv_pool_tokens: Optional[int] = None,
                 kv_block_size: int = 16,
                 kv_layout: Optional[str] = None,
                 kv_host_tokens: Optional[int] = None,
                 checkpoint_rounds: Optional[int] = None,
                 fault_injector: Optional[FaultInjector] = None,
                 fence_timeout_s: Optional[float] = None,
                 quarantine_after: Optional[int] = None,
                 recovery_backoff_s: Optional[float] = None,
                 sched_policy: Optional[str] = None,
                 prefill_chunk: Optional[int] = None,
                 itl_slo_ms: Optional[float] = None,
                 decode_steps: Optional[int] = None,
                 fused: Optional[bool] = None,
                 persistent: Optional[bool] = None,
                 spec_opt_in: Optional[bool] = None,
                 tp: Optional[int] = None,
                 tp_min: Optional[int] = None,
                 degraded: Optional[bool] = None,
                 decode_attn: Optional[str] = None,
                 heartbeat_rounds: Optional[int] = None,
                 watchdog: Any = None):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if speculative_k < 0:
            raise ValueError(f"speculative_k must be >= 0, got {speculative_k}")
        if draft is not None and not speculative_k:
            raise ValueError(
                "draft=(draft_params, draft_cfg) requires speculative_k > 0"
            )
        if draft is not None and draft[1].vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft[1].vocab_size} != target vocab "
                f"{cfg.vocab_size} — draft tokens would be meaningless"
            )
        if speculative_k and (top_k or top_p):
            raise ValueError(
                "speculative serving supports greedy (temperature=0, exact "
                "token identity) and plain temperature sampling (lossless "
                "rejection scheme — models.speculative.sample_accept_row); "
                "top_k/top_p truncation is not modeled in the acceptance "
                "math — disable them with speculative_k"
            )
        if ring_kv:
            # Per-slot ring arena: each slot wraps at its OWN position
            # (slot = pos[b] % arena_len), so ragged continuous batching
            # keeps KV memory at O(window) per slot regardless of stream
            # length. Window CYCLES (Gemma-2) get the cycle arena: local
            # layers ring at their window, global layers keep a max_len
            # arena. With ``speculative_k`` the windowed rings carry k
            # extra SAFETY-MARGIN slots, so a verify round's k+1-token
            # span can never evict a key still inside any live window —
            # bounded KV memory and multi-token steps compose (the r4
            # rejection is gone; O(window + k) is still O(window)).
            if not any(w > 0 for w in cfg.window_cycle):
                raise ValueError(
                    "ring_kv needs a sliding-window config "
                    "(cfg.sliding_window > 0 or a windowed attn_windows "
                    "cycle)"
                )
        # Label + latency summaries FIRST: every env-degrade event below
        # (spec opt-in, scheduler, pool, prefix) carries the server label.
        self._label = f"server{next(GenerationServer._instance_ids)}"
        # Trace context (ISSUE 11): adopt the daemon-injected
        # per-allocation trace id, or mint one — every serving span and
        # event this server emits carries it (self._emit), so guest
        # telemetry joins the daemon's allocation trace end to end.
        self._trace = (
            os.environ.get(ENV_TRACE_CTX, "").strip() or obs.new_trace()
        )
        self._ttft = obs.Rolling()
        self._tok_lat = obs.Rolling()
        # Request lifecycle ledger aggregates (ISSUE 11): per-phase
        # Rolling summaries observed once per retired/failed request
        # (only phases the request actually spent time in — a request
        # that never preempted must not drag the preempted p50 to 0).
        self._phase_roll = {p: obs.Rolling() for p in PHASES}
        self._traces_emitted = 0
        # Speculative serving demoted behind an explicit opt-in (ISSUE 8
        # satellite; see ENV_SPEC_OPT_IN): validation above still rejects
        # malformed spec configs, but a VALID one only arms when opted in
        # — otherwise the server degrades to plain decoding with an event,
        # so the measured-net-loss path is not a reachable default.
        if speculative_k:
            opted = (
                os.environ.get(ENV_SPEC_OPT_IN, "") == "1"
                if spec_opt_in is None else bool(spec_opt_in)
            )
            if not opted:
                self._emit(
                    "spec_disabled", reason="opt_in_required",
                    speculative_k=speculative_k,
                )
                speculative_k = 0
                draft = None
        self.speculative_k = speculative_k
        # Draft-model speculation (production shape for non-repetitive
        # text): the draft keeps its OWN full-length arena at the same
        # per-slot positions as the target; see models.speculative for the
        # cache-consistency argument. ``draft=None`` keeps n-gram drafts.
        self.draft = draft
        if draft is not None:
            self.draft_arena = init_kv_caches(draft[1], max_batch, max_len)
        if any(b < 1 or b > max_len for b in prefill_buckets):
            raise ValueError(
                f"prefill_buckets {prefill_buckets} must lie in [1, max_len]"
            )
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self.params, self.cfg = params, cfg
        self.max_batch, self.max_len = max_batch, max_len
        self.eos_id, self.chunk = eos_id, chunk
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        # int8 KV is the DEFAULT (ISSUE 12; the measured-1.7×-faster path,
        # quality-gated by tools/eval_quality.py): an explicit kv_quant=
        # argument wins; otherwise the daemon-injectable KATA_TPU_KV_QUANT
        # env selects "int8"/"bf16", a malformed value degrading to the
        # default with one kv_quant_invalid event (node-wide knobs never
        # crash a guest — the standard env contract). resolve_kv_quant is
        # shared with PrefixStore's default, so injected stores agree.
        kv_quant = resolve_kv_quant(kv_quant, emit=self._emit)
        self.kv_quant = kv_quant
        # The one sample-vs-greedy decision (transformer._sampling_args):
        # also validates top_k/top_p-without-temperature.
        self._do_sample, self._key = _sampling_args(
            temperature, top_k, jax.random.PRNGKey(seed), top_p
        )
        # Strict mode (ISSUE 4): under KATA_TPU_STRICT=1 (or strict=True)
        # every overlapped round runs inside compat.jaxapi.strict_mode —
        # jax.transfer_guard("disallow") plus rank-promotion "raise"
        # across the dispatch window, with allow_transfer() hatches at the
        # two sanctioned sync points (admission, DeviceFence retire). An
        # implicit host round-trip sneaking back into the dispatch path
        # then raises instead of silently serializing the pipeline.
        self.strict = jaxapi.strict_enabled() if strict is None else bool(strict)
        # Steady-state compile/reshard tripwire (jaxguard JG4xx runtime
        # twin): the FIRST run() is the warmup drain — it traces and
        # compiles the bucketed dispatch surface the JG401 census proved
        # finite. Every run() after it is steady state: zero new XLA
        # compilations and zero unsanctioned device_put calls, counted by
        # compat.jaxapi.compile_tripwire and surfaced as
        # ``steady_state_compiles``/``steady_state_reshards`` in stats()
        # and the heartbeat. A deliberate ctor argument, not an env knob:
        # it gates telemetry, not behavior (greedy outputs are
        # bit-identical either way), so it sits outside the five-leg
        # ENV_* contract jaxguard JG3xx audits.
        self.tripwire = True if tripwire is None else bool(tripwire)
        self._tw_warmed = False
        self._steady_compiles = 0
        self._steady_reshards = 0
        # Device-resident temperature, hoisted once: jnp.float32(x) per
        # dispatch is an implicit scalar upload — a per-round H2D the
        # transfer guard rightly rejects.
        self._temp_dev = jnp.float32(temperature)
        # kv_quant: int8 arena — ~2× less HBM per slot-token, so the same
        # chip serves ~2× the context/slots (per-vector scales; decode
        # dequant fuses into the attention dots). ring_kv: windowed layers
        # hold ``window`` slots per sequence instead of max_len.
        self.ring_kv = ring_kv
        self._cycle = ring_kv and len(cfg.window_cycle) > 1
        # Windowed rings get speculative_k margin slots (see the ring_kv
        # comment above); plain decode (k=0) keeps exactly window slots.
        self._ring_margin = speculative_k if ring_kv else 0
        # Labeled histogram children resolved ONCE: registry lookup +
        # .labels() on every prefill/chunk is pure hot-path overhead —
        # export_metrics(label=...) re-resolves on rename.
        self._bind_histograms()
        # Admission scheduler (ISSUE 8): the policy object that owns the
        # per-round dispatch plan — fifo_batch (identity baseline) admits
        # whole every pass; slo_chunked slices admission prefills into
        # KATA_TPU_PREFILL_CHUNK-token chunks and interleaves at most one
        # per decode round when in-flight ITL is projected over
        # KATA_TPU_ITL_SLO_MS. The env default degrades with a
        # sched_disabled event (unknown policy, incompatible mode); an
        # explicit argument raises — the pool/prefix knob contract.
        explicit_sched = sched_policy is not None
        if sched_policy is None:
            raw = os.environ.get(ENV_SCHED_POLICY, "").strip()
            sched_policy = raw or POLICY_FIFO
            if sched_policy not in POLICIES:
                self._emit(
                    "sched_disabled", reason=f"bad_env:{raw[:32]}",
                )
                sched_policy = POLICY_FIFO
        elif sched_policy not in POLICIES:
            raise ValueError(
                f"unknown sched_policy {sched_policy!r} (have {POLICIES})"
            )
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            # Explicit nonsense raises UNCONDITIONALLY (whatever policy
            # ends up selected, env-injected included) — the explicit-
            # args-raise half of the knob contract.
            raise ValueError(
                f"prefill_chunk must be >= 1 token, got {prefill_chunk}"
            )
        chunk_tokens = (
            resilience.env_int(ENV_PREFILL_CHUNK, DEFAULT_PREFILL_CHUNK,
                               event="prefill_chunk_invalid",
                               server=self._label)
            if prefill_chunk is None else int(prefill_chunk)
        )
        if chunk_tokens < 1:
            # A node-injected nonsense value (parseable but < 1 token)
            # degrades to the default chunk — it must not disable a
            # policy the guest explicitly asked for, nor crash it.
            self._emit(
                "prefill_chunk_invalid", reason=f"bad_env:{chunk_tokens}",
            )
            chunk_tokens = DEFAULT_PREFILL_CHUNK
        slo_ms = (
            resilience.env_float(ENV_ITL_SLO_MS, DEFAULT_ITL_SLO_MS,
                                 event="itl_slo_invalid",
                                 server=self._label)
            if itl_slo_ms is None else float(itl_slo_ms)
        )
        if sched_policy == POLICY_SLO:
            # Chunk resume rides the plain prefill_suffix branch: the
            # ring/cycle folds re-layout rows per slot, and a draft arena
            # has no chunk-resume mirror — same fallback set as the
            # prefix store (docs/guest_guide.md "Scheduling & SLOs").
            reason = None
            if ring_kv:
                reason = "ring_kv"
            elif draft is not None or speculative_k:
                reason = "speculative"
            if reason is not None:
                if explicit_sched:
                    raise ValueError(
                        "sched_policy='slo_chunked' is incompatible with "
                        f"this server ({reason}) — see 'Scheduling & "
                        "SLOs' in docs/guest_guide.md"
                    )
                self._emit(
                    "sched_disabled", reason=reason,
                )
                sched_policy = POLICY_FIFO
        # Multi-step decode multiplier (ISSUE 13): one host dispatch runs
        # a ``chunk × decode_steps``-step scan with on-device EOS/budget
        # masking, so scheduling/fence/obs overhead amortizes over K×
        # more tokens. The standard knob contract: explicit argument
        # raises on nonsense, the daemon-injected env degrades to K=1
        # with a decode_steps_invalid event; incompatible modes
        # (speculative rounds are host-driven lock-step, the ring/cycle
        # fold cannot absorb frozen-lane rewrites across the wrap) raise
        # explicitly and degrade from env.
        explicit_steps = decode_steps is not None
        if decode_steps is not None and int(decode_steps) < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {decode_steps}"
            )
        k_steps = (
            resilience.env_int(ENV_DECODE_STEPS, 1,
                               event="decode_steps_invalid",
                               server=self._label)
            if decode_steps is None else int(decode_steps)
        )
        if k_steps < 1:
            # Parseable nonsense from the node env (e.g. "-2") degrades
            # like every other injected knob — never crashes a guest.
            self._emit("decode_steps_invalid", reason=f"bad_env:{k_steps}")
            k_steps = 1
        if k_steps > 1:
            reason = None
            if self.speculative_k or self.draft is not None:
                reason = "speculative"
            elif ring_kv:
                reason = "ring_kv"
            if reason is not None:
                if explicit_steps:
                    raise ValueError(
                        f"decode_steps={k_steps} is incompatible with this "
                        f"server ({reason}) — see 'Fused scheduling & "
                        "multi-step decode' in docs/guest_guide.md"
                    )
                self._emit("decode_steps_invalid", reason=reason)
                k_steps = 1
        self._decode_steps = k_steps
        # The per-dispatch step count every decode path uses: host-side
        # bookkeeping (ITL normalization, budget gates, block lookahead)
        # keys off this, never off ``chunk`` alone.
        self._dispatch_steps = self.chunk * k_steps
        # Fused prefill+decode dispatch (ISSUE 13): default ON whenever
        # the slo_chunked policy is active (it is inert otherwise — only
        # slo_chunked creates partials). KATA_TPU_FUSED=0 kills it;
        # malformed env values degrade with fused_disabled; an explicit
        # fused=True on a server whose policy never chunks raises.
        explicit_fused = fused is not None
        if fused is None:
            raw_f = os.environ.get(ENV_FUSED, "").strip()
            if raw_f and raw_f not in ("0", "1"):
                self._emit("fused_disabled", reason=f"bad_env:{raw_f[:32]}")
                raw_f = ""
            fused_ok = raw_f != "0"
        else:
            fused_ok = bool(fused)
        if fused_ok and sched_policy != POLICY_SLO:
            if explicit_fused:
                raise ValueError(
                    "fused=True requires sched_policy='slo_chunked' — only "
                    "chunked admission produces the slices a fused "
                    "dispatch carries (docs/guest_guide.md)"
                )
            fused_ok = False  # inert without partials; no event (default)
        self._fused_ok = fused_ok
        self._fused_admissions = 0
        self._fuse_pending = False
        self._fused_ret: Optional[_FusedChunk] = None
        # The request whose admission slice rides the CURRENT fused
        # dispatch — part of the recovery blame cohort (a fault in the
        # fused dispatch implicates it with the lanes; see _recover).
        self._fused_blame: Optional[_Request] = None
        # Persistent on-device decode rounds (ISSUE 20): the standard
        # guest-side env knob contract (KATA_TPU_PERSISTENT — env-only,
        # like KATA_TPU_FUSED): malformed env degrades with
        # persistent_disabled, incompatible modes (speculative rounds
        # are host-driven lock-step, the ring fold cannot absorb a
        # data-dependent step count, and the while_loop is greedy-only
        # — a sampled round's key schedule depends on the step count)
        # raise on an explicit persistent=True and degrade from env.
        explicit_persistent = persistent is not None
        if persistent is None:
            raw_p = os.environ.get(ENV_PERSISTENT, "").strip()
            if raw_p and raw_p not in ("0", "1"):
                self._emit(
                    "persistent_disabled", reason=f"bad_env:{raw_p[:32]}"
                )
                raw_p = ""
            persistent_ok = raw_p == "1"
        else:
            persistent_ok = bool(persistent)
        if persistent_ok:
            reason = None
            if self.speculative_k or self.draft is not None:
                reason = "speculative"
            elif ring_kv:
                reason = "ring_kv"
            elif self._do_sample:
                reason = "sampling"
            if reason is not None:
                if explicit_persistent:
                    raise ValueError(
                        f"persistent=True is incompatible with this server "
                        f"({reason}) — see 'Persistent decode' in "
                        "docs/guest_guide.md"
                    )
                self._emit("persistent_disabled", reason=reason)
                persistent_ok = False
        self._persistent = persistent_ok
        # Per-round persistent accounting: delivered steps of the LAST
        # persistent round (stats/heartbeat "delivered_steps" — stays 0
        # on non-persistent servers, the no-schema-branch contract),
        # cumulative totals, and the per-exit-reason counters the
        # persistent_exit events mirror.
        self._persistent_rounds = 0
        self._last_delivered = 0
        self._delivered_total = 0
        self._persistent_exits = {"cap": 0, "done": 0, "window": 0}
        self._persistent_fut = None  # (delivered, window) of the round in flight
        self._sched = make_scheduler(
            sched_policy, chunk_tokens=chunk_tokens, slo_ms=slo_ms,
            # The round→per-token normalizer DEFAULT: slo_ms is a
            # PER-TOKEN deadline (the decode_token_s unit), rounds
            # deliver ``chunk × decode_steps`` tokens per lane —
            # note_round then learns the ACTUAL per-dispatch count.
            decode_steps=self._dispatch_steps, fused=fused_ok,
            label=self._label,
        )
        self._partial: Optional[_PartialPrefill] = None
        # Recovery supervisor (ISSUE 7). Every knob defaults through the
        # daemon env-injection path and degrades on malformed values —
        # node-wide chaos/cadence knobs must never crash a guest. With
        # everything at its default (no schedule, no deadline, cadence 0)
        # the hot path is untouched: fire() is one truth-test, the fence
        # wrapper calls through inline, and no checkpoint gathers run.
        self._inj = (
            fault_injector if fault_injector is not None
            else FaultInjector.from_env(
                label=self._label, trace=self._trace
            )
        )
        self._fence_timeout_s = (
            resilience.env_float(
                resilience.ENV_FENCE_TIMEOUT, 0.0,
                event="fence_timeout_disabled", server=self._label,
            )
            if fence_timeout_s is None else float(fence_timeout_s)
        )
        self._quarantine_k = max(1, (
            resilience.env_int("KATA_TPU_QUARANTINE_K", 3,
                               event="quarantine_k_invalid",
                               server=self._label)
            if quarantine_after is None else int(quarantine_after)
        ))
        self._backoff_s = (
            resilience.env_float("KATA_TPU_RECOVERY_BACKOFF_S", 0.05,
                                 event="recovery_backoff_invalid",
                                 server=self._label)
            if recovery_backoff_s is None else float(recovery_backoff_s)
        )
        self._supervised = os.environ.get("KATA_TPU_RECOVERY", "1") != "0"
        ckpt = (
            resilience.env_int("KATA_TPU_CHECKPOINT_ROUNDS", 0,
                               event="checkpoint_disabled",
                               server=self._label)
            if checkpoint_rounds is None else int(checkpoint_rounds)
        )
        if ckpt > 0 and (draft is not None or speculative_k):
            # The draft arena is a second cache the lane snapshot does not
            # cover, and speculative rounds are host-driven lock-step —
            # checkpointed restore is unsupported there. Explicit opt-in
            # raises; the env default degrades with an event (recovery
            # still works via from-the-prompt replay, which rebuilds both
            # arenas through the normal admission path).
            if checkpoint_rounds is not None:
                raise ValueError(
                    f"checkpoint_rounds={ckpt} is incompatible with "
                    "speculative/draft serving — recovery falls back to "
                    "full replay there (docs/resilience.md)"
                )
            self._emit(
                "checkpoint_disabled", reason="speculative",
            )
            ckpt = 0
        self._ckpt_every = max(0, ckpt)
        self._ckpt: dict[int, _CkptEntry] = {}
        self._ckpt_round = 0
        self._failures: dict[int, str] = {}
        self._recoveries = 0
        self._quarantined_n = 0
        self._stalls = 0
        self._checkpoints = 0
        self._fail_streak = 0  # consecutive failed rounds (backoff input)
        # Mid-admission bookkeeping for crash unwind: requests popped from
        # the queue but not yet landed in a lane, and the subset the
        # currently-running fill call is admitting (the blast radius a
        # prefill-seam fault is attributed to).
        self._admitting: list[tuple[_Request, Optional[PrefixHit]]] = []
        self._admit_current: list[_Request] = []
        self._draining = False
        self._drain_done = False
        self._drain_announced = False
        self._drain_reason = ""
        # Tensor-parallel serving over the ICI slice (ISSUE 9,
        # guest/tp_serving.py): ``tp=N`` shards params (SERVING_RULES —
        # embeddings replicated, attention/MLP column/row over the model
        # axis), the KV arena OR paged pool, the prefix store, and every
        # decode/prefill executable over a 1×N mesh built from the first N
        # devices. ``None`` resolves the daemon-injected topology env
        # (KATA_TPU_TP override → TPU_VISIBLE_CHIPS → TPU_ACCELERATOR_TYPE
        # → 1); env-derived conflicts DEGRADE to tp=1 with a ``tp_disabled``
        # event while an explicit argument raises — the pool/prefix knob
        # contract. ``mesh=`` keeps its training-layout sharding path for
        # callers that bring their own mesh; the two are mutually
        # exclusive.
        explicit_tp = tp is not None
        if tp is not None:
            tp = int(tp)
            if tp < 1:
                raise ValueError(f"tp must be >= 1, got {tp}")
            if mesh is not None:
                raise ValueError(
                    "pass tp= OR mesh=, not both — tp builds its own 1×N "
                    "serving mesh (guest/tp_serving.py)"
                )
        elif mesh is None:
            tp = tp_serving.tp_from_env(
                label=self._label, trace=self._trace
            )
        else:
            tp = 1
        if tp > 1:
            reason = None
            if ring_kv:
                # The ring/cycle folds re-layout rows per slot and the
                # draft arena is a second cache the serving specs do not
                # cover — same fallback set as the prefix store/pool
                # (docs/guest_guide.md "Tensor-parallel serving").
                reason = "ring_kv"
            elif self.speculative_k or self.draft is not None:
                reason = "speculative"
            elif tp > jax.device_count():
                reason = f"insufficient_devices:{jax.device_count()}"
            if reason is not None:
                if explicit_tp:
                    raise ValueError(
                        f"tp={tp} is incompatible with this server "
                        f"({reason}) — see 'Tensor-parallel serving' in "
                        "docs/guest_guide.md"
                    )
                self._emit(
                    "tp_disabled", reason=reason, tp=tp,
                )
                tp = 1
        self._tp = tp
        if tp > 1:
            mesh = tp_serving.serving_mesh(tp)
        elif mesh is not None:
            from ..parallel.mesh import AXIS_MODEL

            self._tp = mesh.shape.get(AXIS_MODEL, 1)
        # tp-path params shard by the serving regex rules (embeddings
        # replicated); an explicitly injected mesh keeps the training
        # PARAM_RULES layout callers already rely on.
        self._tp_serving_rules = tp > 1
        self._mesh = mesh
        # Degraded-mode chip-loss tolerance (ISSUE 10, docs/resilience.md
        # "Degraded mode"): a PERMANENT fault (chip_loss / ici_error —
        # resilience.classify) cannot be retried away, so the supervisor
        # SHRINKS the mesh instead: re-resolve a feasible degree over the
        # survivors (halving ladder, floored at tp_min), re-shard params
        # from the host donor copy retained here, rebuild the KV state on
        # the smaller mesh, and let the standard restore/replay machinery
        # finish the in-flight load — greedy outputs stay bit-identical
        # because tp never changes the computed values (PR 9 invariance).
        # KATA_TPU_DEGRADED=0 (or degraded=False) kills the whole path
        # (and skips the donor copy's host RAM cost); tp_min floors the
        # ladder (KATA_TPU_TP_MIN, daemon-injectable). Only the tp= path
        # shrinks — an injected mesh= keeps its caller-owned layout.
        self._tp_initial = self._tp
        self._tp_shrinks = 0
        self._tp_devices = (
            list(mesh.devices.flat) if self._tp_serving_rules else []
        )
        self._degraded_ok = (
            tp_serving.degraded_enabled() if degraded is None
            else bool(degraded)
        )
        if tp_min is not None:
            tp_min = int(tp_min)
            if tp_min < 1:
                raise ValueError(f"tp_min must be >= 1, got {tp_min}")
            self._tp_min = tp_min
        else:
            self._tp_min = tp_serving.tp_min_from_env(
                label=self._label, trace=self._trace
            )
        self._params_host = None
        if self._tp_serving_rules and self._degraded_ok:
            from ..parallel.sharding import host_param_copy

            self._params_host = host_param_copy(params)
        self._kv_replicated_warned: set[int] = set()
        # Paged KV pool (ISSUE 6): one block pool shared by all in-flight
        # requests replaces the fixed [max_batch, max_len] slot grid —
        # admission becomes token-budget continuous batching with
        # preemption/requeue, and max_batch turns into the decode LANE
        # count (cheap block-table rows) instead of a memory commitment.
        self.kv_block = int(kv_block_size)
        self.paged = False
        self.kv_pool: Optional[KVPool] = None
        # Pool placement layout + host-RAM offload tier (ISSUE 14) —
        # resolved BEFORE the pool is built (the blocks layout sizes
        # per-shard sub-pools). Standard knob contract: explicit args
        # raise on nonsense, daemon-injected env degrades with events.
        explicit_layout = kv_layout is not None
        if kv_layout is not None:
            if kv_layout not in KV_LAYOUTS:
                raise ValueError(
                    f"unknown kv_layout {kv_layout!r} (have {KV_LAYOUTS})"
                )
        else:
            raw = os.environ.get(ENV_KV_LAYOUT, "").strip()
            if raw and raw not in KV_LAYOUTS:
                self._emit("kv_layout_invalid", reason=f"bad_env:{raw[:32]}")
                raw = ""
            kv_layout = raw or KV_LAYOUT_HEADS
        # Set early: _pool_conflict's progress-guarantee arithmetic needs
        # the layout's shard rounding (re-assigned below if the slotted
        # degrade flips it back to heads).
        self._kv_layout = kv_layout
        explicit_host = kv_host_tokens is not None
        if kv_host_tokens is not None:
            kv_host_tokens = int(kv_host_tokens)
            if kv_host_tokens < 0:
                raise ValueError(
                    f"kv_host_tokens must be >= 0, got {kv_host_tokens}"
                )
        else:
            raw = os.environ.get(ENV_KV_HOST_TOKENS, "")
            try:
                kv_host_tokens = int(raw or 0)
            except ValueError:
                self._emit("kv_host_invalid", reason=f"bad_env:{raw[:32]}")
                kv_host_tokens = 0
            if kv_host_tokens < 0:
                self._emit(
                    "kv_host_invalid", reason=f"bad_env:{kv_host_tokens}"
                )
                kv_host_tokens = 0
        explicit_pool = kv_pool_tokens is not None
        if kv_pool_tokens is None:
            raw = os.environ.get("KATA_TPU_KV_POOL_TOKENS", "")
            try:
                kv_pool_tokens = int(raw or 0)
            except ValueError:
                # A malformed NODE-WIDE env must degrade to the fixed-slot
                # path with an event, never crash a guest that did not opt
                # in (mirrors KATA_TPU_PREFIX_CACHE_TOKENS).
                self._emit(
                    "kv_pool_disabled", reason=f"bad_env:{raw[:32]}",
                )
                kv_pool_tokens = 0
        if kv_pool_tokens > 0:
            reason = self._pool_conflict(
                kv_pool_tokens, ring_kv, draft, speculative_k, prefix_store,
            )
            if reason is not None:
                if explicit_pool:
                    raise ValueError(
                        f"kv_pool_tokens={kv_pool_tokens} is incompatible "
                        f"with this server ({reason}) — see the paged-KV "
                        "compatibility matrix in docs/guest_guide.md"
                    )
                # Node-injected default on an incompatible server: degrade
                # to the fixed-slot path, say so on the event stream.
                self._emit(
                    "kv_pool_disabled", reason=reason,
                )
            else:
                self.paged = True
        # The layout and the host tier are PAGED-pool features: the dense
        # slot grid has no block granularity to shard or demote at. An
        # explicit argument on a slotted server raises; the node-injected
        # env degrades with an event (the standard knob contract).
        if not self.paged:
            if kv_layout == KV_LAYOUT_BLOCKS:
                if explicit_layout:
                    raise ValueError(
                        "kv_layout='blocks' requires a paged KV pool "
                        "(kv_pool_tokens) — see 'KV layouts & host offload "
                        "tier' in docs/guest_guide.md"
                    )
                self._emit("kv_layout_disabled", reason="not_paged")
                kv_layout = KV_LAYOUT_HEADS
            if kv_host_tokens > 0:
                if explicit_host:
                    raise ValueError(
                        "kv_host_tokens requires a paged KV pool "
                        "(kv_pool_tokens) — see 'KV layouts & host offload "
                        "tier' in docs/guest_guide.md"
                    )
                self._emit("kv_host_disabled", reason="not_paged")
                kv_host_tokens = 0
        self._kv_layout = kv_layout  # re-assign: the slotted degrade above
        self._kv_host: Optional[HostKVTier] = (
            HostKVTier(kv_host_tokens, self.kv_block, label=self._label)
            if kv_host_tokens > 0 else None
        )
        # Host-tier traffic, cumulative across prefix-tier rebuilds
        # (recovery folds a dying tier's counts in — stats() snapshot
        # semantics: counters only grow).
        self._host_demotions = 0
        self._host_prefetches = 0
        # One staged resume prefetch (ISSUE 14): the oldest preempted
        # request's spilled rows, uploaded H2D while a decode chunk is in
        # flight so _resume_one lands an already-overlapped transfer.
        # Split rid/rows attributes: every branch tests the HOST int rid
        # only — the device rows tree is never truth-tested.
        self._resume_stage_rid: Optional[int] = None
        self._resume_stage_rows: Any = None
        if self.paged:
            self.arena = None  # the pool IS the arena — no slot grid
            self.kv_pool = KVPool(
                cfg, kv_pool_tokens, self.kv_block, kv_quant=kv_quant,
                label=self._label, shards=self._kv_shards(),
            )
            # Once-per-server layout event (ISSUE 14): the pool's
            # placement shape — under blocks, per-shard bytes are
            # ~logical/tp for every model and the kv_replicated cliff
            # does not exist (that event stays heads-layout-only).
            logical = sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(
                    self.kv_pool.arena
                )
            )
            self._emit(
                "kv_layout", layout=self._kv_layout,
                shards=self.kv_pool.shards,
                per_shard_bytes=logical // self.kv_pool.shards,
                host_tier_tokens=kv_host_tokens,
            )
            self._nb_max = -(-max_len // self.kv_block)
            self._lane_blocks: list[list[int]] = [
                [] for _ in range(max_batch)
            ]
            # Device-mirrored block tables: SCRATCH filler means a lane
            # with no live request (or a finished lane overrunning) writes
            # into the scratch block, never another lane's KV.
            self._bt_host = np.full(
                (max_batch, self._nb_max), SCRATCH_BLOCK, np.int32
            )
            self._preempted: deque[_Preempted] = deque()
            self._plans: dict[int, _LanePlan] = {}
        elif self._cycle:
            self.arena = init_cycle_kv_caches(
                cfg, max_batch, max_len, quantized=kv_quant,
                margin=self._ring_margin,
            )
        else:
            arena_len = (
                cfg.window_cycle[0] + self._ring_margin if ring_kv else max_len
            )
            self.arena = init_kv_caches(
                cfg, max_batch, arena_len, quantized=kv_quant
            )
        # Decode-attention backend (ISSUE 12): resolve ONCE per server —
        # explicit arg > KATA_TPU_DECODE_ATTN env > automatic (the kernel
        # on TPU, the XLA gather path elsewhere) — then build the kernel
        # callable for the current mesh. The resolved name is emitted on
        # the first decode dispatch, lives in stats()["decode_backend"],
        # and is a STATIC argument of _serve_decode so the executable
        # cache can never serve a stale backend.
        self._decode_attn, self._decode_attn_reason, self._decode_interpret = (
            self._resolve_decode_attn(decode_attn)
        )
        self._decode_kernel = None
        self._decode_attn_emitted = False
        self._build_decode_kernel(None)
        if mesh is not None:
            self._shard_over(mesh)
        # Host-side slot state: which request occupies each slot, its
        # absolute position (next cache write index), and its last token.
        self._slot_req: list[Optional[_Request]] = [None] * max_batch
        self._pos = np.zeros(max_batch, np.int32)
        self._last = np.zeros(max_batch, np.int32)
        # deque: admission pops the head every refill — list.pop(0) is O(n)
        # per admission (O(n²) to drain a burst); popleft keeps FIFO order
        # at O(1).
        self._queue: deque[_Request] = deque()
        self._results: dict[int, np.ndarray] = {}
        self._next_rid = 0
        # Pipelined rounds (overlap=True): the one in-flight chunk, and the
        # slot rows admission refilled since the last dispatch — their host
        # prefill values override the in-flight chunk's device rows at the
        # next dispatch (the one-round scheduling lag's merge point).
        self.overlap = overlap
        self._inflight: Optional[_Inflight] = None
        self._fresh_rows: set[int] = set()
        self._t_last_retire = 0.0  # round-cadence anchor (perf_counter)
        # Batched admission runs one [N, bucket] prefill per same-bucket
        # group — the plain arena only: ring/cycle folds and draft-arena
        # mirroring are per-request transforms keyed to a scalar position.
        self._can_batch_prefill = not ring_kv and draft is None
        # Counters for stats(): device rounds dispatched, tokens emitted
        # (pre-trim), speculative drafts offered/accepted. CUMULATIVE over
        # the server's lifetime — run() drains results but never resets
        # these (snapshot semantics, documented on stats()).
        self._rounds = 0
        self._emitted = 0
        self._prefills = 0
        self._batch_prefills = 0
        self._drafts_offered = 0
        self._drafts_accepted = 0
        # Paged-pool counters (stats()-snapshot semantics like the rest).
        self._preemptions = 0
        self._cow_copies = 0
        # Shared-prefix KV store (ISSUE 5). Per-server hit/miss counters
        # stay separate from the store's own (a store may back several
        # servers); per-slot handles pin a hit's segment until the request
        # finishes, so a prefix serving live traffic can never be evicted.
        self._slot_prefix: list[Optional[PrefixHit]] = [None] * max_batch
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_tokens_reused = 0
        explicit = prefix_cache_tokens is not None
        if prefix_cache_tokens is None:
            raw = os.environ.get("KATA_TPU_PREFIX_CACHE_TOKENS", "")
            try:
                prefix_cache_tokens = int(raw or 0)
            except ValueError:
                # A malformed NODE-WIDE env (e.g. "16k") must degrade like
                # every other implicit prefix-cache fallback, never crash
                # a guest server that did not opt in.
                self._emit(
                    "prefix_store_disabled", reason=f"bad_env:{raw[:32]}",
                )
                prefix_cache_tokens = 0
        self.prefix_store: Optional[PrefixStore] = None
        if prefix_store is not None or prefix_cache_tokens > 0:
            if ring_kv or draft is not None:
                # Unsupported modes fall back to cold admission rather than
                # refusing the server: the ring/cycle folds re-layout prefix
                # rows per slot, and a draft server's second arena would
                # miss its own prefix KV. Documented in docs/guest_guide.md.
                self._emit(
                    "prefix_store_disabled",
                    reason="ring_kv" if ring_kv else "draft",
                )
            elif not self.prefill_buckets:
                if explicit or prefix_store is not None:
                    raise ValueError(
                        "prefix caching requires prefill_buckets — matches "
                        "are bucket-aligned so suffix prefills keep the "
                        "bounded executable count"
                    )
                # Capacity came from the daemon-injected env default: a
                # node-wide knob must never crash a guest server that was
                # valid without it — degrade like the other implicit
                # fallbacks and say so on the event stream.
                self._emit(
                    "prefix_store_disabled", reason="no_prefill_buckets",
                )
            elif self.paged:
                # The radix prefix store becomes the shared-prefix TIER of
                # the paged pool (ISSUE 6): segments live in pool blocks,
                # hit admissions share fully-covered blocks with the
                # request's own table (copy-on-write at the boundary), and
                # eviction competes with decode for one budget — so
                # prefix_cache_tokens here is an ENABLE switch, capacity
                # is the pool's. (An injected separate-arena prefix_store
                # disables the pool instead — see _pool_conflict.)
                self.prefix_store = PagedPrefixTier(
                    self.kv_pool, cfg, self.prefill_buckets,
                    label=self._label, host_tier=self._kv_host,
                    on_demote=lambda: self._c_kv_demote.inc(),
                    on_prefetch=lambda: self._c_kv_prefetch.inc(),
                )
            elif prefix_store is not None:
                if (prefix_store.cfg != cfg
                        or prefix_store.buckets != self.prefill_buckets
                        or prefix_store.kv_quant != kv_quant
                        or prefix_store.dtype != cfg.dtype):
                    raise ValueError(
                        "injected prefix_store does not match this server "
                        "(cfg, prefill_buckets, kv_quant and cache dtype "
                        "must all agree — its rows land verbatim in this "
                        "arena)"
                    )
                self.prefix_store = prefix_store
            else:
                self.prefix_store = PrefixStore(
                    cfg, prefix_cache_tokens, self.prefill_buckets,
                    kv_quant=kv_quant, label=self._label,
                )
        if (self._mesh is not None and prefix_store is None
                and isinstance(self.prefix_store, PrefixStore)):
            # Shard the owned standalone store's arena like the serving
            # arena (same [.., KV, D] head axis), so a prefix hit's gather
            # → materialize → suffix prefill stays device-resident on the
            # mesh with no resharding step. (A paged tier lives inside the
            # already-placed pool; an INJECTED store keeps its caller's
            # placement — it may back single-chip servers too.)
            self._place_store(self._mesh)
        # Degraded-mode store bookkeeping (ISSUE 10): a mesh shrink
        # rebuilds an OWNED standalone store empty (its shards on the dead
        # chip are gone) but must only DISABLE an injected one — other
        # servers may share it.
        self._prefix_injected = (
            prefix_store is not None and self.prefix_store is prefix_store
        )
        self._prefix_capacity = int(prefix_cache_tokens or 0)
        # Serving heartbeat + SLO-burn watchdog (ISSUE 15). Standard knob
        # contract: an explicit negative cadence raises, the
        # daemon-injected env degrades to the default with a
        # heartbeat_invalid event. Cadence 0 disables heartbeat AND
        # watchdog AND the loop-phase clock — the uninstrumented path.
        if heartbeat_rounds is not None and int(heartbeat_rounds) < 0:
            raise ValueError(
                f"heartbeat_rounds must be >= 0, got {heartbeat_rounds}"
            )
        hb_every = (
            resilience.env_int(ENV_HEARTBEAT_ROUNDS,
                               DEFAULT_HEARTBEAT_ROUNDS,
                               event="heartbeat_invalid",
                               server=self._label, trace=self._trace)
            if heartbeat_rounds is None else int(heartbeat_rounds)
        )
        if hb_every < 0:
            # Parseable nonsense from the node env degrades like every
            # other injected knob — never crashes a guest.
            self._emit("heartbeat_invalid", reason=f"bad_env:{hb_every}")
            hb_every = DEFAULT_HEARTBEAT_ROUNDS
        self._hb_every = hb_every
        self._hb_round = 0          # rounds counter at the last heartbeat
        self._hb_count = 0
        self._hb_t_last = time.monotonic()
        self._hb_last: Optional[dict] = None
        self._hb_prev: dict = {}    # counter snapshot the deltas diff against
        self._clock = _PhaseClock(armed=hb_every > 0)
        self._clock_prev: dict = {}
        # Device-utilization & HBM ledger (ISSUE 17): armed whenever the
        # heartbeat is (KATA_TPU_DEVLEDGER=0 disarms — the same
        # kill-switch contract as the watchdog). Always constructed so
        # stats() carries the ledger block without a schema branch;
        # disarmed, every hook is one attribute test.
        self._devledger = obs.DeviceLedger(
            armed=hb_every > 0 and obs.devledger.enabled(),
            emit=self._emit, clock=self._clock, tp=self._tp,
            gap_phases=LOOP_PHASES,
            components=self._hbm_components,
        )
        # Watchdog resolution: an injected SLOBurnWatchdog wins (it must
        # have heartbeats to consume — explicit conflict raises); True
        # forces the default config on; False/env "0" disarms; None is
        # the default (armed whenever the heartbeat is).
        if isinstance(watchdog, obs.SLOBurnWatchdog) or watchdog is True:
            if hb_every <= 0:
                raise ValueError(
                    "watchdog requires heartbeat_rounds > 0 — it consumes "
                    "the heartbeats (docs/observability.md)"
                )
            self._watchdog: Optional[obs.SLOBurnWatchdog] = (
                watchdog if isinstance(watchdog, obs.SLOBurnWatchdog)
                else obs.SLOBurnWatchdog(
                    obs.WatchdogConfig.from_env(slo_ms=self._sched.slo_ms),
                    label=self._label, trace=self._trace, emit=self._emit,
                )
            )
        elif watchdog is None and hb_every > 0 and obs.watchdog.enabled():
            self._watchdog = obs.SLOBurnWatchdog(
                obs.WatchdogConfig.from_env(slo_ms=self._sched.slo_ms),
                label=self._label, trace=self._trace, emit=self._emit,
            )
        else:
            self._watchdog = None
        if self._watchdog is not None:
            self._watchdog.bind(self._emit)
        # Persistent step cap (ISSUE 20): the while_loop's max_steps — a
        # static of the persistent executable. Heartbeat cadence bounds
        # it (the host must surface telemetry at least once per
        # heartbeat interval, so one persistent round may not span more
        # rounds-worth of steps than one heartbeat covers); max_len
        # bounds the dense [B, cap] token buffer the loop carries.
        if self._persistent:
            cap_rounds = self._hb_every or DEFAULT_HEARTBEAT_ROUNDS
            self._persistent_cap = max(
                min(self._dispatch_steps * cap_rounds, self.max_len),
                self._dispatch_steps,
            )
        else:
            self._persistent_cap = 0
        # One config event per server (ISSUE 13 observability satellite):
        # the resolved dispatch shape — scheduler policy, decode-steps
        # multiplier, fused flag — so fleet dashboards can segment every
        # later serving metric by configuration without joining stats().
        self._emit(
            "serving_config", sched_policy=self._sched.name,
            decode_steps=self._decode_steps, chunk=self.chunk,
            dispatch_steps=self._dispatch_steps,
            fused=int(self._fused_ok), overlap=int(bool(overlap)),
            paged=int(self.paged), tp=self._tp,
            kv_layout=self._kv_layout,
            prefill_buckets=list(self.prefill_buckets),
            tripwire=int(self.tripwire),
            kv_host_tokens=(
                self._kv_host.capacity_tokens if self._kv_host else 0
            ),
            heartbeat_rounds=self._hb_every,
            watchdog=int(self._watchdog is not None),
            devledger=int(self._devledger.armed),
            persistent=int(self._persistent),
            persistent_cap=self._persistent_cap,
            tp_overlap=int(getattr(self, "_reduce_fn", None) is not None),
        )

    def _emit(self, name: str, **fields) -> None:
        """One emitter for every serving event: attaches the server label
        and the allocation TRACE id (ISSUE 11) so postmortem consumers —
        the flight recorder's dumps in particular — can join any event
        back to the daemon's Allocate span and to the request traces of
        the same incident. Fields win on collision."""
        obs.emit(
            "serving", name,
            **{"server": self._label, "trace": self._trace, **fields},
        )

    # ----- request lifecycle ledger (ISSUE 11) -----------------------------

    def _ledger_to(self, req: _Request, state: Optional[str],
                   now: Optional[float] = None) -> None:
        """Move ``req`` to lifecycle phase ``state``, accruing the time
        since the previous transition into the phase it is leaving.
        ``now`` lets callers stamp at an honest fence point they already
        hold (the first-token fence). ``state=None`` closes the ledger
        (final accrual; :meth:`_finish_trace` emits). No-op on a closed
        ledger — a request can never accrue time twice."""
        if req.state is None:
            return
        if now is None:
            now = time.monotonic()
        dt = now - req.t_state
        if dt > 0:
            req.phases[req.state] = req.phases.get(req.state, 0.0) + dt
        req.state = state
        req.t_state = now

    def _decode_state(self) -> str:
        """Decode time is attributed per-round to the CURRENT mesh state:
        rounds on a shrunken mesh land in ``decode_degraded`` so the
        ledger answers "how much of this request's latency was the
        incident" directly."""
        return (
            PHASE_DECODE_DEGRADED if self._tp < self._tp_initial
            else PHASE_DECODE
        )

    def _finish_trace(self, req: _Request, outcome: str,
                      reason: str = "") -> None:
        """Close a request's lifecycle ledger and emit its one
        ``request_trace`` event. INVARIANT: the six phase fields sum to
        ``wall_s`` (submit → this stamp) by construction — every moment
        of the request's life was in exactly one phase — so latency
        attribution is complete, not sampled (tested within 5% across
        the serving matrix; the slack is float rounding only). Observes
        the per-phase Rolling/histogram aggregates for phases the
        request actually spent time in."""
        if req.state is None:
            return
        now = time.monotonic()
        self._ledger_to(req, None, now)
        wall = max(now - req.t_submit, 0.0)
        fields = {}
        for p in PHASES:
            v = req.phases.get(p, 0.0)
            fields[f"{p}_s"] = round(v, 6)
            if v > 0:
                self._phase_roll[p].observe(v)
                self._h_phase[p].observe(v)
        if reason:
            fields["reason"] = reason
        self._traces_emitted += 1
        self._emit(
            "request_trace", rid=req.rid, outcome=outcome,
            wall_s=round(wall, 6),
            attributed_s=round(sum(req.phases.values()), 6),
            tokens=len(req.out), prompt_len=len(req.prompt),
            replays=req.replays, **fields,
        )

    # ----- serving heartbeat (ISSUE 15) ------------------------------------

    def _hb_counters(self) -> dict:
        """The cumulative counters the heartbeat turns into interval
        deltas — all host ints the loop already maintains."""
        tier = self.prefix_store
        tier_dem = tier.demotions if isinstance(tier, PagedPrefixTier) else 0
        tier_pre = tier.prefetches if isinstance(tier, PagedPrefixTier) else 0
        return {
            "tokens": self._emitted - self._prefills,
            "prefills": self._prefills,
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "preemptions": self._preemptions,
            "recoveries": self._recoveries,
            "kv_demotions": self._host_demotions + tier_dem,
            "kv_prefetches": self._host_prefetches + tier_pre,
            "slo_violations": self._sched.slo_violations,
            "sched_chunks": self._sched.chunks,
            "sched_defers": self._sched.defers,
        }

    def _hbm_components(self) -> dict:
        """Device-resident byte counts the server already knows, for the
        ledger's HBM attribution (ISSUE 17). NON-OVERLAPPING by
        construction so the attributed sum is honest: a paged prefix
        tier's blocks live INSIDE the pool arena (shared budget, ISSUE
        6) and report 0 here — only a standalone store owns a separate
        arena. The host-RAM KV tier is host memory, not HBM, and stays
        out entirely (its footprint already rides the heartbeat as
        kv_host_blocks/tokens). Shard-aware via _hbm_bytes: replicated
        leaves cost devices × nbytes, matching stats()["arena_bytes"]."""
        comp = {
            "params": sum(
                _hbm_bytes(leaf)
                for leaf in jax.tree_util.tree_leaves(self.params)
            ),
            "kv_arena": sum(
                _hbm_bytes(leaf)
                for leaf in jax.tree_util.tree_leaves(
                    self.kv_pool.arena if self.paged else self.arena
                )
            ),
        }
        store = self.prefix_store
        comp["prefix_store"] = (
            0 if store is None or isinstance(store, PagedPrefixTier)
            else sum(
                _hbm_bytes(leaf)
                for leaf in jax.tree_util.tree_leaves(store.arena)
            )
        )
        return comp

    def _maybe_heartbeat(self, force: bool = False) -> None:
        """Emit the periodic ``serving_heartbeat`` when the cadence says
        so (``force`` flushes a partial interval — the end-of-run tail,
        so short bursts still leave one heartbeat on the stream). One
        dict build + one emit every K rounds; everything read is host
        state, so the dispatch pipeline never notices."""
        if not self._hb_every:
            return
        lag = self._rounds - self._hb_round
        if lag < self._hb_every and not (force and lag > 0):
            return
        now = time.monotonic()
        interval_s = max(now - self._hb_t_last, 1e-9)
        snap = self._hb_counters()
        prev = self._hb_prev
        d = {k: snap[k] - prev.get(k, 0) for k in snap}
        itl = self._tok_lat.summary()
        ttft = self._ttft.summary()
        pool = self.kv_pool
        lookups = d["prefix_hits"] + d["prefix_misses"]
        phases = self._clock.snapshot()
        ph = {
            p: round(phases.get(p, 0.0) - self._clock_prev.get(p, 0.0), 6)
            for p in LOOP_PHASES[:-1]
        }
        ph[LOOP_PHASE_OTHER] = round(
            max(interval_s - sum(ph.values()), 0.0), 6
        )
        hb = {
            "round": self._rounds,
            "interval_rounds": lag,
            "interval_s": round(interval_s, 6),
            "tokens_delta": d["tokens"],
            "tokens_per_s": round(d["tokens"] / interval_s, 2),
            "prefills_delta": d["prefills"],
            "slots_busy": sum(r is not None for r in self._slot_req),
            "queued": len(self._queue),
            "preempted_waiting": len(self._preempted) if self.paged else 0,
            "batch_occupancy": round(
                sum(r is not None for r in self._slot_req) / self.max_batch,
                4,
            ),
            # Per-tier memory picture: device pool (+ per-shard fills),
            # host-RAM tier, prefix tier — the capacity numbers PR 14
            # turned sessions-per-chip into.
            "kv_pool_occupancy": pool.occupancy() if pool else 0.0,
            "kv_pool_shard_occupancy": self._pool_shard_occupancy(),
            "kv_host_occupancy": (
                self._kv_host.occupancy() if self._kv_host else 0.0
            ),
            "kv_host_blocks": (
                self._kv_host.blocks_used if self._kv_host else 0
            ),
            "kv_host_tokens": (
                self._kv_host.capacity_tokens if self._kv_host else 0
            ),
            "prefix_store_occupancy": (
                self.prefix_store.occupancy() if self.prefix_store else 0.0
            ),
            # Interval tier traffic + hit rates (the watchdog's
            # host_hit_collapse input).
            "prefix_hits_delta": d["prefix_hits"],
            "prefix_misses_delta": d["prefix_misses"],
            "prefix_hit_rate": (
                round(d["prefix_hits"] / lookups, 4) if lookups else 0.0
            ),
            "kv_demotions_delta": d["kv_demotions"],
            "kv_prefetches_delta": d["kv_prefetches"],
            "preemptions_delta": d["preemptions"],
            "recoveries_delta": d["recoveries"],
            "slo_violations_delta": d["slo_violations"],
            "sched_chunks_delta": d["sched_chunks"],
            "sched_defers_delta": d["sched_defers"],
            # Rolling latency quantiles in ms (recent-window, the
            # Rolling reservoir) — 0.0 before any observation.
            "itl_p50_ms": round(itl.get("p50", 0.0) * 1e3, 3),
            "itl_p99_ms": round(itl.get("p99", 0.0) * 1e3, 3),
            "ttft_p50_ms": round(ttft.get("p50", 0.0) * 1e3, 3),
            "ttft_p99_ms": round(ttft.get("p99", 0.0) * 1e3, 3),
            "slo_ms": self._sched.slo_ms,
            "tp": self._tp,
            "tp_degraded": int(self._tp < self._tp_initial),
            "decode_steps": self._decode_steps,
            # Persistent decode (ISSUE 20): flag + the LAST round's
            # delivered step count — always present (zeros when not
            # persistent), so dashboards segment ITL by actual steps
            # without a schema branch.
            "persistent": int(self._persistent),
            "delivered_steps": self._last_delivered,
            # Steady-state tripwire (ISSUE 19): cumulative, like the
            # stats() fields — any nonzero steady_state_compiles here is
            # a census breach (warm dispatch surface recompiled).
            "tripwire_warmed": int(self._tw_warmed),
            "steady_state_compiles": self._steady_compiles,
            "steady_state_reshards": self._steady_reshards,
            # The daemon-granted chip set (the per-allocation join key
            # the host-side aggregator labels its gauges with).
            "chips": tp_serving.allocation_chips(),
        }
        hb.update(self._sched.heartbeat_fields())
        hb.update({f"phase_{p}_s": v for p, v in ph.items()})
        # Device ledger (ISSUE 17): mfu / device_busy_frac /
        # dispatch_gap_* (full set, zeros before any dispatch) plus the
        # hbm_* poll — present only where the backend supplies
        # memory_stats (omission, never fake zeros). {} disarmed.
        hb.update(self._devledger.heartbeat_fields(interval_s))
        self._emit("serving_heartbeat", **hb)
        for p, v in ph.items():
            self._h_loop[p].observe(v)
        self._hb_count += 1
        self._hb_last = hb
        self._hb_prev = snap
        self._hb_round = self._rounds
        self._hb_t_last = now
        self._clock_prev = phases
        if self._watchdog is not None:
            self._watchdog.observe(hb)

    def _bind_histograms(self) -> None:
        self._h_ttft = _hist_ttft().labels(server=self._label)
        self._h_tok_lat = _hist_decode_token().labels(server=self._label)
        self._h_phase = {
            p: _hist_phase().labels(server=self._label, phase=p)
            for p in PHASES
        }
        self._h_loop = {
            p: _hist_loop_phase().labels(server=self._label, phase=p)
            for p in LOOP_PHASES
        }
        self._c_prefix_hits = _ctr_prefix_hits().labels(server=self._label)
        self._c_prefix_misses = _ctr_prefix_misses().labels(server=self._label)
        self._c_prefix_reused = _ctr_prefix_tokens_reused().labels(
            server=self._label
        )
        self._c_preempt = _ctr_preemptions().labels(server=self._label)
        self._c_cow = _ctr_cow_copies().labels(server=self._label)
        self._c_kv_demote = _ctr_kv_demotions().labels(server=self._label)
        self._c_kv_prefetch = _ctr_kv_prefetches().labels(server=self._label)
        self._c_recover = _ctr_recoveries().labels(server=self._label)
        self._c_quarantine = _ctr_quarantined().labels(server=self._label)
        self._c_stall = _ctr_stalls().labels(server=self._label)
        self._c_sched_chunk = _ctr_sched_chunks().labels(server=self._label)
        self._c_sched_defer = _ctr_sched_defers().labels(server=self._label)
        self._c_slo = _ctr_slo_violations().labels(server=self._label)
        self._c_fused = _ctr_fused_admissions().labels(server=self._label)

    def _kv_shards(self) -> int:
        """How many per-shard sub-pools the paged pool splits into: the
        serving mesh's degree under the blocks layout, 1 everywhere else
        (heads layout, tp=1, slotted). Re-read at every pool (re)build —
        a degraded mesh shrink rebuilds the pool against the CURRENT
        ``self._tp``, so the block-sharded pool re-places onto the
        shrunken mesh with matching sub-pools."""
        if getattr(self, "_kv_layout", KV_LAYOUT_HEADS) == KV_LAYOUT_BLOCKS:
            return max(1, self._tp)
        return 1

    def _pool_conflict(self, pool_tokens: int, ring_kv: bool, draft,
                       speculative_k: int, prefix_store) -> Optional[str]:
        """Why this server cannot run paged — None when it can. The paged
        path shares the dense ragged-decode numerics but not the ring/
        cycle folds (block gather would re-layout the band), the draft
        arena (a second pool), speculative verification (multi-token
        spans), or an injected separate-arena PrefixStore (the pool-backed
        tier is the prefix path here). A mesh — tensor-parallel serving —
        is NOT a conflict anymore (ISSUE 9): the pool arena shards its KV
        head axis like the dense arena, so paged × tp composes. Documented
        as the compatibility matrix in docs/guest_guide.md."""
        if self.kv_block < 1:
            return f"bad_block_size:{self.kv_block}"
        if ring_kv:
            return "ring_kv"
        if draft is not None or speculative_k:
            return "speculative"
        if prefix_store is not None:
            return "injected_prefix_store"
        # Whole blocks per shard (ISSUE 14): the blocks layout rounds the
        # pool down to a multiple of the mesh degree, so the progress
        # guarantee must hold AFTER that rounding — or a node-injected
        # pool one block shy would crash the KVPool constructor instead
        # of degrading here.
        shards = self._kv_shards()
        usable = (
            (pool_tokens // self.kv_block) // shards * shards
            - RESERVED_BLOCKS
        )
        if usable < -(-self.max_len // self.kv_block):
            # Progress guarantee: the drained pool must hold at least one
            # full-length request, or the oldest request could deadlock.
            return f"pool_too_small:{pool_tokens}"
        return None

    def _decode_attn_conflict(self) -> Optional[str]:
        """Why this server structurally cannot run the paged-native
        decode kernel — None when it can. The kernel is single-token
        ragged attention over the pool (or the pool-layout re-view of
        the slotted arena): ring/cycle folds re-layout rows per slot,
        speculative verification decodes multi-token spans, and sliding
        windows / the Gemma-2 softcap are masks it does not model.
        Backend-independent — shape/tiling limits are the separate
        :meth:`_decode_attn_shape_conflict` (they depend on interpret
        mode)."""
        if self.ring_kv:
            return "ring_kv"
        if self.speculative_k or self.draft is not None:
            return "speculative"
        if any(w > 0 for w in self.cfg.window_cycle):
            return "sliding_window"
        if self.cfg.attn_logits_softcap:
            return "logits_softcap"
        return None

    def _decode_attn_shape_conflict(self, interpret: bool) -> Optional[str]:
        """Tile/shape gate: the KV tile (the pool block — the kv_arena
        alignment contract — or :func:`..ops.attention.dense_decode_tile`
        of the slotted arena) and head_dim must satisfy
        :func:`..ops.decode_attn.supports_paged_decode` for the target
        backend (interpret mode has no tiling constraints)."""
        from ..ops.decode_attn import supports_paged_decode

        tile = (
            self.kv_block if self.paged
            else attention.dense_decode_tile(self.max_len)
        )
        if not supports_paged_decode(self.cfg.head_dim, tile,
                                     interpret=interpret):
            return (
                f"unsupported_shape:head_dim={self.cfg.head_dim}"
                f",kv_tile={tile}"
            )
        return None

    def _resolve_decode_attn(self, choice: Optional[str]):
        """Resolve the decode-attention backend: ``(name, reason,
        interpret)``. Explicit argument > env > auto, with the standard
        knob contract — an explicit incompatible choice raises, an
        env-injected one degrades to the automatic pick with an event
        (the reason also rides the decode_attn_backend event). Forcing
        the kernel off-TPU runs it in pallas interpret mode (the CPU
        serving-matrix harness); the automatic pick never interprets —
        interpret mode is far slower than XLA."""
        explicit = choice is not None
        if choice is None:
            raw = os.environ.get(ENV_DECODE_ATTN, "").strip()
            if raw and raw not in attention.DECODE_ATTN_BACKENDS:
                self._emit(
                    "decode_attn_invalid", reason=f"bad_env:{raw[:32]}",
                )
                raw = ""
            choice = raw or None
        elif choice not in attention.DECODE_ATTN_BACKENDS:
            raise ValueError(
                f"unknown decode_attn {choice!r} "
                f"(have {attention.DECODE_ATTN_BACKENDS})"
            )
        if choice == BACKEND_REFERENCE:
            return BACKEND_REFERENCE, "forced", False
        if choice == BACKEND_PAGED:
            interpret = not attention.on_tpu()
            reason = (
                self._decode_attn_conflict()
                or self._decode_attn_shape_conflict(interpret)
            )
            if reason is not None:
                if explicit:
                    raise ValueError(
                        f"decode_attn={BACKEND_PAGED!r} is incompatible "
                        f"with this server ({reason}) — see 'Decode "
                        "attention backends' in docs/guest_guide.md"
                    )
                return BACKEND_REFERENCE, reason, False
            return BACKEND_PAGED, "", interpret
        # Automatic: the kernel on TPU where supported, XLA elsewhere.
        # Structural conflicts outrank the platform reason (they hold on
        # every backend and are the actionable part of the event).
        reason = self._decode_attn_conflict()
        if reason is not None:
            return BACKEND_REFERENCE, reason, False
        if not attention.on_tpu():
            return BACKEND_REFERENCE, "cpu_backend", False
        reason = self._decode_attn_shape_conflict(False)
        if reason is not None:
            return BACKEND_REFERENCE, reason, False
        return BACKEND_PAGED, "", False

    def _build_decode_kernel(self, mesh) -> None:
        """(Re)build the static decode-attention kernel callable for the
        CURRENT mesh — called at construction and again from
        :meth:`_place_arenas` whenever the arena moves (tp serving, crash
        rebuild, degraded mesh shrink: a smaller mesh needs a fresh
        shard_map wrapper, and the fn's identity being the executable
        cache key makes the recompile explicit rather than a stale
        reuse)."""
        # Overlapped tp collectives (ISSUE 20): the reduce hint rides the
        # same lifecycle as the decode kernel — mesh-derived, rebuilt on
        # placement and degraded shrink, identity is an executable cache
        # key. Built before the paged early-return: overlap applies to
        # every decode backend, not just paged.
        self._reduce_fn = tp_serving.overlap_reduce_fn(
            mesh, self.cfg, label=self._label, emit=self._emit,
        )
        if self._decode_attn != BACKEND_PAGED:
            self._decode_kernel = None
            return
        from ..parallel.mesh import AXIS_MODEL

        tp = mesh.shape.get(AXIS_MODEL, 1) if mesh is not None else 1
        self._decode_kernel = attention.make_decode_attn_fn(
            self.cfg, paged=self.paged, block_size=self.kv_block,
            paged_len=self.max_len, arena_len=self.max_len,
            quantized=self.kv_quant, mesh=mesh if tp > 1 else None,
            tp=tp, interpret=self._decode_interpret,
            kv_layout=self._kv_layout if self.paged else KV_LAYOUT_HEADS,
        )

    def _shard_over(self, mesh) -> None:
        """Tensor-parallel serving: place params by their layout-aware
        PartitionSpecs — the serving regex rules
        (``parallel.sharding.SERVING_RULES``: embeddings replicated,
        attention/MLP column/row over model) on the ``tp=`` path, the
        training ``param_specs`` for an explicitly injected ``mesh=`` —
        GSPMD then inserts the tp collectives inside the same jitted
        prefill/decode executables. The KV arena (or paged pool) shards
        its head axis over model when the head count divides; otherwise
        it replicates (correct, memory-heavier). All serving layouts
        shard: the training layout, fused wqkv/w_gateup, int8 QTensors
        (q and scale consistently), and live LoRA adapters — so the
        production shape (tp × fused × int8) runs on a slice without
        merging."""
        from ..parallel.sharding import shard_params, shard_serving_params

        place = (
            shard_serving_params if self._tp_serving_rules else shard_params
        )
        self.params = place(self.params, mesh)
        if self.draft is not None:
            d_params, d_cfg = self.draft
            self.draft = (place(d_params, mesh), d_cfg)
        self._place_arenas(mesh)

    def _place_store(self, mesh) -> None:
        """Shard the standalone prefix store's arena over the mesh (the
        KV head axis when it divides — :func:`.tp_serving.kv_cache_spec`,
        the same spec every other KV layout uses)."""
        from jax.sharding import NamedSharding

        sh = NamedSharding(
            mesh, tp_serving.kv_cache_spec(self.cfg, self._tp)
        )
        self.prefix_store.arena = jax.tree.map(
            lambda c: jax.device_put(c, sh), self.prefix_store.arena
        )

    def _place_arenas(self, mesh) -> None:
        """Device placement of the KV arena(s) — the dense slot grid OR
        the paged block pool — for tensor-parallel serving. Split from
        :meth:`_shard_over` so crash recovery can re-place a freshly
        rebuilt arena/pool without re-sharding params. The divide-or-
        replicate decision lives in ONE place
        (:func:`.tp_serving.kv_heads_shardable`, via the spec helpers),
        shared with the spill-restore uploads."""
        from jax.sharding import NamedSharding

        from ..parallel.mesh import AXIS_MODEL

        tp = mesh.shape.get(AXIS_MODEL, 1)
        layout = self._kv_layout if self.paged else KV_LAYOUT_HEADS
        sh = NamedSharding(
            mesh, tp_serving.kv_cache_spec(self.cfg, tp, layout=layout)
        )
        if (tp > 1 and layout == KV_LAYOUT_HEADS
                and not tp_serving.kv_heads_shardable(self.cfg, tp)
                and tp not in self._kv_replicated_warned):
            # The paged×tp memory cliff's worst edge made LOUD (ISSUE 10
            # satellite; ROADMAP item 3b): when n_kv_heads does not
            # divide tp the KV spec replicates the whole pool/arena onto
            # every shard — correct, but real HBM is tp × the logical
            # figure. One warning event per (server, degree) with the
            # measured extra bytes, instead of the silent replication.
            # HEADS layout only (ISSUE 14): under the blocks layout the
            # cliff does not exist — the once-per-server kv_layout event
            # carries the per-shard figure instead.
            self._kv_replicated_warned.add(tp)
            logical = sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(
                    self.kv_pool.arena if self.paged else self.arena
                )
            )
            self._emit(
                "kv_replicated", tp=tp, n_kv_heads=self.cfg.n_kv_heads,
                extra_bytes=(tp - 1) * logical,
            )
        with jaxapi.allow_transfer(
                "arena placement onto the serving mesh (init, crash "
                "recovery, degraded shrink — a mesh change, never a "
                "per-round path)"):
            if self.paged:
                # The pool IS the arena ([L, 1, NT, KV, D] leaves — the
                # same head-axis position as the slot grid), so paged ×
                # tp shards the one structure every lane's table points
                # into.
                self.kv_pool.arena = jax.tree.map(
                    lambda c: jax.device_put(c, sh), self.kv_pool.arena
                )
            else:
                self.arena = jax.tree.map(
                    lambda c: jax.device_put(c, sh), self.arena
                )
            if self.draft is not None:
                _d_params, d_cfg = self.draft
                d_sh = NamedSharding(
                    mesh, tp_serving.kv_cache_spec(d_cfg, tp)
                )
                self.draft_arena = jax.tree.map(
                    lambda c: jax.device_put(c, d_sh), self.draft_arena
                )
        # The decode kernel wrapper is mesh-specific (ISSUE 12): rebuild
        # it wherever the arena lands — including the degraded shrink's
        # smaller mesh (attribute-guarded: __init__ places the arena
        # before the backend is resolved).
        if getattr(self, "_decode_attn", None) is not None:
            self._build_decode_kernel(mesh)

    # ----- public API ------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 64) -> int:
        if self._draining:
            raise RuntimeError(
                f"server {self._label} is draining "
                f"({self._drain_reason or 'requested'}): not accepting new "
                "requests"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds arena max_len ({self.max_len})"
            )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, prompt, max_new_tokens, t_submit=time.monotonic())
        req.t_state = req.t_submit  # ledger: the queue phase starts here
        self._queue.append(req)
        return rid

    def run(self) -> dict[int, np.ndarray]:
        """Drain queue + slots to completion; returns {rid: tokens[new]}.
        Requests that were quarantined or drained are NOT in the result —
        they surface in :meth:`failures` (every submitted rid appears in
        exactly one of the two; none vanish).

        The FIRST drain is the tripwire warmup (it compiles the bucketed
        dispatch surface); every later drain runs inside
        ``compat.jaxapi.compile_tripwire`` and banks any new XLA compile
        or unsanctioned ``device_put`` into ``steady_state_compiles`` /
        ``steady_state_reshards`` — nonzero means a static arg varied
        per round and the JG401 census contract broke at runtime (see
        docs/observability.md for the breach runbook)."""
        tw_armed = self.tripwire and self._tw_warmed
        try:
            with jaxapi.compile_tripwire(enabled=tw_armed) as tw:
                while self.step():
                    pass
        finally:
            self._tw_warmed = True
            if tw_armed:
                self._steady_compiles += tw.compiles
                self._steady_reshards += tw.transfers
                if tw.compiles or tw.transfers:
                    self._emit(
                        "tripwire_breach",
                        compiles=tw.compiles,
                        reshards=tw.transfers,
                    )
        out, self._results = self._results, {}
        return out

    def failures(self) -> dict[int, str]:
        """Per-request terminal failures: ``{rid: error}`` for every
        request the supervisor quarantined (K consecutive implicated
        rounds) or the drain failed before it started. CUMULATIVE
        snapshot semantics like :meth:`stats` — ``run()`` drains results,
        never failures."""
        return dict(self._failures)

    def request_drain(self, reason: str = "api") -> None:
        """Flag a graceful drain (idempotent, async-signal-safe: it ONLY
        sets state — the ``drain_begin`` event is emitted by the serving
        loop, because obs sinks take locks a signal handler must never
        contend on): admission of queued work stops, in-flight lanes (and
        preempted requests — work that already started) run to
        completion, and when the server is idle the remaining queue fails
        into :meth:`failures` with a final checkpoint event. ``submit()``
        refuses new work from this point on."""
        if self._draining:
            return
        self._drain_reason = reason
        self._draining = True

    def drain(self, reason: str = "api") -> dict[int, np.ndarray]:
        """Synchronous graceful drain: :meth:`request_drain` then
        :meth:`run`. Returns the completed results; everything that never
        started is in :meth:`failures`."""
        self.request_drain(reason)
        return self.run()

    def stats(self) -> dict:
        """Serving counters: device rounds, tokens emitted (pre-trim),
        mean tokens per round, occupancy/utilization gauges, latency
        summaries, and — under ``speculative_k`` — the draft acceptance
        rate (the number the k parameter should be tuned by).

        SNAPSHOT semantics (ISSUE 2): every counter is cumulative over the
        server's lifetime and stats() NEVER resets anything — two
        back-to-back calls with no traffic in between return equal dicts,
        and counters only grow across successive ``run()`` batches
        (``run()`` drains *results*, not telemetry). The latency summaries
        (``ttft_s``, ``decode_token_s``) are count/mean/min/max/p50/p95
        dicts from a bounded reservoir — cumulative counts, recent-window
        quantiles.

        ``prefill_batches`` counts MULTI-request admission forwards only:
        each engagement of a batched ``[N >= 2, bucket]`` admission
        executable — cold ``transformer.prefill_batch`` or the batched
        suffix path (``prefill_suffix`` with a ``[N]`` boundary vector) —
        is one increment, however many rows it carried; single-request
        admissions never touch it, so ``prefills`` (per-request) and this
        field answer different questions. Tested in
        ``tests/test_prefix_cache.py``.

        Prefix-cache fields (ISSUE 5) are ALWAYS present so dashboards
        need no schema branch: with the store disabled,
        ``prefix_hit_ratio`` is 0.0 and the counters stay 0.
        ``prefix_hit_ratio`` is hits / (hits + misses) over this server's
        lookups; ``prefix_tokens_reused`` counts prompt tokens copied from
        the store instead of re-prefilled; ``prefix_store_occupancy`` /
        ``prefix_store_tokens`` / ``prefix_store_bytes`` describe the
        (possibly shared) store's arena."""
        decoded = self._emitted - self._prefills
        busy = sum(r is not None for r in self._slot_req)
        out = {
            "rounds": self._rounds,
            "prefills": self._prefills,
            "prefill_batches": self._batch_prefills,
            "tokens_emitted": self._emitted,  # incl. one prefill token/request
            "tokens_per_round": (
                round(decoded / self._rounds, 3) if self._rounds else 0.0
            ),
            "slots_busy": busy,
            "queued": len(self._queue),
            "batch_occupancy": round(busy / self.max_batch, 4),
            # Mean cache fill of the busy slots: positions written over the
            # per-slot arena length (ring arenas wrap, so cap at 1.0).
            "kv_slot_utilization": self._kv_slot_utilization(),
            "ttft_s": self._ttft.summary(),
            "decode_token_s": self._tok_lat.summary(),
            # KV arena footprint — the number ring/cycle arenas and int8
            # caches exist to shrink (sum over leaves: int8 payloads and
            # quant scales both counted). Summed over ADDRESSABLE SHARDS,
            # not logical nbytes: when the arena replicates under tensor
            # parallelism (n_kv_heads % tp != 0 → kv_spec = P()), every
            # device holds a full copy and real HBM is mesh-size × the
            # logical figure — the stat reports the real cost. Paged
            # servers report the block pool (the pool IS the arena).
            "arena_bytes": sum(
                _hbm_bytes(leaf)
                for leaf in jax.tree_util.tree_leaves(
                    self.kv_pool.arena if self.paged else self.arena
                )
            ),
        }
        # Paged-pool fields (ISSUE 6): ALWAYS present — 0/0.0 on slotted
        # servers — so dashboards need no schema branch (the _PROM_STATS
        # gauges scrape these by name).
        pool = self.kv_pool
        # Host-tier traffic (ISSUE 14): the live prefix tier's counts
        # plus everything folded in from rebuilds and session spills —
        # cumulative, like every other counter here.
        tier = self.prefix_store
        tier_dem = tier.demotions if isinstance(tier, PagedPrefixTier) else 0
        tier_pre = tier.prefetches if isinstance(tier, PagedPrefixTier) else 0
        out.update({
            "kv_pool_occupancy": pool.occupancy() if pool else 0.0,
            "kv_blocks_in_use": pool.blocks_in_use if pool else 0,
            "kv_blocks_total": pool.blocks_total if pool else 0,
            "kv_pool_tokens": pool.capacity_tokens if pool else 0,
            "preemptions": self._preemptions,
            "preempted_waiting": len(self._preempted) if self.paged else 0,
            "cow_copies": self._cow_copies,
            # KV layout + host tier (ISSUE 14): ALWAYS present — layout
            # "heads", shards 1 and zeros on slotted / tier-off servers,
            # so dashboards need no schema branch.
            "kv_layout": self._kv_layout,
            "kv_pool_shards": pool.shards if pool else 1,
            "kv_host_tokens": (
                self._kv_host.capacity_tokens if self._kv_host else 0
            ),
            "kv_host_blocks": (
                self._kv_host.blocks_used if self._kv_host else 0
            ),
            "kv_demotions": self._host_demotions + tier_dem,
            "kv_prefetches": self._host_prefetches + tier_pre,
        })
        # Tensor-parallel fields (ISSUE 9): ALWAYS present — tp_degree 1
        # and shard occupancies 0.0 on unsharded servers — so dashboards
        # need no schema branch (same contract as the pool/scheduler/
        # resilience blocks around this one).
        out.update({
            "tp_degree": self._tp,
            # Degraded mode (ISSUE 10): ALWAYS present — 0/0 on servers
            # that never lost a chip — same no-schema-branch contract.
            "tp_degraded": int(self._tp < self._tp_initial),
            "tp_shrinks": self._tp_shrinks,
            "kv_pool_shard_occupancy": self._pool_shard_occupancy(),
        })
        # Decode-attention backend (ISSUE 12): ALWAYS present — the
        # resolved backend name plus the fallback reason ("" when the
        # kernel is active) — mirrored by the once-per-server
        # decode_attn_backend event and the labeled scrape gauge.
        out.update({
            "decode_backend": self._decode_attn,
            "decode_backend_reason": self._decode_attn_reason,
        })
        # Request lifecycle ledger (ISSUE 11): ALWAYS present — the trace
        # id every event of this server carries, the request_trace count,
        # and per-phase Rolling summaries ({"count": 0} for phases no
        # retired request has spent time in — no schema branch). The
        # future fleet router load-balances on these (where does latency
        # go on THIS replica: queue? prefill? degraded decode?).
        out.update({
            "trace": self._trace,
            "request_traces": self._traces_emitted,
            "request_phase_s": {
                p: self._phase_roll[p].summary() for p in PHASES
            },
        })
        # Scheduler fields (ISSUE 8): ALWAYS present — fifo_batch reports
        # policy name + zeros — so dashboards need no schema branch.
        # sched_queue_delay_s is the submit→admission-grant summary (the
        # TTFT component the scheduler controls); sched_chunks/defers and
        # slo_violations mirror the _total prometheus counters.
        out.update(self._sched.stats())
        # Fused scheduling & multi-step decode (ISSUE 13): ALWAYS present
        # — decode_steps is 1 and fused_admissions 0 on servers that
        # never fuse — same no-schema-branch contract; fused_admissions
        # mirrors the kata_tpu_serving_fused_admissions_total counter.
        out.update({
            "decode_steps": self._decode_steps,
            "fused_enabled": int(self._fused_ok),
            "fused_admissions": self._fused_admissions,
        })
        # Persistent decode (ISSUE 20): ALWAYS present — zeros/False on
        # non-persistent servers, the same no-schema-branch contract.
        # delivered_steps is the LAST round's count (the heartbeat
        # mirrors it); the exits dict partitions persistent_rounds by
        # exit reason, mirroring the persistent_exit event stream.
        out.update({
            "persistent": int(self._persistent),
            "persistent_cap": self._persistent_cap,
            "persistent_rounds": self._persistent_rounds,
            "delivered_steps": self._last_delivered,
            "delivered_steps_total": self._delivered_total,
            "persistent_exits": dict(self._persistent_exits),
        })
        # Steady-state tripwire (ISSUE 19): ALWAYS present — zeros with
        # the tripwire off or before the second run() — same
        # no-schema-branch contract. Nonzero steady_state_compiles is a
        # REGRESSION by definition (bench_trend never calls it flat):
        # the warm dispatch surface recompiled, i.e. a jit static arg
        # varied per round. steady_state_reshards counts device_put
        # calls outside any allow_transfer sanction in warm drains.
        out.update({
            "tripwire_enabled": int(self.tripwire),
            "tripwire_warmed": int(self._tw_warmed),
            "steady_state_compiles": self._steady_compiles,
            "steady_state_reshards": self._steady_reshards,
        })
        # Heartbeat + watchdog (ISSUE 15): ALWAYS present — zeros with
        # the heartbeat disabled — same no-schema-branch contract. The
        # numeric alert fields ride the scrape loop; the ``watchdog``
        # dict carries the detail (active kinds, last dump path).
        wd = self._watchdog.stats() if self._watchdog is not None else {
            "alerts": 0, "active": [], "observed": 0, "last_dump": "",
        }
        out.update({
            "heartbeats": self._hb_count,
            "heartbeat_rounds": self._hb_every,
            "heartbeat_tokens_per_s": (
                self._hb_last.get("tokens_per_s", 0.0)
                if self._hb_last else 0.0
            ),
            "loop_phase_s": {
                p: round(v, 6) for p, v in self._clock.snapshot().items()
            },
            "watchdog_alerts": wd["alerts"],
            "watchdog_active": len(wd["active"]),
            "watchdog": wd,
        })
        # Device ledger (ISSUE 17): mfu / device_busy_frac /
        # dispatch_gap_ms ALWAYS present (zeros disarmed or before the
        # first heartbeat window) so they ride the scrape loop; the
        # ``devledger`` dict carries the detail — hbm_* fields appear
        # there only where the backend supplies memory_stats.
        out.update(self._devledger.stats_fields())
        # Resilience fields (ISSUE 7): ALWAYS present — zeros on a server
        # that never failed — so dashboards need no schema branch.
        out.update({
            "recoveries": self._recoveries,
            "quarantined": self._quarantined_n,
            "device_stalls": self._stalls,
            "checkpoints": self._checkpoints,
            "checkpoint_rounds": self._ckpt_every,
            "failed_requests": len(self._failures),
            "draining": self._draining,
        })
        lookups = self._prefix_hits + self._prefix_misses
        store = self.prefix_store
        out.update({
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "prefix_tokens_reused": self._prefix_tokens_reused,
            "prefix_hit_ratio": (
                round(self._prefix_hits / lookups, 4) if lookups else 0.0
            ),
            "prefix_store_tokens": store.tokens_used if store else 0,
            "prefix_store_occupancy": store.occupancy() if store else 0.0,
            # Paged tier: no arena of its own — its footprint is the pool
            # fraction its segment blocks hold (shared budget, ISSUE 6).
            "prefix_store_bytes": (
                out["arena_bytes"] * store.blocks_used
                // self.kv_pool.num_blocks
                if isinstance(store, PagedPrefixTier) else sum(
                    _hbm_bytes(leaf)
                    for leaf in jax.tree_util.tree_leaves(store.arena)
                ) if store else 0
            ),
        })
        if self.speculative_k:
            out["draft_acceptance"] = (
                round(self._drafts_accepted / self._drafts_offered, 4)
                if self._drafts_offered else 0.0
            )
        return out

    def _pool_shard_occupancy(self) -> list[float]:
        """Per-mesh-shard paged-pool fill, one entry per tp shard.
        Under the BLOCKS layout (ISSUE 14) each shard is a real
        sub-pool — the entries are each shard's own blocks-in-use over
        its usable blocks, and they genuinely diverge. Under the HEADS
        layout every block spans all shards (the pool shards its KV head
        axis or replicates), so each shard's fill equals the logical
        occupancy. ALWAYS a length-``max(1, tp)`` list — zeros at tp=1
        and on slotted servers (no schema branch)."""
        if self._tp <= 1 or not self.paged or self.kv_pool is None:
            return [0.0] * max(1, self._tp)
        if self.kv_pool.shards > 1:
            return self.kv_pool.shard_occupancy()
        return [self.kv_pool.occupancy()] * self._tp

    def _kv_slot_utilization(self) -> float:
        busy = [b for b in range(self.max_batch) if self._slot_req[b] is not None]
        if not busy:
            return 0.0
        if self.ring_kv:
            arena_len = self.cfg.window_cycle[0] + self._ring_margin
        else:
            arena_len = self.max_len
        return round(
            float(np.mean([min(1.0, self._pos[b] / arena_len) for b in busy])),
            4,
        )

    _instance_ids = iter(range(1 << 30))

    def export_metrics(self, port: int = 0, label: Optional[str] = None) -> str:
        """Expose this server's :meth:`stats` as Prometheus gauges
        (``kata_tpu_serving_*``, scrape-time values — the gauges call
        ``stats()`` when collected, no polling thread) alongside the TTFT
        and per-token-latency HISTOGRAMS the server records as it runs.
        The guest-side counterpart of the host daemon's ``utils.metrics``
        endpoint (SURVEY §5 observability). ``port > 0`` also starts the
        /metrics HTTP endpoint (one per process); multiple servers in one
        process distinguish themselves by the ``server`` label. ``label``
        renames this server (default ``server<N>``) — call before traffic
        so histogram samples land under the final label. Returns the
        label."""
        if label:
            self._label = label
            self._bind_histograms()  # future samples land under the new label
        for name, gauge in _prom_gauges().items():
            gauge.labels(server=self._label).set_function(
                lambda self=self, n=name: float(self.stats().get(n, 0.0))
            )
        # Per-shard pool occupancy (ISSUE 9): one labeled child per mesh
        # shard — shard 0 exists on every server (0.0 unsharded), so the
        # scrape schema never branches on the tp degree.
        def _shard_occ(self=self, i=0) -> float:
            occ = self._pool_shard_occupancy()
            return float(occ[i]) if i < len(occ) else 0.0

        shard_gauge = _gauge_shard_occupancy()
        for i in range(max(1, self._tp)):
            shard_gauge.labels(server=self._label, shard=str(i)).set_function(
                partial(_shard_occ, self, i)
            )
        # Decode-attention backend (ISSUE 12): 1 on the active backend's
        # label, 0 on the others — every known backend gets a child so
        # the scrape schema never branches on the selection. Reads the
        # resolved field directly (like _shard_occ): a stats() snapshot
        # per scrape would rebuild every Rolling summary just to compare
        # one immutable string.
        def _backend_active(self=self, be: str = "") -> float:
            return float(self._decode_attn == be)

        backend_gauge = _gauge_decode_backend()
        for be in attention.DECODE_ATTN_BACKENDS:
            backend_gauge.labels(
                server=self._label, backend=be
            ).set_function(partial(_backend_active, self, be))
        # HBM headroom (ISSUE 17): dedicated gauge, NOT the stats()
        # scrape loop — its ``.get(name, 0.0)`` default would fake
        # "0 bytes free" on backends without memory_stats. NaN is the
        # Prometheus idiom for "no data".
        def _headroom(self=self) -> float:
            v = self._devledger.hbm_headroom()
            return float(v) if v is not None else float("nan")

        _gauge_hbm_headroom().labels(server=self._label).set_function(
            _headroom
        )
        if port:
            from ..utils.metrics import serve

            serve(port)
        return self._label

    # ----- scheduling ------------------------------------------------------

    def _sample_first(self, logits: jax.Array) -> int:
        self._key, sub = jax.random.split(self._key)
        return int(_next_token(logits, sub, self._do_sample,  # jaxguard: allow(JG101) admission host read — sanctioned sync (runs under allow_transfer)
                               self._temp_dev, self.top_k,
                               self.top_p)[0])

    def _finish_admission(self, b: int, req: _Request, first: int, pos: int,
                          t_first: float, hit: Optional[PrefixHit] = None,
                          **event_fields) -> None:
        """The admission epilogue every fill path shares: first-token and
        counter bookkeeping, the TTFT observation + event, slot-state
        handoff (with the optional prefix pin), the overlap fresh-row
        mark, and the immediate-finish check. ``t_first`` is the caller's
        clock stamp from the moment the first token LANDED on the host
        (the transfer that fenced the prefill forward) — TTFT must not
        absorb the arena-write/store-insert dispatch that follows it.
        ``event_fields`` extend the ttft event (``batched=n``,
        ``prefix_reused=m``)."""
        req.out.append(first)
        self._prefills += 1
        self._emitted += 1  # the prefill forward emits the first token
        # Ledger: the first-token fence closes the prefill (or recovery-
        # replay) phase — t_first is the same honest post-fence stamp
        # TTFT uses, so attribution and TTFT cannot drift apart.
        self._ledger_to(req, self._decode_state(), now=t_first)
        ttft = t_first - req.t_submit
        self._ttft.observe(ttft)
        self._h_ttft.observe(ttft)
        if req.replays:
            # A crash-recovery replay (ISSUE 7): honest TTFT — the
            # re-observation absorbs the recovery — but labeled, so
            # first-admission consumers (FIFO-order tests, dashboards
            # separating clean TTFT from recovery tail) can filter.
            event_fields = {**event_fields, "replay": req.replays}
        self._emit(
            "ttft", rid=req.rid, ttft_s=round(ttft, 6),
            prompt_len=len(req.prompt), queued=len(self._queue),
            **event_fields,
        )
        self._slot_req[b] = req
        self._slot_prefix[b] = hit  # pinned until the request finishes
        self._pos[b] = pos
        self._last[b] = first
        self._fresh_rows.add(b)  # overlap: override the in-flight row
        # Landed in a lane: no longer mid-admission for crash unwind.
        self._admitting = [
            (r, h) for r, h in self._admitting if r is not req
        ]
        self._maybe_finish(b, [first])

    def _fill_slot(self, b: int, req: _Request,
                   bucket: Optional[int]) -> None:
        """Prefill ``req``'s prompt into arena slot ``b``. ``bucket`` is
        the admission pass's already-resolved prefill bucket (None = exact
        length) — resolved ONCE in :meth:`_admit` so the grouping policy
        and the executable shape compiled here cannot drift apart. A
        bucketed prompt is right-padded to it — one prefill executable per
        bucket rather than one per distinct prompt length (exact: see
        ``transformer.prefill``'s ``true_len``)."""
        self._inj.fire("prefill")
        prompt, true_len = req.prompt, len(req.prompt)
        if bucket is not None and bucket > true_len:
            prompt = np.pad(prompt, (0, bucket - true_len))
        # ring_kv: prefill into a transient prompt-length cache, then fold
        # the live window into the slot's ring (slot s ← the latest
        # position ≡ s mod W) — the arena itself never grows past W.
        cache_len = len(prompt) if self.ring_kv else self.max_len
        # Span fence: _sample_first's int() transfers the sampled token,
        # which depends on the whole prefill forward.
        with obs.span(
            "serving.prefill",
            trace_id=self._trace, server=self._label, rid=req.rid, slot=b,
            prompt_len=true_len, padded_len=len(prompt), tokens=true_len,
        ) as sp:
            caches, last_logits, pos = prefill(  # jaxguard: allow(JG401) cache_len is bucket-quantized by _admit (one executable per bucket); exact/ring mode deliberately trades one compile per distinct prompt length for ring-W memory
                self.params, jnp.asarray(prompt)[None, :], self.cfg,
                cache_len, return_logits=True, kv_quantized=self.kv_quant,
                true_len=jnp.int32(true_len) if bucket is not None else None,
            )
            if self._cycle:
                caches = cycle_ring_caches_from_prefill(
                    caches, pos, self.cfg, self.max_len,
                    margin=self._ring_margin,
                )
            elif self.ring_kv:
                caches = ring_caches_from_prefill(
                    caches, pos, self.cfg.window_cycle[0] + self._ring_margin
                )
            first = self._sample_first(last_logits)
        t_first = time.monotonic()  # the int() above fenced the forward
        self._sched.note_prefill(len(prompt), sp.duration_s)
        self._inj.fire("admission_commit")
        if self.paged:
            self._paged_commit(b, req, caches, 0)
        else:
            self.arena = _write_slot(self.arena, caches, b)
        if self.prefix_store is not None:
            # Populate the store from this full-prompt prefill: the cache
            # rows [0, bucket-aligned bound) are exactly the prompt's real
            # tokens' KV (the bound is < true_len, so pad rows never enter
            # the store). Device-to-device copy; no host sync. (Paged: the
            # tier copies into its own pool blocks, skipping under pool
            # pressure — decode outranks the cache.)
            self.prefix_store.insert(req.prompt, caches, 0)
        if self.draft is not None:
            # The draft prefills the same prompt into its own arena slot
            # (cheap: the draft is a fraction of the target), so its cache
            # tracks the slot's positions from the first verify round on.
            d_params, d_cfg = self.draft
            d_caches, _dl, _dp = prefill(
                d_params, jnp.asarray(prompt)[None, :], d_cfg,
                self.max_len, return_logits=True,
                true_len=jnp.int32(true_len) if bucket is not None else None,
            )
            self.draft_arena = _write_slot(self.draft_arena, d_caches, b)
        self._finish_admission(b, req, first, int(pos), t_first)  # jaxguard: allow(JG101) admission host read — slot position lands with the first token

    def _prefix_lookup_raw(self, req: _Request) -> Optional[PrefixHit]:
        """Store lookup WITHOUT the per-server counters (the paged path
        must reserve pool blocks between lookup and counting — a failed
        reservation cancels the hit before anything monotonic recorded
        it). Returns None when the store is disabled or nothing usable is
        cached; a non-None hit is PINNED."""
        if self.prefix_store is None:
            return None
        hit = self.prefix_store.lookup(req.prompt)
        if hit is not None:
            s_len = len(req.prompt) - hit.length
            no_bucket = self._suffix_bucket(hit.length, s_len) is None
            if no_bucket and any(
                k >= len(req.prompt) for k in self.prefill_buckets
            ):
                # Degraded hit: the suffix fits no bucket inside the arena
                # (an exact-length suffix compiles one executable per
                # distinct prompt length) while the WHOLE prompt does fit
                # one — cold bucketed admission keeps the executable
                # bound, so prefer it. Prompts longer than every bucket
                # keep the hit: cold would be exact-length anyway, and
                # the suffix forward is strictly smaller.
                self.prefix_store.cancel(hit)
                hit = None
        return hit

    def _count_prefix(self, hit: Optional[PrefixHit]) -> None:
        """Record the per-server hit/miss counters for one ADMITTED
        lookup (no-op when the store is disabled — disabled servers must
        keep hit_ratio 0.0 without counting misses)."""
        if self.prefix_store is None:
            return
        if hit is None:
            self._prefix_misses += 1
            self._c_prefix_misses.inc()
            return
        self._prefix_hits += 1
        self._prefix_tokens_reused += hit.length
        self._c_prefix_hits.inc()
        self._c_prefix_reused.inc(hit.length)

    def _fill_slot_suffix(self, b: int, req: _Request,
                          hit: PrefixHit) -> None:
        """Prefix-hit admission: gather the matched ``hit.length`` prefix
        rows out of the store into fresh slot caches (device-to-device),
        prefill ONLY the suffix at that offset
        (``transformer.prefill_suffix``), and write the slot — the cold
        path minus the prefix's forward FLOPs. The suffix right-pads to
        the smallest bucket that still fits the arena (one executable per
        bucket, like cold admission); greedy tokens are identical to the
        cold path (tested)."""
        self._inj.fire("prefill")
        prompt, n, m = req.prompt, len(req.prompt), hit.length
        suffix, s_len = prompt[m:], n - m
        pad = self._suffix_pad(m, s_len)
        if pad > s_len:
            suffix = np.pad(suffix, (0, pad - s_len))
        # Span fence: _sample_first's int() transfers the sampled token,
        # which depends on the gather and the whole suffix forward.
        with obs.span(
            "serving.prefill_suffix",
            trace_id=self._trace, server=self._label, rid=req.rid, slot=b,
            prompt_len=n, reused=m, suffix_len=s_len,
            padded_len=len(suffix), tokens=s_len,
        ) as sp:
            self._inj.fire("store_gather")
            caches = self.prefix_store.materialize(hit, self.max_len)
            caches, last_logits, _pos = prefill_suffix(
                self.params, jnp.asarray(suffix)[None, :], self.cfg, caches,
                jnp.int32(m), return_logits=True, true_len=jnp.int32(s_len),
            )
            first = self._sample_first(last_logits)
        t_first = time.monotonic()  # the int() above fenced the forward
        self._sched.note_prefill(len(suffix), sp.duration_s)
        self._inj.fire("admission_commit")
        if self.paged:
            self._paged_commit(b, req, caches, 0)
        else:
            self.arena = _write_slot(self.arena, caches, b)
        # DEEPEN on hit: the slot caches now hold the WHOLE prompt's KV,
        # so a bucket boundary beyond the match (e.g. the first prompt of
        # a lineage was short and capped the stored depth) becomes
        # storable — insert() no-ops when the match was already the
        # deepest boundary, so arrival order cannot freeze reuse.
        self.prefix_store.insert(req.prompt, caches, 0)
        # pos is host-known (offset + true suffix length): no device read.
        self._finish_admission(b, req, first, n, t_first, hit=hit,
                               prefix_reused=m)

    def _suffix_bucket(self, m: int, s_len: int) -> Optional[int]:
        """The ONE suffix-bucket predicate (routing and padding must not
        drift apart): the smallest bucket that fits the suffix AND the
        arena (``m + pad <= max_len`` — ``dynamic_update_slice`` clamps
        out-of-range writes, which would silently shift real suffix
        rows), or None when no bucket qualifies."""
        return next(
            (k for k in self.prefill_buckets
             if k >= s_len and m + k <= self.max_len),
            None,
        )

    def _suffix_pad(self, m: int, s_len: int) -> int:
        """Padded suffix length for a prefix hit at ``m``: the
        :meth:`_suffix_bucket`, or the exact length when none qualifies
        (``m + s_len = prompt_len <= max_len`` always fits)."""
        pad = self._suffix_bucket(m, s_len)
        return pad if pad is not None else s_len

    def _fill_slots_suffix_batched(self, slots: list[int], pairs: list,
                                   pad_len: int) -> None:
        """Batched prefix-hit admission: N requests matching the SAME
        store segment at the same boundary ``m`` run one ``[N, pad_len]``
        suffix forward over one fanned-out prefix gather, scattering into
        their slots in one vectorized write (:func:`_write_slots`) — the
        suffix-path sibling of :meth:`_fill_slots_batched`, and the shape
        burst arrival with a shared system prompt actually takes. Per-row
        ``true_len`` masking keeps it exact."""
        self._inj.fire("prefill")
        n = len(pairs)
        m = pairs[0][1].length
        suffixes = np.zeros((n, pad_len), np.int32)
        true_lens = np.array(
            [len(req.prompt) - m for req, _ in pairs], np.int32
        )
        for i, (req, _) in enumerate(pairs):
            suffixes[i, : true_lens[i]] = req.prompt[m:]
        # Span fence: the firsts transfer below depends on the gather and
        # every row's suffix forward.
        with obs.span(
            "serving.prefill_suffix_batch",
            trace_id=self._trace, server=self._label, n=n, reused=m, padded_len=pad_len,
            tokens=int(true_lens.sum()),
            rids=[req.rid for req, _ in pairs], slots=list(slots),
        ) as sp:
            self._inj.fire("store_gather")
            caches = self.prefix_store.materialize(
                pairs[0][1], self.max_len, n=n
            )
            caches, last_logits, _pos = prefill_suffix(
                self.params, jnp.asarray(suffixes), self.cfg, caches,
                jnp.int32(m), return_logits=True,
                true_len=jnp.asarray(true_lens),
            )
            if self._do_sample:
                self._key, sub = jax.random.split(self._key)
                firsts = np.asarray(_next_token(  # jaxguard: allow(JG101) admission host read — batched first tokens, sanctioned sync
                    last_logits, sub, True, self._temp_dev,
                    self.top_k, self.top_p,
                ))
            else:
                firsts = np.asarray(jnp.argmax(last_logits, axis=-1))  # jaxguard: allow(JG101) admission host read — sanctioned sync
        t_first = time.monotonic()  # the firsts transfer fenced the forward
        self._sched.note_prefill(n * pad_len, sp.duration_s)
        self._inj.fire("admission_commit")
        if self.paged:
            self._paged_commit_batch(slots, [req for req, _ in pairs],
                                     caches)
        else:
            self.arena = _write_slots(
                self.arena, caches, jnp.asarray(np.asarray(slots, np.int32))
            )
        # DEEPEN on hit (see _fill_slot_suffix): rows now hold whole
        # prompts' KV; insert() no-ops unless a deeper bucket boundary
        # than the match became storable, and dedups within the group.
        for i, (req, _hit) in enumerate(pairs):
            self.prefix_store.insert(req.prompt, caches, i)
        self._batch_prefills += 1
        for i, (b, (req, hit)) in enumerate(zip(slots, pairs)):
            self._finish_admission(
                b, req, int(firsts[i]), m + int(true_lens[i]), t_first,
                hit=hit, batched=n, prefix_reused=m,
            )

    def _fill_slots_batched(self, slots: list[int], reqs: list,
                            pad_len: int) -> None:
        """Admit N same-bucket requests in ONE ``[N, pad_len]`` prefill
        forward (``transformer.prefill_batch``) and one vectorized arena
        scatter (:func:`_write_slots`) — N weight streams collapse to one,
        the dominant TTFT cost under burst arrival. Exactness is per-row
        ``true_len`` masking, same as the sequential bucket path."""
        self._inj.fire("prefill")
        n = len(reqs)
        prompts = np.zeros((n, pad_len), np.int32)
        true_lens = np.array([len(r.prompt) for r in reqs], np.int32)
        for i, req in enumerate(reqs):
            prompts[i, : len(req.prompt)] = req.prompt
        # Span fence: the firsts transfer below depends on every row's
        # full prefill forward.
        with obs.span(
            "serving.prefill_batch",
            trace_id=self._trace, server=self._label, n=n, padded_len=pad_len,
            tokens=int(true_lens.sum()),
            rids=[r.rid for r in reqs], slots=list(slots),
        ) as sp:
            caches, last_logits, pos = prefill_batch(
                self.params, jnp.asarray(prompts), self.cfg, self.max_len,
                jnp.asarray(true_lens), kv_quantized=self.kv_quant,
            )
            if self._do_sample:
                self._key, sub = jax.random.split(self._key)
                firsts = np.asarray(_next_token(  # jaxguard: allow(JG101) admission host read — batched first tokens, sanctioned sync
                    last_logits, sub, True, self._temp_dev,
                    self.top_k, self.top_p,
                ))
            else:
                firsts = np.asarray(jnp.argmax(last_logits, axis=-1))  # jaxguard: allow(JG101) admission host read — sanctioned sync
        t_first = time.monotonic()  # the firsts transfer fenced the forward
        self._sched.note_prefill(n * pad_len, sp.duration_s)
        self._inj.fire("admission_commit")
        if self.paged:
            self._paged_commit_batch(slots, reqs, caches)
        else:
            self.arena = _write_slots(
                self.arena, caches, jnp.asarray(np.asarray(slots, np.int32))
            )
        if self.prefix_store is not None:
            # Each row populates the store (insert() dedups identical
            # prefixes within the group via its longest-match check).
            for i, req in enumerate(reqs):
                self.prefix_store.insert(req.prompt, caches, i)
        self._batch_prefills += 1
        for i, (b, req) in enumerate(zip(slots, reqs)):
            self._finish_admission(
                b, req, int(firsts[i]), int(true_lens[i]), t_first, batched=n
            )

    def _admit(self) -> None:
        """Refill every free slot from the queue (FIFO). The admitted set
        each pass is the FIFO prefix that fits the free slots — batching
        only regroups requests WITHIN that prefix by padded length, so
        fairness is unchanged. Loops because a request can finish during
        its own prefill (eos / 1-token budget) and the freed slot should be
        re-offered immediately rather than idling for a whole chunk.

        Admission is one of strict mode's two SANCTIONED sync regions
        (the other: DeviceFence retire): the prefill uploads the prompt
        and the first-token sample reads it back — inherently
        synchronous, and outside the overlap window's steady state."""
        self._clock.push(LOOP_PHASE_ADMIT)
        try:
            with jaxapi.allow_transfer("admission prefill + first-token read"):
                self._admit_unguarded()
        finally:
            self._clock.pop()

    def _admit_unguarded(self) -> None:
        # Chunks already run THIS pass: the one-chunk-per-decode-round
        # budget must hold across partials too (a partial completing and
        # the next one starting in the same pass share the budget —
        # without this, back-to-back long prompts would stall one round
        # with two slices).
        pass_chunks = 0
        while True:
            # A CHUNKED admission in progress (ISSUE 8) is strictly
            # head-of-line: advance it before anything else admits or
            # resumes. Under SLO pressure it runs one chunk and yields the
            # pass back to decode; otherwise it completes here and the
            # loop continues to further admissions. Started work, so it
            # advances through a drain too (like preempted resumes).
            if self._partial is not None:
                done, pass_chunks = self._advance_partial(pass_chunks)
                if not done:
                    return
                continue
            free = [
                b for b in range(self.max_batch) if self._slot_req[b] is None
            ]
            if not free:
                return
            if self.paged and self._preempted and (
                    not self._queue
                    or self._preempted[0].req.rid < self._queue[0].rid):
                # Preempted requests are older than anything still queued
                # (strict FIFO: nothing admits past them while they wait
                # for the pool to drain) — EXCEPT crash-recovery replays,
                # which front-requeue lane residents that can be older
                # still; the rid comparison keeps global FIFO across both.
                self._clock.push(LOOP_PHASE_HOST)
                try:
                    resumed = self._resume_one(free[0])
                finally:
                    self._clock.pop()
                if not resumed:
                    if self._draining and len(free) == self.max_batch:
                        # Every lane is free and the full rebuilt pool
                        # still cannot hold the spill — it can never
                        # re-admit; fail it rather than wedging the drain.
                        pre = self._preempted.popleft()
                        self._fail_request(
                            pre.req, reason="drained",
                            error="drained mid-flight "
                                  f"({self._drain_reason}): cannot re-admit",
                        )
                        continue
                    return
                continue
            # Draining: preempted requests above still resume, and so do
            # crash-recovery REPLAYS (req.replays > 0 — work that already
            # started and lost its lane to a fault mid-drain must finish,
            # not fail as "drained before start"); nothing genuinely new
            # admits — _finish_drain fails it once the server idles.
            if not self._queue:
                return
            if self._draining and not self._queue[0].replays:
                return
            # SLO-aware deferral (ISSUE 8): consult the policy BEFORE the
            # admission pass. Under projected-ITL pressure the queue head
            # starts a CHUNKED admission instead of a whole prefill —
            # head-of-line, so FIFO is preserved by construction (nothing
            # admits past it until its chunks complete above).
            directive = self._sched.directive(
                live_lanes=sum(r is not None for r in self._slot_req),
                pending_tokens=self._cold_cost(self._queue[0]),
            )
            if not directive.admit:
                if not self._start_partial():
                    return  # paged reservation failed: head-of-line wait
                continue  # the partial branch runs this pass's chunk
            # The admitted set this pass: the FIFO prefix that fits the
            # free lanes AND (paged) whose block reservations succeed —
            # the first request the pool cannot hold stops admission
            # (head-of-line, preserving FIFO; it re-offers when the pool
            # drains). Lookups pin their hit; a failed reservation
            # unwinds the lookup — pin and store counters — before any
            # monotonic counter recorded it.
            # Crash-unwind bookkeeping (ISSUE 7): each popped request is
            # appended to ``_admitting`` IN THE SAME STEP — from that
            # moment it is in neither the queue nor a lane, and a fault
            # anywhere in this pass (a later request's reservation, the
            # fill paths below) must find it there to requeue it, or it
            # would vanish. _finish_admission retires entries one by one.
            take = self._admitting = []
            while self._queue and len(take) < len(free):
                req = self._queue[0]
                if self._draining and not req.replays:
                    # The replayed prefix is admitted; everything behind
                    # it never started and stays queued for _finish_drain.
                    break
                # Attribute a reservation-phase fault to the head-of-line
                # request being reserved, not the innocent lane residents
                # (_recover pulls a blamed-but-still-queued request into
                # the lost set so its quarantine streak is tracked).
                self._admit_current = [req]
                hit = self._prefix_lookup_raw(req)
                try:
                    reserved = (not self.paged
                                or self._reserve_lane_blocks(req, hit))
                except BaseException:
                    # A fault inside the reservation (pool_alloc seam, or
                    # a real allocator error): the request is still queued
                    # but its lookup pin must not leak past the raise.
                    if self.prefix_store is not None:
                        self.prefix_store.unlookup(hit)
                    raise
                self._admit_current = []
                if not reserved:
                    if self.prefix_store is not None:
                        # Reverse the lookup wholesale (pin AND counters,
                        # miss included): the request stays queued and
                        # re-looks-up when the pool drains — cancel()
                        # would count every retry pass as a tier miss.
                        self.prefix_store.unlookup(hit)
                    break
                self._count_prefix(hit)
                self._queue.popleft()
                self._sched.note_queue_delay(
                    time.monotonic() - req.t_submit
                )
                if req.state != PHASE_RECOVERY:
                    # Ledger: admission granted. A crash-recovery replay
                    # stays in its recovery phase through the re-prefill
                    # (the replay IS the recovery cost).
                    self._ledger_to(req, PHASE_PREFILL)
                take.append((req, hit))
            if not take:
                return
            # Prefix-store routing first: a hit takes the suffix-only path
            # (its executable is keyed to the SUFFIX bucket, not the
            # prompt's), misses proceed to cold grouping below. Hits on
            # the SAME segment/boundary/suffix-shape batch into one
            # forward, mirroring the cold grouping. Within-pass reordering
            # between hits and cold groups is the same pass-granular FIFO
            # trade the bucket grouping already makes — the admitted SET
            # is still the FIFO prefix.
            hit_groups: dict[tuple, list] = {}
            # Group by PADDED length (bucket when one fits, exact length
            # otherwise): rows of one prefill executable must share a
            # shape. dict preserves insertion order, so groups stay FIFO.
            groups: dict[int, list] = {}
            for req, hit in take:
                if hit is not None:
                    s_len = len(req.prompt) - hit.length
                    pad_len = self._suffix_pad(hit.length, s_len)
                    hit_groups.setdefault(
                        (id(hit.segment), hit.length, pad_len), []
                    ).append((req, hit))
                    continue
                true_len = len(req.prompt)
                bucket = next(
                    (k for k in self.prefill_buckets if k >= true_len), None
                )
                groups.setdefault(bucket or true_len, []).append(req)
            it = iter(free)
            for (_seg, _m, pad_len), pairs in hit_groups.items():
                if len(pairs) >= 2 and self._can_batch_prefill:
                    self._admit_current = [req for req, _ in pairs]
                    self._fill_slots_suffix_batched(
                        [next(it) for _ in pairs], pairs, pad_len
                    )
                else:
                    for req, hit in pairs:
                        self._admit_current = [req]
                        self._fill_slot_suffix(next(it), req, hit)
            for pad_len, reqs in groups.items():
                if len(reqs) >= 2 and self._can_batch_prefill:
                    self._admit_current = list(reqs)
                    self._fill_slots_batched(
                        [next(it) for _ in reqs], reqs, pad_len
                    )
                else:
                    # Recover the group's bucket-vs-exact decision from its
                    # key: exact-length groups exist only when no bucket
                    # fit, so a key matching a bucket IS that bucket.
                    bucket = (
                        pad_len if pad_len in self.prefill_buckets else None
                    )
                    for req in reqs:
                        self._admit_current = [req]
                        self._fill_slot(next(it), req, bucket)
            self._admitting = []
            self._admit_current = []

    # ----- chunked prefill (ISSUE 8) ---------------------------------------

    def _cold_cost(self, req: _Request) -> int:
        """The padded prefill tokens a whole cold admission of ``req``
        would run — the scheduler's projection input. Deliberately the
        COLD cost even when a prefix hit would shrink it: the lookup pins
        state, so it runs only once the admission path is chosen, and an
        overestimate merely chunks an admission whose first slice then
        completes it."""
        n = len(req.prompt)
        bucket = next((k for k in self.prefill_buckets if k >= n), None)
        return bucket or n

    def _start_partial(self) -> bool:
        """Begin a CHUNKED admission of the queue head: prefix lookup and
        paged block reservation exactly like the normal pass (same unwind
        rules), then park the request as the in-progress partial —
        :meth:`_advance_partial` runs its chunk forwards. False when the
        paged reservation failed (the head re-offers when the pool
        drains; the lookup is fully unwound first)."""
        req = self._queue[0]
        self._admit_current = [req]
        hit = self._prefix_lookup_raw(req)
        try:
            reserved = (not self.paged
                        or self._reserve_lane_blocks(req, hit))
        except BaseException:
            if self.prefix_store is not None:
                self.prefix_store.unlookup(hit)
            raise
        self._admit_current = []
        if not reserved:
            if self.prefix_store is not None:
                self.prefix_store.unlookup(hit)
            return False
        self._count_prefix(hit)
        self._queue.popleft()
        self._sched.note_queue_delay(time.monotonic() - req.t_submit)
        if req.state != PHASE_RECOVERY:
            # Ledger: chunked admission granted — the whole chunked fill
            # (slices AND the deferred rounds between them) is prefill.
            self._ledger_to(req, PHASE_PREFILL)
        # In _admitting from this moment: in neither the queue nor a lane,
        # so a mid-chunk crash must find it here to replay it (ISSUE 7).
        self._admitting = [(req, hit)]
        if hit is not None:
            self._inj.fire("store_gather")
            caches = self.prefix_store.materialize(hit, self.max_len)
            offset = hit.length
        else:
            caches = init_kv_caches(
                self.cfg, 1, self.max_len, quantized=self.kv_quant
            )
            offset = 0
        self._partial = _PartialPrefill(
            req=req, hit=hit, caches=caches, offset=offset, reused=offset
        )
        return True

    def _advance_partial(self, ran: int = 0) -> tuple[bool, int]:
        """Advance the in-progress chunked admission. While the policy
        defers (projected ITL over the SLO) it runs AT MOST ONE chunk per
        pass — ``ran`` carries chunks the pass already spent (a previous
        partial's), so the per-round prefill budget holds across
        back-to-back admissions; once the pressure clears (or the final
        slice is reached) it runs the rest to completion. Returns
        ``(completed, ran')``: completed=True when the admission landed in
        a lane (the caller loops for more admissions), False when this
        pass's chunk budget is spent.

        FUSED PLAN (ISSUE 13): when the policy defers AND asks for
        fusion (``Directive.fused``) AND somebody is decoding to fuse
        with, the chunk does not run here at all — ``_fuse_pending``
        arms the next ``_dispatch_decode``, which batches the slice into
        the decode executable (one dispatch, one fence). With no live
        decode lanes there is nothing to fuse with and the inline slice
        (or run-to-completion) path below is strictly better."""
        self._fuse_pending = False  # re-decided every pass
        while True:
            p = self._partial
            remaining = len(p.req.prompt) - p.offset
            if remaining <= 0:
                # Every slice is already IN FLIGHT on a fused dispatch;
                # the final slice's retire commits the admission. Nothing
                # to run inline, and head-of-line holds until then.
                return False, ran
            live = sum(r is not None for r in self._slot_req)
            d = self._sched.directive(
                live_lanes=live, pending_tokens=remaining, partial=True,
            )
            if not d.admit:
                if d.fused and self._fused_ok and live > 0:
                    # The slice rides the next decode dispatch instead of
                    # stalling a round of its own. Still a DEFERRAL —
                    # the pass chose a chunk over whole admission — so
                    # the defer counters/event keep their meaning; the
                    # fused field says no slice round was paid for it.
                    self._fuse_pending = True
                    self._sched.defers += 1
                    self._c_sched_defer.inc()
                    self._emit(
                        "sched_defer", rid=p.req.rid, offset=p.offset,
                        remaining=remaining, queued=len(self._queue),
                        projected_itl_ms=d.projected_itl_ms,
                        slo_ms=self._sched.slo_ms, fused=1,
                    )
                    return False, ran
                if ran:
                    return False, ran  # one chunk per decode dispatch
                self._sched.defers += 1
                self._c_sched_defer.inc()
                self._emit(
                    "sched_defer", rid=p.req.rid, offset=p.offset,
                    remaining=remaining, queued=len(self._queue),
                    projected_itl_ms=d.projected_itl_ms,
                    slo_ms=self._sched.slo_ms,
                )
            done = self._prefill_one_chunk(p)
            ran += 1
            if done:
                return True, ran

    def _prefill_one_chunk(self, p: _PartialPrefill) -> bool:
        """One ``prefill_chunk``-token slice of a chunked admission: a
        ``prefill_suffix`` forward at the partial's offset over its own
        standalone caches (the PR 5 resume machinery — traced offset and
        true_len, so ONE suffix executable of the chunk's width serves
        every chunk at every offset). Intermediate slices fence before
        returning (the round budget is WALL time — an unfenced dispatch
        would just move the stall to the next decode fence); the final
        slice samples the first token and lands the admission through the
        shared commit + epilogue, bit-identical to the unchunked path
        (tested). Slices are all width ``chunk_tokens`` (the final one
        right-padded + true_len-masked) except near the arena end, where
        padding would spill past ``max_len`` and the slice falls back to
        exact width. True when the admission completed."""
        req = p.req
        n = len(req.prompt)
        suffix, take, width = self._slice_geometry(p)
        last = p.offset + take >= n
        # Blast-radius attribution: a fault in this chunk implicates only
        # this request (stays set through the raise; _recover reads it).
        self._admit_current = [req]
        self._inj.fire("sched_tick")
        self._inj.fire("prefill")
        with obs.span(
            "serving.prefill_chunk",
            trace_id=self._trace, server=self._label, rid=req.rid, offset=p.offset,
            chunk_len=take, padded_len=width, tokens=take,
        ) as sp:
            caches, last_logits, _pos = prefill_suffix(
                self.params, jnp.asarray(suffix)[None, :], self.cfg,
                p.caches, jnp.int32(p.offset), return_logits=True,
                true_len=jnp.int32(take),
            )
            if last:
                first = self._sample_first(last_logits)
            else:
                self._fence_wait(
                    lambda: jax.block_until_ready(last_logits),
                    seam="fence", inject=False,
                )
        p.caches = caches
        p.offset += take
        p.chunks += 1
        self._sched.chunks += 1
        self._c_sched_chunk.inc()
        self._sched.note_prefill(width, sp.duration_s)
        if not last:
            self._admit_current = []
            return False
        t_first = time.monotonic()  # the sample's int() fenced the forward
        self._commit_partial(p, first, t_first)
        return True

    def _commit_partial(self, p: _PartialPrefill, first: int,
                        t_first: float) -> None:
        """The final-slice commit BOTH chunked-completion paths share —
        the inline :meth:`_prefill_one_chunk` and the fused
        :meth:`_apply_fused` (ISSUE 13): land the partial's caches in a
        lane and run the standard admission epilogue. One body, so the
        two paths cannot drift (the bit-identity claim rests on it).
        Lane free by construction: one existed when the partial started
        and nothing fills lanes while it is head-of-line. A partial with
        ``fused`` slices counts as a fused admission wherever its final
        slice ran — earlier slices already rode decode dispatches."""
        req = p.req
        self._inj.fire("admission_commit")
        b = next(
            i for i in range(self.max_batch) if self._slot_req[i] is None
        )
        if self.paged:
            self._paged_commit(b, req, p.caches, 0)
        else:
            self.arena = _write_slot(self.arena, p.caches, b)
        if self.prefix_store is not None:
            # Same DEEPEN-on-completion contract as the suffix fill path:
            # the caches now hold the whole prompt's KV.
            self.prefix_store.insert(req.prompt, p.caches, 0)
        self._partial = None
        if p.fused:
            self._fused_admissions += 1
            self._c_fused.inc()
        self._finish_admission(
            b, req, first, len(req.prompt), t_first, hit=p.hit,
            prefix_reused=p.reused, chunked=p.chunks, fused=p.fused,
        )
        self._admit_current = []

    def _apply_fused(self, fc: Optional[_FusedChunk]) -> None:
        """Land one admission slice that rode a decode dispatch (ISSUE
        13). Intermediate slices were fully booked at dispatch (offset,
        chunk counters); only the FINAL slice has retire-side work:
        sample the first token from the slice's logits future, stamp
        TTFT at that fence, and commit the admission through the same
        arena-write / store-insert / ``_finish_admission`` epilogue the
        inline chunk path uses — bit-identical by construction. A
        recovery that discarded the partial mid-flight (its caches were
        donated into the failed dispatch) leaves ``self._partial``
        changed; the stale record is dropped, and the request replays
        from its prompt via ``_admitting`` as usual."""
        if fc is None:
            return
        p = fc.partial
        if self._partial is not p or not fc.last:
            return
        with jaxapi.allow_transfer("fused admission commit + first token"):
            first = self._sample_first(fc.logits)
            t_first = time.monotonic()  # the int() above fenced the slice
            self._admit_current = [p.req]
            self._commit_partial(p, first, t_first)

    def _maybe_finish(self, b: int, new_tokens: list) -> None:
        req = self._slot_req[b]
        if req is None:
            return
        hit_eos = self.eos_id is not None and self.eos_id in new_tokens
        if hit_eos:
            req.out = req.out[: req.out.index(self.eos_id) + 1]
        if hit_eos or len(req.out) >= req.max_new_tokens:
            req.out = req.out[: req.max_new_tokens]
            self._results[req.rid] = np.asarray(req.out, np.int32)
            req.done = True
            self._finish_trace(req, outcome="completed")
            self._slot_req[b] = None
            handle = self._slot_prefix[b]
            if handle is not None:
                # Unpin the request's prefix segment: it becomes LRU-
                # evictable again once no other in-flight request holds it.
                self.prefix_store.release(handle)
                self._slot_prefix[b] = None
            if self.paged:
                # Return the lane's block refs: private blocks recycle
                # now, tier-shared ones once the tier (and any other lane)
                # lets go. The table resets to SCRATCH so in-flight writes
                # for this lane land in the scratch block.
                self._free_lane(b)

    # ----- paged pool scheduling (ISSUE 6) ---------------------------------

    def _set_lane_table(self, b: int, table: list) -> None:
        """One writer for a lane's block table and its device mirror.
        Entries past the allocation stay SCRATCH (writes of a finished or
        overrunning lane land in the scratch block — never another lane's
        KV; the paged view remaps SCRATCH entries to the never-written
        ZERO block, so reads past the allocation see fresh-arena zeros,
        and positions <= pos always sit inside the allocation by
        construction)."""
        self._lane_blocks[b] = list(table)
        self._bt_host[b, : len(table)] = table
        self._bt_host[b, len(table):] = SCRATCH_BLOCK

    def _free_lane(self, b: int) -> None:
        self.kv_pool.unref(self._lane_blocks[b])
        self._set_lane_table(b, [])

    def _alloc_blocks(self, n: int) -> Optional[list]:
        """``n`` pool blocks, evicting unreferenced prefix-tier segments
        LRU-first under pressure (decode outranks the cache); None when
        live state holds everything."""
        self._inj.fire("pool_alloc")
        got = self.kv_pool.try_alloc(n)
        while got is None:
            tier = self.prefix_store
            if not isinstance(tier, PagedPrefixTier) or not tier.evict_one():
                return None
            got = self.kv_pool.try_alloc(n)
        return got

    def _reserve_lane_blocks(self, req: _Request,
                             hit: Optional[PrefixHit]) -> bool:
        """Reserve the blocks ``req``'s admission scatter needs BEFORE its
        prefill forward runs (a failed reservation must requeue, not waste
        a forward). A hit shares the tier segment's fully-covered blocks
        (pool-refcounted, read-only) and allocates private blocks for the
        rest — including the copy-on-write boundary block when the match
        is not block-aligned. The plan rides in ``_plans`` until the fill
        path commits it."""
        bs = self.kv_block
        n = len(req.prompt)
        shared: list = []
        if hit is not None:
            m = hit.length
            rows = m + self._suffix_pad(m, n - m)
            shared = self.prefix_store.shared_blocks(hit)
        else:
            bucket = next(
                (k for k in self.prefill_buckets if k >= n), None
            )
            rows = bucket or n
        need = -(-rows // bs) - len(shared)
        priv = self._alloc_blocks(need)
        if priv is None:
            return False
        self.kv_pool.ref(shared)
        if hit is not None and hit.length % bs:
            # The boundary block is only partially covered by the match:
            # its private copy is filled from the materialized cache by
            # the admission scatter — the copy-on-write.
            self._cow_copies += 1
            self._c_cow.inc()
        self._plans[req.rid] = _LanePlan(shared + priv, len(shared))
        return True

    def _paged_commit(self, b: int, req: _Request, caches, row) -> None:
        """Land one admission's cache row in the pool: scatter the
        PRIVATE table entries from the freshly prefilled caches (shared
        tier blocks are masked with SCRATCH — their rows are already
        resident and must not be rewritten under the readers sharing
        them) and install the lane table."""
        plan = self._plans.pop(req.rid)
        scatter = (
            [SCRATCH_BLOCK] * plan.n_shared + plan.table[plan.n_shared:]
        )
        self.kv_pool.arena = pool_write_seq(
            self.kv_pool.arena, caches, jnp.int32(row),
            jnp.asarray(np.asarray(scatter, np.int32)),
            block_size=self.kv_block,
        )
        self._set_lane_table(b, plan.table)

    def _paged_commit_batch(self, slots: list[int], reqs: list,
                            caches) -> None:
        """Batched :meth:`_paged_commit`: land a whole same-bucket
        admission group with ONE donated :func:`pool_write_batch`
        dispatch (cache row ``i`` → ``slots[i]``'s private blocks)
        instead of N sequential pool scatters. Shared tier entries are
        SCRATCH-masked per row exactly as in the single form, and tables
        are SCRATCH-padded to the group's widest plan — pad and mask
        entries collide only on SCRATCH, which nothing live reads."""
        plans = [self._plans.pop(req.rid) for req in reqs]
        width = max(len(p.table) for p in plans)
        tables = np.full((len(plans), width), SCRATCH_BLOCK, np.int32)
        for i, plan in enumerate(plans):
            tables[i, plan.n_shared:len(plan.table)] = \
                plan.table[plan.n_shared:]
        self.kv_pool.arena = pool_write_batch(
            self.kv_pool.arena, caches, jnp.asarray(tables),
            block_size=self.kv_block,
        )
        for b, plan in zip(slots, plans):
            self._set_lane_table(b, plan.table)

    def _full_table(self, b: int) -> np.ndarray:
        """The lane's table at FULL width (SCRATCH-padded) — what the
        single spill/restore executable takes."""
        return np.asarray(self._bt_host[b], np.int32)

    def _preempt_lane(self, b: int, reason: str) -> None:
        """Preempt the request in lane ``b`` under pool pressure: spill
        its written KV rows to host (block-granular D2D gather, then one
        sanctioned D2H copy — preemption is a scheduling slow path, not
        the decode hot path), release its blocks and prefix pin, and
        requeue it FIFO. Greedy output is unchanged: restore re-lands the
        spilled rows verbatim and decode resumes at the same ``pos`` with
        the same ``last`` token. Tokens of an in-flight chunk carrying
        this lane are discarded by retire's slot-identity check — wasted
        FLOPs, never wrong tokens."""
        req = self._slot_req[b]
        with jaxapi.allow_transfer("kv pool preemption spill"):
            spilled = jax.tree.map(
                np.asarray,  # jaxguard: allow(JG101) preemption spill — sanctioned slow-path sync (guarded by allow_transfer)
                pool_gather_rows(
                    self.kv_pool.arena, jnp.asarray(self._full_table(b)),
                    block_size=self.kv_block,
                ),
            )
        self.kv_pool.unref(self._lane_blocks[b])
        self._set_lane_table(b, [])
        handle = self._slot_prefix[b]
        if handle is not None:
            self.prefix_store.release(handle)
            self._slot_prefix[b] = None
        # Keep the wait list rid-SORTED: _ensure_blocks preempts
        # youngest-first (descending rid) within a pass, and older
        # requests may already be waiting — resume order must be the
        # SUBMIT order for the strict-FIFO requeue guarantee to hold.
        self._preempted.append(_Preempted(
            req=req, kv=spilled, pos=int(self._pos[b]),
            last=int(self._last[b]),
        ))
        self._preempted = deque(
            sorted(self._preempted, key=lambda p: p.req.rid)
        )
        self._slot_req[b] = None
        self._preemptions += 1
        self._c_preempt.inc()
        if self._kv_host is not None:
            # The spill IS a demotion of an idle session to the host
            # tier (ISSUE 14): account its tokens there — PINNED
            # (in-flight state must never LRU out, and correctness
            # outranks the budget, so it may overflow) — so
            # kv_host_blocks reports the real host-resident population.
            self._kv_host.put(
                ("spill", req.rid), int(self._pos[b]), pinned=True
            )
            self._host_demotions += 1
            self._c_kv_demote.inc()
        self._ledger_to(req, PHASE_PREEMPTED)  # spilled: decode stops here
        self._emit(
            "kv_preempt", rid=req.rid, pos=int(self._pos[b]),
            reason=reason, waiting=len(self._preempted),
            queued=len(self._queue),
        )

    def _resume_one(self, b: int) -> bool:
        """Re-admit the OLDEST preempted request into lane ``b``: allocate
        fresh private blocks for its spilled rows, re-land them (one
        full-width restore executable), and resume decode at the exact
        position the spill cut. False when the pool still cannot hold it
        (the caller waits — strict FIFO, nothing admits past it)."""
        pre = self._preempted[0]
        nb = -(-pre.pos // self.kv_block)
        blocks = self._alloc_blocks(nb)
        if blocks is None:
            return False
        full = np.full(self._nb_max, SCRATCH_BLOCK, np.int32)
        full[:nb] = blocks
        # Consume the staged resume prefetch when it targeted this
        # request (ISSUE 14): the H2D upload started while the previous
        # decode chunk was still in flight, so the restore scatter lands
        # an already-overlapped transfer instead of serializing one here.
        staged = self._resume_stage_rid == pre.req.rid
        if staged:
            rows = self._resume_stage_rows
        else:
            # Prefetch MISS: the staged overlap targeted another rid (or
            # never ran), so this upload serializes inside the decode
            # round — sanctioned as the slow path the staging exists to
            # make rare (JG403 counts any unsanctioned sibling).
            with jaxapi.allow_transfer(
                    "kv resume prefetch miss (serialized H2D re-land)"):
                rows = self._kv_host_upload(pre.kv, paged_rows=True)
        self._resume_stage_rid = None
        self._resume_stage_rows = None
        self.kv_pool.arena = pool_scatter_rows(
            self.kv_pool.arena, rows,
            jnp.asarray(full), block_size=self.kv_block,
        )
        self._set_lane_table(b, blocks)
        self._slot_req[b] = pre.req
        self._slot_prefix[b] = None
        self._pos[b] = pre.pos
        self._last[b] = pre.last
        self._fresh_rows.add(b)  # overlap: override the in-flight row
        # Popped only once LANDED: a recoverable fault inside the restore
        # scatter must still find the request in _preempted (the lost-set
        # source for spilled work) or it would vanish from recovery.
        self._preempted.popleft()
        if self._kv_host is not None:
            self._kv_host.pop(("spill", pre.req.rid))
            self._host_prefetches += 1
            self._c_kv_prefetch.inc()
        self._ledger_to(pre.req, self._decode_state())  # restored: decoding
        self._emit(
            "kv_resume", rid=pre.req.rid, pos=pre.pos,
            waiting=len(self._preempted), queued=len(self._queue),
            prefetched=int(staged),
        )
        return True

    def _ensure_blocks(self) -> None:
        """Grow every live lane's block table to cover the next dispatch
        window (token-budget continuous batching's allocation step),
        OLDEST request first. On pool exhaustion the YOUNGEST live lane is
        preempted (spilled + requeued FIFO) until the older lanes fit —
        progress for the head of the line is guaranteed because a drained
        pool holds at least one full-length request (checked at
        construction). Growth is capped by each request's own budget
        (``prompt + max_new_tokens``): writes past a finished request's
        budget aim at SCRATCH by table-filler design, so no block is ever
        spent on provably dead rows."""
        if not self.paged:
            return
        bs = self.kv_block
        # Overlap keeps one chunk in flight beyond the host-known pos, so
        # the next dispatch can write up to two dispatch windows ahead of
        # it — at decode_steps=K that window is chunk × K tokens (ISSUE
        # 13: the reservation must cover every token one dispatch can
        # write; the on-device budget mask bounds the tail at each
        # request's own cap, which the ``cap`` term below already is).
        lookahead = (
            # Persistent rounds (ISSUE 20) reserve the WHOLE while_loop
            # window up front — the loop bump-allocates against the
            # reservation on device and exits early (reason "window")
            # when a live lane would outrun it; no mid-round host
            # allocation exists to grow a table.
            self._persistent_cap if self._persistent
            else self._dispatch_steps * (2 if self.overlap else 1)
        )
        lanes = sorted(
            (b for b in range(self.max_batch)
             if self._slot_req[b] is not None),
            key=lambda b: self._slot_req[b].rid,
        )
        for b in lanes:
            req = self._slot_req[b]
            if req is None:
                continue  # preempted while growing an older lane
            cap = -(-(len(req.prompt) + req.max_new_tokens) // bs)
            need = min(
                -(-(int(self._pos[b]) + lookahead) // bs), cap, self._nb_max
            )
            while (len(self._lane_blocks[b]) < need
                   and self._slot_req[b] is req):
                got = self._alloc_blocks(need - len(self._lane_blocks[b]))
                if got is not None:
                    self._set_lane_table(b, self._lane_blocks[b] + got)
                    break
                victim = max(
                    (v for v in range(self.max_batch)
                     if self._slot_req[v] is not None),
                    key=lambda v: self._slot_req[v].rid,
                )
                self._clock.push(LOOP_PHASE_HOST)
                try:
                    self._preempt_lane(victim, reason="pool_exhausted")
                finally:
                    self._clock.pop()

    def _stage_resume_prefetch(self) -> None:
        """Async resume prefetch (ISSUE 14): start the H2D upload of the
        OLDEST preempted request's spilled rows while a decode chunk is
        in flight, so by the time ``_resume_one`` lands them the
        transfer has overlapped device compute instead of serializing
        the admission pass. Armed only with the host tier (the knob that
        buys host RAM for idle sessions); one staged upload at a time,
        invalidated whenever the device state rebuilds. The upload rides
        the same sanctioned ``allow_transfer`` class as the restore it
        feeds; ordering against the in-flight chunk is by data
        dependency (the restore scatter consumes the uploaded rows
        inside jit), so strict mode stays clean."""
        if (self._kv_host is None or not self.paged
                or not self._preempted):
            return
        pre = self._preempted[0]
        if self._resume_stage_rid == pre.req.rid:
            return  # already staged for the current head
        with jaxapi.allow_transfer(
                "kv host tier resume prefetch (H2D upload overlapping "
                "the in-flight decode chunk)"):
            self._resume_stage_rows = self._kv_host_upload(
                pre.kv, paged_rows=True
            )
            self._resume_stage_rid = pre.req.rid

    def step(self) -> bool:
        """One SUPERVISED scheduler round. Lock-step (``overlap=False``
        or speculative): refill free slots, then one fenced decode chunk.
        Pipelined (default): dispatch the next chunk from the in-flight
        chunk's device state, THEN retire the in-flight chunk's tokens
        while the device runs — see :meth:`_step_overlapped`. Returns
        False when queue, slots, and pipeline are all empty.

        The recovery supervisor (ISSUE 7) wraps the round: a recoverable
        failure (:func:`.resilience.recoverable` — injected faults,
        watchdog stalls, transient XLA statuses) triggers
        :meth:`_recover` instead of unwinding ``run()``; everything else
        (user bugs, strict-mode guard trips) propagates unchanged. A
        successful round resets failure streaks and takes the periodic
        recovery checkpoint; a requested drain finishes here once the
        server idles.

        Under :attr:`strict` the overlapped round runs inside
        ``compat.jaxapi.strict_mode`` — the transfer guard covers the
        whole dispatch→retire window (lock-step and speculative rounds
        fence synchronously by design, so they are not guarded)."""
        if self._draining and not self._drain_announced:
            # Deferred from request_drain (async-signal-safe there): the
            # loop announces the drain from its own thread.
            self._drain_announced = True
            self._emit(
                "drain_begin", reason=self._drain_reason,
                queued=len(self._queue),
                slots_busy=sum(r is not None for r in self._slot_req),
            )
        try:
            alive = self._step_inner()
            # The periodic checkpoint runs INSIDE the supervised region:
            # its device→host gather is itself a dispatch that can raise
            # transiently, and the crash-tolerance machinery must not be
            # the thing that unwinds run().
            self._note_progress()
        except BaseException as exc:
            if not (self._supervised and resilience.recoverable(exc)):
                # Terminal for the serving loop ("not ours to catch":
                # user bugs, strict-mode guard trips, disabled recovery).
                # Record the incident on the stream AND the always-armed
                # flight-recorder ring before unwinding — the ring dumps
                # its postmortem on this event (obs/flight.py).
                self._emit(
                    "fatal_error",
                    error=f"{type(exc).__name__}: {exc}"[:200],
                    queued=len(self._queue),
                    slots_busy=sum(r is not None for r in self._slot_req),
                )
                raise
            alive = self._recover(exc)
        if self._draining and not self._drain_done and self._drain_idle():
            self._finish_drain()
            alive = False
        # Heartbeat cadence check: one int compare per round; the flush
        # (force=) when the loop idles out leaves a final partial
        # interval on the stream and closes any watchdog profile window.
        self._maybe_heartbeat(force=not alive)
        if not alive and self._watchdog is not None:
            self._watchdog.close()
        return alive

    def _step_inner(self) -> bool:
        # Persistent rounds run lock-step (ISSUE 20): the host must read
        # the delivered count at the fence before it can schedule the
        # next round — there is no fixed-shape in-flight state to
        # pipeline against, and the while_loop already keeps the device
        # busy for the whole round the overlap would have covered.
        if self.overlap and not self.speculative_k and not self._persistent:
            if self.strict:
                with jaxapi.strict_mode(scope="serving.decode_dispatch"):
                    return self._step_overlapped()
            return self._step_overlapped()
        return self._step_lockstep()

    # ----- recovery supervisor (ISSUE 7) -----------------------------------

    def _note_progress(self) -> None:
        """A round completed without a fault: reset the backoff streak
        and every surviving lane resident's implication count, then take
        the periodic recovery checkpoint when the cadence says so."""
        self._fail_streak = 0
        for req in self._slot_req:
            if req is not None:
                req.fails = 0
        if (self._ckpt_every
                and self._rounds - self._ckpt_round >= self._ckpt_every):
            self._clock.push(LOOP_PHASE_HOST)
            try:
                self._checkpoint()
            finally:
                self._clock.pop()

    def _drain_idle(self) -> bool:
        """Nothing in flight anymore: lanes empty, pipeline empty, no
        mid-admission work (preempted requests resume through _admit
        while lanes free up, so an empty lane set with an empty pipeline
        means they drained too — or could not fit and will be failed)."""
        return (
            self._inflight is None
            and not self._admitting
            and all(r is None for r in self._slot_req)
            # Crash-recovery replays in the queue are STARTED work — a
            # fault mid-drain requeued them; they re-admit (the drain
            # gate in _admit lets them through) before the drain closes.
            and not any(r.replays for r in self._queue)
            # Preempted spills are started work too: with lanes now free
            # the next _admit resumes them (or fails them in place when
            # even the full pool cannot hold the spill) — the drain must
            # not close over their heads.
            and not (self.paged and self._preempted)
        )

    def _checkpoint(self) -> None:
        """Snapshot every live lane's KV to host plus the scheduling
        state a restore needs (the PR 6 spill layout). One sanctioned
        ``allow_transfer`` region on the scheduling slow path — at the
        checkpoint cadence, never per round; under overlap the gather
        orders after the in-flight chunk's donated writes, and the host
        ``pos``/``out`` snapshot is the RETIRED state, which is exactly
        what a restore replays from (rows past ``pos`` are masked)."""
        entries: dict[int, _CkptEntry] = {}
        tokens = 0
        with jaxapi.allow_transfer("recovery checkpoint spill"):
            for b in range(self.max_batch):
                req = self._slot_req[b]
                if req is None or req.done:
                    continue
                # Each lane gather is watchdog-bounded (inject=False: the
                # checkpoint is recovery machinery, not an injection seam
                # — chaos schedules keep their crossing counts) so a hung
                # transport raises into the supervisor here too.
                if self.paged:
                    kv = self._fence_wait(
                        lambda b=b: jax.tree.map(
                            np.asarray,  # checkpoint spill — sanctioned fence-wrapped sync (guarded by allow_transfer)
                            pool_gather_rows(
                                self.kv_pool.arena,
                                jnp.asarray(self._full_table(b)),
                                block_size=self.kv_block,
                            ),
                        ),
                        seam="checkpoint", inject=False,
                    )
                else:
                    kv = self._fence_wait(
                        lambda b=b: jax.tree.map(
                            lambda a: np.asarray(a[:, b:b + 1]),  # checkpoint spill — sanctioned fence-wrapped sync (guarded by allow_transfer)
                            self.arena,
                        ),
                        seam="checkpoint", inject=False,
                    )
                entries[req.rid] = _CkptEntry(
                    req=req, out=list(req.out), pos=int(self._pos[b]),
                    last=int(self._last[b]), kv=kv,
                )
                tokens += int(self._pos[b])
        self._ckpt = entries
        self._ckpt_round = self._rounds
        self._checkpoints += 1
        self._emit(
            "checkpoint", round=self._rounds, lanes=len(entries),
            tokens=tokens,
        )

    def _fail_request(self, req: _Request, reason: str,
                      error: str = "") -> None:
        """Terminal per-request failure: surfaced through
        :meth:`failures` and a ``request_failed`` event — never silently
        dropped, never retried again."""
        req.done = True
        self._failures[req.rid] = error or reason
        if self._kv_host is not None:
            # A spilled session that terminally fails releases its
            # host-tier accounting (drained mid-flight, quarantined,
            # chip_lost) — the pinned entry must not leak capacity.
            self._kv_host.pop(("spill", req.rid))
        if self._resume_stage_rid == req.rid:
            # And its staged resume upload: with the request dead the
            # stage would never be consumed, pinning a full spill's
            # device arrays for the server's remaining lifetime.
            self._resume_stage_rid = None
            self._resume_stage_rows = None
        self._emit(
            "request_failed", rid=req.rid, reason=reason,
            error=(error or reason)[:200], emitted=len(req.out),
        )
        self._finish_trace(req, outcome="failed", reason=reason)

    def _recover(self, exc: BaseException) -> bool:
        """Rebuild after a failed round. The device state is rebuilt from
        scratch (the failed round may have poisoned donated buffers);
        every implicated request either restores from the last host
        checkpoint (bounded replay — the post-checkpoint suffix
        regenerates bit-identically under greedy decoding), requeues
        strict-FIFO for a from-the-prompt replay, or — after
        ``quarantine_after`` consecutive implicated failures — fails
        individually into :meth:`failures` so one poison request cannot
        wedge retries forever. Retries back off exponentially (bounded),
        keyed by the consecutive-failure streak."""
        err = f"{type(exc).__name__}: {exc}"[:200]
        self._fail_streak += 1
        self._recoveries += 1
        self._c_recover.inc()
        if isinstance(exc, DeviceStallError):
            self._stalls += 1
            self._c_stall.inc()
        # Permanent faults (ISSUE 10): a dead chip or broken interconnect
        # cannot be retried away — shrink the mesh over the survivors
        # FIRST, then let the standard restore/replay path below run
        # against the degraded mesh (checkpointed host KV re-uploads
        # under the NEW sharding via _kv_host_upload). When no feasible
        # degraded configuration exists (single chip, KATA_TPU_DEGRADED=0,
        # the tp_min floor, an injected mesh=), the load fails LOUDLY:
        # every unfinished rid lands in failures() — none vanish.
        if resilience.classify(exc) == resilience.PERMANENT:
            if not self._degrade_mesh(exc):
                return self._fail_all(err)
        # The implicated set: who loses progress to this round. A fault
        # inside a fill path is attributed to the requests of THAT fill
        # (_admit_current) — their batch-mates just requeue without an
        # implication mark, so a poison prompt quarantines alone instead
        # of dragging the whole admission pass with it. Decode/fence
        # faults implicate every lane resident and the in-flight chunk's
        # pins (the whole cohort shares one executable there).
        blamed = {req.rid for req in self._admit_current if not req.done}
        if not blamed:
            for b in range(self.max_batch):
                req = self._slot_req[b]
                if req is not None and not req.done:
                    blamed.add(req.rid)
            if self._inflight is not None:
                for _b, req in self._inflight.slots:
                    if not req.done:
                        blamed.add(req.rid)
            # An admission slice rode the failed dispatch (ISSUE 13): its
            # request shares the executable with the decode lanes and
            # joins the cohort — a poison prompt fusing every round
            # accrues quarantine strikes like any lane resident, instead
            # of replaying forever while innocents are failed around it.
            # Three sources cover the slice's whole lifecycle: the
            # prep→record window (_fused_blame), a lockstep record
            # awaiting its fence (_fused_ret), and an overlapped record
            # riding the in-flight chunk.
            fused_recs = (
                self._fused_ret,
                self._inflight.fused if self._inflight is not None
                else None,
            )
            for fc in fused_recs:
                if fc is not None and not fc.partial.req.done:
                    blamed.add(fc.partial.req.rid)
            if self._fused_blame is not None and not self._fused_blame.done:
                blamed.add(self._fused_blame.rid)
        lost: dict[int, _Request] = {}
        for b in range(self.max_batch):
            req = self._slot_req[b]
            if req is not None and not req.done:
                lost[req.rid] = req
        if self._inflight is not None:
            for _b, req in self._inflight.slots:
                if not req.done:
                    lost[req.rid] = req
        for req, _hit in self._admitting:
            if not req.done:
                lost[req.rid] = req
        # A blamed request still sitting in the queue (a reservation-
        # phase fault: peeked, never popped) joins the lost set — pulled
        # out of the queue so its quarantine streak is tracked and it
        # requeues strict-FIFO with everyone else instead of retrying
        # forever with fails pinned at zero.
        if blamed - set(lost):
            for req in list(self._queue):
                if req.rid in blamed and req.rid not in lost:
                    self._queue.remove(req)
                    lost[req.rid] = req
        # Release prefix pins. A standalone store's arena survives a
        # transient recovery (decode never donates it); a pool-backed
        # tier is rebuilt with the pool. No-op after a mesh shrink — the
        # degrade path already released against the OLD store.
        self._release_prefix_state()
        quarantined = 0
        survivors: list[_Request] = []
        for rid in sorted(lost):
            req = lost[rid]
            if rid in blamed:
                req.fails += 1
            if req.fails >= self._quarantine_k:
                self._fail_request(req, reason="quarantined", error=err)
                self._ckpt.pop(rid, None)
                self._quarantined_n += 1
                self._c_quarantine.inc()
                quarantined += 1
            else:
                # Ledger: from here until the request is back in a lane
                # (checkpoint restore or replay first token) its time is
                # the recovery phase — the incident's attributed cost.
                self._ledger_to(req, PHASE_RECOVERY)
                survivors.append(req)
        self._reset_device_state()
        # Restore checkpointed survivors into fresh lanes; everything
        # else replays from its prompt via a strict-FIFO front-requeue.
        restored = 0
        replay: list[_Request] = []
        lanes = (b for b in range(self.max_batch))
        try:
            with jaxapi.allow_transfer("crash recovery restore"):
                for req in survivors:  # already rid-sorted
                    entry = self._ckpt.get(req.rid)
                    if entry is not None and self._restore_lane(
                            next(lanes), entry):
                        restored += 1
                    else:
                        req.out = []
                        req.replays += 1
                        replay.append(req)
        except BaseException as exc2:
            if not (self._supervised and resilience.recoverable(exc2)):
                raise
            # A PERMANENT fault during the restore itself (another chip
            # died while we were re-uploading): shrink AGAIN before the
            # reset, or the replay below would land on the dead mesh.
            # With no rung left, fail the load loudly — requeue the
            # survivors not yet in a lane first so _fail_all sees every
            # one of them (none vanish).
            if resilience.classify(exc2) == resilience.PERMANENT:
                if not self._degrade_mesh(exc2):
                    lane_rids = {
                        r.rid for r in self._slot_req if r is not None
                    }
                    self._queue.extendleft(reversed(
                        [r for r in survivors if r.rid not in lane_rids]
                    ))
                    return self._fail_all(
                        f"{type(exc2).__name__}: {exc2}"[:200]
                    )
            # A recoverable fault inside the restore itself (pool_alloc
            # seam, a transient error mid-scatter): the half-restored
            # device state is untrustworthy — reset once more and replay
            # EVERY survivor from its prompt. Full replay is always
            # correct, and none vanish.
            self._reset_device_state()
            counted = {r.rid for r in replay}
            restored = 0
            for req in survivors:
                if req.rid not in counted:
                    req.replays += 1
                req.out = []
                # Ledger: a lane restored before the restore-phase fault
                # moved to decode — it is recovery work again now.
                self._ledger_to(req, PHASE_RECOVERY)
            replay = list(survivors)
        if replay:
            self._queue.extendleft(reversed(replay))
        if self.paged:
            self._preempted = deque(
                sorted(self._preempted, key=lambda p: p.req.rid)
            )
        backoff = 0.0
        if self._backoff_s > 0:
            backoff = min(self._backoff_s * (2 ** (self._fail_streak - 1)),
                          5.0)
        self._emit(
            "recovery", error=err, restored=restored,
            requeued=len(replay), quarantined=quarantined,
            streak=self._fail_streak, backoff_s=round(backoff, 4),
        )
        if backoff:
            time.sleep(backoff)
        return (
            bool(self._queue)
            or any(r is not None for r in self._slot_req)
            or bool(self.paged and self._preempted)
        )

    def _release_prefix_state(self) -> None:
        """Release every prefix pin and cancel mid-admission lookups
        against the CURRENT standalone store (a pool tier dies and is
        rebuilt with its pool), then strip the hits from ``_admitting``
        so later unwind code cannot release them twice — or against a
        replacement store after a mesh shrink."""
        if (self.prefix_store is not None
                and not isinstance(self.prefix_store, PagedPrefixTier)):
            for handle in self._slot_prefix:
                if handle is not None:
                    self.prefix_store.release(handle)
            for _req, hit in self._admitting:
                if hit is not None:
                    self.prefix_store.cancel(hit)
        self._slot_prefix = [None] * self.max_batch
        self._admitting = [(r, None) for r, _h in self._admitting]

    def _degrade_mesh(self, exc: BaseException) -> bool:
        """Elastic mesh-shrink recovery (ISSUE 10): re-resolve a feasible
        tensor-parallel degree over the chips that survived a permanent
        fault (``tp_serving.shrink_ladder`` — tp=4 → 2 → 1, floored at
        ``tp_min``), rebuild the serving mesh over the survivors,
        re-shard params from the host donor copy retained at
        construction, and swap/rebuild the prefix store. The caller's
        normal recovery pass then rebuilds the pool/arena on the new mesh
        (``_reset_device_state`` → ``_place_arenas``) and restores
        checkpointed lanes through ``_kv_host_upload`` under the NEW
        sharding — so recovered greedy outputs stay bit-identical to a
        fault-free run (tp-invariance, PR 9). False when no degraded
        configuration exists; the caller fails the load loudly."""
        permanent_reason = (
            f"chip_loss:{exc.device_index}"
            if isinstance(exc, resilience.ChipLossFault) else "ici_error"
        )
        if (self._tp <= 1 or not self._tp_serving_rules
                or not self._degraded_ok or self._params_host is None):
            why = (
                "degraded_disabled" if not self._degraded_ok
                else "single_chip" if self._tp <= 1
                else "mesh_injected"
            )
            self._emit(
                "chip_loss_fatal", reason=permanent_reason, tp=self._tp,
                why=why,
            )
            return False
        if isinstance(exc, resilience.ChipLossFault):
            i = exc.device_index
            if not 0 <= i < len(self._tp_devices):
                i = 0  # index outside the mesh: one chip is gone all the same
            survivors = self._tp_devices[:i] + self._tp_devices[i + 1:]
        else:
            # ICI fault: every chip answers but collectives over the full
            # ring are untrustworthy — shrink one rung onto fewer chips.
            survivors = list(self._tp_devices)
        new_tp = tp_serving.shrink_ladder(
            self._tp, len(survivors), self._tp_min
        )
        if new_tp is None:
            self._emit(
                "chip_loss_fatal", reason=permanent_reason, tp=self._tp,
                why=f"tp_min_floor:{self._tp_min}",
                survivors=len(survivors),
            )
            return False
        old_tp = self._tp
        self._release_prefix_state()
        if self.prefix_store is not None and not isinstance(
                self.prefix_store, PagedPrefixTier):
            # The standalone store's arena lived on the OLD mesh — its
            # shards on the dead chip are gone, so unlike transient
            # recovery it cannot survive. An OWNED store rebuilds empty
            # (cold cache, warms again from traffic); an INJECTED one may
            # back other servers and is disabled here instead.
            if self._prefix_injected:
                self._emit(
                    "prefix_store_disabled", reason="tp_degraded",
                )
                self.prefix_store = None
            else:
                self.prefix_store = PrefixStore(
                    self.cfg, self._prefix_capacity, self.prefill_buckets,
                    kv_quant=self.kv_quant, label=self._label,
                )
        self._tp = new_tp
        with jaxapi.allow_transfer(
                "degraded-mode mesh shrink: param re-shard from the host "
                "donor copy"):
            if new_tp > 1:
                self._mesh = tp_serving.serving_mesh(
                    new_tp, devices=survivors
                )
                self._tp_devices = survivors[:new_tp]
                from ..parallel.sharding import shard_serving_params

                self.params = shard_serving_params(
                    self._params_host, self._mesh
                )
            else:
                self._mesh = None
                self._tp_devices = []
                self.params = jax.tree.map(jnp.asarray, self._params_host)
            if (self._mesh is not None and not self._prefix_injected
                    and isinstance(self.prefix_store, PrefixStore)):
                self._place_store(self._mesh)
        self._tp_shrinks += 1
        # The scheduler's prefill-rate / round-cadence EWMAs were measured
        # on the OLD mesh — the shrunken one is slower, and stale
        # estimates would mis-project the first post-recovery admissions.
        # Re-bootstrap them on degraded-mesh observations.
        self._sched.reset_estimates()
        self._emit(
            "tp_degraded", reason=permanent_reason, old_tp=old_tp,
            tp=new_tp, survivors=len(survivors), tp_min=self._tp_min,
        )
        return True

    def _fail_all(self, err: str) -> bool:
        """Terminal path for an unrecoverable permanent fault: no
        degraded mesh exists, so no retry can serve the in-flight load.
        Every unfinished request — lanes, the in-flight chunk's pins,
        mid-admission work, preempted spills, the whole queue — fails
        LOUDLY into :meth:`failures` (reason ``chip_lost``); banked
        results survive. The none-vanish invariant holds: every submitted
        rid still ends in exactly one of results/failures. Device state
        is rebuilt so fresh submits can still be served (on real hardware
        the runtime decides whether the surviving configuration comes
        back up)."""
        lost: dict[int, _Request] = {}
        for b in range(self.max_batch):
            req = self._slot_req[b]
            if req is not None and not req.done:
                lost[req.rid] = req
        if self._inflight is not None:
            for _b, req in self._inflight.slots:
                if not req.done:
                    lost[req.rid] = req
        for req, _hit in self._admitting:
            if not req.done:
                lost[req.rid] = req
        if self.paged:
            while self._preempted:
                pre = self._preempted.popleft()
                if not pre.req.done:
                    lost[pre.req.rid] = pre.req
        while self._queue:
            req = self._queue.popleft()
            if not req.done:
                lost[req.rid] = req
        self._release_prefix_state()
        self._reset_device_state()
        self._ckpt = {}
        for rid in sorted(lost):
            self._fail_request(lost[rid], reason="chip_lost", error=err)
        self._emit(
            "recovery", error=err, restored=0, requeued=0,
            quarantined=0, failed=len(lost), streak=self._fail_streak,
            backoff_s=0.0,
        )
        return False

    def _reset_device_state(self) -> None:
        """Fresh pool/arena + cleared device-coupled host mirrors. After
        a failed round the old arena may alias buffers a raising dispatch
        donated away (or hold writes of a half-landed admission) —
        rebuilding is the only state the supervisor can trust. Host-side
        request state (queue, results, failures, checkpoint, preempted
        spills — all host-resident) survives untouched."""
        if self.paged:
            self.kv_pool = KVPool(
                self.cfg, self.kv_pool.num_blocks * self.kv_block,
                self.kv_block, kv_quant=self.kv_quant, label=self._label,
                # Re-read per rebuild: after a degraded mesh shrink the
                # block-sharded pool re-places onto the SHRUNKEN mesh
                # with matching per-shard sub-pools (ISSUE 14).
                shards=self._kv_shards(),
            )
            self._lane_blocks = [[] for _ in range(self.max_batch)]
            self._bt_host[:] = SCRATCH_BLOCK
            self._plans.clear()
            # The staged resume upload targeted the dead pool's placement
            # — discard it; _resume_one re-uploads against the rebuild.
            self._resume_stage_rid = None
            self._resume_stage_rows = None
            if isinstance(self.prefix_store, PagedPrefixTier):
                # Fold the dying tier's host-traffic counts into the
                # server's cumulative totals (stats() snapshot semantics
                # — counters only grow across rebuilds), and drop its
                # demoted segments from the host tier: their radix index
                # dies with the tier, so the parked rows are
                # unreachable; pinned session spills stay.
                self._host_demotions += self.prefix_store.demotions
                self._host_prefetches += self.prefix_store.prefetches
                if self._kv_host is not None:
                    self._kv_host.drop_unpinned()
                self.prefix_store = PagedPrefixTier(
                    self.kv_pool, self.cfg, self.prefill_buckets,
                    label=self._label, host_tier=self._kv_host,
                    on_demote=lambda: self._c_kv_demote.inc(),
                    on_prefetch=lambda: self._c_kv_prefetch.inc(),
                )
            if self._mesh is not None:
                # Tensor-parallel paged serving: the rebuilt pool must be
                # re-placed with the same head-axis sharding the failed
                # one had, so checkpointed lanes restore with identical
                # sharding (ISSUE 9 satellite).
                self._place_arenas(self._mesh)
        else:
            if self._cycle:
                self.arena = init_cycle_kv_caches(
                    self.cfg, self.max_batch, self.max_len,
                    quantized=self.kv_quant, margin=self._ring_margin,
                )
            else:
                arena_len = (
                    self.cfg.window_cycle[0] + self._ring_margin
                    if self.ring_kv else self.max_len
                )
                self.arena = init_kv_caches(
                    self.cfg, self.max_batch, arena_len,
                    quantized=self.kv_quant,
                )
            if self.draft is not None:
                self.draft_arena = init_kv_caches(
                    self.draft[1], self.max_batch, self.max_len
                )
            if self._mesh is not None:
                self._place_arenas(self._mesh)
        self._slot_req = [None] * self.max_batch
        self._inflight = None
        self._fresh_rows.clear()
        # A half-built chunked admission's caches are device state from
        # the failed round — discard; its request is in the lost set (it
        # rides _admitting) and replays from the prompt. Any fused slice
        # record of the failed dispatch dies with it (ISSUE 13).
        self._partial = None
        self._fuse_pending = False
        self._fused_ret = None
        self._fused_blame = None
        # A persistent round's delivered future dies with its dispatch:
        # the donated partial is discarded and the round replays
        # strict-FIFO at dispatch granularity, same as multi-step
        # (ISSUE 20 — recovery stays dispatch-boundary-granular).
        self._persistent_fut = None
        self._admitting = []
        self._admit_current = []

    def _kv_host_upload(self, host_tree, paged_rows: bool):
        """Upload spilled/checkpointed host KV rows back to device. With
        a live mesh (tensor-parallel serving, ISSUE 9) the rows are
        placed with the SAME head-axis sharding the pool/arena carries —
        the restore half of the sanctioned ``allow_transfer`` slow path
        gathers per-shard and re-lands per-shard, so recovered state has
        identical sharding and greedy replay stays bit-identical.
        ``paged_rows``: the full-table spill layout ``[L, NT, KV, D]``
        (head axis 2) vs the slotted snapshot ``[L, 1, S, KV, D]`` (head
        axis 3)."""
        if self._mesh is None:
            return jax.tree.map(jnp.asarray, host_tree)
        from jax.sharding import NamedSharding

        sh = NamedSharding(self._mesh, tp_serving.kv_rows_spec(
            self.cfg, self._tp, head_axis=2 if paged_rows else 3,
            layout=self._kv_layout if self.paged else KV_LAYOUT_HEADS,
        ))
        return jax.tree.map(lambda a: jax.device_put(a, sh), host_tree)

    def _restore_lane(self, b: int, entry: _CkptEntry) -> bool:
        """Re-land one checkpointed request into lane ``b`` of the fresh
        device state: KV rows verbatim (the spill/restore pair), emitted
        tokens truncated to the snapshot, decode resuming at the
        snapshot's ``pos``/``last`` — the same verbatim-restore argument
        as PR 6 preemption, so greedy output is unchanged. False when a
        paged pool cannot hold the rows right now (caller requeues for a
        full replay instead)."""
        req = entry.req
        if self.paged:
            nb = -(-entry.pos // self.kv_block)
            blocks = self._alloc_blocks(nb)
            if blocks is None:
                return False
            full = np.full(self._nb_max, SCRATCH_BLOCK, np.int32)
            full[:nb] = blocks
            self.kv_pool.arena = pool_scatter_rows(
                self.kv_pool.arena,
                self._kv_host_upload(entry.kv, paged_rows=True),
                jnp.asarray(full), block_size=self.kv_block,
            )
            self._set_lane_table(b, blocks)
        else:
            self.arena = _write_slot(
                self.arena, self._kv_host_upload(entry.kv, paged_rows=False),
                b,
            )
        req.out = list(entry.out)
        self._slot_req[b] = req
        self._slot_prefix[b] = None
        self._pos[b] = entry.pos
        self._last[b] = entry.last
        self._fresh_rows.add(b)
        self._ledger_to(req, self._decode_state())  # restored: decoding
        return True

    def _finish_drain(self) -> None:
        """The drain epilogue, once the server idles: fail everything
        that never started (queued, plus any preempted request the pool
        could not re-admit), emit the final checkpoint event, and mark
        the drain complete. Every submitted rid is now in ``results`` or
        :meth:`failures` — none vanish."""
        failed = 0
        while self.paged and self._preempted:
            pre = self._preempted.popleft()
            self._fail_request(pre.req, reason="drained",
                               error="drained mid-flight "
                                     f"({self._drain_reason})")
            failed += 1
        while self._queue:
            req = self._queue.popleft()
            self._fail_request(req, reason="drained",
                               error="drained before start "
                                     f"({self._drain_reason})")
            failed += 1
        self._ckpt = {}
        self._emit(
            "checkpoint", round=self._rounds, lanes=0, tokens=0,
            final=True,
        )
        self._emit(
            "drain", reason=self._drain_reason,
            completed=len(self._results), failed=failed,
        )
        self._drain_done = True

    def _note_round(self, dur_s: float, busy: int,
                    steps: Optional[int] = None) -> None:
        """Feed one decode-round cadence to the scheduler's estimator —
        with the round's ACTUAL delivered steps, so the per-token EWMA
        stays honest under multi-step decode and fused rounds (ISSUE 13
        satellite); an SLO-violating round (slo_chunked only) counts and
        events — the measured ground truth the deadline-driven admission
        steers by. ``steps`` overrides the static dispatch multiplier
        for rounds whose step count is data-dependent — persistent
        rounds (ISSUE 20) pass the while_loop's DELIVERED count."""
        steps = self._dispatch_steps if steps is None else max(steps, 1)
        if self._sched.note_round(dur_s, steps=steps):
            self._c_slo.inc()
            self._emit(
                "slo_violation", round_s=round(dur_s, 6),
                # The per-token figure actually compared to slo_ms (the
                # round cadence over its delivered steps).
                itl_s=round(dur_s / steps, 6),
                slo_ms=self._sched.slo_ms, slots_busy=busy,
            )

    def _fence_wait(self, wait, seam: str = "fence", inject: bool = True):
        """Route one blocking device→host wait through the watchdog
        fence (:func:`.resilience.fence_with_timeout`): the injector's
        ``fence`` seam crosses first (``inject=False`` skips it — used
        by the checkpoint gather, which is recovery machinery rather
        than an injection seam), and a configured ``fence_timeout_s``
        bounds the wait — a hung transport raises
        :class:`DeviceStallError` into the supervisor instead of
        freezing the scheduler. Defaults are a straight call-through."""
        return resilience.fence_with_timeout(
            wait, timeout_s=self._fence_timeout_s, seam=seam,
            injector=self._inj if inject else None, server=self._label,
            trace=self._trace,
        )

    def _decode_budget(self):
        """Per-lane remaining-token UPPER BOUNDS for the on-device
        EOS/budget mask (``decode_steps > 1`` only — K=1 keeps the
        legacy executables untouched). Computed from the host's retired
        token counts, so under overlap it over-estimates by at most the
        in-flight chunk — the mask freezes LATE (trimmed garbage), never
        early (which would drop real tokens). Dead lanes get 0 and
        freeze from step one: their stale rows stop being scribbled.
        Persistent rounds (ISSUE 20) ALWAYS arm the mask — the
        while_loop's exit conditions read it."""
        if self._decode_steps <= 1 and not self._persistent:
            return None
        b = np.zeros(self.max_batch, np.int32)
        for i in range(self.max_batch):
            r = self._slot_req[i]
            if r is not None and not r.done:
                b[i] = max(0, r.max_new_tokens - len(r.out))
        return jnp.asarray(b)

    def _slice_geometry(self, p: _PartialPrefill) -> tuple:
        """The ONE chunk-slice shape rule both chunk paths share (inline
        :meth:`_prefill_one_chunk` and the fused dispatch — the
        bit-identity claim rests on them staying identical):
        ``chunk_tokens`` wide, right-padded + true_len-masked, exact
        width near the arena end (padding past ``max_len`` would clamp
        real rows). Returns ``(suffix, take, width)``."""
        n = len(p.req.prompt)
        c = self._sched.chunk_tokens
        take = min(c, n - p.offset)
        width = c if p.offset + c <= self.max_len else take
        suffix = p.req.prompt[p.offset:p.offset + take]
        if width > take:
            suffix = np.pad(suffix, (0, width - take))
        return suffix, take, width

    def _prepare_fused_chunk(self) -> Optional[tuple]:
        """Consume the pending admission slice for a fused dispatch
        (ISSUE 13): :meth:`_slice_geometry`, with ``p.offset``/counters
        advanced AT DISPATCH so an overlapped pipeline carries one slice
        per round without re-reading the same tokens. Returns
        ``(suffix, offset, take, width, is_last)`` or None when no slice
        is pending. The slice's request joins the recovery BLAME COHORT
        of the dispatch it rides (``_fused_blame`` — cleared by
        ``_note_progress`` once a round survives): a fault anywhere in
        the fused dispatch implicates it alongside the decode lanes, so
        a poison prompt riding fused dispatches accrues quarantine
        strikes instead of replaying forever."""
        if not (self._fuse_pending and self._fused_ok
                and self._partial is not None):
            self._fuse_pending = False
            return None
        self._fuse_pending = False
        p = self._partial
        n = len(p.req.prompt)
        if p.offset >= n:
            return None  # final slice already in flight
        self._fused_blame = p.req
        suffix, take, width = self._slice_geometry(p)
        self._inj.fire("sched_tick")
        offset = p.offset
        p.offset += take
        p.chunks += 1
        p.fused += 1
        self._sched.chunks += 1
        self._c_sched_chunk.inc()
        return suffix, offset, take, width, p.offset >= n

    def _dispatch_decode(self, last, pos, sub):
        """The ONE decode dispatch site (lock-step and overlapped share
        it — and since ISSUE 13, plain AND fused rounds): paged servers
        decode through the block pool (tables uploaded host→device each
        chunk — a few KB riding the dispatch, like ``last``/``pos``;
        allocation itself is pure host work), slot servers through the
        dense arena. When an admission slice is pending under the fused
        plan, the SAME dispatch carries it: ``_fused_serve_decode``
        composes the decode scan and the slice's ``prefill_suffix`` into
        one executable, the slice's logits ride back as a future in
        ``self._fused_ret``, and the caller's retire applies it. Returns
        ``(toks, last, pos)`` futures; the donated arena's successor is
        stored back."""
        self._inj.fire("decode_dispatch")
        if not self._decode_attn_emitted:
            # One decode_attn_backend event per server, at the first
            # decode (ISSUE 12): the resolved backend plus the reason
            # whenever the kernel was not selected — the event-stream
            # mirror of stats()["decode_backend"].
            self._decode_attn_emitted = True
            self._emit(
                "decode_attn_backend", backend=self._decode_attn,
                reason=self._decode_attn_reason, paged=self.paged,
                # The kernel's actual KV tile: the pool block when paged,
                # the derived dense tile when slotted (the alignment
                # contract the guest guide documents for this event).
                block_size=(
                    self.kv_block if self.paged
                    else attention.dense_decode_tile(self.max_len)
                ),
                kv_quant="int8" if self.kv_quant else "bf16",
            )
        steps = self._dispatch_steps
        budget = self._decode_budget()
        eos = self.eos_id if budget is not None else None
        fuse = self._prepare_fused_chunk()
        if fuse is not None:
            p = self._partial
            suffix, offset, take, width, is_last = fuse
            # The slice's prompt tokens and offsets are ADMISSION inputs
            # riding a decode dispatch — the same sanctioned upload class
            # as the _admit window (the strict-mode transfer guard covers
            # the overlapped dispatch this runs inside).
            with jaxapi.allow_transfer("fused admission slice upload"):
                if self.paged:
                    # Device ledger (ISSUE 17): args/kwargs staged once so
                    # on_dispatch can lower THIS dispatch's signature for
                    # cost_analysis (lowering reads avals only — the
                    # donated arena is untouched) and stamp the gap clock.
                    fargs = (
                        self.params, self.kv_pool.arena, last, pos, budget,
                        p.caches, jnp.asarray(suffix)[None, :],
                        jnp.int32(offset), jnp.int32(take), self.cfg,
                        steps, self._do_sample, self.top_k, self._temp_dev,
                        sub,
                    )
                    fkw = dict(
                        top_p=self.top_p,
                        block_tables=jnp.asarray(self._bt_host),
                        block_size=self.kv_block, paged_len=self.max_len,
                        decode_kernel_fn=self._decode_kernel, eos_id=eos,
                        reduce_fn=self._reduce_fn,
                    )
                    self._devledger.on_dispatch(
                        ("fused", True, steps, width, eos is None,
                         budget is None),
                        _fused_serve_decode, fargs, fkw,
                    )
                    (toks, caches, new_last, new_pos, p_caches,
                     p_logits) = _fused_serve_decode(*fargs, **fkw)
                    self.kv_pool.arena = caches
                else:
                    fargs = (
                        self.params, self.arena, last, pos, budget,
                        p.caches, jnp.asarray(suffix)[None, :],  # jaxguard: allow(JG102) exclusive if/else branch — the paged call above never ran; p.caches rebinds right below
                        jnp.int32(offset), jnp.int32(take), self.cfg,
                        steps, self._do_sample, self.top_k, self._temp_dev,
                        sub,
                    )
                    fkw = dict(
                        top_p=self.top_p,
                        decode_kernel_fn=self._decode_kernel, eos_id=eos,
                        reduce_fn=self._reduce_fn,
                    )
                    self._devledger.on_dispatch(
                        ("fused", False, steps, width, eos is None,
                         budget is None),
                        _fused_serve_decode, fargs, fkw,
                    )
                    (toks, caches, new_last, new_pos, p_caches,
                     p_logits) = _fused_serve_decode(*fargs, **fkw)
                    self.arena = caches
            p.caches = p_caches  # this IS the rebind — the donated tree's successor replaces it
            self._fused_ret = _FusedChunk(
                partial=p, take=take, width=width, last=is_last,
                logits=p_logits,
            )
            # Blame handoff: the record now carries the slice through the
            # rest of its dispatch's life (lockstep apply / the
            # overlapped _Inflight) — _recover reads it from there. The
            # side variable only covers the prep→record window, where a
            # sched_tick injection or a raising dispatch would otherwise
            # leave the slice's request unimplicated.
            self._fused_blame = None
            return toks, new_last, new_pos
        if self._persistent:
            # PERSISTENT round (ISSUE 20): one while_loop dispatch that
            # decodes until the heartbeat-cadence cap, a lane freeze, or
            # a live lane's pre-reserved window end — greedy, with the
            # PR 13 on-device freeze mask bounding every lane (budget is
            # always armed here, see _decode_budget). The window vector
            # is each lane's write bound: the reserved block-table span
            # when paged (bump-allocated on device against it), the
            # dense arena length when slotted. ``delivered`` rides back
            # as a future; the retire side fences it and accounts by it.
            cap = self._persistent_cap
            window = np.full(self.max_batch, self.max_len, np.int32)
            if self.paged:
                for b in range(self.max_batch):
                    if self._slot_req[b] is not None:
                        window[b] = min(
                            len(self._lane_blocks[b]) * self.kv_block,
                            self.max_len,
                        )
            arena = self.kv_pool.arena if self.paged else self.arena
            fargs = (
                self.params, arena, last, pos, budget,
                jnp.asarray(window), self.cfg, cap,
            )
            fkw = dict(
                decode_kernel_fn=self._decode_kernel, eos_id=eos,
                reduce_fn=self._reduce_fn,
            )
            if self.paged:
                fkw.update(
                    block_tables=jnp.asarray(self._bt_host),
                    block_size=self.kv_block, paged_len=self.max_len,
                )
            self._devledger.on_dispatch(
                ("persistent", self.paged, cap, eos is None),
                _persistent_serve_decode, fargs, fkw, loop_cap=cap,
            )
            toks, caches, new_last, new_pos, delivered = (
                _persistent_serve_decode(*fargs, **fkw))
            if self.paged:
                self.kv_pool.arena = caches
            else:
                self.arena = caches
            self._persistent_fut = (delivered, window)
            return toks, new_last, new_pos
        if self.paged:
            fargs = (
                self.params, self.kv_pool.arena, last, pos, self.cfg,
                steps, self._do_sample, self.top_k, self._temp_dev, sub,
            )
            fkw = dict(
                top_p=self.top_p, ring=False,
                block_tables=jnp.asarray(self._bt_host),
                block_size=self.kv_block, paged_len=self.max_len,
                decode_kernel_fn=self._decode_kernel, eos_id=eos,
                budget=budget, reduce_fn=self._reduce_fn,
            )
            self._devledger.on_dispatch(
                ("plain", True, steps, eos is None, budget is None),
                _serve_decode, fargs, fkw,
            )
            toks, caches, new_last, new_pos = _serve_decode(*fargs, **fkw)
            self.kv_pool.arena = caches
        else:
            fargs = (
                self.params, self.arena, last, pos, self.cfg, steps,
                self._do_sample, self.top_k, self._temp_dev, sub,
            )
            fkw = dict(
                top_p=self.top_p, ring=self.ring_kv,
                decode_kernel_fn=self._decode_kernel, eos_id=eos,
                budget=budget, reduce_fn=self._reduce_fn,
            )
            self._devledger.on_dispatch(
                ("plain", False, steps, eos is None, budget is None),
                _serve_decode, fargs, fkw,
            )
            toks, caches, new_last, new_pos = _serve_decode(*fargs, **fkw)
            self.arena = caches
        return toks, new_last, new_pos

    def _step_lockstep(self) -> bool:
        self._admit()
        self._ensure_blocks()  # paged: grow tables / preempt before dispatch
        self._fresh_rows.clear()  # lock-step dispatch reads host rows
        active = [b for b in range(self.max_batch) if self._slot_req[b] is not None]
        if not active:
            return (
                bool(self._queue)
                or self._partial is not None
                or bool(self.paged and self._preempted)
            )

        if self.speculative_k:
            # The round's verify transfer (np.asarray inside) is the
            # span's fence; accepted-token accounting lands in a follow-up
            # event because it is only known after the host-side accept.
            before = self._emitted
            with obs.span(
                "serving.verify_round",
                trace_id=self._trace, server=self._label, slots_busy=len(active),
                queued=len(self._queue),
            ) as sp:
                alive = self._step_speculative(active)
            accepted = self._emitted - before
            if accepted:
                tok_lat = sp.duration_s / (accepted / len(active))
                self._tok_lat.observe(tok_lat)
                self._h_tok_lat.observe(tok_lat)
                self._emit(
                    "spec_round", accepted=accepted,
                    offered=self.speculative_k * len(active),
                    dur_s=round(sp.duration_s, 6),
                )
            return alive

        # Always decode exactly ``chunk × decode_steps`` steps: ``steps``
        # is a static arg, so a data-dependent count would compile a
        # fresh full-model decode executable per distinct value (a
        # multi-second latency spike whenever a request neared its
        # budget). Overrun is harmless by construction — writes past
        # max_len clamp to the last entry of a slot that is finished (and
        # refill overwrites the whole slot), _maybe_finish trims tokens
        # past eos/budget, and at decode_steps > 1 the on-device mask
        # freezes finished lanes inside the scan (ISSUE 13).
        self._key, sub = jax.random.split(self._key)
        # The chunk span's duration is honest by construction: np.asarray
        # on the chunk's tokens is a device→host transfer, i.e. the fence.
        with obs.span(
            "serving.decode_chunk",
            trace_id=self._trace, server=self._label,
            tokens=len(active) * self._dispatch_steps,
            slots_busy=len(active), queued=len(self._queue),
            batch_occupancy=round(len(active) / self.max_batch, 4),
        ) as sp:
            self._clock.push(LOOP_PHASE_DISPATCH)
            try:
                toks, last, pos = self._dispatch_decode(
                    jnp.asarray(self._last), jnp.asarray(self._pos), sub
                )
            finally:
                self._clock.pop()
            # Watchdog-fenced chunk boundary: [max_batch, steps] tokens.
            self._clock.push(LOOP_PHASE_RETIRE)
            try:
                toks = self._fence_wait(lambda: np.asarray(toks))  # lock-step round fence — the transfer IS the chunk boundary
            finally:
                self._clock.pop()
        # Persistent retire (ISSUE 20): the delivered step count rides
        # the round's fence as a sibling future — the token transfer
        # above already synchronized the executable, so this read is a
        # landed-buffer copy, not a second wait. Every accounting line
        # below divides by DELIVERED steps, not the static cap: a round
        # that exited early on a freeze or a window edge must not
        # flatter the per-token latency.
        fut, self._persistent_fut = self._persistent_fut, None
        delivered: Optional[int] = None
        if fut is not None:
            delivered = int(np.asarray(fut[0]))  # jaxguard: allow(JG101) persistent round fence — the delivered count IS the round boundary read
            toks = toks[:, :delivered]
        # Ledger retire stamp AFTER the span closed, so the RETIRE pop's
        # fence time is already accrued and the clock snapshot taken here
        # keeps it out of the next retire→dispatch gap window.
        self._devledger.note_retire(delivered_steps=delivered)
        # Per-token decode latency as a client sees it: dispatch wall
        # time over its delivered steps (each step yields one token per
        # slot) — STAYS per-token however large decode_steps is.
        steps_done = self._dispatch_steps if delivered is None else delivered
        tok_lat = sp.duration_s / max(steps_done, 1)
        self._tok_lat.observe(tok_lat)
        self._h_tok_lat.observe(tok_lat)
        self._note_round(sp.duration_s, len(active), steps=delivered)
        # np.array (not asarray): device arrays convert read-only, and
        # _fill_slot writes these rows in place on refill.
        self._last = np.array(last)  # jaxguard: allow(JG101) lock-step fence (writable host copy for refill)
        self._pos = np.array(pos)  # jaxguard: allow(JG101) lock-step fence (writable host copy for refill)
        self._rounds += 1
        for b in active:
            new = toks[b].tolist()
            self._slot_req[b].out.extend(new)
            self._emitted += len(new)
            self._maybe_finish(b, new)
        if fut is not None:
            # Exit attribution, host-side from the fenced carry: the cap
            # was consumed ("cap"); else an UNFINISHED lane sits at its
            # reserved window edge ("window" — _maybe_finish just freed
            # every lane that froze on eos/budget, so survivors at the
            # edge are the ones the loop stopped for); else a freeze
            # needed host service ("done").
            cap = self._persistent_cap
            window = fut[1]
            if delivered >= cap:
                reason = "cap"
            elif any(self._slot_req[b] is not None
                     and self._pos[b] >= window[b] for b in active):  # jaxguard: allow(JG101) host-side numpy — _pos was rebound via np.array at the fence above, window is the dispatch's np reservation vector
                reason = "window"
            else:
                reason = "done"
            self._persistent_rounds += 1
            self._last_delivered = delivered
            self._delivered_total += delivered
            self._persistent_exits[reason] += 1
            self._emit("persistent_exit", reason=reason,
                       delivered=delivered, cap=cap)
        # An admission slice that rode this dispatch (ISSUE 13) lands
        # after the decode tokens, mirroring the overlapped retire order.
        fc, self._fused_ret = self._fused_ret, None
        self._apply_fused(fc)
        # Lock-step rounds have no chunk in flight to overlap, but the
        # staged upload still runs ahead of the NEXT round's admission
        # pass (ISSUE 14) — the resume consumes an already-moving copy.
        self._clock.push(LOOP_PHASE_HOST)
        try:
            self._stage_resume_prefetch()
        finally:
            self._clock.pop()
        return True

    # ----- pipelined rounds (overlap=True) ---------------------------------

    def _step_overlapped(self) -> bool:
        """One pipelined round. Ordering is the whole point: the NEXT chunk
        dispatches first — fed by the in-flight chunk's on-device
        ``last``/``pos`` (no host round-trip), with rows admission refilled
        since the last dispatch merged in — and only then is the in-flight
        chunk retired, so finish detection, refill prefills, and telemetry
        run while the device computes. A chunk dispatched before its
        predecessor's tokens were inspected may decode garbage rows for
        requests that turn out to have finished; retire discards those via
        the slot-identity check, and refill overwrites the whole slot —
        wasted FLOPs on a dead row, never wrong tokens (the module
        header's one-round scheduling lag)."""
        prev, self._inflight = self._inflight, None
        if prev is None:
            self._admit()  # pipeline empty: admission feeds this dispatch
        busy = any(r is not None for r in self._slot_req)
        if busy and (prev is None or self._any_survives(prev)):
            # Paged: grow every live lane's table to cover this dispatch's
            # window (preempting youngest-first under pool pressure)
            # BEFORE the tables upload with the chunk.
            self._ensure_blocks()
            if prev is None:
                last, pos = jnp.asarray(self._last), jnp.asarray(self._pos)
            elif self._fresh_rows:
                mask = np.zeros(self.max_batch, np.bool_)
                mask[list(self._fresh_rows)] = True
                fresh = jnp.asarray(mask)
                last = _merge_rows(prev.last, jnp.asarray(self._last), fresh)
                pos = _merge_rows(prev.pos, jnp.asarray(self._pos), fresh)
            else:
                last, pos = prev.last, prev.pos
            self._fresh_rows.clear()
            self._dispatch_chunk(last, pos)
            # A pending resume's H2D upload overlaps the chunk just
            # dispatched (ISSUE 14) — by retire's admission pass the
            # rows are in flight or landed.
            self._clock.push(LOOP_PHASE_HOST)
            try:
                self._stage_resume_prefetch()
            finally:
                self._clock.pop()
        if prev is not None:
            self._clock.push(LOOP_PHASE_RETIRE)
            try:
                self._retire(prev)  # host work overlaps the dispatched chunk
            finally:
                self._clock.pop()
        return (
            self._inflight is not None
            or bool(self._queue)
            or self._partial is not None
            or any(r is not None for r in self._slot_req)
            or bool(self.paged and self._preempted)
        )

    def _any_survives(self, prev: _Inflight) -> bool:
        """Speculative-dispatch gate: is ANY slot certain to still be
        decoding after the in-flight chunk lands? Budget arithmetic the
        host already holds answers this exactly in one direction — a slot
        at ``len(out) + chunk >= max_new_tokens`` is CERTAIN to finish
        (eos only ever finishes it earlier), so when no slot can survive,
        dispatching the next chunk would burn a whole provably-dead chunk
        (the worst case: budgets aligned to chunk boundaries waste 50% of
        device compute). Skipping costs nothing: the pipeline just drains
        and the next round dispatches lock-step from host state. The
        remaining speculation is eos-shaped only — a slot predicted alive
        may still eos out mid-chunk, wasting its row, never the chunk."""
        prev_req = dict(prev.slots)
        for b in range(self.max_batch):
            req = self._slot_req[b]
            if req is None:
                continue
            if prev_req.get(b) is not req:
                return True  # refilled since dispatch: untouched budget
            if len(req.out) + self._dispatch_steps < req.max_new_tokens:
                return True
        return False

    def _dispatch_chunk(self, last, pos) -> None:
        """Dispatch one decode chunk without fencing: the arena is donated
        forward, tokens/last/pos come back as futures, and a DeviceFence
        starts their async D2H copy so arrival overlaps the next chunk's
        compute. The detached span ends at retire — ``dispatch_s`` records
        the host-side dispatch cost, ``dur_s`` the honest dispatch→arrival
        round time (no forced sync at dispatch)."""
        active = [(b, self._slot_req[b]) for b in range(self.max_batch)
                  if self._slot_req[b] is not None]
        self._key, sub = jax.random.split(self._key)
        # chunk_tokens, NOT tokens: at steady state this span's dur_s is
        # the PIPELINE window (≈ two chunk periods — it opens while the
        # previous chunk still computes), so the tracer's auto-derived
        # tokens/s over dur_s would understate throughput ~2×. Retire
        # attaches round_s (retire→retire cadence) and derives the honest
        # rate from that instead.
        sp = obs.start_span(
            "serving.decode_chunk",
            trace_id=self._trace, server=self._label,
            chunk_tokens=len(active) * self._dispatch_steps,
            slots_busy=len(active), queued=len(self._queue),
            batch_occupancy=round(len(active) / self.max_batch, 4),
            overlapped=True,
        )
        self._clock.push(LOOP_PHASE_DISPATCH)
        try:
            toks, new_last, new_pos = self._dispatch_decode(last, pos, sub)
        finally:
            self._clock.pop()
        sp.mark("dispatch")
        # A fused admission slice dispatched above rides the in-flight
        # record to retire (ISSUE 13) — one slice per pipelined round.
        fc, self._fused_ret = self._fused_ret, None
        self._inflight = _Inflight(
            fence=obs.DeviceFence(toks=toks, last=new_last, pos=new_pos),
            last=new_last, pos=new_pos, slots=active, span=sp,
            t_dispatch=time.perf_counter(), fused=fc,
        )

    def _retire(self, fl: _Inflight) -> None:
        """Land one in-flight chunk: wait on the async token copy (the
        honest fence), apply tokens to the requests that still own their
        slots, then refill freed slots — those prefills affect the chunk
        after next, and their ``_write_slot`` updates chain behind the
        already-dispatched chunk's donated arena."""
        host = self._fence_wait(fl.fence.wait)
        # Honest per-token latency under pipelining is the round CADENCE —
        # retire→retire (one chunk period at steady state), falling back to
        # this chunk's own dispatch anchor when the pipeline was empty (an
        # idle gap must not ride into the latency). The span's dur_s stays
        # the dispatch→arrival pipeline window (≈ two chunk periods when
        # full): useful as in-flight latency, WRONG as a rate denominator —
        # which is why the rate metrics divide round_s, and the span
        # derives tokens_per_s from round_s explicitly.
        now = time.perf_counter()
        round_s = now - max(fl.t_dispatch, self._t_last_retire)
        self._t_last_retire = now
        # Ledger retire stamp: same anchor as round_s (busy time is
        # now − max(dispatch, previous retire) — pipelined chunks never
        # double-count the overlapped window).
        self._devledger.note_retire(now)
        n_tokens = len(fl.slots) * self._dispatch_steps
        fl.span.set(
            round_s=round(round_s, 6),
            tokens_per_s=round(n_tokens / round_s, 2) if round_s > 0 else 0.0,
        )
        fl.span.end()
        toks, last, pos = host["toks"], host["last"], host["pos"]
        tok_lat = round_s / self._dispatch_steps
        self._tok_lat.observe(tok_lat)
        self._h_tok_lat.observe(tok_lat)
        # Retire cadence is the ITL ground truth under pipelining: an
        # admission that stole host time between retires shows up here —
        # exactly what the SLO projection must learn.
        self._note_round(round_s, len(fl.slots))
        self._rounds += 1
        for b, req in fl.slots:
            if self._slot_req[b] is not req:
                continue  # finished earlier and refilled: stale garbage row
            self._last[b] = last[b]
            self._pos[b] = pos[b]
            new = toks[b].tolist()
            req.out.extend(new)
            self._emitted += len(new)
            self._maybe_finish(b, new)
        # An admission slice that rode this chunk (ISSUE 13) lands before
        # the admission pass below — a completed partial unblocks the
        # head of the line for this very pass.
        self._apply_fused(fl.fused)
        self._admit()  # freed slots refill; rows land in _fresh_rows

    def _step_speculative(self, active: list) -> bool:
        """One speculative round over the whole arena: drafts per active
        slot — n-gram from its own request history, or a k-step draft-model
        scan over the draft arena — verified in ONE [B, k+1] forward at
        per-slot positions; up to k+1 tokens per slot per weight stream,
        token-identical to the plain greedy server (the same losslessness
        :mod:`..models.speculative` proves for generate, independent of
        the draft source). Out-of-bound tail writes clamp to the arena's
        last entry, which no valid prefix ever includes (submit guarantees
        prompt + budget <= max_len, so live prefixes end at max_len-2)."""
        from ..models.speculative import (
            accept_drafts,
            draft_sample_propose,
            ngram_propose,
            sample_accept_device,
            verify_logits_step,
            verify_step,
        )

        k = self.speculative_k
        sampling = self._do_sample
        cur = self._last.copy()
        q_dev = None
        if self.draft is not None and sampling:
            # Sampling mode draws drafts from the draft's own distribution
            # (the rejection-sampling proof requires proposals from the
            # reported q); the arena is donated inside the jitted scan.
            # q stays ON DEVICE — sample_accept_device consumes it there.
            d_params, d_cfg = self.draft
            self._key, sub = jax.random.split(self._key)
            drafts_dev, q_dev, self.draft_arena = draft_sample_propose(
                d_params, self.draft_arena, jnp.asarray(cur),
                jnp.asarray(self._pos), d_cfg, k,
                self._temp_dev, sub,
            )
            drafts = np.asarray(drafts_dev)  # jaxguard: allow(JG101) speculative rounds are lock-step by design (verify needs host drafts)
        elif self.draft is not None:
            # k+1 steps, first k kept — the same cache-hole avoidance as
            # models.speculative.draft_propose (its docstring has the
            # argument); _serve_decode rather than draft_propose so the
            # draft arena is DONATED like the main arena (an undonated
            # draft scan would copy the whole draft cache every round).
            d_params, d_cfg = self.draft
            toks_dev, self.draft_arena, _dl, _dp = _serve_decode(
                d_params, self.draft_arena, jnp.asarray(cur),
                jnp.asarray(self._pos), d_cfg, k + 1, False, 0,
                jnp.float32(0.0), jax.random.PRNGKey(0),
            )
            drafts = np.asarray(toks_dev)[:, :k]  # jaxguard: allow(JG101) speculative rounds are lock-step by design
        else:
            drafts = np.zeros((self.max_batch, k), np.int32)
            for b in active:
                req = self._slot_req[b]
                hist = np.concatenate(
                    [req.prompt, np.asarray(req.out[:-1], np.int32)]
                )
                drafts[b] = ngram_propose(hist, int(cur[b]), k)
        toks = np.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
        if sampling:
            # Accept/residual runs ON DEVICE: only token ids and counts
            # cross the transport, never [B, k+1, V] distributions (at
            # production vocab that transfer would dominate the round).
            logits, self.arena = verify_logits_step(
                self.params, self.arena, jnp.asarray(toks),
                jnp.asarray(self._pos), self.cfg, ring=self.ring_kv,
            )
            self._key, sub = jax.random.split(self._key)
            tok_acc, counts = sample_accept_device(
                jnp.asarray(drafts), q_dev, logits,
                self._temp_dev, sub, k,
                has_q=q_dev is not None,
            )
            tok_acc, counts = np.asarray(tok_acc), np.asarray(counts)  # jaxguard: allow(JG101) accept decision is host scheduling input
        else:
            greedy, self.arena = verify_step(
                self.params, self.arena, jnp.asarray(toks),
                jnp.asarray(self._pos), self.cfg, ring=self.ring_kv,
            )
            greedy = np.asarray(greedy)  # jaxguard: allow(JG101) accept decision is host scheduling input
        self._rounds += 1
        for b in active:
            if sampling:
                accepted = tok_acc[b, : counts[b]].tolist()
            else:
                accepted = accept_drafts(drafts[b], greedy[b], k)
            self._slot_req[b].out.extend(accepted)
            self._last[b] = accepted[-1]
            self._pos[b] += len(accepted)
            self._emitted += len(accepted)
            self._drafts_offered += k
            self._drafts_accepted += len(accepted) - 1
            self._maybe_finish(b, accepted)
        return True


def serve_batch(params: Any, cfg: DecoderConfig, prompts: list,
                max_new_tokens: int = 64, **server_kwargs) -> list[np.ndarray]:
    """Convenience: continuous-batch a list of ragged prompts, returning the
    generated tokens in input order."""
    srv = GenerationServer(params, cfg, **server_kwargs)
    rids = [srv.submit(p, max_new_tokens) for p in prompts]
    results = srv.run()
    return [results[r] for r in rids]
