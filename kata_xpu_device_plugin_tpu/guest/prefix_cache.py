"""Shared-prefix KV cache: a device-resident prefix store with a host-side
radix (token-trie) index.

Production serving traffic is dominated by prompts that share a long common
prefix — the system prompt, a few-shot template, a conversation header.
Cold admission re-prefills that prefix from scratch for every request, so
the shared fraction of every prompt is pure repeated prefill FLOPs and
repeated TTFT. vLLM's automatic prefix caching (PagedAttention) and
SGLang's RadixAttention showed the fix: keep prefix KV resident on device,
index it by token ids, and prefill only the suffix. This module is that
capability for the slot/arena serving model of :mod:`.serving`:

- **Device side** — a dedicated KV arena (``capacity_tokens`` rows per
  layer, same leaf layout as a one-slot serving cache: ``[L, 1, cap, KV,
  D]``, bf16 or int8 :class:`~..ops.quant.QTensor`). Prefix segments are
  contiguous token ranges inside it; all copies in and out are jitted
  device-to-device ops (no host sync — the rows never leave HBM).
- **Host side** — a :class:`RadixIndex` (path-compressed token trie) maps
  token prefixes to segments, with refcounts (a segment referenced by an
  in-flight request is never evicted) and LRU eviction of unreferenced
  segments under capacity pressure.

**Bucket alignment.** Every cached boundary is a ``prefill_buckets`` value:
insertion registers entries at each bucket boundary of the stored prefix,
and :meth:`PrefixStore.lookup` returns the longest *bucket-aligned* match.
That preserves the serving executable-count bound — suffix prefills and
prefix-row copies compile one executable per bucket, exactly like cold
bucketed prefill, instead of one per distinct match length.

**Exactness.** A stored segment covers only REAL prompt tokens (the
insertion bound is ≤ ``len(prompt) - 1``, strictly inside the prompt, so
bucket-pad KV rows never enter the store), and a lookup pins its segment
until the server releases it. The suffix-prefill path built on top
(:func:`..models.transformer.prefill_suffix`) reproduces the cold path's
greedy tokens (tested in ``tests/test_prefix_cache.py``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.transformer import DecoderConfig, init_kv_caches


# ----- radix index ---------------------------------------------------------


class _Node:
    """One radix-tree node. ``edges`` maps a first token to ``(label,
    child)`` where ``label`` is the compressed edge's full token array;
    ``entry`` is the segment registered at exactly this node's depth (None
    for structural nodes)."""

    __slots__ = ("edges", "entry", "depth", "parent", "pkey")

    def __init__(self, depth: int, parent: Optional["_Node"], pkey: int = -1):
        self.edges: dict[int, tuple[np.ndarray, _Node]] = {}
        self.entry: Any = None
        self.depth = depth
        self.parent = parent
        self.pkey = pkey  # first token of the edge leading here (prune key)


def _common_len(a: np.ndarray, b: np.ndarray) -> int:
    n = min(len(a), len(b))
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if len(neq) else n


class RadixIndex:
    """Path-compressed token trie mapping exact-length token prefixes to
    opaque values. Pure host code over numpy int arrays — no device state;
    :class:`PrefixStore` owns the pairing with arena segments."""

    def __init__(self) -> None:
        self._root = _Node(0, None)
        self._entries = 0

    def __len__(self) -> int:
        return self._entries

    def longest_match(self, tokens: np.ndarray) -> tuple[int, Any]:
        """Deepest registered entry along ``tokens`` (entries only count
        when their full depth matches ``tokens`` exactly). Returns
        ``(depth, value)`` — ``(0, None)`` when nothing matches."""
        tokens = np.asarray(tokens)
        node, i = self._root, 0
        best: tuple[int, Any] = (0, None)
        while i < len(tokens):
            edge = node.edges.get(int(tokens[i]))
            if edge is None:
                break
            label, child = edge
            m = _common_len(label, tokens[i:])
            if m < len(label):
                break  # diverged mid-edge: child's depth not reached
            node, i = child, i + m
            if node.entry is not None:
                best = (node.depth, node.entry)
        return best

    def insert(self, tokens: np.ndarray, value: Any) -> _Node:
        """Register ``value`` at exactly ``len(tokens)``; returns the node
        (the handle :meth:`remove` takes). An existing entry at that depth
        is left in place (first writer wins) — callers check
        :meth:`longest_match` first when they care."""
        tokens = np.asarray(tokens, np.int32)
        node, i = self._root, 0
        while i < len(tokens):
            t = int(tokens[i])
            edge = node.edges.get(t)
            if edge is None:
                child = _Node(len(tokens), node, t)
                node.edges[t] = (tokens[i:].copy(), child)
                node, i = child, len(tokens)
                break
            label, child = edge
            m = _common_len(label, tokens[i:])
            if m == len(label):
                node, i = child, i + m
                continue
            # Split the edge at the divergence point (node.depth == i at
            # every loop head — the pointer only advances over full labels).
            mid = _Node(i + m, node, t)
            node.edges[t] = (label[:m], mid)
            mid.edges[int(label[m])] = (label[m:], child)
            child.parent, child.pkey = mid, int(label[m])
            node, i = mid, i + m
        if node.entry is None and value is not None:
            node.entry = value
            self._entries += 1
        return node

    def remove(self, node: _Node) -> None:
        """Clear ``node``'s entry and prune now-useless structural nodes
        (entry-free, childless) up the parent chain."""
        if node.entry is not None:
            node.entry = None
            self._entries -= 1
        while (
            node.parent is not None
            and node.entry is None
            and not node.edges
        ):
            parent = node.parent
            parent.edges.pop(node.pkey, None)
            node = parent


# ----- arena allocation ----------------------------------------------------


class _FreeList:
    """First-fit allocator over one token-range; ``free`` coalesces
    neighbors so eviction churn cannot fragment the arena permanently."""

    def __init__(self, capacity: int) -> None:
        self._free: list[tuple[int, int]] = [(0, capacity)] if capacity else []

    def alloc(self, n: int) -> Optional[int]:
        for i, (off, size) in enumerate(self._free):
            if size >= n:
                if size == n:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + n, size - n)
                return off
        return None

    def free(self, off: int, n: int) -> None:
        self._free.append((off, n))
        self._free.sort()
        merged: list[tuple[int, int]] = []
        for o, s in self._free:
            if merged and merged[-1][0] + merged[-1][1] == o:
                merged[-1] = (merged[-1][0], merged[-1][1] + s)
            else:
                merged.append((o, s))
        self._free = merged


@dataclass
class _Segment:
    """One contiguous prefix's KV rows in the store arena. ``refs`` counts
    in-flight requests pinning it (lookup → release); ``tick`` is the LRU
    clock; ``nodes`` are the radix entries (one per bucket boundary)
    pointing into it."""

    offset: int
    length: int
    refs: int = 0
    tick: int = 0
    nodes: list = field(default_factory=list)


@dataclass(frozen=True)
class PrefixHit:
    """A pinned lookup result: ``length`` prefix tokens live at
    ``segment.offset`` in the store arena. Hold it for the request's
    lifetime and :meth:`PrefixStore.release` it exactly once."""

    segment: _Segment
    length: int


# ----- device ops ----------------------------------------------------------
#
# All three are D2D copies inside jit — no host transfer anywhere on the
# hit path (jaxguard-clean by construction; strict mode's transfer guard
# leaves device-to-device moves free). `length` is static and always a
# prefill bucket, so the executable count is bounded by len(buckets)
# (times the admission-group N for _store_put, matching prefill_batch's
# own bound).


@partial(jax.jit, static_argnames=("length",), donate_argnums=(0,))
def _store_put(store, caches, row, offset, length: int):
    """Copy row ``row``'s first ``length`` token positions out of a prefill
    cache pytree (leaves ``[L, N, S, ...]``) into the store arena at token
    offset ``offset``. The store is donated — an insert must not copy the
    whole arena."""
    def put(s, c):
        starts = (0, row) + (0,) * (c.ndim - 2)
        sizes = (c.shape[0], 1, length) + c.shape[3:]
        seg = jax.lax.dynamic_slice(c, starts, sizes)
        at = (0, 0, offset) + (0,) * (s.ndim - 3)
        return jax.lax.dynamic_update_slice(s, seg, at)

    return jax.tree.map(put, store, caches)


@partial(jax.jit,
         static_argnames=("length", "cfg", "max_len", "quantized", "dtype",
                          "n"))
def _materialize(store, offset, length: int, cfg: DecoderConfig,
                 max_len: int, quantized: bool, dtype, n: int = 1):
    """Build a fresh ``n``-row cache pytree (``[L, n, max_len, ...]``) with
    the store rows ``[offset, offset + length)`` landed in EVERY row at
    positions ``[0, length)`` — the pre-populated caches
    :func:`..models.transformer.prefill_suffix` resumes from (``n > 1``:
    the batched-admission form, one shared prefix fanned out to n
    same-match requests). One fused zeros+gather executable per
    (bucket length, n)."""
    caches = init_kv_caches(cfg, n, max_len, dtype=dtype, quantized=quantized)

    def cp(c, s):
        starts = (0, 0, offset) + (0,) * (s.ndim - 3)
        sizes = s.shape[:2] + (length,) + s.shape[3:]
        seg = jax.lax.dynamic_slice(s, starts, sizes)
        seg = jnp.broadcast_to(seg, (seg.shape[0], n) + seg.shape[2:])
        return jax.lax.dynamic_update_slice(c, seg, (0,) * c.ndim)

    return jax.tree.map(cp, caches, store)


# ----- the store -----------------------------------------------------------


class PrefixStore:
    """Device-resident prefix KV store, radix-indexed, bucket-aligned.

    >>> store = PrefixStore(cfg, capacity_tokens=4096, buckets=(64, 256))
    >>> srv = GenerationServer(params, cfg, prefill_buckets=(64, 256),
    ...                        prefix_store=store)

    One store may back several servers in a process (the same system
    prompt served by every replica warms once); it is NOT thread-safe —
    share it only between servers driven from one thread, like the
    servers themselves.

    ``capacity_tokens`` sizes the arena (per layer: ``capacity_tokens`` KV
    rows, bf16 or int8 when ``kv_quant``). ``buckets`` must equal the
    serving ``prefill_buckets`` ladder — every cached boundary is a bucket
    value, which is what keeps the serving executable count bounded.
    """

    def __init__(self, cfg: DecoderConfig, capacity_tokens: int,
                 buckets: tuple, *, kv_quant: Optional[bool] = None,
                 dtype=None, label: str = "") -> None:
        buckets = tuple(sorted(buckets))
        if not buckets:
            raise ValueError(
                "PrefixStore needs a prefill_buckets ladder — bucket-aligned "
                "match boundaries are what bound the executable count"
            )
        if capacity_tokens < buckets[0]:
            raise ValueError(
                f"capacity_tokens={capacity_tokens} cannot hold even the "
                f"smallest bucket ({buckets[0]})"
            )
        # kv_quant=None follows the SAME int8-by-default resolution as
        # GenerationServer (serving.resolve_kv_quant — explicit arg >
        # KATA_TPU_KV_QUANT env > int8), so a default-constructed store
        # injected into a default server matches its arena dtype instead
        # of tripping the mismatch check (ISSUE 12). Call-time import:
        # serving imports this module at its top.
        from .serving import resolve_kv_quant

        kv_quant = resolve_kv_quant(kv_quant)
        self.cfg, self.buckets = cfg, buckets
        self.capacity_tokens = int(capacity_tokens)
        self.kv_quant = bool(kv_quant)
        self.dtype = dtype or cfg.dtype
        self.label = label
        self.arena = init_kv_caches(
            cfg, 1, self.capacity_tokens, dtype=self.dtype, quantized=kv_quant
        )
        self._index = RadixIndex()
        self._freelist = _FreeList(self.capacity_tokens)
        self._segments: list[_Segment] = []
        self._tick = 0
        # Cumulative counters (stats()-style snapshot semantics).
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.evictions = 0
        self.inserts = 0
        self.insert_skips = 0  # capacity pressure with everything pinned

    # -- host-side index operations -----------------------------------------

    def _next_tick(self) -> int:
        self._tick += 1
        return self._tick

    def lookup(self, prompt: np.ndarray) -> Optional[PrefixHit]:
        """Longest bucket-aligned cached prefix of ``prompt``, pinned.
        The match is capped at ``len(prompt) - 1`` — at least one suffix
        token must remain to prefill, because the suffix forward is what
        produces the first sampled token's logits. Returns None on miss
        (counted); a hit bumps the segment's LRU tick and refcount — the
        caller owns exactly one :meth:`release`."""
        prompt = np.asarray(prompt)
        depth, seg = self._index.longest_match(prompt[: len(prompt) - 1])
        if seg is None:
            self.misses += 1
            return None
        seg.refs += 1
        seg.tick = self._next_tick()
        self.hits += 1
        self.tokens_reused += depth
        return PrefixHit(seg, depth)

    def release(self, hit: PrefixHit) -> None:
        hit.segment.refs -= 1
        assert hit.segment.refs >= 0, "PrefixHit released twice"

    def cancel(self, hit: PrefixHit) -> None:
        """Release a hit that was never used (e.g. the caller's suffix
        shape degraded and it fell back to cold admission) and reverse
        the lookup's counters, so hit/miss stats reflect admissions
        actually served from the store."""
        self.release(hit)
        self.hits -= 1
        self.tokens_reused -= hit.length
        self.misses += 1

    def unlookup(self, hit: Optional[PrefixHit]) -> None:
        """Reverse one :meth:`lookup` entirely — counters AND pin — as if
        it never happened. Unlike :meth:`cancel` (the request proceeds
        cold, so the store records a miss), the caller here is NOT
        admitting the request this pass (paged head-of-line block
        reservation failed) and will look it up again when it re-offers —
        retries must not inflate the miss counter."""
        if hit is not None:
            self.cancel(hit)
        self.misses -= 1

    def insert(self, prompt: np.ndarray, caches: Any, row) -> bool:
        """Store ``prompt``'s longest bucket-aligned proper prefix from a
        freshly prefilled cache pytree (``caches`` row ``row`` holds the
        prompt's KV at positions ``0..len(prompt)-1``). Registers a radix
        entry at EVERY bucket boundary of the stored range — all sharing
        one contiguous segment — so a later prompt diverging early still
        matches at the shorter boundary. Under capacity pressure,
        unreferenced segments evict LRU-first; if pinned segments leave no
        room the insert is skipped (never an error). Returns True when a
        new segment was stored."""
        prompt = np.asarray(prompt, np.int32)
        bound = next(
            (b for b in reversed(self.buckets) if b <= len(prompt) - 1), None
        )
        if bound is None:
            return False  # prompt shorter than every bucket: nothing to key
        have, have_seg = self._index.longest_match(prompt[:bound])
        if have >= bound:
            # The full insertable prefix is already stored — but a SHALLOW
            # boundary entry may have been lost (its original segment
            # evicted while a deeper overlapping one survived): repair by
            # pointing missing boundaries at the surviving segment, whose
            # rows cover them.
            self._register_boundaries(prompt, have_seg, bound)
            return False
        offset = self._alloc(bound)
        if offset is None:
            self.insert_skips += 1
            return False
        self.arena = _store_put(
            self.arena, caches, jnp.int32(row), jnp.int32(offset),
            length=bound,
        )
        seg = _Segment(offset, bound, tick=self._next_tick())
        self._register_boundaries(prompt, seg, bound)
        self._segments.append(seg)
        self.inserts += 1
        return True

    def _register_boundaries(self, prompt: np.ndarray, seg: _Segment,
                             upto: int) -> None:
        """Point every bucket boundary ≤ ``upto`` that has no entry yet at
        ``seg`` (whose rows must cover it: ``upto <= seg.length``).
        Boundaries already served — by this segment or an earlier one —
        are left alone."""
        for b in self.buckets:
            if b > upto or b > seg.length:
                break
            depth, _ = self._index.longest_match(prompt[:b])
            if depth >= b:
                continue  # an existing segment already serves this boundary
            seg.nodes.append(self._index.insert(prompt[:b], seg))

    def _alloc(self, n: int) -> Optional[int]:
        offset = self._freelist.alloc(n)
        while offset is None:
            if not self._evict_one():
                return None
            offset = self._freelist.alloc(n)
        return offset

    def _evict_one(self) -> bool:
        """Drop the least-recently-used UNREFERENCED segment. Segments
        pinned by in-flight requests (refs > 0) are never candidates —
        capacity pressure while every segment is referenced fails the
        allocation instead."""
        victims = [s for s in self._segments if s.refs == 0]
        if not victims:
            return False
        seg = min(victims, key=lambda s: s.tick)
        for node in seg.nodes:
            self._index.remove(node)
        self._freelist.free(seg.offset, seg.length)
        self._segments.remove(seg)
        self.evictions += 1
        obs.emit(
            "serving", "prefix_evict",
            store=self.label, tokens=seg.length, offset=seg.offset,
            segments_left=len(self._segments),
        )
        return True

    # -- device-side copies --------------------------------------------------

    def materialize(self, hit: PrefixHit, max_len: int, n: int = 1):
        """A fresh ``[L, n, max_len, ...]`` cache pytree with the hit's
        prefix rows in every row at positions ``[0, hit.length)`` — feed
        it to :func:`..models.transformer.prefill_suffix` with
        ``offset=hit.length``. Pure device op (zeros + D2D gather);
        ``n > 1`` fans one shared prefix out to a same-match admission
        group."""
        return _materialize(
            self.arena, jnp.int32(hit.segment.offset), hit.length,
            self.cfg, max_len, self.kv_quant, self.dtype, n=n,
        )

    # -- reporting -----------------------------------------------------------

    @property
    def tokens_used(self) -> int:
        return sum(s.length for s in self._segments)

    def occupancy(self) -> float:
        return round(self.tokens_used / self.capacity_tokens, 4)

    def stats(self) -> dict:
        """Cumulative store counters + occupancy (snapshot semantics: this
        never resets anything)."""
        return {
            "capacity_tokens": self.capacity_tokens,
            "tokens_used": self.tokens_used,
            "occupancy": self.occupancy(),
            "segments": len(self._segments),
            "entries": len(self._index),
            "hits": self.hits,
            "misses": self.misses,
            "tokens_reused": self.tokens_reused,
            "inserts": self.inserts,
            "insert_skips": self.insert_skips,
            "evictions": self.evictions,
        }
