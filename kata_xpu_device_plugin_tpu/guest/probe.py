"""Guest validation ladder (BASELINE.json configs[0..2]).

Runs INSIDE the Kata guest (or any JAX environment) to verify the devices
the plugin injected actually work: device visibility, basic compute, and the
all-reduce smoke test. Prints one JSON object per check so the results are
machine-comparable against the north star (``jax.device_count() == 8`` on
v5e-8).
"""
from __future__ import annotations

import json
import sys
from typing import Optional


def probe_devices(expected: Optional[int] = None) -> dict:
    """configs[1]: the injected chips initialize and enumerate."""
    import jax

    devices = jax.devices()
    result = {
        "check": "devices",
        "platform": devices[0].platform if devices else "none",
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "devices": [str(d) for d in devices],
        "ok": True,
    }
    if expected is not None:
        result["expected"] = expected
        result["ok"] = jax.device_count() == expected
    return result


def probe_compute() -> dict:
    """A matmul runs on the accelerator and returns sane numerics."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((256, 256), jnp.bfloat16)
    y = (x @ x).astype(jnp.float32)
    ok = bool(jnp.allclose(y, 256.0))
    return {"check": "compute", "ok": ok, "value": float(y[0, 0])}


def probe_all_reduce() -> dict:
    """configs[2]: pmap psum across every visible chip exercises ICI."""
    import jax
    import jax.numpy as jnp

    from ..ops.collectives import pmap_all_reduce

    n = jax.local_device_count()
    x = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)
    out = pmap_all_reduce(x)
    expect = float(n * (n - 1) / 2)
    ok = bool(jnp.allclose(out, expect))
    return {"check": "all_reduce", "devices": n, "ok": ok, "value": float(out[0, 0])}


def run_ladder(expected_devices: Optional[int] = None) -> int:
    """Run all probes; exit code 0 iff every check passed."""
    ok = True
    for result in (
        probe_devices(expected_devices),
        probe_compute(),
        probe_all_reduce(),
    ):
        print(json.dumps(result))
        ok = ok and result["ok"]
    return 0 if ok else 1


if __name__ == "__main__":
    expected = int(sys.argv[1]) if len(sys.argv) > 1 else None
    sys.exit(run_ladder(expected))
