"""Tensor-parallel serving over the ICI slice (ISSUE 9).

The plugin hands Kata guests whole ICI-connected slices and emits the
libtpu topology env (``topology/slice.py`` → CDI containerEdits /
AllocateResponse); this module is the GUEST half of that contract for
serving: it turns the injected topology into a 1×N device mesh so one
:class:`.serving.GenerationServer` shards its params, KV pool, prefix
store, and decode/prefill executables across every chip of the
allocation instead of serving from one.

Resolution ladder for the tensor-parallel degree (``tp_from_env``):

1. ``KATA_TPU_TP`` — the explicit override the daemon's ``--serving-tp``
   knob injects into the AllocateResponse env (``config.serving_tp``).
   ``0``/``1`` pins single-chip serving; malformed values DEGRADE to the
   derived default with a ``tp_disabled`` event (a node-wide knob must
   never crash a guest — the pool/prefix/scheduler env contract).
2. ``TPU_VISIBLE_CHIPS`` — the per-allocation chip list: its length IS
   the slice the guest was granted.
3. ``TPU_ACCELERATOR_TYPE`` — the static slice topology: the host-local
   chip count of the advertised type.
4. Neither present (CPU tests, non-TPU hosts): 1.

A derived degree larger than what JAX actually exposes degrades to 1
with an ``insufficient_devices`` event rather than failing mesh
construction — the env describes the allocation, the backend describes
reality, and serving must come up on whatever is real. On CPU,
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` stands in for the
chips (the tier-1/`make tp` test harness), so the whole
daemon-env → guest-mesh round trip is testable without hardware.

The mesh itself (``serving_mesh``) is the standard ``data×fsdp×model``
mesh with both data axes collapsed to 1 — every parallel rule in
:mod:`..parallel.sharding` (the ``SERVING_RULES`` regex set, the KV
head-axis specs) applies unchanged, and on hardware
``mesh_utils.create_device_mesh`` maps the ``model`` axis onto ICI
neighbors. Host-side scheduling state (``last``/``pos``, block tables)
rides each dispatch as plain uncommitted host arrays exactly as in
single-chip serving: GSPMD replicates them into the executable without a
resharding step in the decode hot path (strict mode's transfer guard and
jaxguard keep it that way).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from .. import obs

# The daemon-injectable override (cdi.constants.ENV_SERVING_TP rides the
# same AllocateResponse path as the pool/prefix/scheduler knobs).
ENV_TP = "KATA_TPU_TP"

# Paged-pool placement layout (ISSUE 14, docs/guest_guide.md "KV layouts
# & host offload tier"): "heads" keeps the historical divide-or-replicate
# head-axis sharding; "blocks" shards the paged pool's TOKEN axis across
# the model mesh — per-shard pool bytes are ~logical/tp for EVERY model,
# GQA included (the kv_replicated cliff does not exist under blocks).
ENV_KV_LAYOUT = "KATA_TPU_KV_LAYOUT"
KV_LAYOUT_HEADS = "heads"
KV_LAYOUT_BLOCKS = "blocks"
KV_LAYOUTS = (KV_LAYOUT_HEADS, KV_LAYOUT_BLOCKS)

# Overlapped tp collectives (ISSUE 20): under tensor-parallel serving
# the two per-layer row-parallel projections (wo, w_down) each carry one
# model-axis psum that GSPMD serializes against the surrounding matmuls.
# KATA_TPU_TP_OVERLAP (guest-side, env-only — like KATA_TPU_DEGRADED)
# keeps the overlap DECOMPOSITION armed by default: the server resolves
# one static ``overlap_reduce_fn`` per mesh and the transformer applies
# it at both sites, splitting each psum into reduce-scatter +
# all-gather so the collective phases pipeline against compute.
# Numerics are exactly the psum's (same shard partials, same summation
# axis order — tested bit-identical at tp=2); "0" restores the single
# fused psum, malformed values degrade with a ``tp_overlap_disabled``
# event.
ENV_TP_OVERLAP = "KATA_TPU_TP_OVERLAP"

# Degraded-mode knobs (ISSUE 10, docs/resilience.md "Degraded mode"):
# the floor of the elastic mesh-shrink ladder a permanent chip fault
# walks (daemon-injectable, cdi.constants.ENV_SERVING_TP_MIN), and the
# guest-side kill switch that disables mesh shrink entirely (a chip loss
# then fails the in-flight load loudly instead of continuing degraded).
ENV_TP_MIN = "KATA_TPU_TP_MIN"
ENV_DEGRADED = "KATA_TPU_DEGRADED"


def degraded_enabled(env: Optional[dict] = None) -> bool:
    """Is elastic mesh-shrink recovery allowed? ``KATA_TPU_DEGRADED=0``
    is the kill switch — any other value (including unset) enables it."""
    env = os.environ if env is None else env
    return env.get(ENV_DEGRADED, "1") != "0"


def tp_min_from_env(*, label: str = "", trace: str = "") -> int:
    """The shrink ladder's floor from the daemon-injected env (default 1
    — degrade all the way to single-chip serving before giving up).
    Rides :func:`.resilience.env_int`'s degrade contract: a malformed
    node-wide knob falls back with one ``tp_min_invalid`` event, never a
    crash."""
    from . import resilience

    return max(1, resilience.env_int(
        ENV_TP_MIN, 1, event="tp_min_invalid", server=label, trace=trace
    ))


def shrink_ladder(tp: int, survivors: int,
                  tp_min: int = 1) -> Optional[int]:
    """The next feasible degraded tensor-parallel degree after a
    permanent fault at degree ``tp``: HALVE until the rung both fits the
    surviving chip count and stays at or above the ``tp_min`` floor
    (tp=4 → 2 → 1). Halving keeps every rung a valid 1×N sub-mesh of the
    original allocation (the same power-of-two sub-slice shapes
    ``topology.preferred`` hands out — see ``degraded_fallbacks``), and
    divisibility-dependent layouts (KV head sharding) re-resolve per rung
    through :func:`kv_heads_shardable`. ``None`` when no rung survives:
    the caller fails the load loudly instead of retrying into a dead
    mesh."""
    floor = max(1, int(tp_min))
    t = tp // 2
    while t >= floor:
        if t <= survivors:
            return t
        t //= 2
    return None


def allocation_chips(env: Optional[dict] = None) -> str:
    """The daemon-granted chip set this guest serves on — the normalized
    ``TPU_VISIBLE_CHIPS`` list, ``""`` outside an allocation. Every
    serving heartbeat carries it (ISSUE 15), so the daemon-side
    aggregator can label its per-allocation gauges with the SAME
    identity its Allocate handler journaled, instead of trusting file
    naming conventions."""
    env = os.environ if env is None else env
    raw = env.get("TPU_VISIBLE_CHIPS", "").strip()
    return ",".join(c.strip() for c in raw.split(",") if c.strip())


def _topology_chips(env) -> int:
    """Chip count the injected topology env describes (1 when absent)."""
    raw = env.get("TPU_VISIBLE_CHIPS", "").strip()
    if raw:
        return len([c for c in raw.split(",") if c.strip()]) or 1
    accel = env.get("TPU_ACCELERATOR_TYPE", "").strip()
    if accel:
        from ..topology.slice import HostTopology

        try:
            return HostTopology.from_accelerator_type(accel).local_chips
        except ValueError:
            return 1
    return 1


def tp_from_env(env: Optional[dict] = None, *, label: str = "",
                device_count: Optional[int] = None,
                trace: str = "") -> int:
    """Resolve the serving tensor-parallel degree from the daemon-injected
    env (see the module header's ladder). Always returns ``>= 1``; every
    degrade emits one ``serving/tp_disabled`` event with a reason
    (``trace`` joins it to the allocation trace, ISSUE 11)."""
    env = os.environ if env is None else env
    t_extra = {"trace": trace} if trace else {}
    raw = env.get(ENV_TP, "").strip()
    tp = None
    if raw:
        try:
            tp = int(raw)
        except ValueError:
            obs.emit(
                "serving", "tp_disabled",
                server=label, reason=f"bad_env:{raw[:32]}", **t_extra,
            )
            tp = None
        else:
            if tp < 0:
                obs.emit(
                    "serving", "tp_disabled",
                    server=label, reason=f"bad_env:{raw[:32]}", **t_extra,
                )
                tp = None
            elif tp == 0:
                tp = 1  # explicit off
    if tp is None:
        tp = _topology_chips(env)
    if tp > 1:
        if device_count is None:
            import jax

            device_count = jax.device_count()
        if tp > device_count:
            obs.emit(
                "serving", "tp_disabled",
                server=label, tp=tp,
                reason=f"insufficient_devices:{device_count}", **t_extra,
            )
            tp = 1
    return max(1, tp)


def serving_mesh(tp: int, devices: Optional[Sequence] = None):
    """The 1×N serving mesh: ``data=1, fsdp=1, model=tp`` over the first
    ``tp`` devices. All of :mod:`..parallel.sharding`'s rules apply
    unchanged (the collapsed data axes are size-1 no-ops), and on real
    slices ``mesh_utils`` places the ``model`` axis on ICI neighbors."""
    import jax

    from ..parallel.mesh import (
        AXIS_DATA,
        AXIS_FSDP,
        AXIS_MODEL,
        build_mesh,
    )

    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < tp:
        raise ValueError(
            f"tp={tp} needs {tp} devices, have {len(devices)}"
        )
    return build_mesh(
        {AXIS_DATA: 1, AXIS_FSDP: 1, AXIS_MODEL: tp}, devices=devices[:tp]
    )


def overlap_reduce_fn(mesh, cfg, *, label: str = "",
                      emit=None):
    """The per-mesh STATIC overlap hint for the transformer's two
    row-parallel reduce sites (ISSUE 20): a callable applied to the
    ``wo`` / ``w_down`` projection outputs that re-constrains the
    pending model-axis psum into a reduce-scatter over the hidden axis
    followed by an all-gather, which XLA's latency-hiding scheduler can
    pipeline against the adjacent matmuls (the ICI-adjacent collective
    overlap "Exploration of TPUs for AI Applications" documents).
    Resolved ONCE per server per mesh — the function's identity is part
    of every decode executable's cache key, exactly like the decode
    kernel callable, so a mesh change can never reuse a stale overlap
    form.

    Returns ``None`` (the single fused psum) when the knob is off
    (``KATA_TPU_TP_OVERLAP=0``), there is no model-parallel mesh, or
    ``cfg.d_model`` does not divide the degree (a ragged hidden shard
    cannot reduce-scatter); malformed knob values degrade with one
    ``tp_overlap_disabled`` event, never a crash."""
    raw = os.environ.get(ENV_TP_OVERLAP, "").strip()
    if raw and raw not in ("0", "1"):
        if emit is not None:
            emit("tp_overlap_disabled", reason=f"bad_env:{raw[:32]}")
        else:
            obs.emit(
                "serving", "tp_overlap_disabled",
                server=label, reason=f"bad_env:{raw[:32]}",
            )
        raw = ""
    if raw == "0" or mesh is None:
        return None
    from ..parallel.mesh import AXIS_MODEL

    tp = dict(mesh.shape).get(AXIS_MODEL, 1)
    if tp <= 1 or cfg.d_model % tp:
        return None
    import jax
    from jax.sharding import NamedSharding

    from ..compat.jaxapi import P

    scattered = NamedSharding(mesh, P(None, None, AXIS_MODEL))
    gathered = NamedSharding(mesh, P(None, None, None))

    def _overlap_reduce(x):
        # Constraint pair: land the partial-sum reduction SHARDED over
        # the hidden axis (GSPMD lowers the pending psum to
        # reduce-scatter), then replicate (all-gather) — two pipelined
        # collective phases computing exactly the psum's value.
        x = jax.lax.with_sharding_constraint(x, scattered)
        return jax.lax.with_sharding_constraint(x, gathered)

    return _overlap_reduce


def kv_heads_shardable(cfg, tp: int) -> bool:
    """The ONE divide-or-replicate decision for serving KV state: the
    head axis shards over ``model`` only when the KV head count divides
    the degree (splitting a GQA group across shards would break its
    structure; replication is correct, memory-heavier). Every KV
    placement — arena, pool, prefix store, spill-restore uploads — must
    route through this predicate so the layouts cannot drift apart."""
    return tp > 1 and cfg.n_kv_heads % tp == 0


def kv_cache_spec(cfg, tp: int, layout: str = KV_LAYOUT_HEADS):
    """PartitionSpec for every serving KV ARENA layout — the dense slot
    arena ``[L, B, S, KV, D]``, the paged pool ``[L, 1, NT, KV, D]`` and
    the prefix-store arena share the head axis at position 3 (int8
    ``QTensor`` scales carry the same leading axes) — sharded over
    ``model`` per :func:`kv_heads_shardable` under the default "heads"
    layout. Under the "blocks" layout (ISSUE 14, paged pools only) the
    TOKEN axis (position 2 — the ``NT`` dim of the pool; whole blocks,
    the pool keeps ``num_blocks`` a multiple of tp) shards over ``model``
    instead: per-shard pool bytes are ``~logical/tp`` for every model —
    no divide-or-replicate decision, no GQA replication cliff."""
    from ..compat.jaxapi import P
    from ..parallel.mesh import AXIS_MODEL

    if layout == KV_LAYOUT_BLOCKS:
        if tp > 1:
            return P(None, None, AXIS_MODEL, None, None)
        return P()
    if kv_heads_shardable(cfg, tp):
        return P(None, None, None, AXIS_MODEL, None)
    return P()


def kv_rows_spec(cfg, tp: int, head_axis: int,
                 layout: str = KV_LAYOUT_HEADS):
    """PartitionSpec for host-spill ROW layouts (checkpoint/preemption
    restore uploads) whose KV head axis sits at ``head_axis`` — the
    paged full-table spill ``[L, NT, KV, D]`` (axis 2) and the slotted
    snapshot ``[L, 1, S, KV, D]`` (axis 3). Same
    :func:`kv_heads_shardable` decision as the arenas they restore
    into, so a restore never forces a resharding. Under the "blocks"
    layout the uploaded rows REPLICATE (a spill's row count is a lane's
    table width, not the pool's — it need not divide tp); the restore
    scatter then re-distributes the rows into the token-sharded pool
    inside the same jitted dispatch, which is data movement GSPMD
    already owns."""
    from ..compat.jaxapi import P
    from ..parallel.mesh import AXIS_MODEL

    if layout == KV_LAYOUT_BLOCKS:
        return P()
    if kv_heads_shardable(cfg, tp):
        return P(*([None] * head_axis), AXIS_MODEL, None)
    return P()
