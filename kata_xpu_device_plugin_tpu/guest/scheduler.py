"""SLO-aware prefill/decode scheduling policies (ISSUE 8).

PR 6 made admission a token-budget problem (the paged pool) and PR 7 made
rounds survivable; what neither touched is WHEN prefill work runs. Today a
long prompt's admission prefill executes as one forward between two decode
rounds, so every in-flight request's inter-token latency absorbs the whole
prompt — the head-of-line blocking FlexNPU (PAPERS.md) and Sarathi-style
chunked prefill exist to remove. This module supplies the missing policy
layer as pluggable objects :class:`GenerationServer` consults each round:

- :class:`Scheduler` (``fifo_batch``) — the identity baseline: every
  admission pass admits the full FIFO prefix in one (possibly batched)
  prefill, exactly the pre-ISSUE-8 behavior. Zero overhead, zero new
  decisions.
- :class:`SLOChunkedScheduler` (``slo_chunked``) — deadline-driven
  admission: when in-flight requests' PROJECTED inter-token latency
  (estimated prefill time of the pending admission plus the observed
  decode-round cadence, normalized per delivered token — the same unit
  as the ``decode_token_s`` metric) would exceed ``KATA_TPU_ITL_SLO_MS``,
  the
  admission is sliced into ``KATA_TPU_PREFILL_CHUNK``-token chunks that
  resume through the PR 5 ``prefill_suffix`` offset machinery, and the
  serving loop interleaves AT MOST ONE chunk with each decode dispatch.
  Decode rounds then stall for one chunk, not one prompt. With no decode
  in flight (or no estimate yet — the first admissions bootstrap the
  EWMAs) admission runs whole, so TTFT is never taxed when there is no
  ITL to protect.

The scheduler only decides WHEN prefill work happens and in what slice
sizes — never what the forwards compute — so greedy outputs under
``slo_chunked`` are bit-identical to ``fifo_batch`` (tested across
paged/slotted × overlap × strict × prefix-hit in
``tests/test_scheduler.py``). Chunking preserves strict FIFO by
construction: a chunked admission is head-of-line — nothing admits past
it while its chunks run — and a mid-chunk crash replays it from the
prompt through the PR 7 strict-FIFO requeue.

Policy selection rides the same env/daemon knob contract as the pool and
prefix stores: ``KATA_TPU_SCHED_POLICY`` (injected node-wide via
``config.sched_policy``) with malformed or incompatible values degrading
to ``fifo_batch`` with a ``sched_disabled`` event, while explicit
constructor arguments raise. jax-free at import: estimates are host
floats, so host-side tests and the daemon can import this module.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import obs

ENV_SCHED_POLICY = "KATA_TPU_SCHED_POLICY"
ENV_PREFILL_CHUNK = "KATA_TPU_PREFILL_CHUNK"
ENV_ITL_SLO_MS = "KATA_TPU_ITL_SLO_MS"

POLICY_FIFO = "fifo_batch"
POLICY_SLO = "slo_chunked"
POLICIES = (POLICY_FIFO, POLICY_SLO)

# A chunk should be several decode chunks' worth of work, small against a
# production prompt; 128 splits a 1k-token system prompt into 8 slices.
DEFAULT_PREFILL_CHUNK = 128
# Interactive serving's common budget: ~20 tok/s perceived streaming rate.
DEFAULT_ITL_SLO_MS = 50.0

# EWMA weight for the prefill-rate / round-cadence estimates: heavy enough
# to converge within a few observations, light enough that one outlier
# round (a compile, a GC pause) does not flip the admission decision.
_EWMA_ALPHA = 0.3


@dataclass(frozen=True)
class Directive:
    """One admission decision. ``admit=True``: run the normal (full)
    admission pass — the fifo_batch behavior. ``admit=False``: advance the
    pending admission by one prefill chunk, then yield the round back to
    decode (``defer_reason`` and the projection say why — they ride the
    ``sched_defer`` event). ``fused=True`` (ISSUE 13, only ever set on a
    deferral by a fused-enabled ``slo_chunked`` policy): the chunk should
    RIDE the next decode dispatch as one fused forward instead of running
    as its own fenced slice round — the serving loop's
    ``_dispatch_decode`` is the single call site for both."""

    admit: bool
    defer_reason: str = ""
    projected_itl_ms: float = 0.0
    fused: bool = False


class Scheduler:
    """The ``fifo_batch`` policy and the base every policy extends: admit
    everything, every pass (today's behavior — the identity baseline the
    bench A/B and the bit-identity tests compare against). Also owns the
    bookkeeping every policy shares: the prefill-rate and round-cadence
    EWMAs, the chunk/defer/violation counters ``stats()`` exposes, and the
    queue-delay summary (submit → admission grant, the component of TTFT
    the scheduler actually controls)."""

    name = POLICY_FIFO

    def __init__(self, *, chunk_tokens: int = 0,
                 slo_ms: float = 0.0, decode_steps: int = 1,
                 fused: bool = False, label: str = ""):
        self.chunk_tokens = int(chunk_tokens)
        self.slo_ms = float(slo_ms)
        # The server's DEFAULT per-dispatch step count: rounds deliver
        # this many tokens per lane, so PER-TOKEN latency (the unit
        # ``slo_ms`` is in, matching the ``decode_token_s`` metric) is
        # the round cadence divided by the delivered steps. It seeds
        # ``_last_steps``; :meth:`note_round` overrides it with the
        # ACTUAL tokens-per-dispatch each round (ISSUE 13 — fused rounds
        # and multi-step decode change the delivered count at runtime,
        # so a static divisor would misproject the SLO).
        self.decode_steps = max(1, int(decode_steps))
        # Fused admission (ISSUE 13): deferrals ask the serving loop to
        # ride the chunk on the decode dispatch instead of running a
        # separate fenced slice round.
        self.fused = bool(fused)
        self.label = label
        self.chunks = 0          # chunked-prefill forwards run
        self.defers = 0          # rounds that deferred admission to decode
        self.slo_violations = 0  # observed rounds over the ITL SLO
        self.queue_delay = obs.Rolling()
        self._prefill_s_per_tok: Optional[float] = None
        # PER-TOKEN decode cadence EWMA (round duration / ACTUAL steps
        # delivered) — the satellite fix: the old code EWMA'd the raw
        # round cadence and divided by a static decode_steps at
        # projection time, which misprojects the moment the delivered
        # tokens-per-dispatch differ from the configured count.
        self._tok_s: Optional[float] = None
        self._last_steps: int = self.decode_steps

    # ----- observations (the serving loop feeds these) ---------------------

    def note_prefill(self, tokens: int, dur_s: float) -> None:
        """One prefill forward completed: fold its per-token cost into the
        rate estimate (chunk forwards count too — they are the freshest
        samples of exactly the work being projected)."""
        if tokens <= 0 or dur_s <= 0:
            return
        per_tok = dur_s / tokens
        if self._prefill_s_per_tok is None:
            self._prefill_s_per_tok = per_tok
        else:
            self._prefill_s_per_tok += _EWMA_ALPHA * (
                per_tok - self._prefill_s_per_tok
            )

    def note_round(self, dur_s: float, steps: int = 0) -> bool:
        """One decode round retired at cadence ``dur_s``, delivering
        ``steps`` tokens per live lane (0 = the configured
        ``decode_steps`` — unit tests and legacy callers). The EWMA
        tracks the PER-TOKEN cadence from the actual tokens-per-dispatch,
        so multi-step decode (``decode_steps=K``) and fused rounds feed
        the projection in the ``slo_ms`` unit directly. Returns True when
        the round violated the policy's ITL SLO (the serving loop emits
        the ``slo_violation`` event — the base policy has no SLO and
        never violates)."""
        if dur_s <= 0:
            return False
        steps = max(1, int(steps) if steps else self.decode_steps)
        self._last_steps = steps
        per_tok = dur_s / steps
        if self._tok_s is None:
            self._tok_s = per_tok
        else:
            self._tok_s += _EWMA_ALPHA * (per_tok - self._tok_s)
        return self._check_slo(per_tok)

    def note_queue_delay(self, delay_s: float) -> None:
        """A request left the queue (admission granted): record its
        submit→grant wait."""
        self.queue_delay.observe(max(0.0, float(delay_s)))

    def reset_estimates(self) -> None:
        """Drop the prefill-rate and per-token-cadence EWMAs. Called by
        the serving loop after a degraded-mode mesh shrink (ISSUE 11) and
        by :meth:`note_config` when the dispatch regime changes (ISSUE
        13): the estimates were measured under the OLD regime — a
        shrunken mesh is slower, a different ``decode_steps`` or fused
        plan changes what one round delivers — and stale values would
        mis-project the first admissions after the change.
        Re-bootstrapping keeps the projection honest (the first
        post-change admission and round re-measure)."""
        self._prefill_s_per_tok = None
        self._tok_s = None
        self._last_steps = self.decode_steps

    def note_config(self, *, decode_steps: Optional[int] = None,
                    fused: Optional[bool] = None) -> bool:
        """Adopt a changed dispatch configuration (ISSUE 13 satellite):
        when the per-dispatch step count K or the fused-plan flag
        CHANGES, the per-round timings the EWMAs hold were measured
        under the old regime and would misproject the SLO —
        :meth:`reset_estimates` drops them. Returns True when anything
        changed (and estimates were reset)."""
        changed = False
        if decode_steps is not None and max(1, int(decode_steps)) != (
                self.decode_steps):
            self.decode_steps = max(1, int(decode_steps))
            changed = True
        if fused is not None and bool(fused) != self.fused:
            self.fused = bool(fused)
            changed = True
        if changed:
            self.reset_estimates()
        return changed

    def _check_slo(self, per_tok_s: float) -> bool:
        return False

    # ----- the decision ----------------------------------------------------

    def directive(self, *, live_lanes: int, pending_tokens: int,
                  partial: bool = False) -> Directive:
        """The per-pass admission decision. ``live_lanes``: requests
        currently decoding (whose ITL a long prefill would stall);
        ``pending_tokens``: the prefill tokens the pending admission still
        needs (the queue head's padded cost, or a partial admission's
        remaining suffix); ``partial=True``: a chunked admission is already
        in progress (head-of-line — the decision is continue-whole vs
        one-more-chunk, never skip)."""
        return Directive(admit=True)

    # ----- introspection ---------------------------------------------------

    def projected_itl_s(self, pending_tokens: int) -> Optional[float]:
        """The PER-TOKEN latency in-flight requests would see if
        ``pending_tokens`` of prefill ran as one forward now: the
        estimated prefill stall amortized over the tokens one dispatch
        actually delivers (``_last_steps`` — learned per round, not the
        static configured count) plus the per-token decode cadence — the
        same unit as the ``decode_token_s`` metric and ``slo_ms``. None
        until both estimates exist (the bootstrap admissions measure
        them)."""
        if self._prefill_s_per_tok is None or self._tok_s is None:
            return None
        steps = max(1, self._last_steps)
        return pending_tokens * self._prefill_s_per_tok / steps + self._tok_s

    def stats(self) -> dict:
        """The always-present scheduler fields ``GenerationServer.stats()``
        merges in (zeros under ``fifo_batch`` — no schema branch)."""
        return {
            "sched_policy": self.name,
            "sched_chunks": self.chunks,
            "sched_defers": self.defers,
            "slo_violations": self.slo_violations,
            "prefill_chunk_tokens": self.chunk_tokens,
            "itl_slo_ms": self.slo_ms,
            "sched_queue_delay_s": self.queue_delay.summary(),
        }

    def heartbeat_fields(self) -> dict:
        """The scheduler's slice of the serving heartbeat (ISSUE 15):
        the admission-wait rolling quantiles in the heartbeat's ms unit
        — queue depth's latency twin, and the number the fleet router
        will route on (how long does THIS replica make requests wait).
        The chunk/defer/violation counters are NOT repeated here: the
        heartbeat already derives their interval ``*_delta`` fields
        from this object's raw counters."""
        q = self.queue_delay.summary()
        return {
            "admission_wait_p50_ms": round(q.get("p50", 0.0) * 1e3, 3),
            "admission_wait_p99_ms": round(q.get("p99", 0.0) * 1e3, 3),
        }


class SLOChunkedScheduler(Scheduler):
    """``slo_chunked``: defer (chunk) the pending admission whenever the
    projected ITL of running it whole would exceed the SLO and somebody is
    decoding to feel it. See the module header for the policy argument."""

    name = POLICY_SLO

    def __init__(self, *, chunk_tokens: int = DEFAULT_PREFILL_CHUNK,
                 slo_ms: float = DEFAULT_ITL_SLO_MS, decode_steps: int = 1,
                 fused: bool = False, label: str = ""):
        if chunk_tokens < 1:
            raise ValueError(
                f"prefill chunk must be >= 1 token, got {chunk_tokens}"
            )
        super().__init__(chunk_tokens=chunk_tokens, slo_ms=slo_ms,
                         decode_steps=decode_steps, fused=fused, label=label)

    def _check_slo(self, per_tok_s: float) -> bool:
        # Per-token, like slo_ms itself (note_round already normalized
        # the cadence by the round's ACTUAL delivered steps — the
        # ``decode_token_s`` metric's unit).
        if per_tok_s * 1000.0 > self.slo_ms:
            self.slo_violations += 1
            return True
        return False

    def directive(self, *, live_lanes: int, pending_tokens: int,
                  partial: bool = False) -> Directive:
        if live_lanes == 0:
            # Nobody is decoding: there is no ITL to protect, and chunking
            # would only tax this request's own TTFT.
            return Directive(admit=True)
        if not partial and pending_tokens <= self.chunk_tokens:
            # The whole admission is one chunk's worth — slicing cannot
            # shrink the stall, so take the cold/batched fast path.
            return Directive(admit=True)
        proj = self.projected_itl_s(pending_tokens)
        if proj is None:
            # Bootstrap: no estimates yet (the first admission and round
            # measure them) — admitting whole is the only honest choice.
            return Directive(admit=True)
        proj_ms = proj * 1000.0
        if proj_ms <= self.slo_ms:
            return Directive(admit=True)
        # The FUSED PLAN (ISSUE 13): a fused-enabled policy asks the
        # serving loop to batch the deferred chunk WITH the decode step
        # (one dispatch, one fence) instead of alternating slice-round /
        # decode-round — decode lanes stop stalling behind admission.
        return Directive(
            admit=False, defer_reason="projected_itl",
            projected_itl_ms=round(proj_ms, 3), fused=self.fused,
        )


def make_scheduler(policy: str, *, chunk_tokens: int, slo_ms: float,
                   decode_steps: int = 1, fused: bool = False,
                   label: str = "") -> Scheduler:
    """Instantiate a policy by knob value. Raises ``ValueError`` on an
    unknown name — the CALLER owns the env-vs-explicit degrade contract
    (``GenerationServer`` degrades env values with a ``sched_disabled``
    event and raises on explicit arguments, like the pool/prefix knobs).
    ``decode_steps`` is the server's per-dispatch step count — the
    DEFAULT round→per-token normalizer (``note_round`` learns the actual
    delivered count per round) that keeps ``slo_ms`` in the same unit as
    the ``decode_token_s`` metric. ``fused`` marks deferrals as fused
    plans (the chunk rides the decode dispatch — ISSUE 13)."""
    if policy == POLICY_FIFO:
        return Scheduler(decode_steps=decode_steps, label=label)
    if policy == POLICY_SLO:
        return SLOChunkedScheduler(
            chunk_tokens=chunk_tokens, slo_ms=slo_ms,
            decode_steps=decode_steps, fused=fused, label=label,
        )
    raise ValueError(
        f"unknown scheduler policy {policy!r} (have {POLICIES})"
    )
