"""Guest-side multi-host initialization from the env the plugin injects.

The plugin's CDI ``containerEdits`` hand every Kata pod of a multi-host
slice a consistent identity (``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES`` —
``topology.runtime_env``; cites ref's absence of any cross-node logic,
SURVEY §7 hard parts). This module is the other half of that contract: JAX
in the guest turns that identity into a ``jax.distributed`` process group so
DCN-coordinated compilation and multi-host collectives work.

Intra-slice ICI needs no software rendezvous (libtpu wires it from the same
env); ``jax.distributed.initialize`` adds the HOST coordination layer —
cross-host barriers, distributed arrays, compilation-cache agreement — and,
for multislice jobs, rides the ``MEGASCALE_*`` env the plugin emits.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class DistributedConfig:
    """Resolved multi-host identity (pre-``jax.distributed`` call)."""

    num_processes: int
    process_id: int
    coordinator_address: Optional[str]  # None for single-host: no-op init

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1


def resolve(env: Optional[dict] = None,
            port: int = DEFAULT_COORDINATOR_PORT) -> DistributedConfig:
    """Derive the process group from the plugin-injected env.

    Worker 0's hostname is the coordinator (every host computes the same
    ordered list, so the choice is consistent without any extra channel).
    Missing/single-host env resolves to a no-op config rather than raising —
    single-host pods must run unmodified.
    """
    env = os.environ if env is None else env
    hostnames = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    if len(hostnames) <= 1:
        # Fail closed on the inverse contradiction too: a nonzero worker id
        # with no multi-host hostname list means THIS pod lost its list — if
        # it silently ran single-host, its slice peers would hang in
        # initialize() waiting for it. (id=0 + no list is plain single-host.)
        raw_id = env.get("TPU_WORKER_ID", "")
        if raw_id.strip() and raw_id.strip() != "0":
            raise ValueError(
                f"TPU_WORKER_ID={raw_id} names a multi-host worker but "
                "TPU_WORKER_HOSTNAMES is missing/single — refusing to run "
                "single-host while slice peers wait"
            )
        return DistributedConfig(1, 0, None)
    try:
        worker_id = int(env.get("TPU_WORKER_ID", ""))
    except ValueError:
        raise ValueError(
            "TPU_WORKER_HOSTNAMES names a multi-host slice but TPU_WORKER_ID "
            "is missing/malformed — the plugin injects both together; "
            "refusing to guess a process id"
        )
    if not 0 <= worker_id < len(hostnames):
        raise ValueError(
            f"TPU_WORKER_ID={worker_id} out of range for "
            f"{len(hostnames)} worker hostnames"
        )
    return DistributedConfig(
        num_processes=len(hostnames),
        process_id=worker_id,
        coordinator_address=f"{hostnames[0]}:{port}",
    )


def initialize_from_env(env: Optional[dict] = None,
                        port: int = DEFAULT_COORDINATOR_PORT,
                        dry_run: bool = False) -> dict:
    """Initialize ``jax.distributed`` from the injected env; returns a JSON-
    friendly summary (mirrors the guest probe ladder's reporting style).

    Single-host: no-op. ``dry_run=True`` reports what would be passed
    without touching JAX (used by tests and the `status` tooling)."""
    cfg = resolve(env, port)
    summary = {
        "multi_host": cfg.multi_host,
        "num_processes": cfg.num_processes,
        "process_id": cfg.process_id,
        "coordinator_address": cfg.coordinator_address,
        "initialized": False,
    }
    if dry_run or not cfg.multi_host:
        return summary
    import jax

    from ..compat.jaxapi import enable_cpu_multiprocess_collectives

    # 0.4.x CPU backends cannot run cross-process computations until the
    # gloo collectives are selected (newer JAX defaults them on). Must
    # happen before the backend is instantiated, i.e. right here.
    enable_cpu_multiprocess_collectives(jax)

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    summary["initialized"] = True
    summary["global_devices"] = jax.device_count()
    summary["local_devices"] = jax.local_device_count()
    return summary
