"""Fault injection + crash-tolerance primitives for the serving loop.

A production server for millions of users cannot lose every in-flight
request because one XLA dispatch raised ``RESOURCE_EXHAUSTED`` or one
fence hung on a TPU maintenance event — yet "the server survives faults"
is unfalsifiable without a way to CAUSE faults deterministically. This
module supplies both halves:

- :class:`FaultInjector` — a seeded, schedule-driven injector with named
  SEAMS wrapped around the serving loop's real failure points
  (``decode_dispatch``, ``prefill``, ``admission_commit``, ``fence``,
  ``pool_alloc``, ``store_gather``, ``sched_tick``). A schedule is a
  comma-separated
  ``<seam>:<round>[:<kind>[:<device>]]`` list (``KATA_TPU_FAULTS`` env),
  where ``round`` is the seam's 0-based invocation count and ``kind`` is
  one of ``raise-transient`` (default), ``raise-oom``, ``hang``, or the
  permanent kinds ``chip_loss`` (fourth field: the lost chip's
  serving-mesh device index) and ``ici_error``. Each entry
  fires exactly once, so a chaos run is REPLAYABLE: the same schedule
  against the same workload produces the same fault sequence (tested),
  which is what lets the recovery supervisor's bit-identity claim be a
  test matrix instead of a hope. Malformed entries degrade (skipped with
  a ``fault_schedule_error`` event) — a node-injected chaos knob must
  never crash a guest that did not opt in.
- :func:`fence_with_timeout` — the watchdog fence. Every blocking
  device→host wait in serving routes through it; with a deadline
  configured (``KATA_TPU_FENCE_TIMEOUT_S``) the wait runs on a watcher
  thread and a ``device_stall`` event + :class:`DeviceStallError` replace
  the infinite hang. With the deadline unset (the default) it calls the
  wait inline — zero threads, zero new syncs on the hot path.
- :func:`recoverable` / :func:`classify` — the supervisor's catch
  predicate and its TRANSIENT-vs-PERMANENT split (ISSUE 10): injected
  faults, stalls, and XLA runtime errors whose status markers indicate a
  transient device condition replay through the existing rebuild path;
  permanent faults (``chip_loss:<device_index>``, ``ici_error``, and XLA
  errors carrying a permanent-device marker) route to elastic mesh-shrink
  recovery instead — a dead chip does not come back on retry. Everything
  else (assertion errors, strict-mode transfer-guard trips, user bugs)
  propagates unchanged.
- :func:`wire_drain` — graceful-drain wiring: SIGTERM and/or a
  maintenance-notice file watch (``KATA_TPU_MAINTENANCE_FILE``, the
  host's TPU-maintenance signal surface) call the server's
  ``request_drain`` so in-flight work finishes and queued work fails
  loudly instead of vanishing with the process.

The recovery supervisor itself lives in :class:`.serving.GenerationServer`
(checkpointed restore via the PR 6 spill machinery); this module is jax-
free at import so the injector and drain wiring also serve host-side
tests.
"""
from __future__ import annotations

import os
import queue
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from .. import obs

# Named seams — the serving loop's real failure surfaces. fire() rejects
# anything else so a typo'd schedule cannot silently never fire.
SEAMS = (
    "decode_dispatch",   # the chunked decode executable dispatch
    "prefill",           # an admission's prefill forward
    "admission_commit",  # the arena/pool write landing an admission
    "fence",             # a blocking device->host wait (retire, lock-step)
    "pool_alloc",        # paged block allocation (OOM surface)
    "store_gather",      # prefix-store gather/materialize on a hit
    "sched_tick",        # a chunked-prefill slice boundary (ISSUE 8)
)

KIND_TRANSIENT = "raise-transient"
KIND_OOM = "raise-oom"
KIND_HANG = "hang"
# Permanent fault kinds (ISSUE 10): the device does not come back on
# retry. ``chip_loss`` optionally carries the lost chip's serving-mesh
# device index as a FOURTH schedule field (``<seam>:<round>:chip_loss:1``,
# default 0); ``ici_error`` models an interconnect failure — chips alive,
# collectives untrustworthy.
KIND_CHIP_LOSS = "chip_loss"
KIND_ICI = "ici_error"
KINDS = (KIND_TRANSIENT, KIND_OOM, KIND_HANG, KIND_CHIP_LOSS, KIND_ICI)
PERMANENT_KINDS = (KIND_CHIP_LOSS, KIND_ICI)

# classify() verdicts.
TRANSIENT = "transient"
PERMANENT = "permanent"

ENV_FAULTS = "KATA_TPU_FAULTS"
ENV_FAULTS_SEED = "KATA_TPU_FAULTS_SEED"
ENV_FENCE_TIMEOUT = "KATA_TPU_FENCE_TIMEOUT_S"
ENV_MAINTENANCE_FILE = "KATA_TPU_MAINTENANCE_FILE"


class TransientFault(RuntimeError):
    """Injected transient dispatch failure (the retryable class)."""


class InjectedOom(RuntimeError):
    """Injected allocation failure; message carries RESOURCE_EXHAUSTED so
    it routes through the same :func:`recoverable` marker match a real
    XLA OOM would."""


class DeviceStallError(TimeoutError):
    """A device fence exceeded its watchdog deadline (real or injected) —
    the bounded replacement for a ``block_until_ready`` that never
    returns."""


class ChipLossFault(RuntimeError):
    """Injected PERMANENT chip failure: serving-mesh device
    ``device_index`` is gone and will not come back on retry — the
    supervisor must shrink the mesh over the survivors (or fail the load
    loudly), never replay into the dead chip."""

    def __init__(self, message: str, device_index: int = 0):
        super().__init__(message)
        self.device_index = int(device_index)


class IciFault(RuntimeError):
    """Injected PERMANENT ICI interconnect failure: the chips answer but
    collectives across the mesh are untrustworthy — same elastic-shrink
    recovery class as :class:`ChipLossFault`, with every chip surviving."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire ``kind`` at the ``round``-th invocation
    (0-based, counted per seam) of ``seam``. ``device`` is meaningful for
    ``chip_loss`` only: the serving-mesh index of the chip that dies."""

    seam: str
    round: int
    kind: str = KIND_TRANSIENT
    device: int = 0


def parse_schedule(raw: str) -> tuple[list[FaultSpec], list[str]]:
    """Parse a ``<seam>:<round>[:<kind>[:<device>]],...`` schedule string
    into specs plus the malformed entries (the caller decides whether to
    event or raise on those — the env path degrades, the explicit path
    raises). The fourth field is valid only for ``chip_loss`` (the lost
    chip's serving-mesh device index, default 0)."""
    specs: list[FaultSpec] = []
    bad: list[str] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3, 4) or parts[0] not in SEAMS:
            bad.append(entry)
            continue
        kind = parts[2] if len(parts) >= 3 else KIND_TRANSIENT
        if kind not in KINDS:
            bad.append(entry)
            continue
        device = 0
        if len(parts) == 4:
            # Only chip_loss carries a device index — a fourth field on
            # any other kind is a malformed entry, not a silent ignore.
            if kind != KIND_CHIP_LOSS:
                bad.append(entry)
                continue
            try:
                device = int(parts[3])
            except ValueError:
                bad.append(entry)
                continue
            if device < 0:
                bad.append(entry)
                continue
        try:
            rnd = int(parts[1])
        except ValueError:
            bad.append(entry)
            continue
        if rnd < 0:
            bad.append(entry)
            continue
        specs.append(FaultSpec(parts[0], rnd, kind, device))
    return specs, bad


@dataclass
class FaultInjector:
    """Deterministic scheduled fault source. ``fire(seam)`` is called at
    every seam crossing; when the seam's invocation count matches a
    scheduled entry, the entry is consumed and the fault raised
    (``fault_injected`` event first). Disarmed (empty schedule — the
    production default) the per-call cost is one attribute test.

    ``seed`` keys the injector's RNG — today only hang jitter draws from
    it, but it is part of the replay contract: (seed, schedule) fully
    determines the fired sequence, recorded in :attr:`fired`.
    """

    schedule: Iterable[FaultSpec] = ()
    seed: int = 0
    label: str = ""
    # Allocation trace id (ISSUE 11): attached to every fault_injected /
    # device_stall event so a chaos run's injections join the request
    # traces and flight-recorder dumps of the same incident.
    trace: str = ""
    hang_s: float = 0.0  # optional real delay before an injected stall
    fired: list = field(default_factory=list)

    def __post_init__(self) -> None:
        pending: dict[tuple[str, int], FaultSpec] = {}
        for spec in self.schedule:
            if spec.seam not in SEAMS:
                raise ValueError(
                    f"unknown fault seam {spec.seam!r} (have {SEAMS})"
                )
            if spec.kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {spec.kind!r} (have {KINDS})"
                )
            pending[(spec.seam, spec.round)] = spec
        self._pending = pending
        self._counts: dict[str, int] = {}
        self._rng = random.Random(self.seed)

    @classmethod
    def from_env(cls, label: str = "", trace: str = "") -> "FaultInjector":
        """The injector the serving loop builds by default: schedule from
        ``KATA_TPU_FAULTS`` (the env the daemon's ``--faults`` chaos knob
        injects), seed from ``KATA_TPU_FAULTS_SEED``. Malformed entries
        are skipped with one ``fault_schedule_error`` event each — the
        node-wide knob must never crash a guest."""
        raw = os.environ.get(ENV_FAULTS, "")
        specs, bad = parse_schedule(raw) if raw else ([], [])
        for entry in bad:
            obs.emit(
                "serving", "fault_schedule_error",
                server=label, entry=entry[:64],
            )
        try:
            seed = int(os.environ.get(ENV_FAULTS_SEED, "0") or 0)
        except ValueError:
            seed = 0
        return cls(schedule=specs, seed=seed, label=label, trace=trace)

    @property
    def armed(self) -> bool:
        return bool(self._pending)

    def fire(self, seam: str) -> None:
        """Cross ``seam``: raise the scheduled fault for this invocation,
        if any. No-op (one dict truth-test) when the schedule is drained
        or empty."""
        if not self._pending:
            return
        if seam not in SEAMS:
            raise ValueError(f"unknown fault seam {seam!r}")
        n = self._counts.get(seam, 0)
        self._counts[seam] = n + 1
        spec = self._pending.pop((seam, n), None)
        if spec is None:
            return
        kind = spec.kind
        self.fired.append((seam, n, kind))
        extra = {"device": spec.device} if kind == KIND_CHIP_LOSS else {}
        if self.trace:
            extra["trace"] = self.trace
        obs.emit(
            "serving", "fault_injected",
            server=self.label, seam=seam, round=n, fault_kind=kind,
            **extra,
        )
        if kind == KIND_TRANSIENT:
            raise TransientFault(f"injected transient fault at {seam}#{n}")
        if kind == KIND_OOM:
            raise InjectedOom(
                f"RESOURCE_EXHAUSTED: injected allocation failure at "
                f"{seam}#{n}"
            )
        if kind == KIND_CHIP_LOSS:
            raise ChipLossFault(
                f"injected permanent chip loss at {seam}#{n} "
                f"(mesh device {spec.device})",
                device_index=spec.device,
            )
        if kind == KIND_ICI:
            raise IciFault(
                f"injected permanent ICI interconnect failure at {seam}#{n}"
            )
        # hang: a simulated stall — the watchdog deadline is short-
        # circuited deterministically (an optional real hang_s delay keeps
        # wall-clock shape when wanted) so chaos tests never actually wait
        # out a production deadline.
        if self.hang_s > 0:
            time.sleep(self.hang_s * (0.5 + self._rng.random()))
        obs.emit(
            "serving", "device_stall",
            server=self.label, seam=seam, injected=True,
            **({"trace": self.trace} if self.trace else {}),
        )
        raise DeviceStallError(f"injected device stall at {seam}#{n}")


class _FenceWorker:
    """One reusable watchdog thread. Armed fences borrow a worker from
    the pool instead of paying a thread spawn per wait (the armed path
    runs at the decode-chunk cadence); a wait that times out ABANDONS
    its worker — the thread is stuck inside the hung call, nothing can
    interrupt a stuck transport — and the next fence draws a fresh one.
    A completed wait returns its worker to the pool."""

    def __init__(self) -> None:
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self.abandoned = False
        threading.Thread(target=self._loop, name="katatpu-fence-watchdog",
                         daemon=True).start()

    def _loop(self) -> None:
        while True:
            wait, box, done = self._q.get()
            try:
                box["value"] = wait()
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                box["error"] = e
            finally:
                done.set()
            if self.abandoned:
                # The caller timed out while we ran and forgot us. (A
                # caller racing its abandoned-mark against this check can
                # at worst strand one idle thread — same order of leak as
                # the hung wait itself.)
                return
            with _FENCE_POOL_LOCK:
                _FENCE_POOL.append(self)


_FENCE_POOL: list[_FenceWorker] = []
_FENCE_POOL_LOCK = threading.Lock()


def _borrow_fence_worker() -> _FenceWorker:
    with _FENCE_POOL_LOCK:
        while _FENCE_POOL:
            w = _FENCE_POOL.pop()
            if not w.abandoned:
                return w
    return _FenceWorker()


def fence_with_timeout(
    wait: Callable[[], object],
    *,
    timeout_s: float = 0.0,
    seam: str = "fence",
    injector: Optional[FaultInjector] = None,
    server: str = "",
    trace: str = "",
) -> object:
    """Run a blocking device wait (``wait`` is a zero-arg callable — a
    ``DeviceFence.wait`` / ``block_until_ready`` / host-transfer closure)
    under the watchdog contract: with ``timeout_s > 0`` the wait runs on
    a daemon thread and exceeding the deadline emits a ``device_stall``
    event and raises :class:`DeviceStallError` instead of hanging the
    scheduler forever (the abandoned thread keeps blocking — nothing can
    interrupt a stuck transport, but the SERVER regains control and can
    rebuild). With ``timeout_s`` unset (default) the wait runs inline —
    no thread, no overhead, bit-for-bit the pre-watchdog behavior.

    ``injector`` crosses the ``seam`` first, so a scheduled ``hang``
    becomes a deterministic stall without waiting out the deadline."""
    if injector is not None:
        injector.fire(seam)
    if not timeout_s or timeout_s <= 0:
        return wait()
    worker = _borrow_fence_worker()
    box, done = {}, threading.Event()
    worker._q.put((wait, box, done))
    if not done.wait(timeout_s):
        worker.abandoned = True
        obs.emit(
            "serving", "device_stall",
            server=server, seam=seam, timeout_s=round(float(timeout_s), 3),
            injected=False,
            **({"trace": trace} if trace else {}),
        )
        raise DeviceStallError(
            f"device fence {seam!r} exceeded {timeout_s}s watchdog deadline"
        )
    if "error" in box:
        raise box["error"]
    return box.get("value")


# Status markers in an XLA runtime error that indicate a transient device
# condition the supervisor may retry; anything else (shape errors, strict-
# mode transfer-guard trips, user bugs) must propagate unchanged.
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "ABORTED",
    "DATA_LOSS",
    "DEADLINE_EXCEEDED",
)

# Markers of a PERMANENT device condition (ISSUE 10): retrying the same
# mesh cannot succeed — the supervisor must shrink over the survivors.
# Checked BEFORE the transient set (a halted chip's message may also
# carry UNAVAILABLE). Heuristic by necessity: the TPU runtime has no
# structured "chip N died" status, these are the phrases its chip-loss
# and ICI failure paths are observed to emit.
_PERMANENT_MARKERS = (
    "device halted",
    "chip has been lost",
    "ici failure",
    "interconnect failure",
)


def classify(exc: BaseException) -> Optional[str]:
    """The supervisor's fault triage (ISSUE 10): :data:`TRANSIENT` routes
    through the existing rebuild-and-replay recovery, :data:`PERMANENT`
    (a dead chip, a broken interconnect) through elastic mesh-shrink —
    replaying into a dead chip can only fail again. ``None`` means not
    ours to catch: the exception propagates unchanged (user bugs, shape
    errors, strict-mode guard trips). XLA errors are matched by type NAME
    so a jax-free host process can import this module."""
    if isinstance(exc, (ChipLossFault, IciFault)):
        return PERMANENT
    if isinstance(exc, (TransientFault, InjectedOom, DeviceStallError)):
        return TRANSIENT
    if type(exc).__name__ == "XlaRuntimeError":
        msg = str(exc)
        low = msg.lower()
        if any(marker in low for marker in _PERMANENT_MARKERS):
            return PERMANENT
        if any(marker in msg for marker in _TRANSIENT_MARKERS):
            return TRANSIENT
    return None


def recoverable(exc: BaseException) -> bool:
    """Should the recovery supervisor catch this and rebuild, rather than
    let it unwind the server? Injected faults and watchdog stalls always
    (transient replay or permanent mesh-shrink — :func:`classify` picks
    the path); real XLA runtime errors only when a status marker says the
    device, not the program, failed."""
    return classify(exc) is not None


def env_int(name: str, default: int, *, event: str = "",
            server: str = "", trace: str = "") -> int:
    """Integer env knob with the repo's degrade contract: a malformed
    node-injected value falls back to ``default`` with one ``event``
    (reason ``bad_env:<raw>``) instead of crashing the guest. ``trace``
    joins the degrade event to the allocation trace (ISSUE 11)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        if event:
            obs.emit("serving", event, server=server,
                     reason=f"bad_env:{raw[:32]}",
                     **({"trace": trace} if trace else {}))
        return default


def env_float(name: str, default: float, *, event: str = "",
              server: str = "", trace: str = "") -> float:
    """Float sibling of :func:`env_int` (same degrade contract)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        if event:
            obs.emit("serving", event, server=server,
                     reason=f"bad_env:{raw[:32]}",
                     **({"trace": trace} if trace else {}))
        return default


class DrainWiring:
    """Handle returned by :func:`wire_drain`: owns the maintenance-watch
    thread and the restored SIGTERM disposition. ``stop()`` detaches both
    (idempotent); ``poll_once()`` runs one maintenance check inline for
    deterministic tests."""

    def __init__(self, server, maintenance_file: str = "",
                 poll_s: float = 1.0):
        self._server = server
        self._file = maintenance_file
        self._poll_s = poll_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_handler = None
        self._sigterm_installed = False

    def poll_once(self) -> bool:
        """One maintenance-notice check; True when it triggered a drain."""
        if self._file and os.path.exists(self._file):
            self._server.request_drain(reason="maintenance_notice")
            return True
        return False

    def _watch(self) -> None:
        while not self._stop.is_set():
            if self.poll_once():
                return
            self._stop.wait(self._poll_s)

    def _start_watch(self) -> None:
        self._thread = threading.Thread(
            target=self._watch, name="katatpu-maintenance-watch", daemon=True
        )
        self._thread.start()

    def _install_sigterm(self) -> None:
        def handler(signum, frame):
            self._server.request_drain(reason="sigterm")
            # Chain a CALLABLE prior handler so a process manager layering
            # its own hook still observes the signal. A SIG_DFL prior
            # disposition is deliberately NOT chained — immediate
            # termination is exactly what the drain exists to prevent;
            # exiting once run() returns is the caller's job.
            if callable(self._prev_handler):
                self._prev_handler(signum, frame)

        try:
            self._prev_handler = signal.signal(signal.SIGTERM, handler)
            self._sigterm_installed = True
        except ValueError:
            # Not the main thread: signal wiring is unavailable there by
            # interpreter rule; the maintenance watch still works.
            self._sigterm_installed = False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._sigterm_installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_handler
                              or signal.SIG_DFL)
            except ValueError:
                pass
            self._sigterm_installed = False

    def __enter__(self) -> "DrainWiring":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def wire_drain(server, *, sigterm: bool = True,
               maintenance_file: Optional[str] = None,
               poll_s: float = 1.0) -> DrainWiring:
    """Wire a server's graceful drain to the two production triggers:
    SIGTERM (pod termination) and a maintenance-notice file
    (``maintenance_file``, default ``KATA_TPU_MAINTENANCE_FILE`` env —
    the path the host surfaces a TPU maintenance event on). Either
    trigger calls ``server.request_drain(...)``: admission stops,
    in-flight work finishes, still-queued requests surface in
    ``failures()``. Returns a :class:`DrainWiring`; call ``stop()`` (or
    use as a context manager) to detach."""
    if maintenance_file is None:
        maintenance_file = os.environ.get(ENV_MAINTENANCE_FILE, "")
    wiring = DrainWiring(server, maintenance_file, poll_s)
    if sigterm:
        wiring._install_sigterm()
    if maintenance_file:
        wiring._start_watch()
    return wiring
