"""kata-xpu-device-plugin-tpu: a TPU-native Kubernetes device plugin for Kata Containers.

A brand-new framework with the capabilities of ``Apokleos/kata-xpu-device-plugin``
(reference: a Go device plugin that exposes vfio-pci NVIDIA GPUs to Kata guests via
CDI), redesigned for Google Cloud TPUs:

- discovery of ``/dev/accel*`` char devices and vendor-``0x1ae0`` PCIe endpoints
  (alongside a generalized vfio-pci passthrough path),
- an ICI slice-topology model as the co-allocation unit (the TPU analogue of the
  reference's IOMMU group; ref ``pkg/device_plugin/device_plugin.go:31``),
- the kubelet device-plugin v1beta1 gRPC API advertising ``google.com/tpu``,
- CDI spec emission that injects device nodes, the ``libtpu.so`` mount, and TPU
  topology environment into Kata guest VMs (ref ``cdi/spec.go``),
- a JAX guest harness (``guest/``, ``models/``, ``ops/``, ``parallel/``) implementing
  the BASELINE validation ladder up to Gemma-2B inference and sharded training.

Subpackage map (host side, no JAX imports):
  cdi/        CDI data model + atomic spec writer        (ref L1: cdi/)
  discovery/  sysfs/devfs scanners + pci.ids naming      (ref L3: device_plugin.go)
  topology/   ICI slice model + preferred allocation     (new; ref stub :378)
  plugin/     kubelet gRPC server + health + manager     (ref L2: generic_device_plugin.go)
  multihost/  TPU_WORKER_ID/HOSTNAMES coordination       (new)
  utils/      logging, metrics, inotify, pod-resources   (ref L0: utils/)
  obs/        unified telemetry: spans, metric factory,  (new; the "no
              JSONL events, profiler hooks               metrics" fix at
                                                         stack scale)

Guest side (JAX; imported lazily so the host daemon never loads jax):
  guest/      device probe + collective smoke ladder
  models/     flagship Gemma-style + Llama-style decoders
  ops/        pallas flash-attention and collective helpers
  parallel/   mesh construction + dp/fsdp/tp/sp sharding rules
"""

__version__ = "0.1.0"
