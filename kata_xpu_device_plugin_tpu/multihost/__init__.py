"""Multi-host slice coordination (SURVEY §7 stage 7).

The reference has zero cross-node logic — its co-allocation unit (the IOMMU
group, ``device_plugin.go:31``) never spans hosts. A TPU v5p-16 slice does:
four hosts, each running its own plugin in its own DaemonSet pod, must hand
their Kata guests a *consistent* view of the slice — the same ordered
``TPU_WORKER_HOSTNAMES`` everywhere and a unique ``TPU_WORKER_ID`` per host —
or libtpu/XLA inside the guests cannot bring up ICI/DCN.

Design constraints (SURVEY §7 "Hard parts"): no central coordinator, and the
assignment must survive pod restarts. Both fall out of making worker-id a
*pure function of stable inputs*: the slice's hostname list, identical on
every host because each source (flags, env, metadata) is slice-wide. Every
host reads the same list independently, finds itself in it, and persists the
result so a restarted pod keeps its identity even if a metadata source flaps.
"""
from .resolver import (
    SliceMembership,
    canonical_order,
    multislice_env,
    parse_worker_network_endpoints,
    resolve_membership,
)

__all__ = [
    "SliceMembership",
    "canonical_order",
    "multislice_env",
    "parse_worker_network_endpoints",
    "resolve_membership",
]
