"""Worker-id / hostname resolution for multi-host slices.

Sources, in precedence order (first complete answer wins):

1. **Explicit config** — ``--worker-id`` + ``--worker-hostnames`` flags (or
   their ``KATA_TPU_*`` env forms). The operator's word is final.
2. **libtpu env** — ``TPU_WORKER_ID`` + ``TPU_WORKER_HOSTNAMES`` already set
   on the node (GKE TPU node pools set these on TPU-VM node pools).
3. **Metadata directory** — files named after the GCE TPU-VM metadata
   attributes, mounted or written by a metadata agent:
   ``agent-worker-number`` (this host's id) and ``worker-network-endpoints``
   (the slice's ordered endpoint list). This is how bare TPU VMs learn their
   identity; the DaemonSet can project the same attributes as files.
4. **Derived** — given only a peer hostname list (flag/env/metadata) *without*
   an id, every host takes its own index in that list. Each source's order is
   authoritative and identical on every host (a DaemonSet hands all pods the
   same flag/env; the metadata attribute is slice-wide), so the assignment is
   a pure function of stable inputs → no coordinator, consistent everywhere,
   stable across restarts. :func:`canonical_order` is exported for genuinely
   unordered host lists (e.g. DNS-discovered peers).

Whatever resolves is persisted to a state file; on later failures (metadata
server down after a pod restart) the persisted identity is reused, and on
*disagreement* the live answer wins but the drift is logged — a resized slice
is a new slice.
"""
from __future__ import annotations

import json
import os
import re
import socket
from dataclasses import dataclass
from typing import Mapping, Optional, Sequence

from ..utils import log

LOG = log.get("multihost")

# GCE TPU-VM metadata attribute names (surfaced to the plugin as files in a
# metadata directory; names match the real attributes so an agent can dump
# them 1:1).
ATTR_WORKER_NUMBER = "agent-worker-number"
ATTR_WORKER_ENDPOINTS = "worker-network-endpoints"
ATTR_ACCEL_TYPE = "accelerator-type"

STATE_FILE = "worker-identity.json"


@dataclass(frozen=True)
class SliceMembership:
    """This host's resolved identity within its slice."""

    worker_id: int
    hostnames: tuple[str, ...]  # canonical order; index == worker id
    source: str  # "config" | "env" | "metadata" | "derived" | "state"

    @property
    def num_hosts(self) -> int:
        return len(self.hostnames) or 1


_ORDINAL_RE = re.compile(r"^(.*?)(\d+)$")


def _sort_key(hostname: str) -> tuple[str, int]:
    """Numeric-suffix-aware ordering: ``…-w-10`` sorts after ``…-w-9``.

    GKE multi-host TPU pods/nodes end in an ordinal (``-w-<N>`` on TPU VMs,
    ``-<N>`` for StatefulSet-style pods); plain lexicographic order would
    scramble ids past 9 hosts, breaking the id↔coordinate correspondence
    libtpu expects.
    """
    m = _ORDINAL_RE.match(hostname)
    if m:
        return (m.group(1), int(m.group(2)))
    return (hostname, -1)


def canonical_order(hostnames: Sequence[str]) -> tuple[str, ...]:
    """The slice-wide canonical hostname ordering (dedup + ordinal sort)."""
    return tuple(sorted(dict.fromkeys(hostnames), key=_sort_key))


def parse_worker_network_endpoints(raw: str) -> tuple[str, ...]:
    """Parse the ``worker-network-endpoints`` metadata attribute.

    Real-world shapes: comma-separated workers, each worker either a bare
    hostname/IP or colon-joined fields (``<id>:<ip>:<port>`` on TPU VMs).
    The *order* of the attribute is the worker order — preserved, not
    re-sorted: the metadata service is authoritative about ids.
    """
    out = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        out.append(_pick_host(entry.split(":")))
    return tuple(out)


_IPV4 = re.compile(r"\d+\.\d+\.\d+\.\d+")


def _pick_host(fields: Sequence[str]) -> str:
    """Best addressable field of one endpoint: hostname > IPv4 > first."""
    for f in fields:
        if f and not f.isdigit() and not _IPV4.fullmatch(f):
            return f
    for f in fields:
        if _IPV4.fullmatch(f):
            return f
    return fields[0]


def _match_self(hostnames: Sequence[str], hostname: str) -> Optional[int]:
    """Index of this host in the list; exact match first, then short-name
    match (metadata lists FQDNs while the pod sees the short hostname).
    The short-name fallback never applies to IPs — '10.0.0.9' must not
    "match" '10.0.0.1' via their shared first octet."""
    for i, h in enumerate(hostnames):
        if h == hostname:
            return i
    if _IPV4.fullmatch(hostname):
        return None
    short = hostname.split(".")[0]
    for i, h in enumerate(hostnames):
        if not _IPV4.fullmatch(h) and h.split(".")[0] == short:
            return i
    return None


def _read_attr(metadata_dir: str, name: str) -> Optional[str]:
    try:
        with open(os.path.join(metadata_dir, name)) as f:
            return f.read().strip()
    except OSError:
        return None


# ----- state persistence ---------------------------------------------------


def _state_path(state_dir: str) -> str:
    return os.path.join(state_dir, STATE_FILE)


def load_state(state_dir: str) -> Optional[SliceMembership]:
    try:
        with open(_state_path(state_dir)) as f:
            raw = json.load(f)
        return SliceMembership(
            worker_id=int(raw["worker_id"]),
            hostnames=tuple(raw["hostnames"]),
            source="state",
        )
    except (OSError, KeyError, ValueError, TypeError):
        return None


def clear_state(state_dir: str) -> None:
    try:
        os.remove(_state_path(state_dir))
    except OSError:
        pass


def save_state(state_dir: str, mem: SliceMembership) -> None:
    try:
        os.makedirs(state_dir, exist_ok=True)
        tmp = _state_path(state_dir) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"worker_id": mem.worker_id, "hostnames": list(mem.hostnames)}, f)
        os.replace(tmp, _state_path(state_dir))
    except OSError:
        LOG.warning("could not persist worker identity to %s", state_dir)


# ----- resolution ----------------------------------------------------------


def resolve_membership(
    env: Optional[Mapping[str, str]] = None,
    *,
    hostname: Optional[str] = None,
    explicit_worker_id: int = -1,
    explicit_hostnames: Sequence[str] = (),
    metadata_dir: str = "",
    state_dir: str = "",
    num_hosts_hint: int = 0,
    state_readonly: bool = False,
    defer_save: bool = False,
) -> Optional[SliceMembership]:
    """Resolve this host's slice membership, or None for a single-host node.

    Returns None only when no source mentions peers at all — the node is a
    standalone host and the default ``worker_id=0`` topology stands.
    ``num_hosts_hint`` (from the accelerator type) guards the persisted-state
    fallback: a persisted multi-host identity on a node whose hardware now
    says "standalone" is a leftover from a deleted slice, not an outage.
    """
    env = os.environ if env is None else env
    hostname = hostname or env.get("HOSTNAME") or socket.gethostname()

    mem = (
        _from_config(explicit_worker_id, explicit_hostnames, hostname)
        or _from_env(env, hostname)
        or _from_metadata(metadata_dir, hostname)
    )
    if mem is None and explicit_worker_id >= 0:
        # Id pinned but no source resolved a membership (nothing lists peers,
        # or the metadata entries don't self-match); peers may merge in below.
        mem = SliceMembership(explicit_worker_id, (), "config")
    if mem is not None and explicit_worker_id >= 0 and mem.source != "config":
        # --worker-id without --worker-hostnames: the operator pins the id,
        # the hostname list still comes from whichever source has it.
        if mem.hostnames and explicit_worker_id >= len(mem.hostnames):
            LOG.warning(
                "--worker-id %d exceeds the %d-host list from %s; honoring it anyway",
                explicit_worker_id,
                len(mem.hostnames),
                mem.source,
            )
        mem = SliceMembership(explicit_worker_id, mem.hostnames, "config")
    if mem is not None and not mem.hostnames:
        # A bare id (GKE sets TPU_WORKER_ID alone on some pools, or a pinned
        # --worker-id) answers "who am I" but not "who else is there" — a
        # later source (or the persisted state during an outage) may still
        # know the peer list; the resolved id stays authoritative.
        peers = _metadata_hostnames(metadata_dir)
        if not peers and state_dir and (st := load_state(state_dir)) is not None:
            # Persisted peers are only trusted when they corroborate the
            # live id and don't contradict an authoritative topology hint —
            # a node reused in a different pool must not resurrect a deleted
            # slice's peer list just because GKE still sets a bare id.
            if num_hosts_hint and st.num_hosts != num_hosts_hint:
                LOG.warning(
                    "discarding persisted peer list (%d hosts): this node's "
                    "topology implies %d host(s) — slice was deleted",
                    st.num_hosts,
                    num_hosts_hint,
                )
                if not state_readonly:
                    clear_state(state_dir)
            elif st.worker_id == mem.worker_id:
                peers = st.hostnames
        if peers and mem.worker_id >= len(peers):
            LOG.warning(
                "worker id %d is not addressable in the %d-host peer list %s; "
                "ignoring the peers",
                mem.worker_id,
                len(peers),
                peers,
            )
            peers = ()
        if peers:
            mem = SliceMembership(mem.worker_id, peers, mem.source)

    if mem is None:
        if state_dir and (persisted := load_state(state_dir)) is not None:
            if num_hosts_hint and persisted.num_hosts != num_hosts_hint:
                LOG.warning(
                    "discarding persisted identity (id=%d, %d hosts): this "
                    "node's topology implies %d host(s) — slice was deleted",
                    persisted.worker_id,
                    persisted.num_hosts,
                    num_hosts_hint,
                )
                if not state_readonly:
                    clear_state(state_dir)
                return None
            LOG.info(
                "no live identity source; reusing persisted worker id %d",
                persisted.worker_id,
            )
            return persisted
        return None

    if not defer_save and not state_readonly:
        persist_membership(state_dir, mem)
    return mem


def persist_membership(state_dir: str, mem: SliceMembership) -> None:
    """Commit an ACCEPTED membership to the state file (drift-aware,
    no-op when unchanged). Callers that validate further — the manager
    checks the membership against the hardware topology — resolve with
    ``defer_save=True`` and call this only on acceptance, so a refused
    identity never haunts later rescans/restarts from disk."""
    if not state_dir or not mem.hostnames:
        # Hostname-less memberships are never persisted: they carry nothing a
        # restart couldn't re-derive, and must not clobber a complete
        # identity saved while the metadata source was up.
        return
    prior = load_state(state_dir)
    if prior is not None and (
        prior.worker_id != mem.worker_id or prior.hostnames != mem.hostnames
    ):
        LOG.warning(
            "worker identity drifted (was id=%d/%d hosts, now id=%d/%d hosts) "
            "— slice was likely recreated",
            prior.worker_id,
            prior.num_hosts,
            mem.worker_id,
            mem.num_hosts,
        )
    if prior is None or (prior.worker_id, prior.hostnames) != (
        mem.worker_id,
        mem.hostnames,
    ):
        save_state(state_dir, mem)


def _from_config(
    worker_id: int, hostnames: Sequence[str], hostname: str
) -> Optional[SliceMembership]:
    """Operator-supplied flags. Order is preserved, not canonicalized — a
    DaemonSet hands every pod the identical flag value, and with an explicit
    ``--worker-id`` the position of each host in the list IS the operator's
    id assignment (re-sorting would scramble it)."""
    if not hostnames:
        return None
    hosts = tuple(dict.fromkeys(hostnames))
    if worker_id >= 0:
        if worker_id >= len(hosts):
            LOG.error(
                "--worker-id %d out of range for %d worker-hostnames; ignoring flags",
                worker_id,
                len(hosts),
            )
            return None
        return SliceMembership(worker_id, hosts, "config")
    idx = _match_self(hosts, hostname)
    if idx is None:
        LOG.warning("this host %r is not in --worker-hostnames %s", hostname, hosts)
        return None
    return SliceMembership(idx, hosts, "derived")


def env_hostnames(env: Mapping[str, str]) -> tuple[str, ...]:
    """The ``TPU_WORKER_HOSTNAMES`` peer list, order preserved (env order is
    authoritative — GKE sets it slice-wide)."""
    raw = env.get("TPU_WORKER_HOSTNAMES", "")
    return tuple(h.strip() for h in raw.split(",") if h.strip())


def from_env(env: Mapping[str, str], hostname: str = "") -> Optional[SliceMembership]:
    """Membership from the libtpu env vars. The ONLY parser of
    ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` in the framework — discovery
    delegates here so the contract cannot diverge between layers."""
    hosts = env_hostnames(env)
    raw_id = env.get("TPU_WORKER_ID", "").strip()
    has_id = raw_id.lstrip("-").isdigit() and int(raw_id) >= 0
    if not hosts:
        # GKE sets TPU_WORKER_ID even on single-host pools; a bare id is
        # meaningful (and harmless) without a peer list.
        return SliceMembership(int(raw_id), (), "env") if has_id else None
    if has_id:
        wid = int(raw_id)
        if wid >= len(hosts):
            # Mirror the merge-path guard at resolve_membership: a malformed
            # node env must not propagate an unaddressable id+peer pair into
            # the CDI spec env.
            LOG.warning(
                "TPU_WORKER_ID %d is not an index into the %d-host "
                "TPU_WORKER_HOSTNAMES %s; ignoring the peer list",
                wid,
                len(hosts),
                hosts,
            )
            return SliceMembership(wid, (), "env")
        return SliceMembership(wid, hosts, "env")
    idx = _match_self(hosts, hostname)
    if idx is None:
        LOG.warning(
            "TPU_WORKER_HOSTNAMES is set but %r is not in it and TPU_WORKER_ID "
            "is absent — cannot derive a worker id (set --node-name?)",
            hostname,
        )
        return None
    return SliceMembership(idx, hosts, "derived")


_from_env = from_env


def _metadata_hostnames(metadata_dir: str) -> tuple[str, ...]:
    """Just the peer list from metadata — usable even when this host's id
    comes from elsewhere (bare TPU_WORKER_ID) and self-matching would fail."""
    if not metadata_dir:
        return ()
    raw = _read_attr(metadata_dir, ATTR_WORKER_ENDPOINTS)
    return parse_worker_network_endpoints(raw) if raw else ()


def _from_metadata(metadata_dir: str, hostname: str) -> Optional[SliceMembership]:
    hosts = _metadata_hostnames(metadata_dir)
    if not hosts:
        return None
    raw_id = _read_attr(metadata_dir, ATTR_WORKER_NUMBER)
    if raw_id is not None and raw_id.isdigit():
        return SliceMembership(int(raw_id), hosts, "metadata")
    idx = _match_self(hosts, hostname)
    if idx is None:
        LOG.warning(
            "metadata lists workers %s but %r is not among them and no "
            "%s attribute exists — cannot derive a worker id",
            hosts,
            hostname,
            ATTR_WORKER_NUMBER,
        )
        return None
    return SliceMembership(idx, hosts, "derived")


# ----- multislice (DCN) ----------------------------------------------------


def multislice_env(
    num_slices: int, slice_id: int, coordinator_address: str
) -> dict[str, str]:
    """MEGASCALE env for multislice jobs: several ICI slices cooperating over
    DCN. Injected alongside the per-slice topology env when the operator
    configures multislice; libtpu's DCN transport reads these directly.
    """
    if num_slices <= 1:
        return {}
    if not 0 <= slice_id < num_slices:
        raise ValueError(f"slice_id {slice_id} out of range for {num_slices} slices")
    env = {
        "MEGASCALE_NUM_SLICES": str(num_slices),
        "MEGASCALE_SLICE_ID": str(slice_id),
    }
    if coordinator_address:
        env["MEGASCALE_COORDINATOR_ADDRESS"] = coordinator_address
    return env
