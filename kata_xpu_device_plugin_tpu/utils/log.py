"""Structured logging setup.

The reference mixes stdlib log, klog and bare Println (SURVEY §5); here one
configured logger tree with either key=value text or JSON lines.

Log lines emitted inside an open ``obs.span`` automatically carry its
``trace``/``span`` ids (ISSUE 2), so an Allocate handler's "allocated"
line joins the span event for the same request without the call sites
threading ids by hand.
"""
from __future__ import annotations

import json
import logging
import sys
import time

ROOT = "katatpu"


def _trace_context() -> dict:
    """trace/span ids of the innermost open obs span (empty at top level).
    Imported lazily per record: log must stay importable before (and
    without) the obs package, and obs.trace itself logs nothing."""
    try:
        from ..obs import trace
    except Exception:
        return {}
    tid = trace.current_trace_id()
    if tid is None:
        return {}
    return {"trace": tid, "span": trace.current_span_id()}


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        entry.update(_trace_context())
        extra = getattr(record, "kv", None)
        if extra:
            entry.update(extra)
        return json.dumps(entry, sort_keys=False)


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = (
            f"{self.formatTime(record, '%Y-%m-%dT%H:%M:%S')} "
            f"{record.levelname[0]} {record.name} {record.getMessage()}"
        )
        extra = dict(_trace_context())
        extra.update(getattr(record, "kv", None) or {})
        if extra:
            base += " " + " ".join(f"{k}={v}" for k, v in extra.items())
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


def setup(level: str = "info", fmt: str = "text") -> logging.Logger:
    logger = logging.getLogger(ROOT)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.propagate = False
    logger.handlers.clear()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_JsonFormatter() if fmt == "json" else _TextFormatter())
    logger.addHandler(handler)
    return logger


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"{ROOT}.{name}")


def kv(**kwargs) -> dict:
    """Usage: log.info("allocated", extra=kv(chips=4, pod=uid))."""
    return {"kv": kwargs}
