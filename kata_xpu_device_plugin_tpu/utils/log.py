"""Structured logging setup.

The reference mixes stdlib log, klog and bare Println (SURVEY §5); here one
configured logger tree with either key=value text or JSON lines.
"""
from __future__ import annotations

import json
import logging
import sys
import time

ROOT = "katatpu"


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "kv", None)
        if extra:
            entry.update(extra)
        return json.dumps(entry, sort_keys=False)


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = (
            f"{self.formatTime(record, '%Y-%m-%dT%H:%M:%S')} "
            f"{record.levelname[0]} {record.name} {record.getMessage()}"
        )
        extra = getattr(record, "kv", None)
        if extra:
            base += " " + " ".join(f"{k}={v}" for k, v in extra.items())
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


def setup(level: str = "info", fmt: str = "text") -> logging.Logger:
    logger = logging.getLogger(ROOT)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.propagate = False
    logger.handlers.clear()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_JsonFormatter() if fmt == "json" else _TextFormatter())
    logger.addHandler(handler)
    return logger


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"{ROOT}.{name}")


def kv(**kwargs) -> dict:
    """Usage: log.info("allocated", extra=kv(chips=4, pod=uid))."""
    return {"kv": kwargs}
