"""Prometheus metrics.

The reference has Prometheus only as an unused indirect dependency (SURVEY §5
"no metrics endpoint"); here the daemon exports real counters/gauges on a
configurable port.
"""
from __future__ import annotations

from typing import Optional

from prometheus_client import Counter, Gauge, start_http_server

NS = "kata_tpu_device_plugin"

devices_total = Gauge(f"{NS}_devices", "Devices advertised", ["resource", "health"])
allocations_total = Counter(
    f"{NS}_allocations_total", "Allocate calls served", ["resource", "outcome"]
)
allocation_chips_total = Counter(
    f"{NS}_allocation_chips_total", "Chips handed out", ["resource"]
)
noncontiguous_allocations_total = Counter(
    f"{NS}_noncontiguous_preferred_total",
    "Preferred-allocation answers that could not be made ICI-contiguous",
    ["resource"],
)
registrations_total = Counter(
    f"{NS}_registrations_total", "Kubelet registrations performed", ["resource"]
)
health_transitions_total = Counter(
    f"{NS}_health_transitions_total", "Device health transitions", ["resource", "to"]
)
rescans_total = Counter(f"{NS}_rescans_total", "Discovery rescans", ["changed"])


def serve(port: int) -> Optional[int]:
    """Start the /metrics HTTP endpoint; 0 disables. Returns the bound port."""
    if not port:
        return None
    start_http_server(port)
    return port
