"""Prometheus metrics for the host daemon.

The reference has Prometheus only as an unused indirect dependency (SURVEY
§5 "no metrics endpoint"); here the daemon exports real counters/gauges on
a configurable port.

Since ISSUE 2 these are thin aliases over :mod:`..obs.metrics`'s factory:
the old module-global ``Counter(...)`` constructors registered directly
against prometheus's process-global registry, so importing this module
twice (``importlib.reload``, a second sys.path alias, the plugin tests
after the serving tests) raised ``Duplicated timeseries in
CollectorRegistry``. The factory is idempotent — it caches by name and
adopts collectors the registry already holds — so re-import is safe and
tests can inject a fresh ``CollectorRegistry`` instead of fighting global
state. Callers keep the old names (``metrics.allocations_total`` etc.)
unchanged.
"""
from __future__ import annotations

from typing import Optional

from ..obs import metrics as obs_metrics

NS = "kata_tpu_device_plugin"

devices_total = obs_metrics.gauge(
    f"{NS}_devices", "Devices advertised", ["resource", "health"]
)
allocations_total = obs_metrics.counter(
    f"{NS}_allocations_total", "Allocate calls served", ["resource", "outcome"]
)
allocation_chips_total = obs_metrics.counter(
    f"{NS}_allocation_chips_total", "Chips handed out", ["resource"]
)
noncontiguous_allocations_total = obs_metrics.counter(
    f"{NS}_noncontiguous_preferred_total",
    "Preferred-allocation answers that could not be made ICI-contiguous",
    ["resource"],
)
registrations_total = obs_metrics.counter(
    f"{NS}_registrations_total", "Kubelet registrations performed", ["resource"]
)
health_transitions_total = obs_metrics.counter(
    f"{NS}_health_transitions_total", "Device health transitions", ["resource", "to"]
)
rescans_total = obs_metrics.counter(
    f"{NS}_rescans_total", "Discovery rescans", ["changed"]
)
plugin_restarts_total = obs_metrics.counter(
    f"{NS}_plugin_restarts_total",
    "Plugin re-serve/re-register attempts after a socket loss "
    "(kubelet restart), by outcome",
    ["resource", "ok"],
)

# Chip-loss tolerance (ISSUE 10): how many chips the health watcher is
# currently holding out of allocation, and how many journaled allocations
# the startup reconcile found referencing vanished devices.
chips_quarantined = obs_metrics.gauge(
    f"{NS}_chips_quarantined",
    "Devices currently Unhealthy — quarantined from allocation by the "
    "health watcher",
    ["resource"],
)
alloc_orphaned = obs_metrics.gauge(
    f"{NS}_alloc_orphaned",
    "Journaled allocations whose devices were missing at the last "
    "daemon-restart reconcile (entries dropped, event emitted)",
    ["resource"],
)

# Guest heartbeat aggregation (ISSUE 15): per-allocation serving gauges
# the daemon re-exports from the guest heartbeat streams it tails
# (plugin/manager.py HeartbeatAggregator; the allocator points each
# allocation's KATATPU_OBS_FILE into --guest-events-dir). Labels:
# ``allocation`` is the granted chip set ("0,1"), ``server`` the
# in-guest GenerationServer label — several servers can share one
# allocation. These are the per-replica occupancy/ITL signals the
# ROADMAP fleet-router tier balances on.
guest_tokens_per_s = obs_metrics.gauge(
    f"{NS}_guest_tokens_per_s",
    "Decoded tokens/s over the guest's last heartbeat interval",
    ["allocation", "server"],
)
guest_itl_p99_ms = obs_metrics.gauge(
    f"{NS}_guest_itl_p99_ms",
    "Guest rolling inter-token-latency p99 (ms) at the last heartbeat",
    ["allocation", "server"],
)
guest_queue_depth = obs_metrics.gauge(
    f"{NS}_guest_queue_depth",
    "Requests queued in the guest server at the last heartbeat",
    ["allocation", "server"],
)
guest_batch_occupancy = obs_metrics.gauge(
    f"{NS}_guest_batch_occupancy",
    "Guest serving-lane occupancy (busy slots / max_batch) at the last "
    "heartbeat",
    ["allocation", "server"],
)
guest_kv_pool_occupancy = obs_metrics.gauge(
    f"{NS}_guest_kv_pool_occupancy",
    "Guest paged KV pool fill at the last heartbeat (0.0 slotted)",
    ["allocation", "server"],
)
guest_kv_host_occupancy = obs_metrics.gauge(
    f"{NS}_guest_kv_host_occupancy",
    "Guest host-RAM KV tier fill at the last heartbeat (0.0 tier off)",
    ["allocation", "server"],
)
guest_mfu = obs_metrics.gauge(
    f"{NS}_guest_mfu",
    "Guest model-FLOP utilization over the last heartbeat interval "
    "(interval FLOPs / wall x public per-chip peak x tp)",
    ["allocation", "server"],
)
guest_hbm_headroom_bytes = obs_metrics.gauge(
    f"{NS}_guest_hbm_headroom_bytes",
    "Guest device memory headroom (limit - in-use) at the last "
    "heartbeat; NO child is created for guests whose backend exposes "
    "no memory_stats (omission, never a fake 0)",
    ["allocation", "server"],
)
guest_last_heartbeat_ts = obs_metrics.gauge(
    f"{NS}_guest_last_heartbeat_ts",
    "Unix timestamp of the guest's last heartbeat (alert on "
    "time() - this for staleness)",
    ["allocation", "server"],
)
guest_watchdog_active = obs_metrics.gauge(
    f"{NS}_guest_watchdog_active",
    "Guest watchdog alert kinds currently active (0 = healthy)",
    ["allocation", "server"],
)
guest_heartbeats_total = obs_metrics.counter(
    f"{NS}_guest_heartbeats_total",
    "Guest serving heartbeats consumed by the daemon aggregator",
    ["allocation", "server"],
)
guest_alerts_total = obs_metrics.counter(
    f"{NS}_guest_alerts_total",
    "Guest watchdog alerts observed by the daemon aggregator",
    ["allocation", "server", "kind"],
)

# gRPC handler latency (ISSUE 2): one histogram, labeled by method —
# Allocate / GetPreferredAllocation / ListAndWatch_update share it.
grpc_handler_seconds = obs_metrics.histogram(
    f"{NS}_grpc_handler_seconds",
    "Device-plugin gRPC handler latency",
    ["method", "resource"],
)


def serve(port: int) -> Optional[int]:
    """Start the /metrics HTTP endpoint; 0 disables. Returns the bound
    port. Idempotent per process (delegates to obs.metrics.serve), so the
    daemon and a guest GenerationServer can both ask for the endpoint."""
    return obs_metrics.serve(port)
