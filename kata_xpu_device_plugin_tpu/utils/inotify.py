"""Minimal ctypes inotify(7) binding.

The reference uses fsnotify for device-node and kubelet-socket watching
(``generic_device_plugin.go:389-457``). This is the same kernel facility bound
directly via libc — no third-party watcher dependency. A polling fallback in
:mod:`..plugin.health` covers filesystems where inotify is unavailable.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import select
import struct
from dataclasses import dataclass

IN_CREATE = 0x00000100
IN_DELETE = 0x00000200
IN_DELETE_SELF = 0x00000400
IN_MOVED_FROM = 0x00000040
IN_MOVED_TO = 0x00000080
IN_ATTRIB = 0x00000004
IN_IGNORED = 0x00008000

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len

_libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6", use_errno=True)


@dataclass(frozen=True)
class Event:
    wd: int
    mask: int
    name: str  # entry name within the watched dir ("" for dir-level events)


class Inotify:
    """An inotify instance watching one or more directories."""

    def __init__(self) -> None:
        fd = _libc.inotify_init1(os.O_NONBLOCK | os.O_CLOEXEC)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._fd = fd
        self._paths: dict[int, str] = {}

    @property
    def fd(self) -> int:
        return self._fd

    def add_watch(
        self,
        path: str,
        mask: int = IN_CREATE | IN_DELETE | IN_MOVED_FROM | IN_MOVED_TO | IN_DELETE_SELF,
    ) -> int:
        wd = _libc.inotify_add_watch(self._fd, path.encode(), mask)
        if wd < 0:
            raise OSError(ctypes.get_errno(), f"inotify_add_watch({path}) failed")
        self._paths[wd] = path
        return wd

    def watch_path(self, wd: int) -> str | None:
        return self._paths.get(wd)

    def read_events(self, timeout: float | None = None) -> list[Event]:
        """Drain pending events, waiting up to ``timeout`` seconds for the first."""
        ready, _, _ = select.select([self._fd], [], [], timeout)
        if not ready:
            return []
        events: list[Event] = []
        while True:
            try:
                data = os.read(self._fd, 65536)
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    break
                raise
            if not data:
                break
            off = 0
            while off + _EVENT_HDR.size <= len(data):
                wd, mask, _cookie, name_len = _EVENT_HDR.unpack_from(data, off)
                off += _EVENT_HDR.size
                raw = data[off : off + name_len]
                off += name_len
                events.append(Event(wd=wd, mask=mask, name=raw.split(b"\0", 1)[0].decode()))
            # another non-blocking read to fully drain
        return events

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
