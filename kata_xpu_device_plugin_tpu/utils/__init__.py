"""Utilities: structured logging, Prometheus metrics, ctypes inotify, and the
pod-resources client (counterpart of the reference's ``utils/``)."""
