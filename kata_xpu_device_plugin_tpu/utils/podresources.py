"""Kubelet pod-resources client.

The reference ships this as dead code (``utils/pod_resources.go:41-61`` —
never called, socket still mounted by the DaemonSet); here it backs the real
``status`` subcommand: verifying which pods actually hold which TPU chips is
how you check a Kata pod owns the slice it was promised (SURVEY §3.5).
"""
from __future__ import annotations

import grpc

from ..plugin.api import glue
from ..plugin.api import podresources_pb2 as prpb

MAX_MSG = 16 * 1024 * 1024  # parity with the reference's 16 MB cap (:26-28)


def list_pod_resources(
    socket_path: str = glue.POD_RESOURCES_SOCKET, timeout_s: float = 10.0
) -> prpb.ListPodResourcesResponse:
    with grpc.insecure_channel(
        f"unix://{socket_path}",
        options=(("grpc.max_receive_message_length", MAX_MSG),),
    ) as ch:
        grpc.channel_ready_future(ch).result(timeout=timeout_s)
        return glue.PodResourcesListerStub(ch).List(
            prpb.ListPodResourcesRequest(), timeout=timeout_s
        )


def device_assignments(
    resp: prpb.ListPodResourcesResponse, resource_prefix: str = ""
) -> list[dict]:
    """Flatten to [{pod, namespace, container, resource, device_ids}]."""
    out = []
    for pod in resp.pod_resources:
        for container in pod.containers:
            for dev in container.devices:
                if resource_prefix and not dev.resource_name.startswith(resource_prefix):
                    continue
                out.append(
                    {
                        "pod": pod.name,
                        "namespace": pod.namespace,
                        "container": container.name,
                        "resource": dev.resource_name,
                        "device_ids": list(dev.device_ids),
                    }
                )
    return out
