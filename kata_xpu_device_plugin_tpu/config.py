"""Daemon configuration.

The reference has no config system at all — vendor id, CDI dir, sysfs path,
pci.ids path, resource namespace, socket naming, strategies and the spec
filename are all hardcoded constants (SURVEY §5 lists each). Every one of
those is a real flag/env here; tests inject temp roots through the same
object instead of monkeypatching package globals.

Precedence: CLI flag > environment (``KATA_TPU_*``) > default.
"""
from __future__ import annotations

import argparse
import os
from dataclasses import MISSING, dataclass, fields

from .cdi import constants as C

# Kubelet filesystem contract (also in plugin.api.glue; duplicated here to
# keep config import-light — glue pulls in grpc).
_KUBELET_SOCKET_DIR = "/var/lib/kubelet/device-plugins"
_POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"


@dataclass
class Config:
    # Host interface roots (ref device_plugin.go:36-37 package vars).
    sysfs_root: str = "/sys"
    dev_root: str = "/dev"
    pci_ids_path: str = ""  # "" = search ladder (system paths, then bundled)

    # CDI (ref device_plugin.go:20, cdi/spec.go:12-14).
    cdi_dir: str = C.DEFAULT_CDI_DIR
    cdi_format: str = "yaml"  # yaml | json
    resource_namespace: str = C.DEFAULT_VENDOR  # CDI vendor + resource prefix
    tpu_resource_class: str = C.DEFAULT_CLASS

    # Device-list strategies (ref generic_device_plugin.go:58-66 hardcodes
    # cdi-cri on, cdi-annotations off).
    strategies: tuple[str, ...] = (C.STRATEGY_CDI_CRI,)

    # Kubelet endpoints (ref generic_device_plugin.go:76, pluginapi constants).
    kubelet_socket_dir: str = _KUBELET_SOCKET_DIR
    kubelet_socket: str = ""  # "" = <kubelet_socket_dir>/kubelet.sock
    pod_resources_socket: str = _POD_RESOURCES_SOCKET

    # TPU specifics.
    accelerator_type: str = ""  # "" = autodetect (env / chip count)
    libtpu_host_path: str = "/usr/lib/tpu/libtpu.so"  # "" disables the mount
    kata_annotations: bool = True  # attach-pci/bdf hints for Kata hot-plug

    # Multi-host slice identity (SURVEY §7 stage 7). Defaults resolve through
    # the multihost ladder (flags → TPU_WORKER_* env → metadata dir → derived
    # from hostname ordering); a standalone host needs none of them.
    worker_id: int = -1  # -1 = auto
    worker_hostnames: tuple[str, ...] = ()
    # Name to match against worker lists. In a non-hostNetwork DaemonSet the
    # pod's own hostname is the pod name, never a node name — project
    # spec.nodeName via the downward API into KATA_TPU_NODE_NAME.
    node_name: str = ""
    metadata_dir: str = ""  # dir of GCE-TPU-VM-style metadata attribute files
    state_dir: str = "/var/run/kata-tpu"  # persisted worker identity ("" off)

    # Multislice: several ICI slices cooperating over DCN (MEGASCALE env).
    num_slices: int = 1
    slice_id: int = 0
    megascale_coordinator: str = ""

    # Generalized VFIO path. Empty vendor tuple = VFIO discovery disabled;
    # ("*",) = all vendors (the reference pins exactly one vendor, 10de).
    vfio_vendors: tuple[str, ...] = ()

    # Behavior the reference lacks (SURVEY §Quirks 9).
    rescan_interval_s: float = 30.0
    health_poll_interval_s: float = 5.0

    # Observability (ISSUE 2: the unified telemetry layer's daemon knobs;
    # the guest stack reads the KATATPU_OBS* env contract directly).
    metrics_port: int = 9400  # 0 disables
    log_level: str = "info"
    log_format: str = "text"
    obs_events_file: str = ""  # JSONL event stream path ("" disables)
    obs_profile_dir: str = ""  # jax.profiler dump dir ("" disables)

    # Persistent XLA compilation cache (ISSUE 3): when set, the daemon
    # injects KATA_TPU_COMPILE_CACHE_DIR into every TPU AllocateResponse
    # (plugin/allocators.py), so granted guest workloads point jax's
    # on-disk executable cache at one per-node directory and the
    # multi-second per-executable compile is paid once per machine, not
    # once per process. Guest side, compat.jaxapi.enable_compilation_cache
    # reads that env directly (bench.py and scripts/ call it on startup;
    # "" there falls back to ~/.cache/kata-tpu/xla-cache).
    # KATA_TPU_COMPILE_CACHE=0 is the in-guest kill switch (cache
    # corruption, read-only fs).
    compile_cache_dir: str = ""

    # Shared-prefix KV cache default (ISSUE 5): when > 0, the daemon
    # injects KATA_TPU_PREFIX_CACHE_TOKENS into every TPU AllocateResponse
    # (plugin/allocators.py) so in-guest GenerationServers default their
    # prefix KV store capacity (guest/prefix_cache.py) from the node's
    # sizing instead of per-workload flags — the same delivery path as
    # compile_cache_dir. 0 leaves the guest default (disabled unless the
    # server opts in via prefix_cache_tokens=).
    prefix_cache_tokens: int = 0

    # Paged KV pool default (ISSUE 6): when > 0, the daemon injects
    # KATA_TPU_KV_POOL_TOKENS into every TPU AllocateResponse so in-guest
    # GenerationServers default to the paged block pool
    # (guest/kv_arena.py) — admission by token budget with preemption/
    # requeue instead of the fixed slot grid. Same delivery path as
    # compile_cache_dir / prefix_cache_tokens; servers in incompatible
    # modes (ring_kv, speculative, mesh) degrade to fixed slots with a
    # kv_pool_disabled event rather than crashing. 0 leaves the guest
    # default (fixed slots unless the server opts in via kv_pool_tokens=).
    kv_pool_tokens: int = 0

    # Paged-pool placement layout (ISSUE 14): when set ("heads" |
    # "blocks"), the daemon injects KATA_TPU_KV_LAYOUT into every TPU
    # AllocateResponse so in-guest paged GenerationServers place their
    # block pool accordingly — "blocks" shards the pool by physical
    # blocks across the serving mesh (per-chip pool bytes ~logical/tp
    # for every model, GQA included; the kv_replicated replication cliff
    # does not exist), "heads" pins the legacy divide-or-replicate
    # head-axis sharding. Same delivery path as the other serving knobs;
    # malformed guest-side values degrade with a kv_layout_invalid
    # event, slotted servers with kv_layout_disabled. Empty leaves the
    # guest default (heads).
    kv_layout: str = ""

    # Host-RAM KV offload tier (ISSUE 14): when > 0, the daemon injects
    # KATA_TPU_KV_HOST_TOKENS so in-guest paged servers park cold KV
    # (unpinned prefix segments under pool pressure, preempted idle
    # sessions) in up to this many tokens of host RAM — LRU demotion
    # runs BEFORE youngest-first preemption, and prefix hits / session
    # resumes prefetch the rows back with the H2D upload overlapping the
    # in-flight decode dispatch. Same delivery path; malformed values
    # degrade in-guest with a kv_host_invalid event. 0 leaves the tier
    # off.
    kv_host_tokens: int = 0

    # KV-cache quantization default (ISSUE 12): when set ("int8" |
    # "bf16"), the daemon injects KATA_TPU_KV_QUANT into every TPU
    # AllocateResponse so in-guest GenerationServers resolve their KV
    # arena dtype from the node's policy. The guest default is int8 (the
    # measured-1.7×-faster arena, quality-gated by tools/eval_quality.py
    # — `make eval-kv`); "bf16" is the node-wide opt-out for models the
    # gate rejects. Same delivery path as the compile/prefix/pool knobs;
    # malformed guest-side values degrade with a kv_quant_invalid event.
    # Empty leaves the guest default.
    kv_quant: str = ""

    # Crash-tolerant serving defaults (ISSUE 7): when > 0, the daemon
    # injects KATA_TPU_CHECKPOINT_ROUNDS into every TPU AllocateResponse
    # so in-guest GenerationServers snapshot live-lane KV to host every N
    # rounds and recover from dispatch failures/stalls by checkpointed
    # replay instead of dropping the queue (guest/resilience.py +
    # guest/serving.py supervisor). Same delivery path as the compile/
    # prefix/pool knobs. 0 leaves the guest default (recovery still works
    # via full replay; checkpoints bound how much is replayed).
    checkpoint_rounds: int = 0

    # Chaos-testing schedule (ISSUE 7): when set, injected as
    # KATA_TPU_FAULTS so every serving workload on the node replays one
    # deterministic fault schedule ("<seam>:<round>[:<kind>],...", see
    # docs/resilience.md). Malformed entries degrade in-guest with a
    # fault_schedule_error event — the knob can never crash a workload.
    faults: str = ""

    # SLO-aware admission scheduling defaults (ISSUE 8): when set, the
    # daemon injects KATA_TPU_SCHED_POLICY / KATA_TPU_PREFILL_CHUNK /
    # KATA_TPU_ITL_SLO_MS into every TPU AllocateResponse so in-guest
    # GenerationServers default their admission policy from the node's
    # serving SLO instead of per-workload flags (guest/scheduler.py:
    # "slo_chunked" slices admission prefills into prefill_chunk-token
    # chunks interleaved with decode whenever projected inter-token
    # latency exceeds itl_slo_ms; "fifo_batch" is today's behavior).
    # Same delivery path as the compile/prefix/pool knobs; unknown or
    # incompatible values degrade in-guest with a sched_disabled event.
    # Empty/0 leaves the guest defaults.
    sched_policy: str = ""
    prefill_chunk: int = 0
    itl_slo_ms: float = 0.0

    # Multi-step decode multiplier (ISSUE 13): when > 1, the daemon
    # injects KATA_TPU_DECODE_STEPS into every TPU AllocateResponse so
    # in-guest GenerationServers run chunk × K decode steps per host
    # dispatch (on-device EOS/budget masking inside the jitted scan
    # freezes finished lanes, so K can be large without overrunning
    # block reservations) — host scheduling, fence, and obs bookkeeping
    # amortize over K× more tokens. Same delivery path as the other
    # serving knobs; malformed values degrade in-guest with a
    # decode_steps_invalid event. 0/1 leaves the guest default (K=1).
    decode_steps: int = 0

    # Tensor-parallel serving degree (ISSUE 9): when > 0, the daemon
    # injects KATA_TPU_TP into every TPU AllocateResponse so in-guest
    # GenerationServers override their topology-derived default
    # (guest/tp_serving.py meshes the granted TPU_VISIBLE_CHIPS slice by
    # default) — pin 1 to force single-chip serving node-wide, or a
    # sub-slice degree for guests that co-locate several servers on one
    # allocation. Same delivery path as the compile/prefix/pool knobs;
    # infeasible values degrade in-guest with a tp_disabled event.
    # 0 leaves the guest default (mesh the whole granted slice).
    serving_tp: int = 0

    # Degraded-mode shrink floor (ISSUE 10): when > 0, the daemon injects
    # KATA_TPU_TP_MIN into every TPU AllocateResponse so in-guest
    # GenerationServers stop their elastic mesh-shrink ladder (chip loss
    # at tp=4 → 2 → 1) at this degree — below it the load fails loudly
    # instead of continuing degraded. Same delivery path as the other
    # serving knobs; malformed values degrade in-guest with a
    # tp_min_invalid event. 0 leaves the guest default (shrink to 1).
    serving_tp_min: int = 0

    # Guest telemetry uplink (ISSUE 15): when set, every TPU Allocate
    # switches the guest's JSONL event stream on (KATATPU_OBS=1) and
    # points KATATPU_OBS_FILE at a per-allocation file under this
    # directory — a host path shared with the guests (hostPath volume /
    # Kata shared dir). The daemon's heartbeat AGGREGATOR tails those
    # files (rotation-safe incremental reads, obs.tail_events) and
    # re-exports per-allocation serving gauges — tokens/s, ITL p99,
    # queue depth, pool occupancy, watchdog alerts — on the existing
    # /metrics endpoint, so fleet dashboards see every allocation's
    # serving health without scraping guests. "" disables both the env
    # stamp and the aggregator.
    guest_events_dir: str = ""
    # Aggregator poll cadence (seconds between tail passes).
    guest_events_poll_s: float = 5.0
    # Per-stream growth cap in MiB: the aggregator truncates a guest
    # event file once its consumed prefix exceeds this (the guest's
    # O_APPEND writer continues at the new EOF; nothing in-guest
    # rotates these files, so the daemon is the rotator of last
    # resort). 0 disables truncation.
    guest_events_max_mb: int = 64
    # In-guest serving heartbeat cadence override (ISSUE 15): when > 0,
    # injected as KATA_TPU_HEARTBEAT_ROUNDS so guests emit their
    # serving_heartbeat every K rounds (guest default 32; malformed
    # values degrade in-guest with a heartbeat_invalid event). 0 leaves
    # the guest default.
    heartbeat_rounds: int = 0

    # Per-allocation trace context (ISSUE 11): when enabled (default),
    # every TPU Allocate stamps the trace id of its own plugin.Allocate
    # span into KATA_TPU_TRACE_CTX in the AllocateResponse env, so
    # in-guest GenerationServers join their spans/events — request
    # lifecycle traces, recovery/degraded events, flight-recorder dumps
    # — to the daemon's allocation trace (docs/architecture.md
    # "Daemon → guest trace context"). --no-trace-context disables the
    # stamp; guests then mint their own trace ids.
    trace_context: bool = True

    # Kubelet registration retry policy (ISSUE 7 satellite): attempts ×
    # exponential backoff (plus jitter) before a plugin gives up with a
    # registration_exhausted event. The old hardcoded 5 × 1 s ladder gave
    # up for good after ~31 s of kubelet downtime.
    register_attempts: int = 5
    register_backoff_s: float = 1.0

    def __post_init__(self) -> None:
        if not self.kubelet_socket:
            self.kubelet_socket = os.path.join(self.kubelet_socket_dir, "kubelet.sock")
        for s in self.strategies:
            if s not in C.ALL_STRATEGIES:
                raise ValueError(f"unknown device-list strategy: {s!r}")
        if self.cdi_format not in ("yaml", "json"):
            raise ValueError(f"cdi-format must be yaml or json, got {self.cdi_format!r}")
        if self.num_slices < 1:
            raise ValueError(f"num-slices must be >= 1, got {self.num_slices}")
        if self.num_slices > 1 and not 0 <= self.slice_id < self.num_slices:
            raise ValueError(
                f"slice-id {self.slice_id} out of range for {self.num_slices} slices"
            )
        if self.sched_policy not in ("", "fifo_batch", "slo_chunked"):
            raise ValueError(
                f"sched-policy must be fifo_batch or slo_chunked, got "
                f"{self.sched_policy!r}"
            )
        if self.kv_quant not in ("", "int8", "bf16"):
            raise ValueError(
                f"kv-quant must be int8 or bf16, got {self.kv_quant!r}"
            )
        if self.kv_layout not in ("", "heads", "blocks"):
            raise ValueError(
                f"kv-layout must be heads or blocks, got {self.kv_layout!r}"
            )
        if self.kv_host_tokens < 0:
            raise ValueError(
                f"kv-host-tokens must be >= 0, got {self.kv_host_tokens}"
            )
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill-chunk must be >= 0, got {self.prefill_chunk}"
            )
        if self.itl_slo_ms < 0:
            raise ValueError(
                f"itl-slo-ms must be >= 0, got {self.itl_slo_ms}"
            )
        if self.decode_steps < 0:
            raise ValueError(
                f"decode-steps must be >= 0, got {self.decode_steps}"
            )
        if self.serving_tp < 0:
            raise ValueError(
                f"serving-tp must be >= 0, got {self.serving_tp}"
            )
        if self.serving_tp_min < 0:
            raise ValueError(
                f"serving-tp-min must be >= 0, got {self.serving_tp_min}"
            )
        if self.serving_tp and self.serving_tp_min > self.serving_tp:
            raise ValueError(
                f"serving-tp-min {self.serving_tp_min} exceeds serving-tp "
                f"{self.serving_tp} — the shrink ladder could never start"
            )
        if self.guest_events_poll_s <= 0:
            raise ValueError(
                f"guest-events-poll-s must be > 0, got "
                f"{self.guest_events_poll_s}"
            )
        if self.guest_events_max_mb < 0:
            raise ValueError(
                f"guest-events-max-mb must be >= 0, got "
                f"{self.guest_events_max_mb}"
            )
        if self.heartbeat_rounds < 0:
            raise ValueError(
                f"heartbeat-rounds must be >= 0, got {self.heartbeat_rounds}"
            )
        if self.register_attempts < 1:
            raise ValueError(
                f"register-attempts must be >= 1, got {self.register_attempts}"
            )
        if self.register_backoff_s < 0:
            raise ValueError(
                f"register-backoff-s must be >= 0, got {self.register_backoff_s}"
            )
        if len(set(self.worker_hostnames)) != len(self.worker_hostnames):
            raise ValueError("worker-hostnames contains duplicates")
        if self.worker_id >= 0 and self.worker_hostnames and (
            self.worker_id >= len(self.worker_hostnames)
        ):
            raise ValueError(
                f"worker-id {self.worker_id} out of range for "
                f"{len(self.worker_hostnames)} worker-hostnames"
            )

    @property
    def tpu_resource_name(self) -> str:
        """The extended resource advertised for TPU chips (GKE convention
        ``google.com/tpu``; the reference's analogue is ``nvidia.com/<MODEL>``)."""
        return f"{self.resource_namespace}/{self.tpu_resource_class}"

    @property
    def tpu_cdi_kind(self) -> str:
        return f"{self.resource_namespace}/{self.tpu_resource_class}"

    @property
    def vfio_cdi_kind(self) -> str:
        return f"{self.resource_namespace}/{C.VFIO_CLASS}"


_ENV_PREFIX = "KATA_TPU_"


def _flag(name: str) -> str:
    return "--" + name.replace("_", "-")


def add_flags(parser: argparse.ArgumentParser) -> None:
    for f in fields(Config):
        env_val = os.environ.get(_ENV_PREFIX + f.name.upper())
        # Raw field default, NOT a Config() instance: __post_init__ resolves
        # derived values (kubelet_socket from kubelet_socket_dir), and a
        # resolved default would pin the flag to the production path even
        # when the user overrides the directory it derives from.
        default = f.default if f.default is not MISSING else f.default_factory()  # type: ignore[misc]
        if f.type in ("tuple[str, ...]",):
            default = ",".join(default) if env_val is None else env_val
            parser.add_argument(_flag(f.name), default=default, help=f"csv ({f.name})")
        elif isinstance(default, bool):
            val = default if env_val is None else env_val.lower() in ("1", "true", "yes")
            parser.add_argument(
                _flag(f.name), default=val, action=argparse.BooleanOptionalAction
            )
        elif isinstance(default, (int, float)) and not isinstance(default, bool):
            typ = type(default)
            parser.add_argument(
                _flag(f.name), type=typ, default=typ(env_val) if env_val else default
            )
        else:
            parser.add_argument(_flag(f.name), default=env_val if env_val is not None else default)


def from_args(args: argparse.Namespace) -> Config:
    kwargs = {}
    for f in fields(Config):
        val = getattr(args, f.name)
        if f.type == "tuple[str, ...]":
            val = tuple(v for v in str(val).split(",") if v) if val else ()
        kwargs[f.name] = val
    return Config(**kwargs)
