"""CLI entry point (counterpart of the reference's ``cmd/main.go:5-7``).

The reference's ``main()`` is a single bare call — no flags, no signal
handling, no subcommands (SURVEY L4). Here:

- ``run``     start the device-plugin daemon (every constant is a flag)
- ``status``  one-shot report: discovery, CDI specs on disk, pod assignments
- ``version``
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys


def main(argv: list[str] | None = None) -> int:
    from . import __version__

    parser = argparse.ArgumentParser(
        prog="kata-tpu-device-plugin",
        description="TPU-native Kubernetes device plugin for Kata Containers",
    )
    parser.add_argument(
        "--version", action="version", version=f"kata-tpu-device-plugin {__version__}"
    )
    sub = parser.add_subparsers(dest="command")
    from .config import add_flags

    run_p = sub.add_parser("run", help="run the device-plugin daemon")
    add_flags(run_p)
    status_p = sub.add_parser("status", help="report discovery + allocation state")
    add_flags(status_p)
    status_p.add_argument("--json", action="store_true", dest="as_json")
    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    if args.command in (None, "version"):
        print(f"kata-tpu-device-plugin {__version__}")
        return 0
    if args.command == "run":
        return _run(args)
    if args.command == "status":
        return _status(args)
    parser.error(f"unknown command {args.command!r}")
    return 2


def _run(args: argparse.Namespace) -> int:
    from .config import from_args
    from .plugin.manager import PluginManager
    from .utils import log, metrics

    cfg = from_args(args)
    logger = log.setup(cfg.log_level, cfg.log_format)
    metrics.serve(cfg.metrics_port)
    mgr = PluginManager(cfg)

    def _on_signal(signum, _frame):
        logger.info("signal received, shutting down", extra=log.kv(signal=signum))
        mgr.stop()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    mgr.start()
    mgr.run_forever()  # ref: blocks on <-stop (device_plugin.go:114)
    return 0


def _status(args: argparse.Namespace) -> int:
    from .config import from_args
    from .discovery import scan_tpus, scan_vfio
    from .discovery.pciids import PciIds

    cfg = from_args(args)
    db = PciIds.load(cfg.pci_ids_path or None)
    tpu = scan_tpus(cfg.sysfs_root, cfg.dev_root, pci_ids=db,
                    accelerator_type=cfg.accelerator_type or None)
    report: dict = {
        "tpu": {
            "resource": cfg.tpu_resource_name,
            "chips": [
                {
                    "index": c.index,
                    "dev_path": c.dev_path,
                    "pci_address": c.pci_address,
                    "numa_node": c.numa_node,
                    "present": os.path.exists(c.dev_path),
                }
                for c in tpu.chips
            ],
            "accelerator_type": tpu.topology.accelerator_type,
            "chips_per_host_bounds": tpu.topology.chips_per_host_bounds_str(),
            "num_hosts": tpu.topology.num_hosts,
            "worker_id": tpu.topology.worker_id,
        },
        "cdi_specs": sorted(
            os.path.join(cfg.cdi_dir, f)
            for f in (os.listdir(cfg.cdi_dir) if os.path.isdir(cfg.cdi_dir) else [])
            if f.endswith((".yaml", ".json"))
        ),
    }
    if cfg.vfio_vendors:
        vendors = () if cfg.vfio_vendors == ("*",) else cfg.vfio_vendors
        vfio = scan_vfio(cfg.sysfs_root, vendors)
        report["vfio"] = {
            f"{v}:{d}": groups for (v, d), groups in sorted(vfio.models.items())
        }
    try:
        from .utils.podresources import device_assignments, list_pod_resources

        resp = list_pod_resources(cfg.pod_resources_socket, timeout_s=2.0)
        report["pod_assignments"] = device_assignments(resp, cfg.resource_namespace)
    except Exception as e:
        report["pod_assignments_error"] = str(e) or type(e).__name__

    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        t = report["tpu"]
        print(f"resource: {t['resource']}")
        print(f"accelerator_type: {t['accelerator_type']} "
              f"(bounds {t['chips_per_host_bounds']}, hosts {t['num_hosts']}, "
              f"worker {t['worker_id']})")
        print(f"chips: {len(t['chips'])}")
        for c in t["chips"]:
            mark = "ok" if c["present"] else "MISSING"
            print(f"  accel{c['index']}: {c['dev_path']} [{mark}]"
                  + (f" pci={c['pci_address']}" if c["pci_address"] else "")
                  + (f" numa={c['numa_node']}" if c["numa_node"] is not None else ""))
        for path in report["cdi_specs"]:
            print(f"cdi spec: {path}")
        if "vfio" in report:
            for model, groups in report["vfio"].items():
                print(f"vfio {model}: groups {','.join(groups)}")
        if "pod_assignments" in report:
            for a in report["pod_assignments"]:
                print(f"pod {a['namespace']}/{a['pod']}/{a['container']}: "
                      f"{a['resource']} = {','.join(a['device_ids'])}")
        else:
            print(f"pod-resources: unavailable ({report['pod_assignments_error']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
