"""CLI entry point (counterpart of the reference's ``cmd/main.go:5-7``).

The reference's ``main()`` is a single bare call — no flags, no signal
handling, no subcommands (SURVEY L4). Here:

- ``run``     start the device-plugin daemon (every constant is a flag)
- ``status``  one-shot report: discovery, CDI specs on disk, pod assignments
- ``version``
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys


def main(argv: list[str] | None = None) -> int:
    from . import __version__

    parser = argparse.ArgumentParser(
        prog="kata-tpu-device-plugin",
        description="TPU-native Kubernetes device plugin for Kata Containers",
    )
    parser.add_argument(
        "--version", action="version", version=f"kata-tpu-device-plugin {__version__}"
    )
    sub = parser.add_subparsers(dest="command")
    from .config import add_flags

    run_p = sub.add_parser("run", help="run the device-plugin daemon")
    add_flags(run_p)
    status_p = sub.add_parser("status", help="report discovery + allocation state")
    add_flags(status_p)
    status_p.add_argument("--json", action="store_true", dest="as_json")
    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    if args.command in (None, "version"):
        print(f"kata-tpu-device-plugin {__version__}")
        return 0
    if args.command == "run":
        return _run(args)
    if args.command == "status":
        return _status(args)
    parser.error(f"unknown command {args.command!r}")
    return 2


def _run(args: argparse.Namespace) -> int:
    from .config import from_args
    from .plugin.manager import PluginManager
    from .utils import log, metrics

    cfg = from_args(args)
    logger = log.setup(cfg.log_level, cfg.log_format)
    metrics.serve(cfg.metrics_port)
    if cfg.obs_events_file:
        # Daemon-side JSONL event stream (ISSUE 2): spans from the gRPC
        # handlers land in the same pipeline the guest stack writes to.
        from . import obs

        obs.set_default_sink(obs.EventSink(cfg.obs_events_file))
    if cfg.obs_profile_dir:
        os.environ.setdefault("KATATPU_OBS_PROFILE_DIR", cfg.obs_profile_dir)
    mgr = PluginManager(cfg)

    # Self-pipe shutdown: the handler runs ON the main thread, which may be
    # mid-start() holding a plugin-server lock, or mid-Event.wait() holding
    # that event's internal lock — so the handler must not touch locks or
    # Events at all (manager.request_stop docs). It only writes a byte
    # (async-signal-safe); a watcher thread does the actual stop request
    # from a different thread, where Event.set cannot self-deadlock.
    sig_r, sig_w = os.pipe()

    def _on_signal(signum, _frame):
        try:
            os.write(sig_w, bytes([signum & 0x7F]))
        except OSError:
            pass

    def _signal_watcher():
        while True:
            data = os.read(sig_r, 1)
            if data and data[0] == (signal.SIGUSR1 & 0x7F):
                # Observability hook: dump live manager state as one
                # structured log line, keep running. On a SEPARATE short
                # thread — debug_report/logging take manager+handler locks,
                # and a dump wedged on one of them must not stop this
                # watcher from reading the next (shutdown) signal byte.
                def _dump():
                    try:
                        logger.info(
                            "debug state dump (SIGUSR1)",
                            extra=log.kv(state=json.dumps(mgr.debug_report())),
                        )
                    except Exception as e:
                        logger.error("debug dump failed", extra=log.kv(err=str(e)))

                threading.Thread(target=_dump, name="debug-dump", daemon=True).start()
                continue
            logger.info(
                "signal received, shutting down",
                extra=log.kv(signal=data[0] if data else "?"),
            )
            mgr.request_stop()
            return

    import threading

    threading.Thread(target=_signal_watcher, name="signal-watcher", daemon=True).start()
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGUSR1, _on_signal)

    try:
        mgr.start()
        mgr.run_forever()  # ref: blocks on <-stop (device_plugin.go:114)
    finally:
        mgr.stop()
    return 0


def _status(args: argparse.Namespace) -> int:
    from .config import from_args
    from .plugin.manager import PluginManager

    cfg = from_args(args)
    # The manager's scan, not a raw scan_tpus: status must report the same
    # multihost-overlaid identity the daemon writes into CDI specs — but a
    # read-only command must not touch the daemon's persisted state.
    tpu, vfio = PluginManager(cfg, state_readonly=True).scan()
    report: dict = {
        "tpu": {
            "resource": cfg.tpu_resource_name,
            "chips": [
                {
                    "index": c.index,
                    "dev_path": c.dev_path,
                    "pci_address": c.pci_address,
                    "numa_node": c.numa_node,
                    "present": os.path.exists(c.dev_path),
                }
                for c in tpu.chips
            ],
            "accelerator_type": tpu.topology.accelerator_type,
            "chips_per_host_bounds": tpu.topology.chips_per_host_bounds_str(),
            "num_hosts": tpu.topology.num_hosts,
            "worker_id": tpu.topology.worker_id,
            "worker_hostnames": list(tpu.topology.worker_hostnames),
        },
        "cdi_specs": sorted(
            os.path.join(cfg.cdi_dir, f)
            for f in (os.listdir(cfg.cdi_dir) if os.path.isdir(cfg.cdi_dir) else [])
            if f.endswith((".yaml", ".json"))
        ),
    }
    if cfg.vfio_vendors:
        report["vfio"] = {
            f"{v}:{d}": groups for (v, d), groups in sorted(vfio.models.items())
        }
    try:
        from .utils.podresources import device_assignments, list_pod_resources

        resp = list_pod_resources(cfg.pod_resources_socket, timeout_s=2.0)
        report["pod_assignments"] = device_assignments(resp, cfg.resource_namespace)
    except Exception as e:
        report["pod_assignments_error"] = str(e) or type(e).__name__

    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        t = report["tpu"]
        print(f"resource: {t['resource']}")
        print(f"accelerator_type: {t['accelerator_type']} "
              f"(bounds {t['chips_per_host_bounds']}, hosts {t['num_hosts']}, "
              f"worker {t['worker_id']})")
        print(f"chips: {len(t['chips'])}")
        for c in t["chips"]:
            mark = "ok" if c["present"] else "MISSING"
            print(f"  accel{c['index']}: {c['dev_path']} [{mark}]"
                  + (f" pci={c['pci_address']}" if c["pci_address"] else "")
                  + (f" numa={c['numa_node']}" if c["numa_node"] is not None else ""))
        for path in report["cdi_specs"]:
            print(f"cdi spec: {path}")
        if "vfio" in report:
            for model, groups in report["vfio"].items():
                print(f"vfio {model}: groups {','.join(groups)}")
        if "pod_assignments" in report:
            for a in report["pod_assignments"]:
                print(f"pod {a['namespace']}/{a['pod']}/{a['container']}: "
                      f"{a['resource']} = {','.join(a['device_ids'])}")
        else:
            print(f"pod-resources: unavailable ({report['pod_assignments_error']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
