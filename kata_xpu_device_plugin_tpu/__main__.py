"""CLI entry point (counterpart of the reference's ``cmd/main.go:5-7``).

The reference's ``main()`` is a single call with no flags, no signal handling
(SURVEY L4). This entry point grows into a real CLI (``run`` / ``status`` /
``version`` subcommands with full flag coverage) as the framework lands; it is
kept minimal-but-working at every commit.
"""
from __future__ import annotations

import sys


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from . import __version__

    if argv[:1] in ([], ["version"], ["--version"]):
        print(f"kata-tpu-device-plugin {__version__}")
        return 0
    print(f"unknown command: {argv[0]!r} (available: version)", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
