"""ICI slice topology model + topology-aware preferred allocation (the TPU
analogue of the reference's IOMMU-group co-allocation unit; implements what
``GetPreferredAllocation`` stubs out at generic_device_plugin.go:378-386)."""
from .preferred import (
    Placement,
    alignment_score,
    chip_ids_to_indexes,
    choose_chips,
    degraded_fallbacks,
    guest_meshable_counts,
)
from .slice import (
    FAMILIES,
    HostTopology,
    TpuFamily,
    chip_coord,
    coord_chip,
    detect_accelerator_type,
    parse_accelerator_type,
    runtime_env,
)

__all__ = [
    "Placement",
    "alignment_score",
    "chip_ids_to_indexes",
    "choose_chips",
    "degraded_fallbacks",
    "guest_meshable_counts",
    "FAMILIES",
    "HostTopology",
    "TpuFamily",
    "chip_coord",
    "coord_chip",
    "detect_accelerator_type",
    "parse_accelerator_type",
    "runtime_env",
]
