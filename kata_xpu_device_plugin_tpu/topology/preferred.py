"""Topology-aware preferred allocation.

The reference stubs ``GetPreferredAllocation`` (``generic_device_plugin.go:
378-386`` returns ``nil, nil``) — for interchangeable VFIO groups that is
merely lazy; for TPUs it is wrong (SURVEY §Quirks 8). A 4-chip request on a
v5e-8 host must get an ICI-contiguous 2x2 sub-grid, or the guest's mesh cannot
use ICI between its chips. This module picks such sub-grids.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from .slice import Coord, HostTopology, chip_coord, coord_chip


@dataclass(frozen=True)
class Placement:
    """A chosen chip set, with whether it is ICI-contiguous."""

    chips: tuple[int, ...]
    contiguous: bool
    shape: Optional[Coord] = None


def _placements(grid: Coord, shape: Coord) -> Iterable[tuple[Coord, Coord]]:
    """All axis-aligned origins (and orientations) where ``shape`` fits in
    ``grid``. Both xy orientations of the sub-grid are considered (a 1x2 slice
    can lie along x or y — ICI links exist both ways)."""
    seen = set()
    sx, sy, sz = shape
    for oriented in {(sx, sy, sz), (sy, sx, sz)}:
        ox_max = grid[0] - oriented[0]
        oy_max = grid[1] - oriented[1]
        oz_max = grid[2] - oriented[2]
        if min(ox_max, oy_max, oz_max) < 0:
            continue
        for oz in range(oz_max + 1):
            for oy in range(oy_max + 1):
                for ox in range(ox_max + 1):
                    key = ((ox, oy, oz), oriented)
                    if key not in seen:
                        seen.add(key)
                        yield (ox, oy, oz), oriented


def _chips_in_box(topo: HostTopology, origin: Coord, shape: Coord) -> list[int]:
    fam = topo.family
    chips = []
    for dz in range(shape[2]):
        for dy in range(shape[1]):
            for dx in range(shape[0]):
                chips.append(
                    coord_chip(fam, (origin[0] + dx, origin[1] + dy, origin[2] + dz))
                )
    return sorted(chips)


def choose_chips(
    topo: HostTopology,
    available: Sequence[int],
    count: int,
    must_include: Sequence[int] = (),
) -> Placement:
    """Pick ``count`` chips from ``available``, preferring an ICI-contiguous
    axis-aligned sub-grid that covers ``must_include``.

    Falls back to the lowest-indexed available chips (non-contiguous) when no
    valid box fits — the kubelet treats preferred allocation as advisory, so
    returning *something* keeps Allocate functional, and the plugin flags
    non-contiguity in its metrics/logs.
    """
    avail = sorted(set(available))
    must = sorted(set(must_include))
    if count > len(avail) or len(must) > count or not set(must) <= set(avail):
        raise ValueError(
            f"cannot allocate {count} chips from {len(avail)} available "
            f"(must_include={must})"
        )
    shape = topo.family.subslices.get(count)
    if shape is not None:
        grid = topo.local_grid()
        avail_set = set(avail)
        best: Optional[tuple[tuple, list[int], Coord]] = None
        for origin, oriented in _placements(grid, shape):
            chips = _chips_in_box(topo, origin, oriented)
            if not set(chips) <= avail_set or not set(must) <= set(chips):
                continue
            # Deterministic preference: lowest chip ids first (stable across
            # kubelet retries, like the reference's sorted group ids).
            key = tuple(chips)
            if best is None or key < best[0]:
                best = (key, chips, oriented)
        if best is not None:
            return Placement(chips=tuple(best[1]), contiguous=True, shape=best[2])
    # No contiguous box available (fragmented host or odd count).
    fill = [c for c in avail if c not in must]
    chosen = sorted(must + fill[: count - len(must)])
    return Placement(chips=tuple(chosen), contiguous=False)


def guest_meshable_counts(topo: HostTopology) -> list[int]:
    """Chip counts a guest can bring up as a 1×N tensor-parallel serving
    mesh from the env this host emits — exactly the requestable sub-slice
    sizes. The allocation-hint half of the daemon↔guest topology
    contract (ISSUE 9): every sub-slice shape in ``family.subslices`` is
    an axis-aligned ICI box, so the contiguous placements
    :func:`choose_chips` prefers are precisely the allocations
    ``guest.tp_serving`` can mesh with the ``model`` axis riding ICI
    neighbors. Consistency is asserted in ``tests/test_tp_serving.py``:
    every contiguous preferred placement's size appears here, and every
    count here round-trips ``topology.runtime_env`` →
    ``tp_serving.tp_from_env`` → ``tp_serving.serving_mesh``."""
    return topo.valid_request_counts()


def degraded_fallbacks(topo: HostTopology, count: int) -> list[int]:
    """The tensor-parallel degrees a guest can land on when chips of a
    ``count``-chip allocation die — the host-side half of the
    degraded-mode contract (ISSUE 10). The guest's elastic shrink walks
    a HALVING ladder (``guest.tp_serving.shrink_ladder``: tp=4 → 2 → 1),
    and every rung must be a size this host could itself have allocated
    as an ICI-contiguous sub-slice, or a ``tp_degraded`` event would
    name a degree the family table cannot interpret. Returned
    descending; consistency with :func:`guest_meshable_counts` is
    asserted in ``tests/test_degraded.py`` (the tripwire if a family
    table drifts)."""
    meshable = set(guest_meshable_counts(topo))
    out = []
    t = count // 2
    while t >= 1:
        if t == 1 or t in meshable:
            out.append(t)
        t //= 2
    return out


def chip_ids_to_indexes(ids: Iterable[str]) -> list[int]:
    """Device-plugin device ids are strings; chips are host-local ints."""
    return [int(i) for i in ids]


def alignment_score(topo: HostTopology, chips: Sequence[int]) -> float:
    """1.0 when the set is exactly a valid sub-grid; used by tests/metrics."""
    try:
        placement = choose_chips(topo, chips, len(chips))
    except ValueError:
        return 0.0
    return 1.0 if placement.contiguous and set(placement.chips) == set(chips) else 0.0
