"""ICI slice topology model.

The reference's co-allocation unit is the IOMMU group — any N group ids are
interchangeable (``generic_device_plugin.go:322-341``). TPU chips are NOT
interchangeable: they sit at fixed coordinates in the host's ICI grid, and only
axis-aligned contiguous sub-grids form valid slices (SURVEY §7 "Hard parts").
This module models host grids per TPU family, maps chip indexes to ICI
coordinates, validates requestable sub-slice shapes, and emits the libtpu
topology environment (``TPU_ACCELERATOR_TYPE``, ``TPU_CHIPS_PER_HOST_BOUNDS``,
``TPU_HOST_BOUNDS``, ``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``,
``TPU_VISIBLE_CHIPS``) that JAX/XLA in the Kata guest needs to bring up ICI.
"""
from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Optional, Sequence

from ..utils import log

LOG = log.get("topology")

Coord = tuple[int, int, int]


@dataclass(frozen=True)
class TpuFamily:
    """Static per-generation host layout."""

    name: str  # family prefix in TPU_ACCELERATOR_TYPE, e.g. "v5litepod"
    chips_per_host: int
    host_grid: Coord  # ICI grid of one host's chips, e.g. (2, 4, 1) for v5e-8
    # Requestable chip counts within ONE host, mapped to their sub-grid shape.
    # (Multi-host slices always take whole hosts; partial-host allocation only
    # exists where the cloud exposes it — v5e/v6e 1/4/8-chip machine shapes.)
    subslices: dict[int, Coord]
    # Suffix in the accelerator type counts chips (v5e/v6e) or TensorCores
    # (v2-v4/v5p, 2 cores per chip).
    suffix_counts_cores: bool
    # ICI mesh dimensionality of the *slice*: 2 for the 2D-torus families
    # (v2/v3/v5e/v6e, hosts extend the grid in y), 3 for the 3D-torus
    # families (v4/v5p, hosts stack 2x2x1 bricks in z).
    slice_dims: int = 2


_SUBHOST_8 = {1: (1, 1, 1), 2: (1, 2, 1), 4: (2, 2, 1), 8: (2, 4, 1)}

FAMILIES: dict[str, TpuFamily] = {
    f.name: f
    for f in (
        TpuFamily("v2", 4, (2, 2, 1), {4: (2, 2, 1)}, True, slice_dims=2),
        TpuFamily("v3", 4, (2, 2, 1), {4: (2, 2, 1)}, True, slice_dims=2),
        TpuFamily("v4", 4, (2, 2, 1), {4: (2, 2, 1)}, True, slice_dims=3),
        TpuFamily("v5p", 4, (2, 2, 1), {4: (2, 2, 1)}, True, slice_dims=3),
        TpuFamily("v5litepod", 8, (2, 4, 1), dict(_SUBHOST_8), False, slice_dims=2),
        TpuFamily("v6e", 8, (2, 4, 1), dict(_SUBHOST_8), False, slice_dims=2),
    )
}


def parse_accelerator_type(accel_type: str) -> tuple[TpuFamily, int]:
    """``"v5litepod-8"`` -> (family, total chips in the slice).

    Raises ValueError for unknown families or malformed strings.
    """
    name, sep, suffix = accel_type.partition("-")
    fam = FAMILIES.get(name)
    if fam is None or not sep or not suffix.isdigit():
        raise ValueError(f"unknown accelerator type: {accel_type!r}")
    n = int(suffix)
    chips = n // 2 if fam.suffix_counts_cores else n
    if chips < 1:
        raise ValueError(f"accelerator type too small: {accel_type!r}")
    return fam, chips


def chip_coord(fam: TpuFamily, index: int) -> Coord:
    """ICI coordinate of host-local chip ``index`` (row-major over the grid)."""
    gx, gy, _gz = fam.host_grid
    if not 0 <= index < fam.chips_per_host:
        raise ValueError(f"chip index {index} out of range for {fam.name}")
    return (index % gx, (index // gx) % gy, index // (gx * gy))


def coord_chip(fam: TpuFamily, coord: Coord) -> int:
    gx, gy, _gz = fam.host_grid
    x, y, z = coord
    return x + y * gx + z * gx * gy


@dataclass(frozen=True)
class HostTopology:
    """The slice topology as seen from one host."""

    accelerator_type: str
    family: TpuFamily
    total_chips: int  # whole slice
    local_chips: int  # on this host
    num_hosts: int
    worker_id: int = 0
    worker_hostnames: tuple[str, ...] = ()

    @classmethod
    def from_accelerator_type(
        cls,
        accel_type: str,
        worker_id: int = 0,
        worker_hostnames: Sequence[str] = (),
    ) -> "HostTopology":
        fam, chips = parse_accelerator_type(accel_type)
        local = min(chips, fam.chips_per_host)
        num_hosts = max(1, math.ceil(chips / fam.chips_per_host))
        return cls(
            accelerator_type=accel_type,
            family=fam,
            total_chips=chips,
            local_chips=local,
            num_hosts=num_hosts,
            worker_id=worker_id,
            worker_hostnames=tuple(worker_hostnames),
        )

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    def local_grid(self) -> Coord:
        """Grid of the chips present on this host (sub-host slices shrink it)."""
        if self.local_chips == self.family.chips_per_host:
            return self.family.host_grid
        shape = self.family.subslices.get(self.local_chips)
        if shape is None:
            raise ValueError(
                f"{self.accelerator_type}: {self.local_chips} chips/host has no valid grid"
            )
        return shape

    def host_bounds(self) -> Coord:
        """How hosts tile the full slice grid (``TPU_HOST_BOUNDS``).

        Hosts stack along y for 2D-torus families and along z for 3D ones —
        matching how slices grow: v2/v3/v5e/v6e pods extend the host grid in
        y; v4/v5p pods stack 2x2x1 host bricks in z.
        """
        if self.num_hosts == 1:
            return (1, 1, 1)
        if self.family.slice_dims == 2:
            return (1, self.num_hosts, 1)
        return (1, 1, self.num_hosts)

    def valid_request_counts(self) -> list[int]:
        """Chip counts a pod may request on this host."""
        if self.is_multi_host:
            return [self.local_chips]  # whole host only
        return sorted(c for c in self.family.subslices if c <= self.local_chips)

    def chips_per_host_bounds_str(self) -> str:
        gx, gy, gz = self.local_grid()
        return f"{gx},{gy},{gz}"

    def host_bounds_str(self) -> str:
        hx, hy, hz = self.host_bounds()
        return f"{hx},{hy},{hz}"


# PCI device id → TPU family (ids also named in discovery.pciids; kept here so
# topology resolves generation without importing discovery).
GOOGLE_DEVICE_TO_FAMILY = {
    "0027": "v2",
    "0056": "v3",
    "005e": "v4",
    "0062": "v5p",
    "0063": "v5litepod",
    "006f": "v6e",
}


def detect_accelerator_type(
    env: Optional[dict[str, str]] = None,
    chip_count: Optional[int] = None,
    pci_device_id: Optional[str] = None,
) -> str:
    """Best-effort accelerator type: env (GKE sets TPU_ACCELERATOR_TYPE on TPU
    node pools) → PCI-device-id family + chip-count heuristic.

    Without env, the generation comes from the chips' PCI device id when
    known (a v4 host must not be labelled v5litepod — wrong slice_dims) and
    the count is rounded UP to the nearest shape that has a valid grid (a
    host with 3 healthy chips of a 4-chip machine is still a 4-chip machine)
    so every returned type survives ``HostTopology.local_grid()``.
    """
    env = os.environ if env is None else env
    from_env = env.get("TPU_ACCELERATOR_TYPE")
    if from_env:
        return from_env
    fam_name = GOOGLE_DEVICE_TO_FAMILY.get((pci_device_id or "").lower())
    if fam_name is None:
        # A wrong family means wrong slice_dims/host bounds and a guest whose
        # ICI mesh won't come up — the operator must hear about the guess.
        fam_name = "v5litepod"
        LOG.warning(
            "TPU family not identifiable: assuming %s; set TPU_ACCELERATOR_TYPE "
            "on the node if this is wrong",
            fam_name,
            extra=log.kv(pci_device_id=pci_device_id or "<none>"),
        )
    fam = FAMILIES[fam_name]
    n = max(1, chip_count or 1)
    if n <= fam.chips_per_host:
        chips = min(c for c in fam.subslices if c >= n)
    else:
        chips = math.ceil(n / fam.chips_per_host) * fam.chips_per_host
    suffix = chips * 2 if fam.suffix_counts_cores else chips
    return f"{fam_name}-{suffix}"


def runtime_env(
    topo: HostTopology, visible_chips: Optional[Sequence[int]] = None
) -> dict[str, str]:
    """The env block injected into the guest via CDI ``containerEdits`` so
    libtpu initializes the ICI mesh (SURVEY §2 TPU-equivalents table)."""
    env = {
        "TPU_ACCELERATOR_TYPE": topo.accelerator_type,
        "TPU_CHIPS_PER_HOST_BOUNDS": topo.chips_per_host_bounds_str(),
        "TPU_HOST_BOUNDS": topo.host_bounds_str(),
        "TPU_WORKER_ID": str(topo.worker_id),
        "TPU_SKIP_MDS_QUERY": "true",
    }
    if topo.worker_hostnames:
        env["TPU_WORKER_HOSTNAMES"] = ",".join(topo.worker_hostnames)
    if visible_chips is not None:
        env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in visible_chips)
    return env
