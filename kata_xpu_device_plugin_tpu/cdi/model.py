"""CDI spec data model.

A complete, typed re-design of the reference's hand-rolled CDI structs
(ref ``cdi/spec.go:17-83``): the reference models only ``deviceNodes``; TPUs
additionally need ``mounts`` (libtpu.so) and ``env`` (ICI topology) inside
``containerEdits``, so those are first-class here. Serialization follows the
CDI 0.6.0 schema (camelCase keys, empty fields omitted).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.:-]*$")
_VENDOR_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9.-]*[A-Za-z0-9]$")
_CLASS_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_-]*$")


def _prune(d: dict[str, Any]) -> dict[str, Any]:
    """Drop None/empty entries so emitted YAML matches the canonical CDI shape."""
    return {k: v for k, v in d.items() if v not in (None, [], {}, "")}


@dataclass
class DeviceNode:
    """A /dev node to create inside the container (CDI ``deviceNodes`` entry).

    The reference emits exactly one, ``/dev/vfio/<group>`` (ref
    device_plugin.go:71-73); the TPU path emits ``/dev/accel<N>`` (+ ``/dev/vfio/*``
    when VFIO-bound) and optionally explicit type/major/minor for Kata guests
    where the host devtmpfs is not visible.
    """

    path: str
    host_path: Optional[str] = None
    type: Optional[str] = None  # "c" | "b"
    major: Optional[int] = None
    minor: Optional[int] = None
    permissions: Optional[str] = None  # e.g. "rw"
    uid: Optional[int] = None
    gid: Optional[int] = None

    def to_dict(self) -> dict[str, Any]:
        return _prune(
            {
                "path": self.path,
                "hostPath": self.host_path,
                "type": self.type,
                "major": self.major,
                "minor": self.minor,
                "permissions": self.permissions,
                "uid": self.uid,
                "gid": self.gid,
            }
        )


@dataclass
class Mount:
    """A bind mount into the container (CDI ``mounts`` entry).

    Absent from the reference model; required here to inject ``libtpu.so`` into
    the Kata guest (SURVEY §2: "/dev/vfio DeviceNode in CDI" → "… plus mounts
    for libtpu.so").
    """

    host_path: str
    container_path: str
    options: list[str] = field(default_factory=lambda: ["ro", "nosuid", "nodev", "bind"])
    type: Optional[str] = "bind"

    def to_dict(self) -> dict[str, Any]:
        return _prune(
            {
                "hostPath": self.host_path,
                "containerPath": self.container_path,
                "options": list(self.options),
                "type": self.type,
            }
        )


@dataclass
class Hook:
    """An OCI lifecycle hook (CDI ``hooks`` entry); modeled for completeness."""

    hook_name: str
    path: str
    args: list[str] = field(default_factory=list)
    env: list[str] = field(default_factory=list)
    timeout: Optional[int] = None

    def to_dict(self) -> dict[str, Any]:
        return _prune(
            {
                "hookName": self.hook_name,
                "path": self.path,
                "args": list(self.args),
                "env": list(self.env),
                "timeout": self.timeout,
            }
        )


@dataclass
class ContainerEdits:
    """OCI spec edits applied by the runtime when a CDI device is requested
    (ref ``cdi/spec.go:26-29``, which carries only ``deviceNodes``)."""

    env: list[str] = field(default_factory=list)  # "KEY=value" strings
    device_nodes: list[DeviceNode] = field(default_factory=list)
    mounts: list[Mount] = field(default_factory=list)
    hooks: list[Hook] = field(default_factory=list)

    def add_env(self, key: str, value: str) -> "ContainerEdits":
        self.env.append(f"{key}={value}")
        return self

    def merge(self, other: "ContainerEdits") -> "ContainerEdits":
        self.env.extend(other.env)
        self.device_nodes.extend(other.device_nodes)
        self.mounts.extend(other.mounts)
        self.hooks.extend(other.hooks)
        return self

    def is_empty(self) -> bool:
        return not (self.env or self.device_nodes or self.mounts or self.hooks)

    def to_dict(self) -> dict[str, Any]:
        return _prune(
            {
                "env": list(self.env),
                "deviceNodes": [n.to_dict() for n in self.device_nodes],
                "mounts": [m.to_dict() for m in self.mounts],
                "hooks": [h.to_dict() for h in self.hooks],
            }
        )


@dataclass
class Device:
    """A named CDI device (ref ``cdi/spec.go:21-24``).

    ``name`` is the device id part of the qualified name; for TPUs this is the
    stable chip index within the host (``0``..``chips_per_host-1``), not the
    fragile global bus-walk counter the reference uses (ref quirk 5,
    device_plugin.go:175).
    """

    name: str
    container_edits: ContainerEdits = field(default_factory=ContainerEdits)
    annotations: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not _NAME_RE.match(self.name):
            raise ValueError(f"invalid CDI device name: {self.name!r}")

    def to_dict(self) -> dict[str, Any]:
        return _prune(
            {
                "name": self.name,
                "annotations": dict(self.annotations),
                "containerEdits": self.container_edits.to_dict(),
            }
        )


@dataclass
class Spec:
    """A CDI spec file: one kind, many devices (ref ``cdi/spec.go:17-20``)."""

    kind: str
    cdi_version: str = "0.6.0"
    devices: list[Device] = field(default_factory=list)
    annotations: dict[str, str] = field(default_factory=dict)
    container_edits: ContainerEdits = field(default_factory=ContainerEdits)

    def __post_init__(self) -> None:
        parse_kind(self.kind)  # validates

    @property
    def vendor(self) -> str:
        return parse_kind(self.kind)[0]

    @property
    def cls(self) -> str:
        return parse_kind(self.kind)[1]

    def add_device(self, device: Device) -> "Spec":
        if any(d.name == device.name for d in self.devices):
            raise ValueError(f"duplicate CDI device name: {device.name!r}")
        self.devices.append(device)
        return self

    def device_names(self) -> list[str]:
        return [d.name for d in self.devices]

    def to_dict(self) -> dict[str, Any]:
        return _prune(
            {
                "cdiVersion": self.cdi_version,
                "kind": self.kind,
                "annotations": dict(self.annotations),
                "devices": [d.to_dict() for d in self.devices],
                "containerEdits": self.container_edits.to_dict(),
            }
        )

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Spec":
        """Inverse of :meth:`to_dict`; used by tests and the `status` command."""
        spec = cls(
            kind=data["kind"],
            cdi_version=data.get("cdiVersion", "0.6.0"),
            annotations=dict(data.get("annotations", {})),
            container_edits=_edits_from_dict(data.get("containerEdits", {})),
        )
        for d in data.get("devices", []):
            spec.add_device(
                Device(
                    name=d["name"],
                    annotations=dict(d.get("annotations", {})),
                    container_edits=_edits_from_dict(d.get("containerEdits", {})),
                )
            )
        return spec


def _edits_from_dict(data: dict[str, Any]) -> ContainerEdits:
    return ContainerEdits(
        env=list(data.get("env", [])),
        device_nodes=[
            DeviceNode(
                path=n["path"],
                host_path=n.get("hostPath"),
                type=n.get("type"),
                major=n.get("major"),
                minor=n.get("minor"),
                permissions=n.get("permissions"),
                uid=n.get("uid"),
                gid=n.get("gid"),
            )
            for n in data.get("deviceNodes", [])
        ],
        mounts=[
            Mount(
                host_path=m["hostPath"],
                container_path=m["containerPath"],
                options=list(m.get("options", [])),
                type=m.get("type"),
            )
            for m in data.get("mounts", [])
        ],
        hooks=[
            Hook(
                hook_name=h["hookName"],
                path=h["path"],
                args=list(h.get("args", [])),
                env=list(h.get("env", [])),
                timeout=h.get("timeout"),
            )
            for h in data.get("hooks", [])
        ],
    )


def parse_kind(kind: str) -> tuple[str, str]:
    """Split and validate a CDI kind ``vendor/class`` (e.g. ``google.com/tpu``)."""
    vendor, sep, cls = kind.partition("/")
    if not sep or not _VENDOR_RE.match(vendor) or not _CLASS_RE.match(cls):
        raise ValueError(f"invalid CDI kind: {kind!r} (want vendor/class)")
    return vendor, cls
