"""CDI spec model + writer (counterpart of the reference's ``cdi/`` package)."""
from . import constants
from .model import ContainerEdits, Device, DeviceNode, Hook, Mount, Spec, parse_kind
from .names import is_qualified_name, parse_qualified_name, qualified_name
from .writer import FORMAT_JSON, FORMAT_YAML, load, remove, render, save, spec_filename, spec_path

__all__ = [
    "constants",
    "ContainerEdits",
    "Device",
    "DeviceNode",
    "Hook",
    "Mount",
    "Spec",
    "parse_kind",
    "qualified_name",
    "parse_qualified_name",
    "is_qualified_name",
    "FORMAT_JSON",
    "FORMAT_YAML",
    "render",
    "save",
    "load",
    "remove",
    "spec_filename",
    "spec_path",
]
