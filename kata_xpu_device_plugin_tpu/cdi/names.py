"""CDI qualified device names: ``vendor/class=device``.

Counterpart of the reference's thin wrapper over the upstream CDI parser
(ref ``cdi/cdi-utils.go:9-11``) — implemented natively here since the qualified
name is the load-bearing contract between the Allocate response and the spec
file on disk (SURVEY §3.3: "the CDI device name matches the Allocate-returned
qualified name is the load-bearing invariant").
"""
from __future__ import annotations

from .model import _NAME_RE, parse_kind


def qualified_name(vendor: str, cls: str, device: str) -> str:
    """Build ``vendor/class=device`` (ref generic_device_plugin.go:277)."""
    kind = f"{vendor}/{cls}"
    parse_kind(kind)
    if not _NAME_RE.match(device):
        raise ValueError(f"invalid CDI device id: {device!r}")
    return f"{kind}={device}"


def parse_qualified_name(name: str) -> tuple[str, str, str]:
    """Split ``vendor/class=device`` into its three parts, validating each."""
    kind, sep, device = name.partition("=")
    if not sep or not device:
        raise ValueError(f"invalid CDI qualified name: {name!r}")
    vendor, cls = parse_kind(kind)
    if not _NAME_RE.match(device):
        raise ValueError(f"invalid CDI device id in {name!r}")
    return vendor, cls, device


def is_qualified_name(name: str) -> bool:
    try:
        parse_qualified_name(name)
        return True
    except ValueError:
        return False
