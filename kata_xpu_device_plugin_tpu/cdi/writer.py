"""Atomic CDI spec file writer/loader.

Fixes two reference defects (SURVEY §Quirks 7, and the non-atomic writes of
``cdi/spec.go:85-127``):

- per-kind spec filenames (``<vendor>-<class>.yaml``) instead of one hardcoded
  ``cdi-vfio-xxxx`` for everything, so multiple vendors/classes coexist;
- atomic write (tempfile in the same directory + ``os.replace``) so containerd
  never reads a half-written spec.
"""
from __future__ import annotations

import json
import os
import tempfile

import yaml

from .model import Spec

FORMAT_YAML = "yaml"
FORMAT_JSON = "json"


def spec_filename(kind: str, fmt: str = FORMAT_YAML) -> str:
    """``google.com/tpu`` -> ``google.com-tpu.yaml`` (upstream CDI convention)."""
    vendor, _, cls = kind.partition("/")
    ext = "json" if fmt == FORMAT_JSON else "yaml"
    return f"{vendor}-{cls}.{ext}"


def spec_path(spec_dir: str, kind: str, fmt: str = FORMAT_YAML) -> str:
    return os.path.join(spec_dir, spec_filename(kind, fmt))


def render(spec: Spec, fmt: str = FORMAT_YAML) -> str:
    data = spec.to_dict()
    if fmt == FORMAT_JSON:
        return json.dumps(data, indent=2, sort_keys=False) + "\n"
    if fmt == FORMAT_YAML:
        return yaml.safe_dump(data, sort_keys=False, default_flow_style=False)
    raise ValueError(f"unknown CDI spec format: {fmt!r}")


def save(spec: Spec, spec_dir: str, fmt: str = FORMAT_YAML) -> str:
    """Write the spec atomically under ``spec_dir``; returns the final path.

    (Ref ``cdi/spec.go:85-127`` writes non-atomically with a hardcoded name and
    swallows errors with ``fmt.Println``; here failures raise.)
    """
    os.makedirs(spec_dir, mode=0o755, exist_ok=True)
    path = spec_path(spec_dir, spec.kind, fmt)
    content = render(spec, fmt)
    fd, tmp = tempfile.mkstemp(dir=spec_dir, prefix=".cdi-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, 0o644)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load(path: str) -> Spec:
    """Read a spec file back (yaml or json); used by tests and ``status``."""
    with open(path) as f:
        text = f.read()
    data = json.loads(text) if path.endswith(".json") else yaml.safe_load(text)
    return Spec.from_dict(data)


def remove(spec_dir: str, kind: str) -> None:
    """Best-effort removal of both formats of a kind's spec (shutdown path)."""
    for fmt in (FORMAT_YAML, FORMAT_JSON):
        try:
            os.unlink(spec_path(spec_dir, kind, fmt))
        except FileNotFoundError:
            pass
