"""CDI constants for the TPU device plugin.

Counterpart of the reference's ``cdi/spec.go:12-14`` and ``cdi/constant.go:8-12``
(CDI version, kind, annotation prefix, device-list strategy names) — but with the
kind/vendor flipped to Google TPUs, and everything here overridable through
:mod:`kata_xpu_device_plugin_tpu.config` rather than hardcoded (the reference
hardcodes all of these; SURVEY §5 "Config / flag system: none").
"""

# CDI spec schema version this writer emits. 0.6.0 is what containerd 1.7+/CRI-O
# 1.28+ accept and what the reference pins (ref cdi/spec.go:12).
CDI_VERSION = "0.6.0"

# Resource/CDI identity for Cloud TPUs. The reference uses "nvidia.com/gpu"
# (ref cdi/spec.go:13); GKE's convention for TPUs is "google.com/tpu".
DEFAULT_VENDOR = "google.com"
DEFAULT_CLASS = "tpu"
DEFAULT_KIND = f"{DEFAULT_VENDOR}/{DEFAULT_CLASS}"

# Kind used for the generalized whole-VM PCI passthrough path (VFIO-bound TPUs
# or any other vendor's accelerator), mirroring the reference's only mode.
VFIO_CLASS = "vfio"

# Annotation key prefix consumed by container runtimes with CDI support
# (ref cdi/spec.go:14).
CDI_K8S_PREFIX = "cdi.k8s.io/"

# Kata-specific CDI device annotations. The reference emits these on every CDI
# device so the Kata runtime hot-plugs the PCI function into the guest VM
# (ref pkg/device_plugin/device_plugin.go:62-68).
ANNOTATION_ATTACH_PCI = "attach-pci"
ANNOTATION_BDF = "bdf"

# Device-list strategies: how allocated devices are communicated to the runtime
# (ref cdi/constant.go:8-12 and generic_device_plugin.go:52-71). The reference
# hardcodes cdi-cri on / cdi-annotations off; here both are real config.
STRATEGY_CDI_CRI = "cdi-cri"
STRATEGY_CDI_ANNOTATIONS = "cdi-annotations"
STRATEGY_ENVVAR = "envvar"
ALL_STRATEGIES = (STRATEGY_CDI_CRI, STRATEGY_CDI_ANNOTATIONS, STRATEGY_ENVVAR)

# Env var surfaced to the container naming the CDI vendor/class it was granted
# (ref generic_device_plugin.go:348-350 emits KUBERNETES_CDI_VENDOR_CLASS).
ENV_CDI_VENDOR_CLASS = "KUBERNETES_CDI_VENDOR_CLASS"

# TPU runtime environment injected into the guest so libtpu/JAX initialize the
# ICI mesh correctly (the TPU-native analogue of "the device node is enough" on
# the NVIDIA/VFIO path; SURVEY §2 equivalence table).
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_CHIPS_PER_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
ENV_TPU_HOST_BOUNDS = "TPU_HOST_BOUNDS"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_TPU_SKIP_MDS_QUERY = "TPU_SKIP_MDS_QUERY"

# Persistent XLA compilation cache directory handed to the guest (ISSUE 3):
# compat.jaxapi.enable_compilation_cache reads this env in-guest, so the
# daemon's --compile-cache-dir knob reaches every allocated workload.
ENV_COMPILE_CACHE_DIR = "KATA_TPU_COMPILE_CACHE_DIR"

# Default shared-prefix KV cache capacity handed to the guest (ISSUE 5):
# guest.serving.GenerationServer reads this env when the caller passes no
# prefix_cache_tokens, so the daemon's --prefix-cache-tokens knob sizes
# the in-guest prefix KV store per node.
ENV_PREFIX_CACHE_TOKENS = "KATA_TPU_PREFIX_CACHE_TOKENS"

# Default paged KV pool capacity handed to the guest (ISSUE 6):
# guest.serving.GenerationServer reads this env when the caller passes no
# kv_pool_tokens, switching admission to token-budget continuous batching
# over one shared block pool (guest/kv_arena.py) sized per node.
ENV_KV_POOL_TOKENS = "KATA_TPU_KV_POOL_TOKENS"

# KV-cache quantization default handed to the guest (ISSUE 12):
# guest.serving.GenerationServer defaults to the int8 KV arena (the
# measured-1.7×-faster path, quality-gated by tools/eval_quality.py);
# the daemon's --kv-quant knob injects "bf16" to opt a node out (or
# "int8" to pin the default explicitly). Malformed values degrade
# in-guest with a kv_quant_invalid event; an explicit kv_quant= server
# argument always wins.
ENV_KV_QUANT = "KATA_TPU_KV_QUANT"

# Paged-pool placement layout handed to the guest (ISSUE 14):
# guest.serving.GenerationServer reads this when the caller passes no
# explicit kv_layout — "blocks" shards the paged pool by physical BLOCKS
# across the serving mesh (per-chip pool bytes ~logical/tp for every
# model, GQA included; no kv_replicated cliff), "heads" pins the legacy
# divide-or-replicate head-axis sharding. Malformed values degrade
# in-guest with a kv_layout_invalid event; a slotted server degrades the
# injected default with kv_layout_disabled.
ENV_KV_LAYOUT = "KATA_TPU_KV_LAYOUT"

# Host-RAM KV offload tier capacity handed to the guest (ISSUE 14):
# when > 0, in-guest paged servers park cold KV — unpinned prefix
# segments under pool pressure, preempted idle sessions — in host RAM
# (LRU demotion BEFORE youngest-first preemption) and prefetch it back
# asynchronously on prefix hit / session resume. Malformed values
# degrade in-guest with a kv_host_invalid event.
ENV_KV_HOST_TOKENS = "KATA_TPU_KV_HOST_TOKENS"

# Recovery-checkpoint cadence handed to the guest (ISSUE 7):
# guest.serving.GenerationServer snapshots live-lane KV to host every N
# rounds when the caller passes no checkpoint_rounds, so the daemon's
# --checkpoint-rounds knob arms crash-tolerant serving node-wide.
ENV_CHECKPOINT_ROUNDS = "KATA_TPU_CHECKPOINT_ROUNDS"

# Fault-injection schedule handed to the guest (ISSUE 7): the daemon's
# --faults chaos knob rides the same path, so a whole node's serving
# workloads replay one deterministic fault schedule
# (guest/resilience.py FaultInjector.from_env; malformed entries degrade).
ENV_FAULT_SCHEDULE = "KATA_TPU_FAULTS"

# Tensor-parallel serving degree handed to the guest (ISSUE 9):
# guest.serving.GenerationServer reads this when the caller passes no
# explicit tp — the daemon's --serving-tp knob overrides the topology-
# derived default (TPU_VISIBLE_CHIPS / TPU_ACCELERATOR_TYPE chip count)
# so a node can pin single-chip serving (1) or a sub-slice degree.
# Malformed or infeasible values degrade in-guest with a tp_disabled
# event (guest/tp_serving.py).
ENV_SERVING_TP = "KATA_TPU_TP"

# Floor of the degraded-mode mesh-shrink ladder handed to the guest
# (ISSUE 10): after a permanent chip fault the in-guest server halves
# its tensor-parallel degree over the surviving chips but never below
# this (guest/tp_serving.py shrink_ladder; docs/resilience.md "Degraded
# mode"). Malformed values degrade in-guest with a tp_min_invalid event.
# The guest-side kill switch KATA_TPU_DEGRADED=0 is env-only.
ENV_SERVING_TP_MIN = "KATA_TPU_TP_MIN"

# Per-allocation trace context handed to the guest (ISSUE 11): the
# daemon's Allocate handler stamps the trace id of its own
# ``plugin.Allocate`` span into this env, so in-guest
# GenerationServers adopt it as their trace id — guest spans and
# lifecycle events (``request_trace``, ``recovery``, ``tp_degraded``,
# flight-recorder dumps) then join the daemon's allocation trace end
# to end (docs/architecture.md "Daemon → guest trace context").
# --no-trace-context disables the stamp; guests then mint their own.
ENV_TRACE_CTX = "KATA_TPU_TRACE_CTX"

# Multi-step decode multiplier handed to the guest (ISSUE 13):
# guest.serving.GenerationServer runs chunk × K decode steps per host
# dispatch (on-device EOS/budget masking freezes finished lanes inside
# the jitted scan) when the caller passes no explicit decode_steps, so
# the daemon's --decode-steps knob amortizes host scheduling/fence/obs
# overhead node-wide. Malformed values degrade in-guest with a
# decode_steps_invalid event. The fused-dispatch kill switch
# KATA_TPU_FUSED=0 is env-only (guest-side), like KATA_TPU_DEGRADED.
ENV_DECODE_STEPS = "KATA_TPU_DECODE_STEPS"

# SLO-aware admission scheduling handed to the guest (ISSUE 8):
# guest.serving.GenerationServer reads these when the caller passes no
# explicit scheduler args — policy ("fifo_batch" | "slo_chunked"; unknown
# values degrade in-guest with a sched_disabled event), the chunked-
# prefill slice size in tokens, and the inter-token-latency SLO in ms the
# slo_chunked policy defers admissions against (guest/scheduler.py).
ENV_SCHED_POLICY = "KATA_TPU_SCHED_POLICY"
ENV_PREFILL_CHUNK = "KATA_TPU_PREFILL_CHUNK"
ENV_ITL_SLO_MS = "KATA_TPU_ITL_SLO_MS"

# Guest telemetry uplink (ISSUE 15): with --guest-events-dir set, every
# TPU Allocate switches the guest's JSONL event stream ON and points it
# at a per-allocation file under that (shared, e.g. hostPath-mounted)
# directory — the daemon's heartbeat aggregator (plugin/manager.py)
# tails those files and re-exports per-allocation serving gauges on the
# existing utils.metrics endpoint: the upward twin of the ISSUE 11
# daemon→guest trace handoff. ENV_HEARTBEAT_ROUNDS sets the in-guest
# heartbeat cadence (guest/serving.py; malformed values degrade with a
# heartbeat_invalid event).
ENV_OBS = "KATATPU_OBS"
ENV_OBS_FILE = "KATATPU_OBS_FILE"
ENV_HEARTBEAT_ROUNDS = "KATA_TPU_HEARTBEAT_ROUNDS"

# Default location where containerd/CRI-O pick up CDI spec files
# (ref pkg/device_plugin/device_plugin.go:20).
DEFAULT_CDI_DIR = "/var/run/cdi"

# Canonical in-guest path for the injected libtpu (mounted read-only from the
# host TPU-VM image so XLA in the Kata guest drives the chips directly).
LIBTPU_CONTAINER_PATH = "/usr/lib/tpu/libtpu.so"
LIBTPU_ENV = "TPU_LIBRARY_PATH"
