"""Collective helpers over the device mesh.

XLA's collectives (psum/all_gather/reduce_scatter/ppermute) ARE the
distributed backend on TPU — they compile onto ICI/DCN links (SURVEY §5's
TPU-native equivalence for the reference's gRPC/NCCL-less world). These
wrappers exist for the guest smoke ladder (BASELINE configs[2]: "pmap
all-reduce smoke test") and for tests that assert collective correctness on
the virtual CPU mesh; model code relies on GSPMD-inserted collectives.
"""
from __future__ import annotations

from functools import partial

import jax
from jax import lax

from ..compat.jaxapi import Mesh, P, shard_map


def pmap_all_reduce(x_per_device: jax.Array) -> jax.Array:
    """BASELINE configs[2] smoke: psum over all local devices via pmap.
    Input leading axis = device count."""
    return jax.pmap(lambda x: lax.psum(x, "i"), axis_name="i")(x_per_device)


def mesh_all_reduce(mesh: Mesh, x: jax.Array, axis: str) -> jax.Array:
    @partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P()
    )
    def _psum(x_shard):
        return lax.psum(x_shard, axis)

    return _psum(x)


def ring_all_reduce(mesh: Mesh, x: jax.Array, axis: str) -> jax.Array:
    """Explicit ring all-reduce via ppermute — demonstrates (and tests) the
    neighbor-hop pattern ring attention relies on. XLA's native psum is what
    production code should use."""
    n = mesh.shape[axis]

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
    )
    def _ring(x_shard):
        perm = [(j, (j + 1) % n) for j in range(n)]

        def step(_t, carry):
            acc, blk = carry
            blk = lax.ppermute(blk, axis, perm)
            return acc + blk, blk

        total, _ = lax.fori_loop(0, n - 1, step, (x_shard, x_shard))
        return total

    return _ring(x)


def all_gather(mesh: Mesh, x: jax.Array, axis: str) -> jax.Array:
    @partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
    )
    def _ag(x_shard):
        return lax.all_gather(x_shard, axis, tiled=True)

    return _ag(x)


def reduce_scatter(mesh: Mesh, x: jax.Array, axis: str) -> jax.Array:
    @partial(
        shard_map, mesh=mesh, in_specs=P(None), out_specs=P(axis), check_vma=False
    )
    def _rs(x_full):
        return lax.psum_scatter(x_full, axis, scatter_dimension=0, tiled=True)

    return _rs(x)
