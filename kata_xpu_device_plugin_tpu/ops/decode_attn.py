"""Pallas TPU fused decode attention (single-token GQA attention into a KV
cache).

The decode hot loop is bandwidth-bound, but XLA lowers one decode-attention
step to ~8 small ops (dot, scale, mask, max, exp, sum, div, dot) per layer —
at B=8 each op touches a few hundred KB, so the step pays ~8 op-dispatch
latencies per layer for ~0.07 ms of actual HBM traffic (measured on v5e:
0.57 ms/step of attention against a 0.02 ms roofline; see BASELINE.md). This
kernel fuses the whole thing into ONE pallas program per layer and, because
the causal frontier is the scalar-prefetched ``pos``, it skips cache blocks
past the valid prefix entirely — XLA's version must always read the padded
``max_len`` cache, this one reads only ``pos+1`` entries.

Numerics: logits/softmax/accumulator in fp32 (the dots take bf16 inputs with
``preferred_element_type=fp32`` — MXU-native), identical structure to
:mod:`.flash`'s online softmax so the two kernels stay oracle-compatible
with :func:`.attention.reference_attention`.

Measured verdict (v5e, Gemma-2B, B=8, 128-step decode scan): the kernel
LOSES to the XLA path end-to-end — 1068 vs 1281 tok/s — because the scan
launches it once per layer per step (2304 launches) and per-launch overhead
outweighs the fused-op and cache-tail savings at these shapes. It therefore
ships OFF by default (``KATA_TPU_DECODE_KERNEL=1`` opts in, see
:func:`.attention.decode_eligible`) and stays numerics-verified in tests;
the win it was built for (dispatch overhead) is real but XLA's scan-internal
fusion already prices it lower.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# Pallas has no stable import home yet; these two stay experimental on
# every supported JAX line (see docs/compat_and_lint.md).
from jax.experimental import pallas as pl  # lint: allow(JX002) pallas-only API
from jax.experimental.pallas import tpu as pltpu  # lint: allow(JX002) pallas-only API

from ..compat.jaxapi import pallas_tpu_compiler_params

NEG_INF = -1e30


def supports_decode(sq: int, sk: int, d: int) -> bool:
    """Kernel constraints: single query token, lane-aligned head_dim, cache
    length a multiple of the 128-entry block."""
    return sq == 1 and (d % 128 == 0 or d == 64) and sk % 128 == 0 and sk >= 128


def _decode_kernel(
    pos_ref,  # scalar prefetch: [1] int32 — shared absolute position
    q_ref,  # [1, 1, G, D] block of native [B, 1, H, D]
    k_ref,  # [1, BK, 1, D] block of native [B, S, KV, D]
    v_ref,  # [1, BK, 1, D]
    o_ref,  # [1, 1, G, D]
    m_scr,  # [G, 128] fp32 running max (col 0)
    l_scr,  # [G, 128] fp32 running denom
    acc_scr,  # [G, D] fp32
    *,
    scale: float,
    block_k: int,
):
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)
    pos = pos_ref[0]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Whole-block skip above the causal frontier (the index maps clamp the
    # k/v block index at the frontier too, so the skipped blocks are never
    # even DMA'd — decode traffic scales with pos, not max_len).
    @pl.when(ki * block_k <= pos)
    def _compute():
        q = q_ref[0, 0]  # [G, D] native dtype
        k = k_ref[0, :, 0, :]  # [BK, D]
        v = v_ref[0, :, 0, :]
        logits = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, BK] fp32
        k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(k_pos <= pos, logits, NEG_INF)

        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finalize():
        denom = l_scr[:, 0:1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0, 0] = (acc_scr[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def pallas_decode_attention(
    q: jax.Array,  # [B, 1, H, D]
    k: jax.Array,  # [B, S, KV, D] — KV cache (padded past ``pos``)
    v: jax.Array,
    pos: jax.Array,  # scalar int32: last valid cache index (absolute position)
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused single-token GQA attention into a cache. ``pos`` is shared by
    the whole batch (the decode scan advances all rows in lockstep)."""
    B, Sq, H, D = q.shape
    _, S, KV, _ = k.shape
    assert Sq == 1, "decode kernel is single-token"
    assert H % KV == 0, (H, KV)
    G = H // KV
    assert S % block_k == 0, (S, block_k)

    # Native layouts throughout — no transposed cache copies (for KV>1 a
    # transpose would re-materialize the whole cache every step). q blocks
    # take the G heads of one KV group (heads kv*G..kv*G+G-1 are contiguous
    # in H); k/v blocks stride the KV axis in place.
    grid = (B, KV, S // block_k)
    kernel = functools.partial(
        _decode_kernel, scale=float(1.0 / (D**0.5)), block_k=block_k
    )

    def q_index(b, h, ki, pos_ref):
        del ki, pos_ref
        return (b, 0, h, 0)

    def kv_index(b, h, ki, pos_ref):
        # Clamp at the causal frontier: blocks past pos map to the frontier
        # block, whose copy pallas elides (same index as the previous grid
        # step) — the unwritten cache tail is never fetched from HBM.
        return (b, jnp.minimum(ki, pos_ref[0] // block_k), h, 0)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, G, D), q_index),
                pl.BlockSpec((1, block_k, 1, D), kv_index),
                pl.BlockSpec((1, block_k, 1, D), kv_index),
            ],
            out_specs=pl.BlockSpec((1, 1, G, D), q_index),
            scratch_shapes=[
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, 1, H, D), q.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, k, v)
    return out
