"""Pallas TPU fused decode attention (single-token GQA attention into a KV
cache) — the lockstep whole-cache kernel AND the paged-native split-K
kernel (ISSUE 12).

**Lockstep kernel** (:func:`pallas_decode_attention`): the decode hot loop
is bandwidth-bound, but XLA lowers one decode-attention step to ~8 small
ops (dot, scale, mask, max, exp, sum, div, dot) per layer — at B=8 each op
touches a few hundred KB, so the step pays ~8 op-dispatch latencies per
layer for ~0.07 ms of actual HBM traffic (measured on v5e: 0.57 ms/step of
attention against a 0.02 ms roofline; see BASELINE.md). This kernel fuses
the whole thing into ONE pallas program per layer and, because the causal
frontier is the scalar-prefetched ``pos``, it skips cache blocks past the
valid prefix entirely. Measured verdict (v5e, Gemma-2B, B=8, 128-step
scan): it LOSES to the XLA path end-to-end — 1068 vs 1281 tok/s — because
per-launch overhead outweighs the fused-op savings at these shapes; it
ships OFF by default (``KATA_TPU_DECODE_KERNEL=1`` opts in, see
:func:`.attention.decode_eligible`) and stays numerics-verified in tests.

**Paged-native split-K kernel** (:func:`pallas_paged_decode_attention`):
the serving decode path. Instead of the ``_paged_view`` gather that
rebuilds a dense ``[B, max_len]`` operand out of the block pool every
step (``models/transformer.py`` paged branch — a full copy of every live
lane's KV through HBM per layer per step), each program walks the lane's
**block table directly** via scalar prefetch: grid ``(batch lane, KV
head, KV-length split)``, where split ``ki`` DMAs physical pool block
``table[b, ki]`` in place and folds it into a flash-decode-style running
max/sum/accumulator carry (the split-K partial-softmax reduction — the
same online softmax as :mod:`.flash`, carried across splits in VMEM
scratch). Ragged per-lane positions ride the prefetched ``pos`` vector:
splits past a lane's causal frontier clamp their index map to the
frontier block, so the unwritten tail is never even DMA'd — per-lane
traffic scales with ``pos[b]``, not ``max_len``. int8 ``QTensor`` pools
dequantize IN KERNEL (payload+scale blocks ride together; the int8·scale
multiply runs in fp32 registers exactly like
:func:`..ops.quant.dequantize_kv`, value-identical), so the quantized
pool never materializes a bf16 copy in HBM — cache read traffic is the
int8 bytes plus scales. Tensor parallelism composes via ``shard_map`` +
the serving KV-head specs (:func:`..parallel.sharding.decode_attn_specs`
— a pallas call has no SPMD partitioning rule, so the wrapper is what
lets it partition instead of replicating); see
:func:`.attention.make_decode_attn_fn`.

Numerics: logits/softmax/accumulator in fp32 (the dots take bf16 inputs
with ``preferred_element_type=fp32`` — MXU-native), identical structure
to :mod:`.flash`'s online softmax so the kernels stay oracle-compatible
with :func:`.attention.reference_attention` (greedy tokens match the XLA
path across the serving matrix; tested in tests/test_decode_attn_paged.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# Pallas has no stable import home yet; these two stay experimental on
# every supported JAX line (see docs/compat_and_lint.md).
from jax.experimental import pallas as pl  # lint: allow(JX002) pallas-only API
from jax.experimental.pallas import tpu as pltpu  # lint: allow(JX002) pallas-only API

from ..compat.jaxapi import pallas_tpu_compiler_params
from .quant import QTensor

NEG_INF = -1e30


def supports_decode(sq: int, sk: int, d: int) -> bool:
    """Kernel constraints: single query token, lane-aligned head_dim, cache
    length a multiple of the 128-entry block."""
    return sq == 1 and (d % 128 == 0 or d == 64) and sk % 128 == 0 and sk >= 128


def _decode_kernel(
    pos_ref,  # scalar prefetch: [1] int32 — shared absolute position
    q_ref,  # [1, 1, G, D] block of native [B, 1, H, D]
    k_ref,  # [1, BK, 1, D] block of native [B, S, KV, D]
    v_ref,  # [1, BK, 1, D]
    o_ref,  # [1, 1, G, D]
    m_scr,  # [G, 128] fp32 running max (col 0)
    l_scr,  # [G, 128] fp32 running denom
    acc_scr,  # [G, D] fp32
    *,
    scale: float,
    block_k: int,
):
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)
    pos = pos_ref[0]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Whole-block skip above the causal frontier (the index maps clamp the
    # k/v block index at the frontier too, so the skipped blocks are never
    # even DMA'd — decode traffic scales with pos, not max_len).
    @pl.when(ki * block_k <= pos)
    def _compute():
        q = q_ref[0, 0]  # [G, D] native dtype
        k = k_ref[0, :, 0, :]  # [BK, D]
        v = v_ref[0, :, 0, :]
        logits = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, BK] fp32
        k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(k_pos <= pos, logits, NEG_INF)

        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finalize():
        denom = l_scr[:, 0:1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0, 0] = (acc_scr[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def pallas_decode_attention(
    q: jax.Array,  # [B, 1, H, D]
    k: jax.Array,  # [B, S, KV, D] — KV cache (padded past ``pos``)
    v: jax.Array,
    pos: jax.Array,  # scalar int32: last valid cache index (absolute position)
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused single-token GQA attention into a cache. ``pos`` is shared by
    the whole batch (the decode scan advances all rows in lockstep)."""
    B, Sq, H, D = q.shape
    _, S, KV, _ = k.shape
    assert Sq == 1, "decode kernel is single-token"
    assert H % KV == 0, (H, KV)
    G = H // KV
    assert S % block_k == 0, (S, block_k)

    # Native layouts throughout — no transposed cache copies (for KV>1 a
    # transpose would re-materialize the whole cache every step). q blocks
    # take the G heads of one KV group (heads kv*G..kv*G+G-1 are contiguous
    # in H); k/v blocks stride the KV axis in place.
    grid = (B, KV, S // block_k)
    kernel = functools.partial(
        _decode_kernel, scale=float(1.0 / (D**0.5)), block_k=block_k
    )

    def q_index(b, h, ki, pos_ref):
        del ki, pos_ref
        return (b, 0, h, 0)

    def kv_index(b, h, ki, pos_ref):
        # Clamp at the causal frontier: blocks past pos map to the frontier
        # block, whose copy pallas elides (same index as the previous grid
        # step) — the unwritten cache tail is never fetched from HBM.
        return (b, jnp.minimum(ki, pos_ref[0] // block_k), h, 0)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, G, D), q_index),
                pl.BlockSpec((1, block_k, 1, D), kv_index),
                pl.BlockSpec((1, block_k, 1, D), kv_index),
            ],
            out_specs=pl.BlockSpec((1, 1, G, D), q_index),
            scratch_shapes=[
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, 1, H, D), q.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, k, v)
    return out


# ----- paged-native split-K decode attention (ISSUE 12) ---------------------


def supports_paged_decode(d: int, block_size: int,
                          interpret: bool = False) -> bool:
    """Shape gate for the paged-native kernel. The KV tile IS one pool
    block (``guest.kv_arena.KVPool`` physical block ``t`` occupies pool
    rows ``t*bs .. (t+1)*bs`` — the layout contract the index map rides),
    so on hardware the block size must satisfy the TPU sublane quantum
    (8 rows; Mosaic sub-tiles bf16/int8 within it) and head_dim the lane
    width. Interpret mode (the CPU test/serving-matrix path) has no
    tiling constraints — any positive shape runs."""
    if interpret:
        return d >= 1 and block_size >= 1
    return (d % 128 == 0 or d == 64) and block_size >= 8 and block_size % 8 == 0


def _paged_decode_kernel(
    pos_ref,  # scalar prefetch: [B] int32 — per-lane LAST query position
    tbl_ref,  # scalar prefetch: [B, NB] int32 — physical block tables
    qlen_ref,  # scalar prefetch: [B] int32 — per-lane query lengths (≤ SQ)
    *refs,  # [lo,] q, k, v (each payload [, scale]) blocks, outs, scratches
    scale: float,
    block_k: int,
    grid_k: int,
    quantized: bool,
    sq: int,
    shard_blocks: int = 0,
    stats: bool = False,
):
    if shard_blocks:
        # Shard-local form (ISSUE 14, the blocks pool layout): this
        # program sees only its shard's [1, NT/tp, KV, D] pool slice;
        # ``lo_ref`` is the shard's first global block id and splits
        # whose table entry falls outside [lo, lo + shard_blocks) are
        # SKIPPED entirely (ownership mask — the owner shard computes
        # them; the merge in make_decode_attn_fn recombines).
        lo_ref, *refs = refs
    q_ref, *refs = refs  # [1, SQ, G, D] block of [B, SQ, H, D]
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, *refs = refs
    else:
        k_ref, v_ref, *refs = refs
    if stats:
        # Raw split-K partials instead of the normalized output: the
        # fp32 accumulator (pre-division) plus the running max and
        # denominator — what the cross-shard online-softmax merge
        # consumes (same quantities the VMEM scratch carries across
        # splits, surfaced per lane × KV head).
        o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    b = pl.program_id(0)
    ki = pl.program_id(2)
    pos = pos_ref[b]
    q_len = qlen_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Whole-split skip above the lane's causal frontier (the index maps
    # clamp the physical block at the frontier too, so skipped splits are
    # never DMA'd — per-lane decode traffic scales with pos[b], not the
    # table width). ``pos`` is the LAST query's position, so every
    # earlier query's frontier is inside the skip bound. Shard-local
    # programs additionally skip splits their shard does not own.
    run = ki * block_k <= pos
    if shard_blocks:
        t = tbl_ref[b, jnp.minimum(ki, tbl_ref.shape[1] - 1)]
        lo = lo_ref[0]
        run = run & (t >= lo) & (t < lo + shard_blocks)

    @pl.when(run)
    def _compute():
        G = q_ref.shape[2]
        q = q_ref[0].reshape(sq * G, q_ref.shape[3])  # [SQ·G, D] native
        if quantized:
            # Fused int8 dequant: value-identical to quant.dequantize_kv
            # (int8→fp32, ·fp32 scale, cast to the activation dtype) but
            # in registers — the bf16 pool copy never exists in HBM.
            k = (k_ref[0, :, 0, :].astype(jnp.float32)
                 * ks_ref[0, :, 0, :]).astype(q.dtype)  # [BK, D]
            v = (v_ref[0, :, 0, :].astype(jnp.float32)
                 * vs_ref[0, :, 0, :]).astype(q.dtype)
        else:
            k = k_ref[0, :, 0, :]  # [BK, D]
            v = v_ref[0, :, 0, :]
        logits = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [SQ·G, BK] fp32
        k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        # Per-lane query lengths (ISSUE 13): queries are RIGHT-ALIGNED —
        # row j (of SQ) sits at absolute position pos - (SQ-1) + j, and
        # rows j < SQ - q_len are padding: every logit masks to the
        # FINITE NEG_INF, so p = exp(0) = 1 across the row and finalize
        # emits a harmless mean of V — garbage the caller never reads
        # (bounded, no NaN/inf), NOT zeros. SQ == 1 with q_len == 1
        # reduces to the original single-token mask (k_pos <= pos)
        # bit-for-bit.
        j = lax.broadcasted_iota(jnp.int32, logits.shape, 0) // G
        q_pos = pos - (sq - 1) + j
        mask = (k_pos <= q_pos) & (j >= sq - q_len)
        logits = jnp.where(mask, logits, NEG_INF)

        # Split-K partial-softmax reduction: running max/denominator/
        # accumulator carried across splits in VMEM scratch (flash-decode
        # style; structurally identical to .flash's online softmax).
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == grid_k - 1)
    def _finalize():
        G = q_ref.shape[2]
        if stats:
            # Raw partials out: the merge divides AFTER recombining the
            # shards (dividing here would bake in a denominator the
            # other shards still add to).
            m_ref[0, 0] = m_scr[...]
            l_ref[0, 0] = l_scr[...]
            o_ref[0] = acc_scr[...].reshape(
                sq, G, acc_scr.shape[-1]
            ).astype(o_ref.dtype)
            return
        denom = l_scr[:, 0:1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0] = (acc_scr[...] / denom).reshape(
            sq, G, acc_scr.shape[-1]
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_size", "paged_len", "interpret",
                              "shard_blocks", "return_stats")
)
def pallas_paged_decode_attention(
    q: jax.Array,  # [B, SQ, H, D] (SQ == 1: the decode-scan step)
    k,  # [1, NT, KV, D] pool slice — jax.Array or int8 QTensor
    v,
    tables: jax.Array,  # [B, NB] int32 physical block ids (SCRATCH→ZERO'd)
    pos: jax.Array,  # [B] int32: per-lane LAST query position (ragged)
    q_lens: "jax.Array | None" = None,  # [B] int32 per-lane query lengths
    *,
    block_size: int,
    paged_len: int,
    interpret: bool = False,
    shard_lo: "jax.Array | None" = None,  # [1] int32: shard's 1st block id
    shard_blocks: int = 0,  # blocks this shard holds (0 = unsharded)
    return_stats: bool = False,
):
    """Paged-native ragged decode attention: each lane attends its block-
    table view of the shared pool IN PLACE — no ``_paged_view`` gather
    back to a dense ``[B, paged_len]`` operand. ``tables`` must already
    have SCRATCH entries remapped to the ZERO block (the transformer's
    ``view_tables``), so unmapped splits read the zeros the dense path
    would read; every position ``> pos[b]`` is masked before softmax
    regardless, which is the same bit-identity argument the gather path
    makes. Dead lanes (stale ``pos``) clamp their index maps into the
    table and produce garbage no caller reads — exactly the dense
    contract.

    PER-LANE QUERY LENGTHS (ISSUE 13, the mixed-batch form): ``SQ > 1``
    carries a multi-token span per lane — query row ``j`` sits at
    absolute position ``pos[b] - (SQ-1) + j`` (right-aligned), and
    ``q_lens[b] <= SQ`` marks how many trailing rows are real; the
    leading pad rows are fully masked and emit bounded garbage (a mean
    of V — finite, never NaN) that nothing reads. One
    dispatch can therefore carry N decode lanes at ``q_len = 1``
    alongside an admission lane running a chunk-wide slice. ``q_lens``
    defaults to all-``SQ`` (every row real — the uniform span the
    transformer's paged S > 1 branch passes); ``SQ == 1`` reduces
    bit-for-bit to the single-token kernel.

    SHARD-LOCAL FORM (ISSUE 14, the blocks pool layout): when
    ``shard_blocks > 0``, ``k``/``v`` are ONE shard's ``[1, NT/tp, KV,
    D]`` slice of a token-axis-sharded pool and ``shard_lo`` its first
    global block id; splits whose table entry this shard does not own
    are skipped (never DMA'd — each shard reads only its local blocks)
    and DMA indices localize as ``table[b, ki] - lo``. Pair it with
    ``return_stats=True``: the call then returns ``(acc, m, l)`` — the
    fp32 pre-division accumulator plus the running max / denominator
    per ``[B, KV, SQ·G]`` row (trailing 128 lane broadcast, col 0 is
    the value) — and the caller recombines shards with the standard
    online-softmax merge before dividing
    (``ops.attention.make_decode_attn_fn``)."""
    quantized = isinstance(k, QTensor)
    B, Sq, H, D = q.shape
    kq = k.q if quantized else k
    NT, KV = kq.shape[1], kq.shape[2]
    assert H % KV == 0, (H, KV)
    G = H // KV
    NB = tables.shape[1]
    bs = block_size
    assert NT % bs == 0, (NT, bs)
    if q_lens is None:
        q_lens = jnp.full((B,), Sq, jnp.int32)
    shard_local = shard_blocks > 0
    if shard_local:
        assert NT // bs == shard_blocks, (NT, bs, shard_blocks)
        assert shard_lo is not None, "shard-local form needs shard_lo"
    # Splits actually visible through the view (the gather path truncates
    # its view at paged_len; here the causal mask covers the tail of the
    # last partial block — see the bit-identity note above).
    grid_k = min(NB, -(-paged_len // bs))
    grid = (B, KV, grid_k)
    kernel = functools.partial(
        _paged_decode_kernel, scale=float(1.0 / (D**0.5)), block_k=bs,
        grid_k=grid_k, quantized=quantized, sq=Sq,
        shard_blocks=shard_blocks, stats=return_stats,
    )

    n_prefetch = 4 if shard_local else 3

    def q_index(b, h, ki, *prefetch):
        del ki, prefetch
        return (b, 0, h, 0)

    def stat_index(b, h, ki, *prefetch):
        del ki, prefetch
        return (b, h, 0, 0)

    def kv_index(b, h, ki, pos_ref, tbl_ref, qlen_ref, *rest):
        # Clamp at the lane's causal frontier: splits past pos[b] map to
        # the frontier block, whose copy pallas elides (same index as the
        # previous grid step) — the unwritten tail is never fetched. The
        # second clamp bounds a dead lane's stale pos inside the table.
        # Shard-local: localize the global block id; table entries the
        # shard does not own map to the CONSTANT local block 0 — their
        # splits are ownership-masked (the fetched block is never read),
        # and the constant index lets pallas elide consecutive non-owned
        # splits' copies exactly like the frontier clamp does, so each
        # shard's DMA traffic stays ~its own blocks, not the full table.
        del qlen_ref
        blk = jnp.minimum(jnp.minimum(ki, pos_ref[b] // bs), NB - 1)
        t = tbl_ref[b, blk]
        if shard_local:
            loc = t - rest[0][0]
            owned = (loc >= 0) & (loc < shard_blocks)
            t = jnp.where(owned, loc, 0)
        return (0, t, h, 0)

    in_specs = [pl.BlockSpec((1, Sq, G, D), q_index)]
    operands = [q]
    for c in (k, v):
        in_specs.append(pl.BlockSpec((1, bs, 1, D), kv_index))
        if quantized:
            operands.extend([c.q, c.scale])
            in_specs.append(pl.BlockSpec((1, bs, 1, 1), kv_index))
        else:
            operands.append(c)

    if return_stats:
        out_specs = (
            pl.BlockSpec((1, Sq, G, D), q_index),
            pl.BlockSpec((1, 1, Sq * G, 128), stat_index),
            pl.BlockSpec((1, 1, Sq * G, 128), stat_index),
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, Sq, H, D), jnp.float32),
            jax.ShapeDtypeStruct((B, KV, Sq * G, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, KV, Sq * G, 128), jnp.float32),
        )
    else:
        out_specs = pl.BlockSpec((1, Sq, G, D), q_index)
        out_shape = jax.ShapeDtypeStruct((B, Sq, H, D), q.dtype)

    prefetch = [
        jnp.asarray(pos, jnp.int32).reshape(B),
        jnp.asarray(tables, jnp.int32),
        jnp.asarray(q_lens, jnp.int32).reshape(B),
    ]
    if shard_local:
        prefetch.append(jnp.asarray(shard_lo, jnp.int32).reshape(1))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=n_prefetch,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((Sq * G, 128), jnp.float32),
                pltpu.VMEM((Sq * G, 128), jnp.float32),
                pltpu.VMEM((Sq * G, D), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*prefetch, *operands)
    return out
