"""Attention implementations.

Three interchangeable implementations behind one signature
``(q, k, v, causal=True, q_offset=None) -> out``:

- :func:`reference_attention` — plain XLA einsum path (always correct;
  XLA already fuses mask+softmax into the matmuls well on TPU);
- :func:`flash_attention` — pallas TPU kernel (:mod:`.flash`), blockwise
  online-softmax so the [S, S] score matrix never materializes in HBM;
- :func:`make_ring_attention` (:mod:`..parallel.ring`) — sequence-parallel
  ring attention over an ICI axis for long-context (SURVEY: long-context is
  first-class, not an afterthought).

Shapes: q [B, Sq, H, D]; k/v [B, Sk, KV, D] with H a multiple of KV (GQA:
Gemma-2B uses KV=1, Llama-3-8B KV=8). ``q_offset`` is the absolute position
of q's first token when attending into a longer KV prefix (decode).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _expand_kv(k: jax.Array, n_heads: int) -> jax.Array:
    """[B, S, KV, D] → [B, S, H, D] by repeating each KV head H/KV times.

    Only for code paths that genuinely need materialized heads; the attention
    implementations below are GQA-grouped and never call it — repeating KV
    multiplies HBM cache traffic by H/KV on the bandwidth-bound decode path.
    """
    kv = k.shape[2]
    if kv == n_heads:
        return k
    return jnp.repeat(k, n_heads // kv, axis=2)


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,
    window: int = 0,
    k_positions: Optional[jax.Array] = None,
    logits_softcap: float = 0.0,
) -> jax.Array:
    """XLA attention, GQA-grouped: q's H heads fold into [KV, H/KV] groups so
    K/V are read once per KV head — no ``jnp.repeat`` of the KV cache (on MQA
    decode that repeat would multiply cache traffic up to H×). Dots run in
    the inputs' native dtype (bf16 on TPU: the MXU does bf16×bf16→fp32 at 2×
    fp32 throughput) with fp32 accumulation via ``preferred_element_type``;
    softmax math stays fp32. Used on CPU, in tests, and as the numerics
    oracle for the pallas kernel.

    ``window > 0`` (requires ``causal``) restricts each query to the last
    ``window`` keys — sliding-window attention (Mistral-style; position
    ``p`` sees keys in ``(p - window, p]``).

    ``k_positions`` overrides the keys' implied positions (``arange(Sk)``)
    with explicit ABSOLUTE positions, shape [Sk] or [B, Sk] — the ring
    KV buffer stores its band out of order (slot = position % window) and
    negative entries mark unwritten slots (always masked).

    ``logits_softcap > 0`` (Gemma-2: 50.0) caps pre-mask attention logits
    to ``tanh(l / c) · c``."""
    B, Sq, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    assert H % KV == 0, (H, KV)
    assert window == 0 or causal, "sliding window implies causal"
    assert k_positions is None or causal, (
        "k_positions (ring-buffer slot positions) requires causal=True — "
        "the validity mask for unwritten slots lives in the causal branch"
    )
    G = H // KV
    qg = q.reshape(B, Sq, KV, G, D)
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    )
    logits = logits * (1.0 / float(D) ** 0.5)
    if logits_softcap:
        logits = jnp.tanh(logits / logits_softcap) * logits_softcap
    if causal:
        q_pos = jnp.arange(Sq)
        k_pos = jnp.arange(Sk) if k_positions is None else k_positions

        def band(qp, kp):  # causal upper bound + optional window lower bound
            m = kp <= qp
            if window > 0:
                m &= kp > qp - window
            if k_positions is not None:
                m &= kp >= 0  # unwritten ring slots carry negative positions
            return m

        per_row = (q_offset is not None and jnp.ndim(q_offset) == 1) or (
            k_positions is not None and k_positions.ndim == 2
        )
        if per_row:
            # Per-row offsets ([B]): ragged decode — each batch row sits at
            # its own position in its KV prefix (continuous batching).
            if q_offset is not None:
                qp = q_pos[None, :] + (
                    q_offset[:, None] if jnp.ndim(q_offset) == 1
                    else q_offset
                )
            else:
                qp = jnp.broadcast_to(q_pos[None, :], (B, Sq))
            kp = k_pos if k_pos.ndim == 2 else k_pos[None, :]
            mask = band(qp[:, :, None], kp[:, None, :])  # [B, Sq, Sk]
            logits = jnp.where(mask[:, None, None], logits, -1e30)
        else:
            if q_offset is not None:
                q_pos = q_pos + q_offset
            mask = band(q_pos[:, None], k_pos[None, :])  # [Sq, Sk]
            logits = jnp.where(mask[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, Sq, H, D).astype(q.dtype)


def on_tpu() -> bool:
    """True when the default backend executes on TPU hardware — directly
    (platform ``tpu``) or through a remote-TPU relay plugin whose platform
    name differs but whose device kind names a TPU generation."""
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    if dev.platform == "tpu":
        return True
    kind = str(getattr(dev, "device_kind", "")).lower()
    return "tpu" in kind or any(g in kind for g in ("v4", "v5e", "v5p", "v6e"))


def flash_eligible(sq: int, sk: int, d: int, q_offset=None) -> bool:
    """Trace-time dispatch decision shared by :func:`flash_attention` and the
    bench's path reporting: pallas flash runs for self-attention shapes on
    TPU where a kernel launch pays for itself."""
    from .flash import supports

    return (
        on_tpu()
        and q_offset is None  # decode-into-cache: tiny q, XLA path
        and sq >= 128
        and supports(sq, sk, d)
    )


def decode_eligible(sq: int, sk: int, d: int, causal: bool, q_offset) -> bool:
    """Trace-time gate for the fused decode kernel — the ONE place the
    dispatch condition lives (the bench's path label uses it too, so label
    and dispatch cannot drift).

    OFF by default: measured head-to-head on v5e (Gemma-2B, B=8, 128-step
    scan), the kernel decodes at 1068 tok/s vs 1281 tok/s for the XLA path —
    a decode step launches the kernel once per layer (18 × 128 = 2304
    launches per scan) and the per-launch overhead exceeds what fusing the
    ~8 small XLA ops saves at these shapes. ``KATA_TPU_DECODE_KERNEL=1``
    opts in (the kernel stays numerics-verified in tests); ``=0`` forces it
    off regardless — the bench supervisor's retry kill switch."""
    import os

    from .decode_attn import supports_decode

    if os.environ.get("KATA_TPU_DECODE_KERNEL", "") != "1":
        return False
    return (
        causal
        and q_offset is not None
        and jnp.ndim(q_offset) == 0  # kernel wants the lockstep scalar pos
        and on_tpu()
        and supports_decode(sq, sk, d)
    )


# ----- paged-native decode backend (ISSUE 12) -------------------------------
#
# The serving decode step's attention backend: the paged-native split-K
# pallas kernel (ops/decode_attn.pallas_paged_decode_attention) or the
# legacy gather-back-to-dense XLA path. Selection is resolved ONCE per
# GenerationServer (never per trace — a per-trace env read would let a
# toggled variable silently mix cached executables, the ops.quant._W8A8
# lesson) and threaded down as the static ``decode_kernel_fn`` argument of
# transformer.forward, so the executable cache key carries the decision.
DECODE_ATTN_ENV = "KATA_TPU_DECODE_ATTN"
BACKEND_PAGED = "pallas_paged"
BACKEND_REFERENCE = "xla_reference"
DECODE_ATTN_BACKENDS = (BACKEND_PAGED, BACKEND_REFERENCE)


def dense_decode_tile(arena_len: int) -> int:
    """KV tile for running the SLOTTED (dense ragged) arena through the
    paged-native kernel: the ``[B, S, KV, D]`` arena reshapes zero-copy to
    the pool layout ``[1, B·S, KV, D]`` (row ``b·S + s`` is exactly lane
    b's position s), with a synthetic block table ``table[b, j] = b·(S/t)
    + j`` — so one kernel serves both arena models. The tile must divide
    the arena length; 0 means no supported tile (the dispatch falls back
    to the XLA path)."""
    for t in (128, 64, 32, 16, 8):
        if arena_len % t == 0:
            return t
    return 0


def make_decode_attn_fn(
    cfg,
    *,
    paged: bool,
    block_size: int = 0,
    paged_len: int = 0,
    arena_len: int = 0,
    quantized: bool = False,
    mesh=None,
    tp: int = 1,
    interpret: bool = False,
    kv_layout: str = "heads",
):
    """Build the serving decode-attention kernel callable
    ``fn(q, ck, cv, tables, pos) -> [B, 1, H, D]`` the transformer's
    ragged decode branches dispatch through (static ``decode_kernel_fn``).

    ``paged=True``: ``ck``/``cv`` are the layer's ``[1, NT, KV, D]`` pool
    slice (bf16 or int8 QTensor) and ``tables`` the lanes' view tables;
    the kernel's KV tile is the pool's ``block_size`` (the alignment
    contract ``guest.kv_arena.KVPool`` documents). ``paged=False``: the
    slotted arena rides the SAME kernel through the zero-copy pool-layout
    reshape + synthetic tables of :func:`dense_decode_tile` (``tables``
    is ignored — pass None).

    ``mesh``/``tp``: tensor-parallel serving wraps the pallas call in
    ``shard_map`` with the serving KV-head specs
    (:func:`..parallel.sharding.decode_attn_specs`) — explicit specs are
    what let a custom call partition over the model axis instead of
    replicating; the kv-replicated layout (n_kv_heads % tp != 0) runs
    fully replicated inside the same wrapper.

    ``kv_layout="blocks"`` (ISSUE 14, paged × tp only): the pool shards
    its TOKEN axis, so each shard runs the kernel's SHARD-LOCAL form
    over only the physical blocks it owns (DMA stays on-shard;
    ownership-masked splits) with raw split-K partials out, and the
    wrapper recombines shards with the same online-softmax merge the
    kernel carries across splits — ``m = pmax``, the correction factor
    ``exp(m_s − m)`` rescales each shard's denominator and accumulator
    into one psum, division happens once after the merge. The merge is
    the associative recombination flash-decode is built on; a lane
    whose blocks all live on one shard reduces bit-for-bit to the
    unsharded kernel (the other shards contribute exact zeros).

    Raises on configs the kernel cannot model (sliding windows, the
    Gemma-2 attention-logit softcap, unsupported tiles) — eligibility
    lives with the caller (``GenerationServer._resolve_decode_attn``),
    this builder only refuses to build something silently wrong."""
    from .decode_attn import (
        pallas_paged_decode_attention,
        supports_paged_decode,
    )

    if any(w > 0 for w in cfg.window_cycle):
        raise ValueError(
            "the paged-native decode kernel has no sliding-window mask — "
            "windowed configs stay on the XLA path"
        )
    if cfg.attn_logits_softcap:
        raise ValueError(
            "the paged-native decode kernel does not model the attention-"
            "logit softcap — capped configs stay on the XLA path"
        )
    if paged:
        bs, plen = int(block_size), int(paged_len)
    else:
        bs, plen = dense_decode_tile(int(arena_len)), int(arena_len)
    if not supports_paged_decode(cfg.head_dim, bs, interpret=interpret):
        raise ValueError(
            f"paged decode kernel unsupported shape: head_dim="
            f"{cfg.head_dim}, kv_tile={bs} (interpret={interpret})"
        )

    def pool_form(q, ck, cv, tables, pos, q_lens=None):
        if not paged:
            # Zero-copy re-view of the slotted arena as a pool: row
            # b·S + s IS lane b's position s, tables are the identity
            # mapping over each lane's own rows.
            B, S = q.shape[0], plen
            nb_row = S // bs

            def reshape(a):
                return a.reshape((1, B * S) + a.shape[2:])

            tables = (
                jnp.arange(B, dtype=jnp.int32)[:, None] * nb_row
                + jnp.arange(nb_row, dtype=jnp.int32)[None, :]
            )
            ck = jax.tree.map(reshape, ck)
            cv = jax.tree.map(reshape, cv)
        return pallas_paged_decode_attention(
            q, ck, cv, tables, pos, q_lens, block_size=bs, paged_len=plen,
            interpret=interpret,
        )

    if mesh is None or tp <= 1:
        # Multi-token spans with per-lane query lengths (ISSUE 13) are
        # supported on the unsharded wrapper only — the transformer's
        # paged S > 1 branch checks this marker; the tp shard_map forms
        # below keep their single-token signature (sharded spans take
        # the gather path).
        pool_form.multi_query = True
        return pool_form

    from ..compat.jaxapi import P, shard_map
    from ..parallel.mesh import AXIS_MODEL
    from ..parallel.sharding import decode_attn_specs

    q_spec, kv_spec, out_spec = decode_attn_specs(
        cfg, tp, quantized, kv_layout=kv_layout
    )
    if paged and kv_layout == "blocks":
        from jax import lax

        from .decode_attn import pallas_paged_decode_attention as _paged
        from .quant import QTensor

        def blocks_form(q, ck, cv, tables, pos):
            # Shard-local kernel + online-softmax merge over the model
            # axis (see the docstring's layout note). Operand shapes in
            # here are LOCAL: the pool slice is [1, NT/tp, KV, D].
            kq = ck.q if isinstance(ck, QTensor) else ck
            nb_local = kq.shape[1] // bs
            lo = (lax.axis_index(AXIS_MODEL) * nb_local).astype(
                jnp.int32
            ).reshape(1)
            acc, m, l = _paged(
                q, ck, cv, tables, pos, None, block_size=bs,
                paged_len=plen, interpret=interpret,
                shard_lo=lo, shard_blocks=nb_local, return_stats=True,
            )
            B, Sq, H, D = q.shape
            KV = m.shape[1]
            G = H // KV
            m0, l0 = m[..., 0], l[..., 0]  # [B, KV, Sq·G]
            m_all = lax.pmax(m0, AXIS_MODEL)
            # exp(m_s − m) rescales each shard's partials onto the
            # global max; a shard with no owned splits for a lane sits
            # at m_s = NEG_INF → factor 0 → exact zero contribution.
            corr = jnp.exp(m0 - m_all)
            l_all = lax.psum(l0 * corr, AXIS_MODEL)

            def to_h(x):  # [B, KV, Sq·G] → [B, Sq, H] (H = KV·G order)
                return x.reshape(B, KV, Sq, G).transpose(
                    0, 2, 1, 3
                ).reshape(B, Sq, H)

            acc_all = lax.psum(acc * to_h(corr)[..., None], AXIS_MODEL)
            denom = to_h(jnp.where(l_all == 0.0, 1.0, l_all))
            return (acc_all / denom[..., None]).astype(q.dtype)

        return shard_map(
            blocks_form,
            mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec, P(None, None), P(None)),
            out_specs=out_spec,
            check_vma=False,
        )
    if paged:
        return shard_map(
            pool_form,
            mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec, P(None, None), P(None)),
            out_specs=out_spec,
            check_vma=False,  # no collectives: outputs are shard-local
        )

    # Slotted: the synthetic tables are built INSIDE the shard (they are
    # not an operand), so the wrapped signature drops them.
    sharded = shard_map(
        lambda q, ck, cv, pos: pool_form(q, ck, cv, None, pos),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P(None)),
        out_specs=out_spec,
        check_vma=False,
    )

    def slotted(q, ck, cv, tables, pos):
        del tables
        return sharded(q, ck, cv, pos)

    return slotted


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,
    window: int = 0,
    logits_softcap: float = 0.0,
) -> jax.Array:
    """Trace-time dispatch over the pallas kernels on TPU: the blockwise
    flash kernel for self-attention (prefill/training) and the fused
    single-token kernel for decode-into-cache; the XLA reference elsewhere
    (pallas interpret mode on CPU is far slower than XLA) and for shapes
    where a kernel launch can't pay for itself. ``window > 0`` (the
    sliding-window band) runs the flash kernel too on eligible
    self-attention shapes — it masks AND block-skips the band in forward
    and backward — and the reference elsewhere (the fused decode kernel
    has no lower mask bound, so windowed decode stays on the XLA path).
    ``logits_softcap > 0`` (Gemma-2) is modeled by the flash kernels in
    forward AND backward, so softcap configs keep the pallas prefill; the
    fused decode kernel does not model it, so softcap decode stays XLA."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if window > 0:
        if causal and flash_eligible(Sq, Sk, D, q_offset):
            from .flash import pallas_flash_attention

            return pallas_flash_attention(q, k, v, causal=True, window=window,
                                          softcap=logits_softcap)
        return reference_attention(q, k, v, causal=causal, q_offset=q_offset,
                                   window=window, logits_softcap=logits_softcap)
    if logits_softcap == 0.0 and decode_eligible(Sq, Sk, D, causal, q_offset):
        from .decode_attn import pallas_decode_attention

        return pallas_decode_attention(q, k, v, q_offset)
    if not flash_eligible(Sq, Sk, D, q_offset):
        return reference_attention(q, k, v, causal=causal, q_offset=q_offset,
                                   logits_softcap=logits_softcap)
    from .flash import pallas_flash_attention

    return pallas_flash_attention(q, k, v, causal=causal,
                                  softcap=logits_softcap)


def best_attention(*args, **kwargs):
    """Alias: the framework default (flash on TPU, reference elsewhere)."""
    return flash_attention(*args, **kwargs)
