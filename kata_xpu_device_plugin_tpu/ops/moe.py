"""Mixture-of-experts FFN with expert parallelism (Switch-style top-1
routing, dense dispatch/combine einsums).

The reference runs no model code (SURVEY §2 "parallelism strategies —
ABSENT"); this completes the guest-side parallelism stack (dp/fsdp/tp/sp +
pp + ep). TPU-first design: routing is expressed as dense one-hot
dispatch/combine tensors feeding batched einsums — static shapes, no
gather/scatter, everything tiles onto the MXU — and expert parallelism is
pure GSPMD: expert-major tensors carry a sharding constraint on the
``expert`` mesh axis, and XLA inserts the all-to-all that moves tokens to
their experts' devices over ICI. No hand-written collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

AXIS_EXPERT = "expert"

Params = dict


@dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    # Per-expert buffer = ceil(tokens/experts * factor); tokens routed past
    # it are dropped (their residual stream passes through unchanged).
    capacity_factor: float = 2.0


def expert_mesh(n_devices: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh sharding experts across devices."""
    from ..parallel.mesh import mesh_1d

    return mesh_1d(n_devices, AXIS_EXPERT, devices)


def init_moe_params(key: jax.Array, cfg: MoEConfig, dtype=jnp.float32) -> Params:
    kr, kg, ki, ko = jax.random.split(key, 4)
    d, f, e = cfg.d_model, cfg.d_ff, cfg.num_experts

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(kr, (d, e), d),
        "w_gate": dense(kg, (e, d, f), d),  # expert-major: shard dim 0 over ep
        "w_in": dense(ki, (e, d, f), d),
        "w_out": dense(ko, (e, f, d), f),
    }


def moe_param_specs() -> Params:
    """PartitionSpecs for the params: experts sharded, router replicated."""
    return {
        "router": P(),
        "w_gate": P(AXIS_EXPERT),
        "w_in": P(AXIS_EXPERT),
        "w_out": P(AXIS_EXPERT),
    }


def _constrain(x: jax.Array, mesh: Optional[Mesh], spec: P) -> jax.Array:
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def moe_ffn(
    params: Params,
    x: jax.Array,
    cfg: MoEConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jax.Array, jax.Array]:
    """Apply the MoE FFN to ``x`` of shape (..., d_model).

    Returns ``(y, aux_loss)`` where ``aux_loss`` is the Switch load-balancing
    term (num_experts * sum over experts of fraction-routed x mean-prob),
    minimized at uniform routing.
    """
    orig_shape = x.shape
    tokens = x.reshape(-1, cfg.d_model)
    n_tok, e = tokens.shape[0], cfg.num_experts
    capacity = max(1, math.ceil(n_tok / e * cfg.capacity_factor))

    logits = tokens @ params["router"].astype(tokens.dtype)  # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,) top-1
    gate = jnp.max(probs, axis=-1)  # (T,)

    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, E)
    # Position of each token within its expert's buffer (0-based), computed
    # with a cumsum — static shapes, no sort/scatter.
    pos = jnp.einsum("te,te->t", jnp.cumsum(onehot, axis=0) - 1.0, onehot)
    kept = pos < capacity
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)[:, None, :]
        * kept[:, None, None]
    )  # (T, E, C) 0/1
    combine = dispatch * gate[:, None, None]  # (T, E, C)

    # Token -> expert buffers. Sharding the E axis makes XLA all-to-all the
    # tokens onto the expert-parallel devices.
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(tokens.dtype), tokens)
    expert_in = _constrain(expert_in, mesh, P(AXIS_EXPERT, None, None))

    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_gate"])) * (
        jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"])
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    expert_out = _constrain(expert_out, mesh, P(AXIS_EXPERT, None, None))

    y = jnp.einsum("tec,ecd->td", combine.astype(tokens.dtype), expert_out)
    # Dropped tokens (over capacity) contribute zero — the caller's residual
    # connection carries them through, as in Switch Transformer.

    # Switch f_i is the PRE-drop routed fraction: clamping by `kept` would
    # cap an over-capacity expert's penalty at capacity/T — under-penalizing
    # exactly the collapsed-router state the loss exists to prevent.
    frac_routed = jnp.mean(onehot, axis=0)  # (E,)
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    aux_loss = e * jnp.sum(frac_routed * mean_prob)
    return y.reshape(orig_shape), aux_loss


def reference_moe(params: Params, x: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Per-token direct computation (no capacity, no dispatch tensors): what
    ``moe_ffn`` must match when capacity is ample."""
    tokens = x.reshape(-1, cfg.d_model)
    logits = tokens @ params["router"].astype(tokens.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1).astype(tokens.dtype)

    def per_token(tok, i, g):
        h = jax.nn.silu(tok @ params["w_gate"][i]) * (tok @ params["w_in"][i])
        return g * (h @ params["w_out"][i])

    out = jax.vmap(per_token)(tokens, idx, gate)
    return out.reshape(x.shape)
