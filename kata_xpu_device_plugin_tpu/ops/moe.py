"""Mixture-of-experts FFN with expert parallelism (top-k routing, capacity,
sort-based dispatch).

The reference runs no model code (SURVEY §2 "parallelism strategies —
ABSENT"); this completes the guest-side parallelism stack (dp/fsdp/tp/sp +
pp + ep). TPU-first design:

- routing is top-k (Switch semantics at k=1: the raw chosen probability is
  the gate; Mixtral semantics at k>1: gates renormalized over the chosen k);
- dispatch is a SORT: token-copies are ordered by expert id with XLA's sort
  (TPU-efficient, stable), positions within each expert's capacity buffer
  come from a cumsum of per-expert counts, and tokens move via scatter-add /
  gather on ``[E*capacity, d]`` buffers. Memory is O(T·K + E·C·d) — the
  dense ``[T, E, C]`` dispatch tensor of a one-hot einsum formulation never
  exists (VERDICT r1 item 6);
- expert parallelism is pure GSPMD: the expert-major buffers carry a
  sharding constraint on the ``expert`` mesh axis and XLA inserts the
  all-to-all that moves tokens to their experts' devices over ICI. No
  hand-written collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

AXIS_EXPERT = "expert"

Params = dict


@dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    # Per-expert buffer = ceil(T*top_k/experts * factor); token-copies routed
    # past it are dropped (their residual stream passes through unchanged).
    capacity_factor: float = 2.0
    top_k: int = 1

    def __post_init__(self):
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts={self.num_experts}]"
            )


def expert_mesh(n_devices: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh sharding experts across devices."""
    from ..parallel.mesh import mesh_1d

    return mesh_1d(n_devices, AXIS_EXPERT, devices)


def init_moe_params(key: jax.Array, cfg: MoEConfig, dtype=jnp.float32) -> Params:
    kr, kg, ki, ko = jax.random.split(key, 4)
    d, f, e = cfg.d_model, cfg.d_ff, cfg.num_experts

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(kr, (d, e), d),
        "w_gate": dense(kg, (e, d, f), d),  # expert-major: shard dim 0 over ep
        "w_in": dense(ki, (e, d, f), d),
        "w_out": dense(ko, (e, f, d), f),
    }


def moe_param_specs() -> Params:
    """PartitionSpecs for the params: experts sharded, router replicated."""
    return {
        "router": P(),
        "w_gate": P(AXIS_EXPERT),
        "w_in": P(AXIS_EXPERT),
        "w_out": P(AXIS_EXPERT),
    }


def _constrain(x: jax.Array, mesh: Optional[Mesh], spec: P) -> jax.Array:
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _route(params: Params, tokens: jax.Array, cfg: MoEConfig):
    """Shared router: (top-k gates [T,K] fp32, expert ids [T,K] int32,
    full softmax probs [T,E] fp32)."""
    logits = tokens @ params["router"].astype(tokens.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p, top_e = jax.lax.top_k(probs, cfg.top_k)  # (T, K)
    if cfg.top_k == 1:
        gates = top_p  # Switch: the raw chosen probability
    else:
        gates = top_p / jnp.sum(top_p, axis=-1, keepdims=True)  # Mixtral
    return gates, top_e.astype(jnp.int32), probs


def moe_ffn(
    params: Params,
    x: jax.Array,
    cfg: MoEConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jax.Array, jax.Array]:
    """Apply the MoE FFN to ``x`` of shape (..., d_model).

    Returns ``(y, aux_loss)`` where ``aux_loss`` is the load-balancing term
    (num_experts * sum over experts of fraction-routed x mean-prob),
    minimized at uniform routing.
    """
    orig_shape = x.shape
    tokens = x.reshape(-1, cfg.d_model)
    T, E, K = tokens.shape[0], cfg.num_experts, cfg.top_k
    capacity = max(1, math.ceil(T * K / E * cfg.capacity_factor))

    gates, top_e, probs = _route(params, tokens, cfg)

    # ----- dispatch by sort (no [T, E, C] dense tensor) --------------------
    flat_e = top_e.reshape(-1)  # (T*K,) expert of each token-copy
    flat_gate = gates.reshape(-1)
    flat_tok = jnp.arange(T * K, dtype=jnp.int32) // K  # owning token

    order = jnp.argsort(flat_e, stable=True)  # expert-major, original order
    sorted_e = flat_e[order]
    sorted_tok = flat_tok[order]
    sorted_gate = flat_gate[order]

    counts = jnp.bincount(flat_e, length=E)  # (E,) tokens routed per expert
    starts = jnp.cumsum(counts) - counts  # exclusive prefix
    pos = jnp.arange(T * K, dtype=jnp.int32) - starts[sorted_e]
    kept = pos < capacity
    # Dropped copies are parked at their expert's slot 0 with a zeroed
    # contribution — a scatter-ADD of zeros, harmless and shape-static.
    slot = sorted_e * capacity + jnp.where(kept, pos, 0)

    contrib = tokens[sorted_tok] * kept[:, None].astype(tokens.dtype)
    expert_in = (
        jnp.zeros((E * capacity, cfg.d_model), tokens.dtype).at[slot].add(contrib)
    ).reshape(E, capacity, cfg.d_model)
    # Sharding the E axis makes XLA all-to-all the buffers onto the
    # expert-parallel devices.
    expert_in = _constrain(expert_in, mesh, P(AXIS_EXPERT, None, None))

    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_gate"])) * (
        jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"])
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    expert_out = _constrain(expert_out, mesh, P(AXIS_EXPERT, None, None))

    # ----- combine: gather each copy's output, weight, sum per token ------
    gathered = expert_out.reshape(E * capacity, cfg.d_model)[slot]
    weight = (sorted_gate * kept).astype(tokens.dtype)
    y = (
        jnp.zeros((T, cfg.d_model), tokens.dtype)
        .at[sorted_tok]
        .add(gathered * weight[:, None])
    )
    # Dropped tokens (over capacity) contribute zero — the caller's residual
    # connection carries them through, as in Switch Transformer.

    # Load balancing: f_i is the PRE-drop routed fraction — clamping by
    # `kept` would cap an over-capacity expert's penalty at capacity/(T*K),
    # under-penalizing exactly the collapsed-router state the loss prevents.
    frac_routed = counts.astype(jnp.float32) / (T * K)
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    aux_loss = E * jnp.sum(frac_routed * mean_prob)
    return y.reshape(orig_shape), aux_loss


def reference_moe(params: Params, x: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Per-token direct computation (no capacity, no dispatch machinery):
    what ``moe_ffn`` must match when capacity is ample."""
    tokens = x.reshape(-1, cfg.d_model)
    gates, top_e, _probs = _route(params, tokens, cfg)

    def per_token(tok, idxs, gs):
        out = jnp.zeros_like(tok)
        for j in range(cfg.top_k):  # static unroll over k
            i = idxs[j]
            h = jax.nn.silu(tok @ params["w_gate"][i]) * (tok @ params["w_in"][i])
            out = out + gs[j].astype(tok.dtype) * (h @ params["w_out"][i])
        return out

    out = jax.vmap(per_token)(tokens, top_e, gates)
    return out.reshape(x.shape)
