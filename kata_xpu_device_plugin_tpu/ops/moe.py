"""Mixture-of-experts FFN with expert parallelism (top-k routing, capacity,
sort-based dispatch).

The reference runs no model code (SURVEY §2 "parallelism strategies —
ABSENT"); this completes the guest-side parallelism stack (dp/fsdp/tp/sp +
pp + ep). TPU-first design:

- routing is top-k (Switch semantics at k=1: the raw chosen probability is
  the gate; Mixtral semantics at k>1: gates renormalized over the chosen k);
- dispatch is a SORT: token-copies are ordered by expert id with XLA's sort
  (TPU-efficient, stable), positions within each expert's capacity buffer
  come from a cumsum of per-expert counts, and tokens move via scatter-add /
  gather on ``[E*capacity, d]`` buffers. Memory is O(T·K + E·C·d) — the
  dense ``[T, E, C]`` dispatch tensor of a one-hot einsum formulation never
  exists (VERDICT r1 item 6);
- expert parallelism is pure GSPMD: the expert-major buffers carry a
  sharding constraint on the ``expert`` mesh axis and XLA inserts the
  all-to-all that moves tokens to their experts' devices over ICI. No
  hand-written collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..compat.jaxapi import Mesh, NamedSharding, P, shard_map

AXIS_EXPERT = "expert"

Params = dict


@dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    # Per-expert buffer = ceil(T*top_k/experts * factor); token-copies routed
    # past it are dropped (their residual stream passes through unchanged).
    capacity_factor: float = 2.0
    top_k: int = 1

    def __post_init__(self):
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts={self.num_experts}]"
            )


def expert_mesh(n_devices: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh sharding experts across devices."""
    from ..parallel.mesh import mesh_1d

    return mesh_1d(n_devices, AXIS_EXPERT, devices)


def init_moe_params(key: jax.Array, cfg: MoEConfig, dtype=jnp.float32) -> Params:
    kr, kg, ki, ko = jax.random.split(key, 4)
    d, f, e = cfg.d_model, cfg.d_ff, cfg.num_experts

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(kr, (d, e), d),
        "w_gate": dense(kg, (e, d, f), d),  # expert-major: shard dim 0 over ep
        "w_in": dense(ki, (e, d, f), d),
        "w_out": dense(ko, (e, f, d), f),
    }


def moe_param_specs() -> Params:
    """PartitionSpecs for the params: experts sharded, router replicated."""
    return {
        "router": P(),
        "w_gate": P(AXIS_EXPERT),
        "w_in": P(AXIS_EXPERT),
        "w_out": P(AXIS_EXPERT),
    }


def _constrain(x: jax.Array, mesh: Optional[Mesh], spec: P) -> jax.Array:
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def expert_axis_for(mesh: Optional[Mesh]) -> str:
    """The mesh axis experts shard over: a dedicated ``expert`` axis when the
    mesh has one (the 1-D ep mesh), otherwise ``model`` — in the composed
    train mesh the tensor-parallel axis doubles as the expert axis (MoE
    layers use expert parallelism where dense layers use tp, the standard
    Switch/Mixtral layout)."""
    if mesh is None:
        return AXIS_EXPERT
    if AXIS_EXPERT in mesh.axis_names:
        return AXIS_EXPERT
    from ..parallel.mesh import AXIS_MODEL

    return AXIS_MODEL


def _route(params: Params, tokens: jax.Array, cfg: MoEConfig):
    """Shared router: (top-k gates [T,K] fp32, expert ids [T,K] int32,
    full softmax probs [T,E] fp32)."""
    logits = tokens @ params["router"].astype(tokens.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p, top_e = jax.lax.top_k(probs, cfg.top_k)  # (T, K)
    if cfg.top_k == 1:
        gates = top_p  # Switch: the raw chosen probability
    else:
        gates = top_p / jnp.sum(top_p, axis=-1, keepdims=True)  # Mixtral
    return gates, top_e.astype(jnp.int32), probs


def moe_ffn(
    params: Params,
    x: jax.Array,
    cfg: MoEConfig,
    mesh: Optional[Mesh] = None,
    axis: Optional[str] = None,
) -> tuple[jax.Array, jax.Array]:
    """Apply the MoE FFN to ``x`` of shape (..., d_model).

    Returns ``(y, aux_loss)`` where ``aux_loss`` is the load-balancing term
    (num_experts * sum over experts of fraction-routed x mean-prob),
    minimized at uniform routing. ``axis`` names the mesh axis experts shard
    over (default: :func:`expert_axis_for`).
    """
    axis = axis or expert_axis_for(mesh)
    orig_shape = x.shape
    tokens = x.reshape(-1, cfg.d_model)
    T, E, K = tokens.shape[0], cfg.num_experts, cfg.top_k
    capacity = max(1, math.ceil(T * K / E * cfg.capacity_factor))

    gates, top_e, probs = _route(params, tokens, cfg)
    expert_in, slot, sorted_tok, weight, counts = _dispatch(
        tokens, top_e, gates, E, capacity
    )
    # Sharding the E axis makes XLA all-to-all the buffers onto the
    # expert-parallel devices.
    expert_in = _constrain(expert_in, mesh, P(axis, None, None))

    expert_out = _expert_mlp(params, expert_in)
    expert_out = _constrain(expert_out, mesh, P(axis, None, None))

    y = _combine(expert_out, slot, sorted_tok, weight, T, cfg.d_model)
    # Load balancing: f_i is the PRE-drop routed fraction — clamping by
    # `kept` would cap an over-capacity expert's penalty at capacity/(T*K),
    # under-penalizing exactly the collapsed-router state the loss prevents.
    frac_routed = counts.astype(jnp.float32) / (T * K)
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    aux_loss = E * jnp.sum(frac_routed * mean_prob)
    return y.reshape(orig_shape), aux_loss


def _dispatch(tokens, top_e, gates, E: int, capacity: int):
    """Sort-based dispatch (no [T, E, C] dense tensor): token-copies ordered
    by expert id, positions within each expert's capacity buffer from a
    cumsum of per-expert counts, moved via scatter-add. Returns
    ``(expert_in [E, C, d], slot, sorted_tok, combine_weight, counts)``."""
    T, K = gates.shape
    d = tokens.shape[-1]
    flat_e = top_e.reshape(-1)  # (T*K,) expert of each token-copy
    flat_gate = gates.reshape(-1)
    flat_tok = jnp.arange(T * K, dtype=jnp.int32) // K  # owning token

    order = jnp.argsort(flat_e, stable=True)  # expert-major, original order
    sorted_e = flat_e[order]
    sorted_tok = flat_tok[order]
    sorted_gate = flat_gate[order]

    counts = jnp.bincount(flat_e, length=E)  # (E,) tokens routed per expert
    starts = jnp.cumsum(counts) - counts  # exclusive prefix
    pos = jnp.arange(T * K, dtype=jnp.int32) - starts[sorted_e]
    kept = pos < capacity
    # Dropped copies are parked at their expert's slot 0 with a zeroed
    # contribution — a scatter-ADD of zeros, harmless and shape-static.
    slot = sorted_e * capacity + jnp.where(kept, pos, 0)

    contrib = tokens[sorted_tok] * kept[:, None].astype(tokens.dtype)
    expert_in = (
        jnp.zeros((E * capacity, d), tokens.dtype).at[slot].add(contrib)
    ).reshape(E, capacity, d)
    weight = (sorted_gate * kept).astype(tokens.dtype)
    return expert_in, slot, sorted_tok, weight, counts


def _expert_einsum(subs: str, x: jax.Array, w) -> jax.Array:
    """Expert-major ``einsum(subs, x, w)`` that also streams int8
    :class:`..quant.QTensor` weights: the dot runs on the int8 payload cast
    to the activation dtype (the cast fuses into the weight read, so HBM
    traffic is the int8 bytes) and the per-expert fp32 scale — one per
    output channel, ``[E, 1, out]`` — multiplies the einsum RESULT, exactly
    the post-dot form ``quant.weight_matmul`` uses for dense layers."""
    from .quant import QTensor

    if isinstance(w, QTensor):
        y = jnp.einsum(
            subs, x, w.q.astype(x.dtype), preferred_element_type=jnp.float32
        )
        return (y * w.scale.astype(jnp.float32)).astype(x.dtype)
    return jnp.einsum(subs, x, w.astype(x.dtype))


def _expert_mlp(params: Params, expert_in: jax.Array) -> jax.Array:
    """[E, C, d] → [E, C, d] silu-gated MLP, expert-major. Weights cast to
    the activation dtype (bf16-compute/fp32-params convention of the dense
    FFN path — and the sharded variant's return all_to_all must carry bf16
    buffers, not fp32-promoted ones); int8 QTensor experts stream their
    int8 payload with post-dot per-expert scales (``_expert_einsum``)."""
    h = jax.nn.silu(
        _expert_einsum("ecd,edf->ecf", expert_in, params["w_gate"])
    ) * _expert_einsum("ecd,edf->ecf", expert_in, params["w_in"])
    return _expert_einsum("ecf,efd->ecd", h, params["w_out"])


def _combine(expert_out, slot, sorted_tok, weight, T: int, d: int) -> jax.Array:
    """Gather each copy's expert output, weight by its gate, sum per token.
    Dropped tokens (over capacity) contribute zero — the caller's residual
    connection carries them through, as in Switch Transformer."""
    gathered = expert_out.reshape(-1, d)[slot]
    return (
        jnp.zeros((T, d), expert_out.dtype)
        .at[sorted_tok]
        .add(gathered * weight[:, None])
    )


def _assign_token_axes(lead, axes, mesh: Mesh, expert_axis: str):
    """Statically place each mesh axis on the batch or sequence dim of a
    [B, S, d] activation so the shard_map token sharding MATCHES the layout
    the surrounding ops already use: data-ish axes prefer the batch dim
    (that's ``parallel.sharding.batch_spec``'s batch placement), while the
    seq axis and the expert axis prefer the sequence dim (seq because the
    activations are already S-sharded there; the expert axis because
    splitting S is a local dynamic-slice, not a cross-dim reshuffle).
    Falls back to the other dim when sizes don't divide; returns
    ``(b_axes, s_axes)`` or ``None`` when no placement covers every axis —
    misaligned boundaries are exactly what makes SPMD fall back to
    involuntary full rematerialization in the grad path.
    """
    try:  # lazy: ops must not import parallel at module load (cycle)
        from ..parallel.mesh import AXIS_SEQ
    except ImportError:  # pragma: no cover
        AXIS_SEQ = "seq"

    b_rem, s_rem = lead
    b_axes, s_axes = [], []
    for a in axes:
        n = mesh.shape[a]
        if n == 1:
            continue  # size-1 axes shard nothing — leave them off the spec
        prefer_s = a == expert_axis or a == AXIS_SEQ
        choices = ("s", "b") if prefer_s else ("b", "s")
        for dim in choices:
            if dim == "b" and b_rem % n == 0:
                b_axes.append(a)
                b_rem //= n
                break
            if dim == "s" and s_rem % n == 0:
                s_axes.append(a)
                s_rem //= n
                break
        else:
            return None
    return tuple(b_axes), tuple(s_axes)


def dispatch_shardable(
    tokens_shape, num_experts: int, mesh: Mesh, expert_axis: Optional[str] = None
) -> bool:
    """Whether :func:`moe_ffn_sharded`'s divisibility constraints hold for
    this token count/mesh (trace-time static). ``tokens_shape`` is the
    activation's leading shape ``(B, S)`` — the layout-aligned check — or a
    flat token count (legacy flattened dispatch)."""
    expert_axis = expert_axis or expert_axis_for(mesh)
    if num_experts % mesh.shape[expert_axis]:
        return False
    all_axes = tuple(a for a in mesh.axis_names if a != expert_axis) + (
        expert_axis,
    )
    if isinstance(tokens_shape, (tuple, list)):
        return _assign_token_axes(
            tuple(tokens_shape), all_axes, mesh, expert_axis
        ) is not None
    n_total = math.prod(mesh.shape[a] for a in mesh.axis_names)
    return tokens_shape % n_total == 0


def moe_ffn_sharded(
    params: Params,
    x: jax.Array,
    cfg: MoEConfig,
    mesh: Mesh,
    expert_axis: Optional[str] = None,
) -> tuple[jax.Array, jax.Array]:
    """Data-sharded MoE FFN (GShard layout): tokens are sharded over ALL
    mesh axes, so the sort/cumsum/scatter dispatch runs on T/n_devices
    tokens per device instead of being replicated global work (the r2
    weakness of :func:`moe_ffn` at scale); experts are sharded over
    ``expert_axis`` and the two ``lax.all_to_all`` exchanges carry only the
    [E, C_local, d] capacity buffers over ICI.

    Per-device capacity is ``ceil(T_local*K/E * capacity_factor)`` — the
    same expected load as the global formula, applied per shard (a token
    only competes with its shard's tokens for buffer slots).

    Requires T divisible by the mesh size and E by the expert-axis size
    (callers can pre-check with :func:`dispatch_shardable` and fall back to
    the GSPMD :func:`moe_ffn`). Returns ``(y, aux_loss)`` with the aux term
    computed from GLOBAL routing fractions (psum over the whole mesh).
    """
    expert_axis = expert_axis or expert_axis_for(mesh)
    token_axes = tuple(a for a in mesh.axis_names if a != expert_axis)
    all_axes = token_axes + (expert_axis,)
    n_total = math.prod(mesh.shape[a] for a in all_axes)
    ep = mesh.shape[expert_axis]

    orig_shape = x.shape
    T = math.prod(orig_shape[:-1])
    E, K = cfg.num_experts, cfg.top_k
    if E % ep:
        raise ValueError(f"{E} experts not divisible by {expert_axis}={ep}")
    # [B, S, d] activations keep their 2-D token layout at the shard_map
    # boundary (batch axes on B, seq/expert axes on S — _assign_token_axes)
    # so entering/leaving the dispatch never crosses dims; a flattened
    # [T, d] input falls back to sharding T over every axis.
    placement = (
        _assign_token_axes(orig_shape[:2], all_axes, mesh, expert_axis)
        if x.ndim == 3 else None
    )
    if x.ndim == 3 and placement is None and T % n_total == 0:
        # (B, S) has no aligned per-dim placement but the flat count still
        # divides: fall back to the legacy flattened layout (correct, just
        # pays the cross-dim reshard) — callers pre-checking with a flat
        # dispatch_shardable(int) count must keep working.
        x = x.reshape(-1, cfg.d_model)
    if x.ndim == 3 and placement is None:
        raise ValueError(
            f"tokens {orig_shape[:-1]} not divisible by mesh size {n_total}"
        )
    if placement is not None:
        b_axes, s_axes = placement
        tokens = x
        tok_spec = P(b_axes or None, s_axes or None, None)
    else:
        tokens = x.reshape(-1, cfg.d_model)
        if T % n_total:
            raise ValueError(
                f"token count {T} not divisible by mesh size {n_total}"
            )
        tok_spec = P(all_axes, None)
    t_loc = T // n_total
    capacity = max(1, math.ceil(t_loc * K / E * cfg.capacity_factor))

    def per_device(router, w_gate, w_in, w_out, tok_blk):
        # tok_blk [T_loc, d] (or [B_loc, S_loc, d] in the aligned layout);
        # w_* [E_loc, ...] local expert shard.
        blk_shape = tok_blk.shape
        tok_blk = tok_blk.reshape(-1, cfg.d_model)
        gates, top_e, probs = _route({"router": router}, tok_blk, cfg)
        expert_in, slot, sorted_tok, weight, counts = _dispatch(
            tok_blk, top_e, gates, E, capacity
        )
        # Exchange: every device sends expert e's buffer to e's owner and
        # receives its own experts' buffers from every token shard in its
        # expert-axis group. [E, C, d] → [E/ep, ep*C, d].
        expert_in = lax.all_to_all(
            expert_in, expert_axis, split_axis=0, concat_axis=1, tiled=True
        )
        out = _expert_mlp({"w_gate": w_gate, "w_in": w_in, "w_out": w_out}, expert_in)
        expert_out = lax.all_to_all(
            out, expert_axis, split_axis=1, concat_axis=0, tiled=True
        )
        y = _combine(expert_out, slot, sorted_tok, weight, t_loc, cfg.d_model)

        # Aux from GLOBAL fractions: local counts/prob-sums psum over the
        # whole mesh (every device routes a disjoint token shard).
        counts_g = lax.psum(counts, all_axes)
        probs_g = lax.psum(jnp.sum(probs, axis=0), all_axes)
        total = T * K
        frac_routed = counts_g.astype(jnp.float32) / total
        mean_prob = probs_g / T
        aux = E * jnp.sum(frac_routed * mean_prob)
        return y.reshape(blk_shape), aux

    mapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(),  # router replicated
            P(expert_axis), P(expert_axis), P(expert_axis),  # expert-major
            tok_spec,
        ),
        out_specs=(tok_spec, P()),
        check_vma=False,  # aux is psum-replicated; weights invariant over token axes
    )
    y, aux = mapped(
        params["router"], params["w_gate"], params["w_in"], params["w_out"], tokens
    )
    return y.reshape(orig_shape), aux


def reference_moe(params: Params, x: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Per-token direct computation (no capacity, no dispatch machinery):
    what ``moe_ffn`` must match when capacity is ample."""
    tokens = x.reshape(-1, cfg.d_model)
    gates, top_e, _probs = _route(params, tokens, cfg)

    def per_token(tok, idxs, gs):
        out = jnp.zeros_like(tok)
        for j in range(cfg.top_k):  # static unroll over k
            i = idxs[j]
            h = jax.nn.silu(tok @ params["w_gate"][i]) * (tok @ params["w_in"][i])
            out = out + gs[j].astype(tok.dtype) * (h @ params["w_out"][i])
        return out

    out = jax.vmap(per_token)(tokens, top_e, gates)
    return out.reshape(x.shape)
