"""Pallas TPU flash attention (forward).

Blockwise online-softmax attention: the [Sq, Sk] score matrix never reaches
HBM — each (q-block, k-block) tile is computed in VMEM on the MXU, with
running max/denominator carried in VMEM scratch across the (sequential) last
grid dimension. Supports GQA/MQA natively by index-mapping each q head onto
its KV head, so KV heads are never materialized H/KV times.

Used for prefill/inference (the decode hot path is tiny-q and stays on XLA;
training uses the XLA reference path which autodiffs). Numerics oracle:
``tests/test_ops.py`` compares against ``reference_attention`` on CPU via
interpret mode, and the bench compares on the real chip.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def pick_block(seq_len: int, requested: int) -> Optional[int]:
    """Largest usable block ≤ requested: divides ``seq_len``, multiple of 8,
    at least 128 (TPU tile constraints). None when no such block exists —
    callers then take the XLA reference path."""
    start = min(requested, seq_len)
    start -= start % 8  # descend over 8-aligned candidates only
    for b in range(start, 127, -8):
        if seq_len % b == 0:
            return b
    return None


def supports(sq: int, sk: int, d: int) -> bool:
    """Whether the pallas kernel can run these self-attention shapes."""
    return (
        (d % 128 == 0 or d == 64)
        and pick_block(sq, 512) is not None
        and pick_block(sk, 512) is not None
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
    block_q: int, block_k: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute():
        # Dots run in the inputs' native dtype: on TPU the MXU does
        # bf16×bf16→fp32 at ~2× fp32 throughput, so casting inputs up before
        # the dot would halve kernel FLOPs. Softmax math and both
        # accumulators stay fp32 (preferred_element_type below).
        q = q_ref[0, 0, :, :]  # [BQ, D]
        k = k_ref[0, 0, :, :]  # [BK, D]
        v = v_ref[0, 0, :, :]  # [BK, D]

        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK] fp32

        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(k_pos <= q_pos, logits, NEG_INF)

        m_prev = m_scr[:, 0:1]  # [BQ, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)  # [BQ, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)  # [BQ, BK]
        correction = jnp.exp(m_prev - m_new)  # [BQ, 1]
        l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)

        acc = acc_scr[...] * correction  # [BQ, D]
        acc = acc + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[...] = acc

    if causal:
        # Skip k-blocks entirely above the causal frontier — ~half the grid
        # at long sequence; the MXU never sees fully-masked tiles.
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(compute)
    else:
        compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        denom = l_scr[:, 0:1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0, 0, :, :] = (acc_scr[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """q [B, Sq, H, D]; k/v [B, Sk, KV, D], H % KV == 0. Self-attention only
    (``q_offset`` unsupported here — callers fall back to the reference)."""
    if q_offset is not None:
        raise ValueError("pallas_flash_attention is for self-attention (q_offset=None)")
    B, Sq, H, D = q.shape
    _, Sk, KV, _ = k.shape
    assert H % KV == 0, (H, KV)
    group = H // KV
    block_q = pick_block(Sq, block_q)
    block_k = pick_block(Sk, block_k)
    if block_q is None or block_k is None:
        raise ValueError(
            f"no valid flash block for Sq={Sq}, Sk={Sk} (need a divisor ≥128, "
            "multiple of 8); use reference_attention"
        )
    grid = (B, H, Sq // block_q, Sk // block_k)

    scale = float(1.0 / (D ** 0.5))
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
    )
    # Pallas TPU tiles the LAST TWO dims: run the kernel in [B, H, S, D]
    # layout so (S-block, D) are the tiled pair.
    q_t = q.transpose(0, 2, 1, 3)  # [B, H, Sq, D]
    k_t = k.transpose(0, 2, 1, 3)  # [B, KV, Sk, D]
    v_t = v.transpose(0, 2, 1, 3)
    out_t = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // group, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // group, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q_t.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
            pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_t, k_t, v_t)
    return out_t.transpose(0, 2, 1, 3)
