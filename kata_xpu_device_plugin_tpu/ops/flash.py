"""Pallas TPU flash attention (forward + custom_vjp backward).

Blockwise online-softmax attention: the [Sq, Sk] score matrix never reaches
HBM — each (q-block, k-block) tile is computed in VMEM on the MXU, with
running max/denominator carried in VMEM scratch across the (sequential) last
grid dimension. Supports GQA/MQA natively by index-mapping each q head onto
its KV head, so KV heads are never materialized H/KV times.

Training-ready: the forward also emits the per-row logsumexp, and a
``jax.custom_vjp`` backward recomputes each tile's probabilities from it
(FlashAttention-2 style — dq gridded over q-blocks, dk/dv over k-blocks), so
the Llama-scale training path never materializes [S, S] either. The decode
hot path is tiny-q and lives in :mod:`.decode_attn`. Numerics oracle:
``tests/test_ops.py`` compares forward AND gradients against
``reference_attention`` on CPU via interpret mode; the bench compares on the
real chip.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# Pallas has no stable import home yet; these two stay experimental on
# every supported JAX line (see docs/compat_and_lint.md).
from jax.experimental import pallas as pl  # lint: allow(JX002) pallas-only API
from jax.experimental.pallas import tpu as pltpu  # lint: allow(JX002) pallas-only API

from ..compat.jaxapi import pallas_tpu_compiler_params

NEG_INF = -1e30
# Lane width for per-row side outputs (logsumexp, delta): only column 0 is
# read back, so keep the HBM footprint at 8 lanes (sublane-aligned) rather
# than a full 128-lane tile.
ROW_W = 8
# Default block edge. Swept on v5e (scripts/exp_flash_blocks.py, Gemma-2B
# S=2048 prefill): 1024×1024 beat 512×512 by ~1.7% full-model; pick_block
# descends from here, so shorter sequences still get their largest divisor.
DEFAULT_BLOCK = 1024


def pick_block(seq_len: int, requested: int) -> Optional[int]:
    """Largest usable block ≤ requested: divides ``seq_len``, multiple of
    32, at least 128. None when no such block exists — callers then take
    the XLA reference path. 32 alignment (not just the fp32 sublane 8)
    keeps the block a whole number of sublane tiles for every supported
    dtype (fp32 8, bf16 16, int8 32): an 8-aligned-but-not-16-aligned
    block (e.g. 1016) is a bf16 tiling violation Mosaic may reject at
    compile time."""
    start = min(requested, seq_len)
    start -= start % 32  # descend over all-dtype-tileable candidates only
    for b in range(start, 127, -32):
        if seq_len % b == 0:
            return b
    return None


def supports(sq: int, sk: int, d: int) -> bool:
    """Whether the pallas kernel can run these self-attention shapes."""
    return (
        (d % 128 == 0 or d == 64)
        and pick_block(sq, DEFAULT_BLOCK) is not None
        and pick_block(sk, DEFAULT_BLOCK) is not None
    )


def _flash_kernel(
    off_ref, q_ref, k_ref, v_ref, o_ref, *rest,
    scale: float, causal: bool, block_q: int, block_k: int, emit_lse: bool,
    window: int = 0, softcap: float = 0.0,
):
    if emit_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)
    # Global-position offsets (scalar-prefetched): zero for plain
    # self-attention; ring attention passes each device's sequence offsets
    # so the causal frontier is judged on GLOBAL positions.
    q_off, k_off = off_ref[0], off_ref[1]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute():
        # Dots run in the inputs' native dtype: on TPU the MXU does
        # bf16×bf16→fp32 at ~2× fp32 throughput, so casting inputs up before
        # the dot would halve kernel FLOPs. Softmax math and both
        # accumulators stay fp32 (preferred_element_type below).
        q = q_ref[0, 0, :, :]  # [BQ, D]
        k = k_ref[0, 0, :, :]  # [BK, D]
        v = v_ref[0, 0, :, :]  # [BK, D]

        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK] fp32
        if softcap > 0.0:
            # Gemma-2 logit cap, applied pre-mask exactly like the XLA
            # reference: cap·tanh(s/cap). Elementwise, so the blockwise
            # online softmax is unaffected.
            logits = jnp.tanh(logits / softcap) * softcap

        if causal:
            q_pos = q_off + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_off + ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            band = k_pos <= q_pos
            if window > 0:  # sliding window: keys in (q_pos - window, q_pos]
                band &= k_pos > q_pos - window
            logits = jnp.where(band, logits, NEG_INF)

        m_prev = m_scr[:, 0:1]  # [BQ, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)  # [BQ, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)  # [BQ, BK]
        correction = jnp.exp(m_prev - m_new)  # [BQ, 1]
        l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)

        acc = acc_scr[...] * correction  # [BQ, D]
        acc = acc + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[...] = acc

    if causal:
        # Skip k-blocks entirely outside the band: above the (global)
        # causal frontier, and (with a sliding window) wholly below the
        # window's lower edge — the MXU never sees fully-masked tiles.
        live = k_off + ki * block_k <= q_off + qi * block_q + block_q - 1
        if window > 0:
            live &= (
                k_off + (ki + 1) * block_k - 1
                > q_off + qi * block_q - window
            )
        pl.when(live)(compute)
    else:
        compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        denom = l_scr[:, 0:1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0, 0, :, :] = (acc_scr[...] / denom).astype(o_ref.dtype)
        if emit_lse:
            # Logsumexp per query row, saved for the backward recompute
            # (stored ROW_W-wide; read back as column 0).
            lse = m_scr[:, 0:1] + jnp.log(denom)
            lse_ref[0, 0, :, :] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd_call(q_t, k_t, v_t, causal, block_q, block_k, group, interpret, scale,
              offsets=(0, 0), need_lse=True, window=0, softcap=0.0):
    """[B, H, S, D]-layout forward returning (out, logsumexp[B, H, Sq, ROW_W]
    or None). ``offsets = (q_off, k_off)`` are global sequence offsets (may
    be traced scalars — ring attention passes per-device offsets).
    ``need_lse=False`` (inference: no backward, no ring merge) skips the
    logsumexp write entirely — it is pure extra HBM traffic there."""
    B, H, Sq, D = q_t.shape
    Sk = k_t.shape[2]
    grid = (B, H, Sq // block_q, Sk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, emit_lse=need_lse, window=window, softcap=softcap,
    )
    offs = jnp.asarray(offsets, jnp.int32)  # (q_off, k_off) tuple or [2] array
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki, off: (b, h, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, qi, ki, off: (b, h // group, ki, 0)
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, ROW_W), lambda b, h, qi, ki, off: (b, h, qi, 0)
    )
    out_specs = [q_spec] + ([row_spec] if need_lse else [])
    out_shape = [jax.ShapeDtypeStruct(q_t.shape, q_t.dtype)] + (
        [jax.ShapeDtypeStruct((B, H, Sq, ROW_W), jnp.float32)] if need_lse else []
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((block_q, 128), jnp.float32),  # running max (col 0)
                pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
                pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
            ],
        ),
        out_shape=out_shape,
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, q_t, k_t, v_t)
    return (res[0], res[1]) if need_lse else (res[0], None)


# ----- backward (FlashAttention-2 style: recompute p from q/k + logsumexp,
# dq gridded over q-blocks, dk/dv gridded over k-blocks) --------------------


def _bwd_dq_kernel(
    off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, window: int = 0,
    softcap: float = 0.0,
):
    qi, ki = pl.program_id(2), pl.program_id(3)
    num_k = pl.num_programs(3)
    q_off, k_off = off_ref[0], off_ref[1]

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]  # [BQ, 1]
        delta = delta_ref[0, 0][:, 0:1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if softcap > 0.0:
            # Recompute the cap exactly as the forward did: p comes from
            # the CAPPED logits, and d(cap·tanh(s/cap))/ds = 1 − tanh²
            # joins the ds bracket below.
            t = jnp.tanh(s / softcap)
            s = t * softcap
        p = jnp.exp(s - lse)  # [BQ, BK]
        if causal:
            q_pos = q_off + qi * block_q + lax.broadcasted_iota(jnp.int32, p.shape, 0)
            k_pos = k_off + ki * block_k + lax.broadcasted_iota(jnp.int32, p.shape, 1)
            band = k_pos <= q_pos
            if window > 0:
                band &= k_pos > q_pos - window
            p = jnp.where(band, p, 0.0)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        ds = p * (dp - delta) * scale
        if softcap > 0.0:
            ds = ds * (1.0 - t * t)
        dq_scr[...] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        live = k_off + ki * block_k <= q_off + qi * block_q + block_q - 1
        if window > 0:
            live &= (
                k_off + (ki + 1) * block_k - 1
                > q_off + qi * block_q - window
            )
        pl.when(live)(compute)
    else:
        compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale: float, causal: bool, block_q: int, block_k: int,
    window: int = 0, softcap: float = 0.0,
):
    ki, qi = pl.program_id(2), pl.program_id(3)
    num_q = pl.num_programs(3)
    q_off, k_off = off_ref[0], off_ref[1]

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if softcap > 0.0:
            t = jnp.tanh(s / softcap)
            s = t * softcap
        p = jnp.exp(s - lse)  # [BQ, BK]
        if causal:
            q_pos = q_off + qi * block_q + lax.broadcasted_iota(jnp.int32, p.shape, 0)
            k_pos = k_off + ki * block_k + lax.broadcasted_iota(jnp.int32, p.shape, 1)
            band = k_pos <= q_pos
            if window > 0:
                band &= k_pos > q_pos - window
            p = jnp.where(band, p, 0.0)
        pv = p.astype(do.dtype)
        dv_scr[...] += lax.dot_general(
            pv, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BK, D]
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        if softcap > 0.0:
            ds = ds * (1.0 - t * t)
        ds = ds.astype(q.dtype)
        dk_scr[...] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BK, D]

    if causal:
        # This k-block only sees q-blocks at or below the frontier (and,
        # with a sliding window, within the band's reach).
        live = k_off + ki * block_k <= q_off + qi * block_q + block_q - 1
        if window > 0:
            live &= (
                k_off + (ki + 1) * block_k - 1
                > q_off + qi * block_q - window
            )
        pl.when(live)(compute)
    else:
        compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_call(q_t, k_t, v_t, out_t, lse, do_t, causal, block_q, block_k,
              group, interpret, scale, offsets=(0, 0), dlse=None, window=0,
              softcap=0.0):
    """Gradients in the [B, H, S, D] layout. dk/dv are per Q-HEAD here; the
    caller sums head groups down to the KV heads.

    ``dlse`` is the cotangent of the logsumexp output (ring attention's
    merge differentiates through it): d lse_i/d s_ij = p_ij, so it simply
    joins the ds bracket — ds = p·(dp − (Δ − dlse))·scale."""
    B, H, Sq, D = q_t.shape
    Sk = k_t.shape[2]
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, XLA-side, stored
    # ROW_W-wide like the logsumexp.
    delta = jnp.sum(do_t.astype(jnp.float32) * out_t.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse
    delta = jnp.broadcast_to(delta[..., None], (B, H, Sq, ROW_W))
    offs = jnp.asarray(offsets, jnp.int32)

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki, off: (b, h, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, qi, ki, off: (b, h // group, ki, 0)
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, ROW_W), lambda b, h, qi, ki, off: (b, h, qi, 0)
    )

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window, softcap=softcap,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, Sq // block_q, Sk // block_k),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
            out_specs=q_spec,
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(q_t.shape, q_t.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, q_t, k_t, v_t, do_t, lse, delta)

    # dk/dv: grid sequential over q-blocks; indices (b, h, ki, qi).
    q_spec2 = pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qi, off: (b, h, qi, 0))
    kv_spec2 = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, ki, qi, off: (b, h // group, ki, 0)
    )
    kv_out_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, ki, qi, off: (b, h, ki, 0)
    )
    row_spec2 = pl.BlockSpec(
        (1, 1, block_q, ROW_W), lambda b, h, ki, qi, off: (b, h, qi, 0)
    )
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window, softcap=softcap,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, Sk // block_k, Sq // block_q),
            in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
            out_specs=[kv_out_spec, kv_out_spec],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
        ),
        out_shape=[
            # fp32 partials: each is a per-q-head contribution that the
            # caller sums across the GQA group — rounding to bf16 BEFORE
            # that sum would grow gradient error with the group size.
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, q_t, k_t, v_t, do_t, lse, delta)
    return dq, dk_h, dv_h


def _group_kv_grads(dk_h, dv_h, KV, group):
    """Per-q-head dk/dv → per-KV-head (sum each group of G q-heads)."""
    B, H, Sk, D = dk_h.shape
    dk = dk_h.reshape(B, KV, group, Sk, D).sum(axis=2)
    dv = dv_h.reshape(B, KV, group, Sk, D).sum(axis=2)
    return dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, block_q, block_k, interpret, window, softcap):
    group = q.shape[2] // k.shape[2]
    scale = float(1.0 / (q.shape[3] ** 0.5))
    # Pallas TPU tiles the LAST TWO dims: run kernels in [B, H, S, D] layout
    # so (S-block, D) are the tiled pair. No lse output on the primal path —
    # inference would pay its HBM write for nothing.
    out_t, _ = _fwd_call(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        causal, block_q, block_k, group, interpret, scale, need_lse=False,
        window=window, softcap=softcap,
    )
    return out_t.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, window, softcap):
    """VJP forward rule: the zero-offset case of the block rules — one
    numerical implementation for both the self-attention and ring paths."""
    (out, _lse), res = _flash_block_fwd(
        q, k, v, jnp.zeros((2,), jnp.int32), causal, block_q, block_k,
        interpret, window=window, softcap=softcap,
    )
    return out, res


def _flash_bwd(causal, block_q, block_k, interpret, window, softcap, res, dout):
    lse = res[4]
    B, H, Sq = lse.shape[:3]
    dlse_zero = jnp.zeros((B, Sq, H), jnp.float32)
    dq, dk, dv, _doffs = _flash_block_bwd(
        causal, block_q, block_k, interpret, softcap, window, res,
        (dout, dlse_zero),
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ----- ring-attention block API (differentiable) ---------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_block(q, k, v, offs, causal, block_q, block_k, interpret,
                 softcap=0.0, window=0):
    out, _ = _flash_block_fwd(q, k, v, offs, causal, block_q, block_k,
                              interpret, softcap=softcap, window=window)
    return out


def _flash_block_fwd(q, k, v, offs, causal, block_q, block_k, interpret,
                     softcap=0.0, window=0):
    group = q.shape[2] // k.shape[2]
    scale = float(1.0 / (q.shape[3] ** 0.5))
    q_t = q.transpose(0, 2, 1, 3)
    k_t = k.transpose(0, 2, 1, 3)
    v_t = v.transpose(0, 2, 1, 3)
    out_t, lse = _fwd_call(q_t, k_t, v_t, causal, block_q, block_k, group,
                           interpret, scale, offsets=offs, window=window,
                           softcap=softcap)
    out = (out_t.transpose(0, 2, 1, 3), lse[..., 0].transpose(0, 2, 1))
    return out, (q_t, k_t, v_t, out_t, lse, offs)


def _flash_block_bwd(causal, block_q, block_k, interpret, softcap, window,
                     res, cts):
    import numpy as _np

    q_t, k_t, v_t, out_t, lse, offs = res
    dout, dlse_bsh = cts
    B, H, Sq, D = q_t.shape
    KV = k_t.shape[1]
    group = H // KV
    scale = float(1.0 / (D**0.5))
    Sk = k_t.shape[2]
    # The backward re-blocks independently of the forward (logsumexp is
    # per-row, not per-block) and caps at 512: its dq/dkv kernels hold
    # several fp32 [BQ, BK] intermediates plus scratch in VMEM, a footprint
    # the 1024 forward default was never swept for on the training path.
    # Lengths with no divisor ≤512 (e.g. 544 = 32·17) keep the forward's
    # block — the forward proved it compiles, and a valid block is required.
    block_q = pick_block(Sq, min(block_q, 512)) or block_q
    block_k = pick_block(Sk, min(block_k, 512)) or block_k
    do_t = dout.transpose(0, 2, 1, 3)
    # defvjp without symbolic_zeros: the lse cotangent is always a dense
    # array (zeros when lse is unused downstream).
    dlse = dlse_bsh.transpose(0, 2, 1).astype(jnp.float32)  # [B, H, Sq]
    dq, dk_h, dv_h = _bwd_call(
        q_t, k_t, v_t, out_t, lse, do_t, causal, block_q, block_k, group,
        interpret, scale, offsets=offs, dlse=dlse, window=window,
        softcap=softcap,
    )
    dk, dv = _group_kv_grads(dk_h, dv_h, KV, group)
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3).astype(k_t.dtype),
        dv.transpose(0, 2, 1, 3).astype(v_t.dtype),
        _np.zeros(offs.shape, jax.dtypes.float0),  # int offsets: no gradient
    )


_flash_block.defvjp(_flash_block_fwd, _flash_block_bwd)


def flash_block_attention(
    q: jax.Array,  # [B, S_q, H, D]
    k: jax.Array,  # [B, S_k, KV, D]
    v: jax.Array,
    q_offset,  # global position of q[0] (scalar, may be traced)
    k_offset,  # global position of k[0]
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    interpret: bool = False,
    softcap: float = 0.0,
    window: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """One block-pair's partial attention for ring attention: returns
    ``(out, lse)`` where ``out`` is softmax-normalized WITHIN the block and
    ``lse [B, S_q, H]`` is its log-sum-exp — exactly what the ring's running
    (m, l, acc) merge needs to combine blocks across ``ppermute`` steps.
    Differentiable (custom_vjp recomputes blockwise; the lse cotangent joins
    the ds bracket), so the fused sp path trains. ``softcap`` applies the
    Gemma-2 logit cap inside each block (elementwise pre-softmax, so the
    cross-block lse merge is unaffected). ``window`` applies the sliding-
    window band on GLOBAL positions (``q_offset``/``k_offset`` aware), so a
    sequence-parallel ring can run Mistral/Gemma-2 windowed layers."""
    assert q.shape[3] == k.shape[3] and q.shape[2] % k.shape[2] == 0, (
        q.shape, k.shape)
    bq = pick_block(q.shape[1], block_q)
    bk = pick_block(k.shape[1], block_k)
    if bq is None or bk is None:
        raise ValueError(f"no valid flash block for Sq={q.shape[1]}, Sk={k.shape[1]}")
    offs = jnp.stack([jnp.int32(q_offset), jnp.int32(k_offset)])
    return _flash_block(q, k, v, offs, causal, bq, bk, interpret, softcap,
                        window)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret", "window", "softcap"))
def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    interpret: bool = False,
    window: int = 0,
    softcap: float = 0.0,
) -> jax.Array:
    """q [B, Sq, H, D]; k/v [B, Sk, KV, D], H % KV == 0. Self-attention only
    (``q_offset`` unsupported here — callers fall back to the reference).
    Differentiable: a custom_vjp recomputes attention blockwise from the
    saved logsumexp, so training never materializes [Sq, Sk].
    ``window > 0`` applies the sliding-window band (requires ``causal``);
    out-of-band blocks are skipped in forward AND backward, so Mistral-style
    long-sequence attention costs O(S·window), not O(S²). ``softcap > 0``
    applies the Gemma-2 logit cap (forward and both backward kernels model
    the tanh, so softcap configs train on the flash path too)."""
    if q_offset is not None:
        raise ValueError("pallas_flash_attention is for self-attention (q_offset=None)")
    if window > 0 and not causal:
        raise ValueError("sliding window implies causal attention")
    B, Sq, H, D = q.shape
    _, Sk, KV, _ = k.shape
    assert H % KV == 0, (H, KV)
    block_q = pick_block(Sq, block_q)
    block_k = pick_block(Sk, block_k)
    if block_q is None or block_k is None:
        raise ValueError(
            f"no valid flash block for Sq={Sq}, Sk={Sk} (need a divisor ≥128, "
            "multiple of 8); use reference_attention"
        )
    return _flash(q, k, v, causal, block_q, block_k, interpret, window, softcap)
