"""LoRA: low-rank adapters for parameter-efficient fine-tuning.

The reference ships no training stack at all (SURVEY §2 — zero ML code);
this is guest-side capability in the same style as :mod:`.quant`: a weight
is wrapped in a pytree node and the ONE weight-apply hook
(:func:`.quant.weight_matmul`) dispatches on it, so the decoder layer,
``lax.scan`` stacking, generation, and serving all work unchanged.

    y = x @ stop_gradient(base) + ((x @ a) @ b) · (alpha / rank)

- ``base`` is frozen via ``stop_gradient`` — XLA dead-code-eliminates the
  base weight-gradient outer products, so the backward pays only the
  adapter cost, and the optimizer state covers adapter leaves only
  (~0.1% of model size at rank 8).
- ``base`` may itself be an int8 :class:`.quant.QTensor` — QLoRA: frozen
  int8 weights streamed through the quantized matmul, bf16 adapters on
  top — with no extra code.
- ``b`` initializes to zero (standard LoRA), so a freshly adapted model is
  EXACTLY the base model; tests pin this.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp

# Layer-dict keys that can take adapters: the same 2-D matmul operands
# ops.quant can quantize.
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")


class LoRAWeight(NamedTuple):
    """A frozen base weight plus a trainable low-rank delta (NamedTuple ⇒
    pytree: rides through jit/scan/grad like any array)."""

    base: Any  # [..., in, out] array or QTensor
    a: jax.Array  # [..., in, r]
    b: jax.Array  # [..., r, out]
    scale: jax.Array  # () fp32 — alpha / rank


def lora_matmul(x: jax.Array, w: LoRAWeight) -> jax.Array:
    """``x @ w`` with the base frozen and the low-rank path in the
    activation dtype (the [.., r] bottleneck is tiny next to the base
    stream)."""
    from .quant import weight_matmul

    base = w.base
    if not isinstance(base, tuple):  # QTensor is a NamedTuple (tuple)
        base = jax.lax.stop_gradient(base)
    else:
        base = type(base)(*(jax.lax.stop_gradient(t) for t in base))
    y = weight_matmul(x, base)
    delta = (x @ w.a.astype(x.dtype)) @ w.b.astype(x.dtype)
    return y + delta * w.scale.astype(x.dtype)


def _wrap(w: Any, key: jax.Array, rank: int, alpha: float) -> LoRAWeight:
    shape = (w.q if hasattr(w, "q") else w).shape  # [..., in, out]
    *lead, d_in, d_out = shape
    a = jax.random.normal(key, (*lead, d_in, rank), jnp.float32) / jnp.sqrt(d_in)
    b = jnp.zeros((*lead, rank, d_out), jnp.float32)
    # scale broadcast to the leading (layer-stack) dims: every leaf of a
    # scanned pytree needs the leading L axis for lax.scan to slice.
    scale = jnp.full(tuple(lead), alpha / rank, jnp.float32)
    return LoRAWeight(w, a, b, scale)


def apply_lora(params: dict, key: jax.Array, rank: int = 8,
               alpha: float = 16.0,
               targets: Sequence[str] = DEFAULT_TARGETS) -> dict:
    """Wrap each present target weight in ``params['layers']`` with a
    fresh adapter (b = 0 ⇒ the adapted model initially equals the base).
    Works on the training layout, the fused layout (pass
    ``targets=('wqkv', ...)``), and int8-quantized bases (QLoRA)."""
    layers = params["layers"]
    present = [t for t in targets if t in layers]
    if not present:
        raise ValueError(
            f"no LoRA targets {tuple(targets)} in layers "
            f"{sorted(layers)} — fused layouts need e.g. targets=('wqkv',)"
        )
    keys = jax.random.split(key, len(present))
    out_layers = dict(layers)
    for t, k in zip(present, keys):
        out_layers[t] = _wrap(layers[t], k, rank, alpha)
    out = dict(params)
    out["layers"] = out_layers
    return out


def merge_lora(params: dict) -> dict:
    """Fold trained adapters back into plain weights (for serving /
    quantization): ``W' = base + (a @ b)·scale``. Float bases keep their
    dtype; int8 (QLoRA) bases dequantize and merge to FP32 — the
    pre-quantization dtype is unrecoverable from a QTensor — so re-cast or
    re-quantize (``quantize_decoder_params``) the result for serving."""
    from .quant import QTensor, dequantize

    def fold(w):
        if not isinstance(w, LoRAWeight):
            return w
        base = dequantize(w.base) if isinstance(w.base, QTensor) else w.base
        delta = jnp.einsum(
            "...ir,...ro->...io", w.a, w.b,
            preferred_element_type=jnp.float32,
        ) * w.scale[..., None, None]
        return (base.astype(jnp.float32) + delta).astype(base.dtype)

    out = dict(params)
    out["layers"] = {k: fold(v) for k, v in params["layers"].items()}
    return out


def lora_trainable_mask(params: Any) -> Any:
    """Pytree of bools marking the adapter (a/b) leaves — what
    :func:`split_trainable` partitions on (base weights and everything
    else are False); also usable directly as an ``optax.masked`` mask."""

    def mask_node(node):
        if isinstance(node, LoRAWeight):
            base_mask = jax.tree.map(lambda _: False, node.base)
            return LoRAWeight(base_mask, True, True, False)  # noqa: FBT003
        return jax.tree.map(lambda _: False, node)

    return {
        k: ({kk: mask_node(vv) for kk, vv in v.items()} if k == "layers"
            else jax.tree.map(lambda _: False, v))
        for k, v in params.items()
    }


def split_trainable(params: Any):
    """Partition an adapted tree into ``(trainable_leaves, rebuild)``:
    ``trainable_leaves`` is the flat list of adapter (a/b) arrays and
    ``rebuild(new_leaves)`` reassembles the full tree. Differentiating
    through ``rebuild`` keeps frozen leaves (including int8 QLoRA bases,
    which ``jax.grad`` rejects as inputs) out of the grad computation
    entirely, and the optimizer state covers exactly the adapters."""
    mask_flat = jax.tree.leaves(lora_trainable_mask(params))
    flat, treedef = jax.tree.flatten(params)
    assert len(flat) == len(mask_flat)
    trainable = [x for x, m in zip(flat, mask_flat) if m]
    frozen = [x for x, m in zip(flat, mask_flat) if not m]

    def rebuild(trainable_new):
        it_t, it_f = iter(trainable_new), iter(frozen)
        return jax.tree.unflatten(
            treedef, [next(it_t) if m else next(it_f) for m in mask_flat]
        )

    return trainable, rebuild


def make_lora_train_step(cfg, lr: float = 1e-4, attn_fn: Any = None,
                         mesh: Any = None):
    """Fine-tuning step over an adapted param tree: returns
    ``(init_state, step)`` like :func:`..parallel.sharding.make_train_step`
    but differentiating and optimizing ONLY the adapter leaves
    (:func:`split_trainable`); the frozen base never enters ``jax.grad``
    — which is also what makes int8 QLoRA bases trainable-over.

    ``mesh``: multi-chip fine-tuning. ``init_state`` places the adapted
    tree by its layout-aware specs (``parallel.sharding.param_specs`` —
    bases by PARAM_RULES including int8 QTensors, ``a`` on the in-axis,
    ``b`` on the out-axis sharding), the Adam moments inherit the adapter
    shardings through ``optimizer.init`` on the sharded leaves, and the
    jitted step runs GSPMD — fine-tune Llama-scale bases on a slice with
    the base fsdp-sharded instead of replicated. Shard token batches with
    ``parallel.shard_batch``."""
    import optax

    from ..models.transformer import next_token_loss

    optimizer = optax.adamw(lr)

    def init_state(params):
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.sharding import shard_params

            params = shard_params(params, mesh)
            trainable, _ = split_trainable(params)
            opt = optimizer.init(trainable)  # moments inherit leaf shardings
            # Scalar leaves (adamw count, the step counter) must be
            # mesh-REPLICATED like make_train_step's: a restored checkpoint
            # otherwise mixes single-device and mesh-committed arrays,
            # which jit rejects.
            rep = NamedSharding(mesh, PartitionSpec())
            opt = jax.tree.map(
                lambda x: jax.device_put(x, rep) if jnp.ndim(x) == 0 else x,
                opt,
            )
            step0 = jax.device_put(jnp.zeros((), jnp.int32), rep)
            return {"params": params, "opt": opt, "step": step0}
        trainable, _ = split_trainable(params)
        return {"params": params, "opt": optimizer.init(trainable),
                "step": jnp.int32(0)}

    # NOT donated: state["params"] holds the frozen base, which callers
    # still reference (donating it would invalidate their arrays for the
    # ~0.1%-of-model-size adapter update it could save).
    @jax.jit
    def step(state, tokens):
        trainable, rebuild = split_trainable(state["params"])
        loss, grads = jax.value_and_grad(
            lambda t: next_token_loss(rebuild(t), tokens, cfg, attn_fn=attn_fn)
        )(trainable)
        updates, new_opt = optimizer.update(grads, state["opt"], trainable)
        new_trainable = optax.apply_updates(trainable, updates)
        return {"params": rebuild(new_trainable), "opt": new_opt,
                "step": state["step"] + 1}, loss

    return init_state, step
