"""Weight-only int8 quantization for the bandwidth-bound decode path.

Greedy decode streams every weight byte from HBM once per step (BASELINE.md
roofline), so halving the bytes nearly halves the step time — the classic
weight-only-quantization serving trade. This module quantizes the decoder's
layer weight matrices to symmetric per-output-channel int8:

    scale[out] = max(|w[:, out]|) / 127        (fp32)
    q[in, out] = round(w[in, out] / scale[out])  (int8)

and the matmul applies the scale AFTER the dot — ``x @ (q·s) == (x @ q) · s``
when ``s`` varies only over the output axis — so the weights are streamed
from HBM as int8 and cast to bf16 on the fly inside the fused matmul; the
fp32 scale multiply touches only the tiny ``[B, 1, out]`` activation.

Scope: inference only, the layer weight stacks — dense matrices AND MoE
expert stacks (per-expert scales; the router stays fp so routing is
untouched). The norms and embedding keep their original dtype; the tied
unembedding is the embedding and is left bf16 so logit quality is
unaffected. Quantize AFTER
:func:`..models.transformer.fuse_decoder_params` — fusing concatenates raw
weight matrices.

The reference has no quantization (or any ML code — SURVEY §2); this is the
"actually fast" axis of the TPU-first rebuild, same as the pallas kernels.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

# Layer-dict keys eligible for weight-only quantization: matmul/einsum
# weight operands streamed every decode step. MoE expert stacks quantize
# with per-expert, per-output-channel scales ([L, E, 1, out] — the default
# axis=-2 reduction); the tiny router stays fp so top-k routing decisions
# are untouched by quantization error. Norm scales are 1-D (and numerically
# load-bearing) — never quantized.
QUANTIZABLE = ("wqkv", "wq", "wk", "wv", "wo", "w_gateup", "w_gate", "w_up",
               "w_down", "moe_w_gate", "moe_w_in", "moe_w_out")


class QTensor(NamedTuple):
    """A symmetric per-channel int8 weight: ``deq = q * scale`` with ``scale``
    broadcastable against ``q`` (NamedTuple ⇒ automatic pytree, so QTensors
    ride through jit/scan/device_put like any array pair)."""

    q: jax.Array  # int8, original weight shape [..., in, out]
    scale: jax.Array  # fp32, [..., 1, out]


def quantize(w: jax.Array, axis: int = -2) -> QTensor:
    """Symmetric int8 quantization, reducing |w| over ``axis`` (default: the
    input/contraction axis, giving one scale per output channel)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q.astype(jnp.int8), scale)


def dequantize(qt: QTensor, dtype=jnp.float32) -> jax.Array:
    return (qt.q.astype(jnp.float32) * qt.scale).astype(dtype)


# Snapshotted at import, NOT read per trace: a per-trace env read means
# toggling the variable after the first compile silently has no effect on
# cached executables while newly traced call sites pick it up — mixed-mode
# programs with no error. One snapshot per process is unambiguous; in-
# process harnesses toggle explicitly via set_w8a8() (which documents the
# retrace requirement) instead of mutating the environment.
_W8A8 = __import__("os").environ.get("KATA_TPU_W8A8", "") == "1"


def set_w8a8(on: bool) -> None:
    """Programmatic W8A8 toggle for harnesses (bench, eval_quality).
    Affects only executables traced AFTER the call — jit-cached
    executables keep the mode they were traced with, so flip the flag
    before building the variant's (fresh) jitted callables."""
    global _W8A8
    _W8A8 = bool(on)


def w8a8_enabled() -> bool:
    """Opt-in int8×int8 decode dots (``KATA_TPU_W8A8=1`` at process start,
    or :func:`set_w8a8`): activations quantize per-vector on the fly and
    the dot runs int8×int8→int32 on the MXU's int8 mode, removing the
    int8→bf16 weight-convert from the streamed path (VERDICT r3: the
    convert tax is ~10 points of the int8 roofline). Costs activation-
    quantization error — measure quality per model before enabling in
    production: ``scripts/eval_quality.py`` (``make eval``) runs the
    bf16/int8/W8A8/int8-KV ladder and reports delta-CE, logit drift, and
    top-1 agreement vs the bf16 baseline."""
    return _W8A8


def broadcast_trailing(s: jax.Array, ndim: int) -> jax.Array:
    """``[..., d]`` → ``[..., 1, ..., d]`` at rank ``ndim``: the explicit
    trailing-dim broadcast, legal under strict mode's
    rank_promotion="raise" (identical values — implicit rank promotion
    would have inserted the same axes). Leading (e.g. per-expert) dims
    are preserved; a value already at rank passes through. The ONE
    implementation for every scale/bias/norm broadcast in the decoder
    (rms_norm, rope, ring_positions, qkv biases, int8 scales)."""
    if s.ndim >= ndim:
        return s
    return s.reshape(s.shape[:-1] + (1,) * (ndim - s.ndim) + s.shape[-1:])


def weight_matmul(x: jax.Array, w: Any) -> jax.Array:
    """The one ``activation @ weight`` used by the decoder layer: a plain
    cast-to-activation-dtype matmul for arrays; for :class:`QTensor` the
    int8-streaming form ``(x @ q) * scale`` — the int8→bf16 cast fuses into
    the matmul's weight read, so HBM traffic is the int8 bytes (or, under
    :func:`w8a8_enabled`, a full int8×int8 dot with both scales applied
    post-hoc); for :class:`.lora.LoRAWeight` the
    frozen-base-plus-low-rank-delta form."""
    if isinstance(w, QTensor):
        if w8a8_enabled():
            xq = quantize(x, axis=-1)  # per-vector activation scales
            y = jax.lax.dot_general(
                xq.q, w.q,
                (((xq.q.ndim - 1,), (w.q.ndim - 2,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            # x-scale broadcasts over the out axis, w-scale over the rows.
            return (
                y.astype(jnp.float32) * xq.scale
                * broadcast_trailing(w.scale[..., 0, :], y.ndim)
            ).astype(x.dtype)
        y = jnp.matmul(
            x, w.q.astype(x.dtype), preferred_element_type=jnp.float32
        )
        return (y * broadcast_trailing(w.scale[..., 0, :], y.ndim)).astype(x.dtype)
    if isinstance(w, tuple):  # LoRAWeight (import deferred: lora → quant)
        from .lora import LoRAWeight, lora_matmul

        if isinstance(w, LoRAWeight):
            return lora_matmul(x, w)
        raise TypeError(f"unknown weight wrapper {type(w).__name__}")
    return x @ w.astype(x.dtype)


def quantize_decoder_params(params: dict) -> dict:
    """Quantize a decoder param pytree's layer weight matrices to int8
    (:data:`QUANTIZABLE` keys; everything else passes through). Works on both
    the training layout (separate wq/wk/wv) and the fused inference layout
    from :func:`..models.transformer.fuse_decoder_params` — fuse first, the
    fused layout is both faster and quantizes to fewer tensors."""
    layers = params["layers"]
    if any(isinstance(v, QTensor) for v in layers.values()):
        return params  # already quantized
    if any(isinstance(v, tuple) for v in layers.values()):
        # Quantizing AROUND live adapters would silently leave the wrapped
        # (dominant) weights unquantized. Both correct orders exist:
        raise ValueError(
            "params contain LoRA adapters: for QLoRA quantize FIRST then "
            "apply_lora; for int8 serving of a tuned model merge_lora "
            "first, then quantize"
        )
    out_layers = {
        k: (quantize(v) if k in QUANTIZABLE else v) for k, v in layers.items()
    }
    out = dict(params)
    out["layers"] = out_layers
    return out


def quantize_kv(x: jax.Array) -> QTensor:
    """Quantize fresh k/v vectors for an int8 KV cache: one symmetric scale
    per (batch, position, kv-head) vector — amax over the head_dim axis.
    x: [..., KV, D] → QTensor(q [..., KV, D] int8, scale [..., KV, 1])."""
    return quantize(x, axis=-1)


def dequantize_kv(cache: "QTensor | jax.Array", dtype) -> jax.Array:
    """Read side of the int8 KV cache: a no-op for plain arrays; for
    QTensors the int8·scale multiply stays an elementwise producer that XLA
    fuses into the attention dots — the bf16 cache never materializes in
    HBM, so cache read traffic is the int8 bytes plus scales. The multiply
    runs in fp32 (like :func:`dequantize`): casting the fp32 scale down to
    bf16 first would stack ~0.2% scale truncation on the int8 error."""
    if isinstance(cache, QTensor):
        return (cache.q.astype(jnp.float32) * cache.scale).astype(dtype)
    return cache


def params_hbm_bytes(params: Any) -> int:
    """Bytes a decode step streams for the weights: the actual pytree leaf
    sizes (int8 payloads + their scales included) — the honest denominator
    for a quantized roofline, vs assuming 2 bytes/param."""
    return sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
    )
