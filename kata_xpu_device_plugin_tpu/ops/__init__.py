"""TPU compute ops: attention implementations (XLA reference, pallas flash),
collective helpers, the expert-parallel MoE FFN, and weight-only int8
quantization for the bandwidth-bound decode path."""
from .attention import best_attention, flash_attention, reference_attention
from .collectives import (
    all_gather,
    mesh_all_reduce,
    pmap_all_reduce,
    reduce_scatter,
    ring_all_reduce,
)
from .moe import (
    AXIS_EXPERT,
    MoEConfig,
    dispatch_shardable,
    expert_mesh,
    init_moe_params,
    moe_ffn,
    moe_ffn_sharded,
    moe_param_specs,
    reference_moe,
)
from .lora import (
    LoRAWeight,
    apply_lora,
    lora_trainable_mask,
    make_lora_train_step,
    merge_lora,
    split_trainable,
)
from .quant import (
    QTensor,
    dequantize,
    dequantize_kv,
    params_hbm_bytes,
    quantize,
    quantize_decoder_params,
    quantize_kv,
    weight_matmul,
)

__all__ = [
    "LoRAWeight",
    "apply_lora",
    "lora_trainable_mask",
    "make_lora_train_step",
    "merge_lora",
    "split_trainable",
    "QTensor",
    "dequantize",
    "dequantize_kv",
    "params_hbm_bytes",
    "quantize",
    "quantize_decoder_params",
    "quantize_kv",
    "weight_matmul",
    "best_attention",
    "flash_attention",
    "reference_attention",
    "all_gather",
    "mesh_all_reduce",
    "pmap_all_reduce",
    "reduce_scatter",
    "ring_all_reduce",
    "AXIS_EXPERT",
    "MoEConfig",
    "dispatch_shardable",
    "expert_mesh",
    "init_moe_params",
    "moe_ffn",
    "moe_ffn_sharded",
    "moe_param_specs",
    "reference_moe",
]
