"""TPU compute ops: attention implementations (XLA reference, pallas flash)
and collective helpers."""
from .attention import best_attention, flash_attention, reference_attention
from .collectives import (
    all_gather,
    mesh_all_reduce,
    pmap_all_reduce,
    reduce_scatter,
    ring_all_reduce,
)

__all__ = [
    "best_attention",
    "flash_attention",
    "reference_attention",
    "all_gather",
    "mesh_all_reduce",
    "pmap_all_reduce",
    "reduce_scatter",
    "ring_all_reduce",
]
