"""Kubelet device-plugin layer: gRPC server, allocation policies, health
watching, and the discovery→CDI→serve orchestration (counterpart of the
reference's ``pkg/device_plugin``)."""
from .allocators import TpuAllocator, VfioAllocator
from .health import HealthWatcher
from .manager import (
    AllocationJournal,
    PluginManager,
    build_tpu_spec,
    build_vfio_spec,
)
from .server import AllocationError, DevicePluginServer, DeviceState, WatchedDevice

__all__ = [
    "AllocationJournal",
    "TpuAllocator",
    "VfioAllocator",
    "HealthWatcher",
    "PluginManager",
    "build_tpu_spec",
    "build_vfio_spec",
    "AllocationError",
    "DevicePluginServer",
    "DeviceState",
    "WatchedDevice",
]
