"""Device and socket health watching.

Counterpart of the reference's fsnotify ``healthCheck`` goroutine
(``generic_device_plugin.go:389-457``): watches device nodes to flip
Healthy/Unhealthy in the ListAndWatch stream, and the plugin's own socket to
detect a kubelet restart and re-register. Differences:

- inotify (ctypes, :mod:`..utils.inotify`) *accelerates* a periodic existence
  poll rather than replacing it — char devices like ``/dev/accel*`` don't
  reliably emit create/remove the way ``/dev/vfio/<group>`` does (SURVEY §7
  "Hard parts"), and a poll converges even when events are lost;
- health is driver-level, not just dev-node existence (SURVEY §7 hard part
  #4), WITHOUT ever open()ing the nodes — probing an exclusive-open device
  (vfio groups, accel chips) would race the guest/VMM's own open and make
  VM startup fail transiently. Instead each chip additionally watches the
  kernel's driver-state paths: its ``/sys/class/accel`` entry (removed on
  driver unbind while the stale ``/dev`` node can linger) or, for
  vfio-bound chips, the ``/dev/vfio/<group>`` node the kernel removes on
  unbind (``tpu_watched_devices`` pairs them up);
- one watcher serves all plugins (the reference spawns one per plugin and
  leaks the old one on restart).
"""
from __future__ import annotations

import os
import threading
from typing import Sequence

from ..utils import inotify, log, metrics
from .api import glue
from .server import DevicePluginServer

LOG = log.get("health")


class HealthWatcher(threading.Thread):
    def __init__(
        self,
        plugins: Sequence[DevicePluginServer],
        poll_interval_s: float = 5.0,
        use_inotify: bool = True,
    ):
        super().__init__(name="health-watcher", daemon=True)
        self._plugins = list(plugins)
        self._poll_interval = poll_interval_s
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._ino: inotify.Inotify | None = None
        if use_inotify:
            try:
                self._ino = inotify.Inotify()
            except OSError as e:
                LOG.warning("inotify unavailable, polling only", extra=log.kv(err=str(e)))

    def add_plugin(self, plugin: DevicePluginServer) -> None:
        with self._lock:
            self._plugins.append(plugin)
        self._sync_watches()

    def remove_plugin(self, plugin: DevicePluginServer) -> None:
        with self._lock:
            if plugin in self._plugins:
                self._plugins.remove(plugin)

    def stop(self) -> None:
        self._stop.set()

    # ----- internals -------------------------------------------------------

    def _watched_dirs(self) -> set[str]:
        dirs: set[str] = set()
        with self._lock:
            plugins = list(self._plugins)
        for p in plugins:
            dirs.add(p.socket_dir)
            for dev in p.state.snapshot():
                for path in dev.watch_paths:
                    dirs.add(os.path.dirname(path))
        return dirs

    def _sync_watches(self) -> None:
        if self._ino is None:
            return
        for d in self._watched_dirs():
            if os.path.isdir(d):
                try:
                    self._ino.add_watch(d)
                except OSError:
                    pass

    def run(self) -> None:
        self._sync_watches()
        while not self._stop.is_set():
            if self._ino is not None:
                # Block on events up to the poll interval, then evaluate:
                # events make reaction immediate, the poll makes it converge.
                self._ino.read_events(timeout=self._poll_interval)
            else:
                self._stop.wait(self._poll_interval)
            if self._stop.is_set():
                return
            self.evaluate()
            self._sync_watches()  # directories may have (re)appeared

    def evaluate(self) -> None:
        """One convergence pass; also called directly by tests for determinism."""
        with self._lock:
            plugins = list(self._plugins)
        for plugin in plugins:
            if plugin.stopped:
                continue
            for dev in plugin.state.snapshot():
                if not dev.watch_paths:
                    continue
                alive = all(os.path.exists(p) for p in dev.watch_paths)
                health = glue.HEALTHY if alive else glue.UNHEALTHY
                if plugin.state.set_health(dev.id, health):
                    metrics.health_transitions_total.labels(
                        resource=plugin.resource_name, to=health
                    ).inc()
                    LOG.info(
                        "device health changed",
                        extra=log.kv(
                            resource=plugin.resource_name, device=dev.id, health=health
                        ),
                    )
            # Kubelet restart wipes the plugin-socket dir (ref :444-453).
            if plugin.serving and not os.path.exists(plugin.socket_path):
                LOG.info(
                    "plugin socket removed (kubelet restart?), re-registering",
                    extra=log.kv(resource=plugin.resource_name),
                )
                try:
                    plugin.restart()
                except Exception as e:
                    LOG.error(
                        "plugin restart failed",
                        extra=log.kv(resource=plugin.resource_name, err=str(e)),
                    )
