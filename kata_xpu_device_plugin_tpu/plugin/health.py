"""Device and socket health watching.

Counterpart of the reference's fsnotify ``healthCheck`` goroutine
(``generic_device_plugin.go:389-457``): watches device nodes to flip
Healthy/Unhealthy in the ListAndWatch stream, and the plugin's own socket to
detect a kubelet restart and re-register. Differences:

- inotify (ctypes, :mod:`..utils.inotify`) *accelerates* a periodic existence
  poll rather than replacing it — char devices like ``/dev/accel*`` don't
  reliably emit create/remove the way ``/dev/vfio/<group>`` does (SURVEY §7
  "Hard parts"), and a poll converges even when events are lost;
- health is driver-level, not just dev-node existence (SURVEY §7 hard part
  #4), via two complementary signals. Each chip watches the kernel's
  driver-state paths alongside its dev node: its ``/sys/class/accel`` entry
  (removed on driver unbind while the stale ``/dev`` node can linger) or,
  for vfio-bound chips, the ``/dev/vfio/<group>`` node the kernel removes
  on unbind (``tpu_watched_devices`` pairs them up). On top of existence,
  :func:`node_alive` classifies char devices by probing with a
  non-blocking ``open()``: an orphaned inode whose driver is gone answers
  ``ENXIO``/``ENODEV`` (dead) even though the path exists, while a node
  held exclusively by a guest answers ``EBUSY`` (alive). The probe is
  never aimed at a device that currently looks healthy — that would race
  the VMM's exclusive open every poll — only at confirming recovery of an
  Unhealthy one, and at allocate time (before any guest holds the node);
- one watcher serves all plugins (the reference spawns one per plugin and
  leaks the old one on restart).
"""
from __future__ import annotations

import errno
import os
import stat
import threading
import time
from typing import Sequence

from .. import obs
from ..utils import inotify, log, metrics
from .api import glue
from .server import DevicePluginServer

LOG = log.get("health")

#: errnos from open(2) on a char device that mean "the driver behind this
#: inode is gone" — the node is a leftover the unbind didn't clean up.
_ORPHANED_ERRNOS = frozenset({errno.ENXIO, errno.ENODEV})


def node_alive(path: str) -> bool:
    """Driver-level liveness of a device path (ref re-validates sysfs at
    allocate time, ``generic_device_plugin.go:329-338``; for ``/dev/accel*``
    the equivalent signal lives behind the inode, not in the path).

    - missing path → dead;
    - regular files / directories / sysfs entries → existence is the signal;
    - char devices → a non-blocking ``open()`` probe, classified by errno:
      ``ENXIO``/``ENODEV`` mean the driver no longer backs the inode (dead);
      anything else — notably ``EBUSY``/``EACCES`` from a guest's exclusive
      open — means a live driver answered (alive). A successful open is
      closed immediately.
    """
    try:
        st = os.stat(path)
    except OSError:
        return False
    if not stat.S_ISCHR(st.st_mode):
        return True
    try:
        fd = os.open(path, os.O_RDONLY | os.O_NONBLOCK | os.O_CLOEXEC)
    except OSError as e:
        return e.errno not in _ORPHANED_ERRNOS
    os.close(fd)
    return True


class HealthWatcher(threading.Thread):
    def __init__(
        self,
        plugins: Sequence[DevicePluginServer],
        poll_interval_s: float = 5.0,
        use_inotify: bool = True,
        restart_backoff_s: float = 1.0,
        restart_backoff_max_s: float = 60.0,
        clock=time.monotonic,
    ):
        super().__init__(name="health-watcher", daemon=True)
        self._plugins = list(plugins)
        self._poll_interval = poll_interval_s
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Restart retry state (ISSUE 7 satellite): a failed
        # plugin.restart() used to be logged once and forgotten until the
        # next *socket event* — with events lost (char devices are flaky
        # emitters) the plugin stayed dead indefinitely. Now every
        # evaluate() pass re-offers the restart under bounded exponential
        # backoff: {id(plugin): (consecutive_failures, not_before_t)}.
        self._restart_backoff_s = restart_backoff_s
        self._restart_backoff_max_s = restart_backoff_max_s
        self._restart_state: dict[int, tuple[int, float]] = {}
        self._clock = clock
        self._ino: inotify.Inotify | None = None
        if use_inotify:
            try:
                self._ino = inotify.Inotify()
            except OSError as e:
                LOG.warning("inotify unavailable, polling only", extra=log.kv(err=str(e)))

    def add_plugin(self, plugin: DevicePluginServer) -> None:
        with self._lock:
            self._plugins.append(plugin)
        self._sync_watches()

    def remove_plugin(self, plugin: DevicePluginServer) -> None:
        with self._lock:
            if plugin in self._plugins:
                self._plugins.remove(plugin)

    def stop(self) -> None:
        self._stop.set()

    # ----- internals -------------------------------------------------------

    def _watched_dirs(self) -> set[str]:
        dirs: set[str] = set()
        with self._lock:
            plugins = list(self._plugins)
        for p in plugins:
            dirs.add(p.socket_dir)
            for dev in p.state.snapshot():
                for path in dev.watch_paths:
                    dirs.add(os.path.dirname(path))
        return dirs

    def _sync_watches(self) -> None:
        if self._ino is None:
            return
        for d in self._watched_dirs():
            if os.path.isdir(d):
                try:
                    self._ino.add_watch(d)
                except OSError:
                    pass

    def run(self) -> None:
        self._sync_watches()
        while not self._stop.is_set():
            if self._ino is not None:
                # Block on events up to the poll interval, then evaluate:
                # events make reaction immediate, the poll makes it converge.
                self._ino.read_events(timeout=self._poll_interval)
            else:
                self._stop.wait(self._poll_interval)
            if self._stop.is_set():
                return
            self.evaluate()
            self._sync_watches()  # directories may have (re)appeared

    def evaluate(self) -> None:
        """One convergence pass; also called directly by tests for determinism."""
        with self._lock:
            plugins = list(self._plugins)
        for plugin in plugins:
            if plugin.stopped:
                # A stopped plugin no longer serves or watches anything:
                # its gauge must not keep reporting the last live count.
                metrics.chips_quarantined.labels(
                    resource=plugin.resource_name
                ).set(0)
                continue
            unhealthy = 0
            for dev in plugin.state.snapshot():
                if not dev.watch_paths:
                    if dev.health == glue.UNHEALTHY:
                        unhealthy += 1
                    continue
                # Existence of the dev+driver-state pair decides steady-state
                # health WITHOUT open()ing anything: probing a healthy,
                # possibly guest-held node every poll would race the VMM's
                # exclusive open (the watcher winning the race fails VM
                # startup). The open-probe classifier runs only to confirm
                # RECOVERY of an already-Unhealthy device — a lingering node
                # must answer open(2) (or be guest-held, EBUSY) before it
                # flips back to Healthy — and at allocate time
                # (``manager.tpu_chip_alive``), which runs before any guest
                # can hold the node.
                alive = all(os.path.exists(p) for p in dev.watch_paths)
                if alive and dev.health == glue.UNHEALTHY:
                    alive = all(node_alive(p) for p in dev.watch_paths)
                health = glue.HEALTHY if alive else glue.UNHEALTHY
                if health == glue.UNHEALTHY:
                    unhealthy += 1
                if plugin.state.set_health(dev.id, health):
                    metrics.health_transitions_total.labels(
                        resource=plugin.resource_name, to=health
                    ).inc()
                    # Per-chip quarantine contract (ISSUE 10): one event
                    # per flip, so the guest-side tp_degraded stream and
                    # the daemon-side quarantine stream can be joined on
                    # the same chip-loss incident. Re-admission (the
                    # open-probe recovery classifier above) events too —
                    # a flap is visible as the pair, not silence.
                    obs.emit(
                        "plugin",
                        "chip_quarantined" if health == glue.UNHEALTHY
                        else "chip_readmitted",
                        resource=plugin.resource_name, device=dev.id,
                    )
                    LOG.info(
                        "device health changed",
                        extra=log.kv(
                            resource=plugin.resource_name, device=dev.id, health=health
                        ),
                    )
            metrics.chips_quarantined.labels(
                resource=plugin.resource_name
            ).set(unhealthy)
            # Kubelet restart wipes the plugin-socket dir (ref :444-453).
            if plugin.serving and not os.path.exists(plugin.socket_path):
                self._try_restart(plugin)

    def _try_restart(self, plugin: DevicePluginServer) -> bool:
        """One bounded-backoff restart offer. A failure schedules the next
        attempt (exponential, capped) and is re-offered by every later
        evaluate() pass — the periodic poll guarantees convergence even
        when no further socket event arrives; success clears the backoff.
        Both outcomes land on ``plugin_restarts_total{ok=...}`` and
        failures additionally emit a ``plugin_restart_failed`` obs
        event."""
        # Backoff state joins _plugins under this class's lock: the
        # watcher thread is its only writer today, but add()/remove()
        # callers share the instance and the map must not be one
        # refactor away from a torn read.
        with self._lock:
            fails, not_before = self._restart_state.get(
                id(plugin), (0, 0.0)
            )
        now = self._clock()
        if now < not_before:
            return False  # backing off; a later pass re-offers
        LOG.info(
            "plugin socket removed (kubelet restart?), re-registering",
            extra=log.kv(resource=plugin.resource_name, attempt=fails + 1),
        )
        try:
            plugin.restart()
        except Exception as e:
            fails += 1
            delay = min(
                self._restart_backoff_s * (2 ** (fails - 1)),
                self._restart_backoff_max_s,
            )
            with self._lock:
                self._restart_state[id(plugin)] = (fails, now + delay)
            metrics.plugin_restarts_total.labels(
                resource=plugin.resource_name, ok="false"
            ).inc()
            obs.emit(
                "plugin", "plugin_restart_failed",
                resource=plugin.resource_name, attempt=fails,
                err=str(e)[:200], retry_in_s=round(delay, 3),
            )
            LOG.error(
                "plugin restart failed",
                extra=log.kv(
                    resource=plugin.resource_name, err=str(e),
                    attempt=fails, retry_in_s=delay,
                ),
            )
            return False
        with self._lock:
            self._restart_state.pop(id(plugin), None)
        metrics.plugin_restarts_total.labels(
            resource=plugin.resource_name, ok="true"
        ).inc()
        return True
