"""Hand-written gRPC service/stub bindings over the generated pb2 messages.

``grpcio-tools`` is not a runtime dependency; the handful of method bindings
the kubelet APIs need are clearer written out than generated. Method paths
(``/v1beta1.DevicePlugin/...``) are the wire contract with the kubelet and
must not change.
"""
from __future__ import annotations

import grpc

from . import deviceplugin_pb2 as pb
from . import podresources_pb2 as prpb

DEVICE_PLUGIN_VERSION = "v1beta1"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

# Kubelet filesystem contract (ref generic_device_plugin.go:76,201).
KUBELET_SOCKET_DIR = "/var/lib/kubelet/device-plugins"
KUBELET_SOCKET = f"{KUBELET_SOCKET_DIR}/kubelet.sock"
POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"

_REG = "v1beta1.Registration"
_DP = "v1beta1.DevicePlugin"
_PR = "v1alpha1.PodResourcesLister"


class RegistrationServicer:
    """Kubelet-side Register endpoint; subclassed by the fake kubelet in tests."""

    def Register(self, request: pb.RegisterRequest, context) -> pb.Empty:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        return pb.Empty()


def add_registration_to_server(servicer: RegistrationServicer, server: grpc.Server) -> None:
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        )
    }
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(_REG, handlers),))


class RegistrationStub:
    """Client the plugin uses to register with the kubelet
    (ref generic_device_plugin.go:200-219)."""

    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{_REG}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )


class DevicePluginServicer:
    """Base for the plugin's kubelet-facing service
    (ref generic_device_plugin.go:222-386)."""

    def GetDevicePluginOptions(self, request: pb.Empty, context) -> pb.DevicePluginOptions:
        return pb.DevicePluginOptions()

    def ListAndWatch(self, request: pb.Empty, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        return iter(())

    def GetPreferredAllocation(
        self, request: pb.PreferredAllocationRequest, context
    ) -> pb.PreferredAllocationResponse:
        return pb.PreferredAllocationResponse()

    def Allocate(self, request: pb.AllocateRequest, context) -> pb.AllocateResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        return pb.AllocateResponse()

    def PreStartContainer(
        self, request: pb.PreStartContainerRequest, context
    ) -> pb.PreStartContainerResponse:
        return pb.PreStartContainerResponse()


def add_device_plugin_to_server(servicer: DevicePluginServicer, server: grpc.Server) -> None:
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=pb.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=pb.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(_DP, handlers),))


class DevicePluginStub:
    """Client side of the plugin service: used by the kubelet (and our fake
    kubelet tests, and the plugin's own readiness self-dial)."""

    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{_DP}/GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{_DP}/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{_DP}/GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{_DP}/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{_DP}/PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )


class PodResourcesListerServicer:
    """Kubelet-side pod-resources service; subclassed by the fake kubelet."""

    def List(self, request: prpb.ListPodResourcesRequest, context) -> prpb.ListPodResourcesResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        return prpb.ListPodResourcesResponse()


def add_pod_resources_to_server(servicer: PodResourcesListerServicer, server: grpc.Server) -> None:
    handlers = {
        "List": grpc.unary_unary_rpc_method_handler(
            servicer.List,
            request_deserializer=prpb.ListPodResourcesRequest.FromString,
            response_serializer=prpb.ListPodResourcesResponse.SerializeToString,
        )
    }
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(_PR, handlers),))


class PodResourcesListerStub:
    """Client for the kubelet pod-resources API (the reference's dead code,
    utils/pod_resources.go:41-61, made live by the `status` subcommand)."""

    def __init__(self, channel: grpc.Channel):
        self.List = channel.unary_unary(
            f"/{_PR}/List",
            request_serializer=prpb.ListPodResourcesRequest.SerializeToString,
            response_deserializer=prpb.ListPodResourcesResponse.FromString,
        )
