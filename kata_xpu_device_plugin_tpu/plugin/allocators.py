"""Resource-specific allocation policies.

Split out of the server (the reference fuses policy into the gRPC handlers,
``generic_device_plugin.go:274-355``): the server validates ids and streams
health; allocators decide CDI names, env and topology.

Env contract with the guest: *static* slice topology (accelerator type, host
bounds, worker id/hostnames, libtpu mount) rides the CDI spec's spec-level
``containerEdits`` — identical for every pod on the host; the *per-allocation*
``TPU_VISIBLE_CHIPS`` rides the AllocateResponse env, merged (the reference
overwrites the env map it just built — SURVEY §Quirks 4).
"""
from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

from .. import obs
from .. import topology as topo_mod
from ..cdi import constants as C
from ..cdi import qualified_name
from ..topology import runtime_env
from ..discovery.tpu import TpuInventory
from ..discovery.vfio import VfioInventory
from ..utils import log, metrics
from .api import deviceplugin_pb2 as pb
from .server import AllocationError

LOG = log.get("alloc")


class TpuAllocator:
    """Allocation policy for ``google.com/tpu``: chip ids are host-local
    indexes; preferred picks ICI-contiguous boxes; Allocate re-validates the
    chip's device node against the live host (ref re-validation at
    generic_device_plugin.go:329-338, done against /dev/accel instead)."""

    def __init__(
        self,
        inventory: Callable[[], TpuInventory],
        vendor: str,
        cls: str,
        strategies: Sequence[str] = (C.STRATEGY_CDI_CRI,),
        libtpu_host_path: str = "",
        revalidate: Optional[Callable[[object], bool]] = None,
        compile_cache_dir: str = "",
        prefix_cache_tokens: int = 0,
        kv_pool_tokens: int = 0,
        kv_quant: str = "",
        kv_layout: str = "",
        kv_host_tokens: int = 0,
        checkpoint_rounds: int = 0,
        fault_schedule: str = "",
        sched_policy: str = "",
        prefill_chunk: int = 0,
        itl_slo_ms: float = 0.0,
        decode_steps: int = 0,
        serving_tp: int = 0,
        serving_tp_min: int = 0,
        trace_context: bool = True,
        guest_events_dir: str = "",
        heartbeat_rounds: int = 0,
    ):
        self._inventory = inventory
        self._vendor = vendor
        self._cls = cls
        self._strategies = tuple(strategies)
        self._resource = f"{vendor}/{cls}"
        self._libtpu_host_path = libtpu_host_path
        # Guest-side persistent XLA compile cache (config.compile_cache_dir):
        # rides the AllocateResponse env so every granted workload points
        # jax's on-disk executable cache at the same per-node directory.
        self._compile_cache_dir = compile_cache_dir
        # Guest-side shared-prefix KV store default capacity
        # (config.prefix_cache_tokens): same delivery path — in-guest
        # GenerationServers read KATA_TPU_PREFIX_CACHE_TOKENS when no
        # explicit prefix_cache_tokens is passed.
        self._prefix_cache_tokens = int(prefix_cache_tokens)
        # Guest-side paged KV pool default capacity (config.kv_pool_tokens):
        # same delivery path — in-guest GenerationServers read
        # KATA_TPU_KV_POOL_TOKENS when no explicit kv_pool_tokens is passed.
        self._kv_pool_tokens = int(kv_pool_tokens)
        # KV-arena quantization policy (ISSUE 12, config.kv_quant): same
        # delivery path — the guest default is int8 (eval_quality-gated);
        # "bf16" opts the node out, "int8" pins it explicitly.
        self._kv_quant = str(kv_quant)
        # Paged-pool placement layout + host-RAM offload tier (ISSUE 14,
        # config.kv_layout / kv_host_tokens): same delivery path —
        # "blocks" shards the guest pool by physical blocks across the
        # serving mesh; kv_host_tokens arms the host-RAM tier cold KV
        # demotes to before preemption. Malformed/incompatible values
        # degrade in-guest with kv_layout_invalid / kv_layout_disabled /
        # kv_host_invalid / kv_host_disabled events.
        self._kv_layout = str(kv_layout)
        self._kv_host_tokens = int(kv_host_tokens)
        # Crash-tolerance knobs (ISSUE 7, config.checkpoint_rounds /
        # config.faults): recovery-checkpoint cadence and the chaos
        # fault schedule, same delivery path — in-guest servers read
        # KATA_TPU_CHECKPOINT_ROUNDS / KATA_TPU_FAULTS when the caller
        # passes nothing explicit.
        self._checkpoint_rounds = int(checkpoint_rounds)
        self._fault_schedule = str(fault_schedule)
        # SLO-aware admission scheduling (ISSUE 8, config.sched_policy /
        # prefill_chunk / itl_slo_ms): same delivery path — in-guest
        # servers read KATA_TPU_SCHED_POLICY / KATA_TPU_PREFILL_CHUNK /
        # KATA_TPU_ITL_SLO_MS when the caller passes nothing explicit;
        # unknown/incompatible values degrade in-guest with an event.
        self._sched_policy = str(sched_policy)
        self._prefill_chunk = int(prefill_chunk)
        self._itl_slo_ms = float(itl_slo_ms)
        # Multi-step decode multiplier (ISSUE 13, config.decode_steps):
        # same delivery path — in-guest servers run chunk × K decode
        # steps per dispatch when the caller passes nothing explicit.
        self._decode_steps = int(decode_steps)
        # Tensor-parallel serving override (ISSUE 9, config.serving_tp):
        # same delivery path — in-guest servers mesh the granted slice by
        # default (guest/tp_serving.py derives the degree from
        # TPU_VISIBLE_CHIPS); KATA_TPU_TP pins it node-wide.
        self._serving_tp = int(serving_tp)
        # Degraded-mode shrink floor (ISSUE 10, config.serving_tp_min):
        # same delivery path — in-guest servers stop the chip-loss
        # mesh-shrink ladder at this degree (guest/tp_serving.py).
        self._serving_tp_min = int(serving_tp_min)
        # Per-allocation trace context (ISSUE 11, config.trace_context):
        # each Allocate stamps its own span's trace id (or a fresh one
        # when no span is open) into KATA_TPU_TRACE_CTX, so the guest's
        # serving telemetry joins the daemon's allocation trace.
        self._trace_context = bool(trace_context)
        # Guest telemetry uplink (ISSUE 15, config.guest_events_dir /
        # heartbeat_rounds): each Allocate switches the guest's JSONL
        # event stream on and points it at a per-allocation file under
        # the shared dir, so the manager's heartbeat aggregator can tail
        # serving heartbeats back out — the upward twin of the trace
        # handoff above. heartbeat_rounds > 0 additionally pins the
        # in-guest heartbeat cadence node-wide.
        self._guest_events_dir = str(guest_events_dir)
        self._heartbeat_rounds = int(heartbeat_rounds)
        # Driver-level liveness check supplied by the manager
        # (``manager.tpu_chip_alive``: node_alive over the same
        # dev+driver-state pair health watches); bare existence would hand a
        # pod the orphaned node a driver unbind leaves behind. The
        # existence-only fallback applies only to direct construction in
        # tests.
        self._revalidate = revalidate or (lambda chip: os.path.exists(chip.dev_path))

    def allocate(self, device_ids: Sequence[str]) -> pb.ContainerAllocateResponse:
        inv = self._inventory()
        chips = []
        for dev_id in device_ids:
            if not dev_id.isdigit():
                raise AllocationError(f"malformed TPU device id {dev_id!r}")
            try:
                chip = inv.chip(int(dev_id))
            except KeyError:
                raise AllocationError(f"TPU chip {dev_id} not in current inventory")
            if not self._revalidate(chip):
                raise AllocationError(f"TPU chip {dev_id} failed liveness re-validation")
            chips.append(chip)

        resp = pb.ContainerAllocateResponse()
        names = [qualified_name(self._vendor, self._cls, str(c.index)) for c in chips]
        if C.STRATEGY_CDI_CRI in self._strategies:
            for name in names:
                resp.cdi_devices.add(name=name)
        if C.STRATEGY_CDI_ANNOTATIONS in self._strategies:
            resp.annotations[f"{C.CDI_K8S_PREFIX}{self._vendor}_{self._cls}"] = ",".join(names)
        if C.STRATEGY_ENVVAR in self._strategies:
            # Direct injection for runtimes without CDI: everything the CDI
            # spec's containerEdits would carry — device nodes, the libtpu
            # mount, and the static slice-topology env — must ride the
            # AllocateResponse itself, or libtpu in the pod can't bring up ICI.
            for c in chips:
                resp.devices.add(
                    container_path=c.dev_path, host_path=c.dev_path, permissions="rw"
                )
            for key, val in runtime_env(inv.topology).items():
                resp.envs[key] = val
            if self._libtpu_host_path and os.path.exists(self._libtpu_host_path):
                resp.mounts.add(
                    container_path=C.LIBTPU_CONTAINER_PATH,
                    host_path=self._libtpu_host_path,
                    read_only=True,
                )
                resp.envs[C.LIBTPU_ENV] = C.LIBTPU_CONTAINER_PATH
        resp.envs[C.ENV_CDI_VENDOR_CLASS] = self._resource
        resp.envs[C.ENV_TPU_VISIBLE_CHIPS] = ",".join(str(c.index) for c in chips)
        if self._trace_context:
            # The daemon→guest trace-context handoff (ISSUE 11): inside
            # the gRPC handler this is the plugin.Allocate span's trace
            # id, so everything the guest emits under it — request
            # lifecycle traces, recovery/degraded events, flight-recorder
            # dumps — joins the allocation's trace; a direct (test) call
            # with no open span mints a fresh id, which still gives every
            # workload of the allocation one shared join key.
            resp.envs[C.ENV_TRACE_CTX] = (
                obs.current_trace_id() or obs.new_trace()
            )
        if self._guest_events_dir:
            # Per-allocation heartbeat stream (ISSUE 15): the file name
            # carries the granted chip set — the same identity the
            # journal records and the heartbeat's own "chips" field
            # reports — so the aggregator can label gauges even for a
            # stream that dies before its first heartbeat.
            ident = "-".join(str(c.index) for c in chips)
            resp.envs[C.ENV_OBS] = "1"
            resp.envs[C.ENV_OBS_FILE] = os.path.join(
                self._guest_events_dir, f"guest_{ident}.jsonl"
            )
        if self._heartbeat_rounds > 0:
            resp.envs[C.ENV_HEARTBEAT_ROUNDS] = str(self._heartbeat_rounds)
        if self._compile_cache_dir:
            resp.envs[C.ENV_COMPILE_CACHE_DIR] = self._compile_cache_dir
        if self._prefix_cache_tokens > 0:
            resp.envs[C.ENV_PREFIX_CACHE_TOKENS] = str(
                self._prefix_cache_tokens
            )
        if self._kv_pool_tokens > 0:
            resp.envs[C.ENV_KV_POOL_TOKENS] = str(self._kv_pool_tokens)
        if self._kv_quant:
            resp.envs[C.ENV_KV_QUANT] = self._kv_quant
        if self._kv_layout:
            resp.envs[C.ENV_KV_LAYOUT] = self._kv_layout
        if self._kv_host_tokens > 0:
            resp.envs[C.ENV_KV_HOST_TOKENS] = str(self._kv_host_tokens)
        if self._checkpoint_rounds > 0:
            resp.envs[C.ENV_CHECKPOINT_ROUNDS] = str(self._checkpoint_rounds)
        if self._fault_schedule:
            resp.envs[C.ENV_FAULT_SCHEDULE] = self._fault_schedule
        if self._sched_policy:
            resp.envs[C.ENV_SCHED_POLICY] = self._sched_policy
        if self._prefill_chunk > 0:
            resp.envs[C.ENV_PREFILL_CHUNK] = str(self._prefill_chunk)
        if self._itl_slo_ms > 0:
            resp.envs[C.ENV_ITL_SLO_MS] = str(self._itl_slo_ms)
        if self._decode_steps > 1:
            resp.envs[C.ENV_DECODE_STEPS] = str(self._decode_steps)
        if self._serving_tp_min > 0:
            resp.envs[C.ENV_SERVING_TP_MIN] = str(self._serving_tp_min)
        if self._serving_tp > 0:
            resp.envs[C.ENV_SERVING_TP] = str(self._serving_tp)
            if self._serving_tp > len(chips):
                # The override exceeds what this allocation can mesh: the
                # guest will degrade to tp=1 with a tp_disabled event
                # (guest/tp_serving.py clamps to real devices) — flag the
                # misconfiguration host-side too so the operator sees it
                # before reading guest event streams.
                LOG.warning(
                    "serving-tp exceeds the granted chip count; guest "
                    "will degrade to single-chip serving",
                    extra=log.kv(
                        serving_tp=self._serving_tp, chips=len(chips)
                    ),
                )
        return resp

    def preferred(
        self, available: Sequence[str], must_include: Sequence[str], size: int
    ) -> list[str]:
        inv = self._inventory()
        placement = topo_mod.choose_chips(
            inv.topology,
            topo_mod.chip_ids_to_indexes(available),
            size,
            topo_mod.chip_ids_to_indexes(must_include),
        )
        if not placement.contiguous:
            metrics.noncontiguous_allocations_total.labels(resource=self._resource).inc()
            LOG.warning(
                "no ICI-contiguous placement possible",
                extra=log.kv(available=",".join(available), size=size),
            )
        elif size not in topo_mod.guest_meshable_counts(inv.topology):
            # Consistency half of the daemon↔guest topology contract
            # (ISSUE 9): a contiguous hint whose size the guest cannot
            # mesh as a 1×N slice would hand out ICI neighbors the
            # serving mesh then can't use — by construction
            # (family.subslices keys ARE the meshable counts) this never
            # fires; the log is the tripwire if a family table drifts.
            LOG.warning(
                "contiguous placement size is not a guest-meshable "
                "sub-slice",
                extra=log.kv(size=size),
            )
        return [str(c) for c in placement.chips]


class VfioAllocator:
    """Allocation policy for whole-VM passthrough: device ids are IOMMU group
    ids (the reference's model, kept for the generalized path)."""

    def __init__(
        self,
        inventory: Callable[[], VfioInventory],
        vendor: str,
        model_key: tuple[str, str],
        revalidate: Optional[Callable[[str], bool]] = None,
    ):
        self._inventory = inventory
        self._vendor = vendor
        self._model_key = model_key
        self._revalidate = revalidate

    def allocate(self, device_ids: Sequence[str]) -> pb.ContainerAllocateResponse:
        inv = self._inventory()
        resp = pb.ContainerAllocateResponse()
        names = []
        for group in device_ids:
            devs = inv.groups.get(group)
            if not devs:
                raise AllocationError(f"IOMMU group {group} not in current inventory")
            if self._revalidate and not self._revalidate(group):
                raise AllocationError(f"IOMMU group {group} failed sysfs re-validation")
            names.append(qualified_name(self._vendor, C.VFIO_CLASS, group))
        for name in names:
            resp.cdi_devices.add(name=name)
        resp.envs[C.ENV_CDI_VENDOR_CLASS] = f"{self._vendor}/{C.VFIO_CLASS}"
        return resp

    def preferred(
        self, available: Sequence[str], must_include: Sequence[str], size: int
    ) -> list[str]:
        """NUMA-aware pick (generalizes the ref's nil stub at
        generic_device_plugin.go:378-386): groups are functionally
        interchangeable, but cross-socket DMA costs — so fill from the NUMA
        node that (a) already hosts the must-include groups and (b) can
        satisfy the most of the request, before spilling to other nodes."""
        inv = self._inventory()

        def node_of(group: str):
            devs = inv.groups.get(group) or []
            nodes = {d.numa_node for d in devs if d.numa_node is not None}
            return nodes.pop() if len(nodes) == 1 else None

        picked = list(must_include)
        rest = [a for a in available if a not in must_include]
        by_node: dict[object, list[str]] = {}
        for g in rest:
            by_node.setdefault(node_of(g), []).append(g)
        pinned = {node_of(g) for g in must_include} - {None}
        # Nodes the request is already on first, then by how much of the
        # remainder they can satisfy; unknown-NUMA groups last.
        order = sorted(
            by_node,
            key=lambda n: (n not in pinned, n is None, -len(by_node[n])),
        )
        for node in order:
            picked.extend(by_node[node])
        return picked[:size]
