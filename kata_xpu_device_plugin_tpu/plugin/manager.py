"""Orchestration: discovery → CDI specs → plugin servers → rescan loop.

Counterpart of the reference's ``InitiateDevicePlugin`` + ``generateCDISpec`` +
``createDevicePlugins`` (``device_plugin.go:44-124``), with the pieces the
reference lacks: periodic re-discovery (SURVEY §Quirks 9), a clean shared
shutdown path, per-kind CDI spec files, and the TPU-native spec content
(libtpu mount + slice topology env, SURVEY §2 equivalence table).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional, Sequence

from .. import cdi, obs
from ..cdi import constants as C
from ..config import Config
from ..discovery import pciids
from ..discovery.sysfs import ACCEL_CLASS_SUBDIR, read_id_file, read_link_base
from ..discovery.tpu import TpuInventory, scan_tpus
from ..discovery.vfio import VfioInventory, scan_vfio
from ..multihost import multislice_env, resolve_membership
from ..multihost.resolver import clear_state, persist_membership
from ..topology import runtime_env
from ..topology.slice import HostTopology
from ..utils import log, metrics
from . import health
from .allocators import TpuAllocator, VfioAllocator
from .health import HealthWatcher
from .server import DevicePluginServer, DeviceState, WatchedDevice

LOG = log.get("manager")


# ----- CDI spec builders ---------------------------------------------------


def build_tpu_spec(inv: TpuInventory, cfg: Config) -> cdi.Spec:
    """CDI spec for the TPU chips (ref ``generateCDISpec``, device_plugin.go:
    55-80, redesigned): per-chip ``/dev/accel`` device nodes, spec-level
    libtpu mount + static slice-topology env shared by every allocation."""
    spec = cdi.Spec(kind=cfg.tpu_cdi_kind, cdi_version=C.CDI_VERSION)
    env = runtime_env(inv.topology)  # static: type, bounds, worker id/hosts
    env.update(multislice_env(cfg.num_slices, cfg.slice_id, cfg.megascale_coordinator))
    for key, val in sorted(env.items()):
        spec.container_edits.add_env(key, val)
    if cfg.libtpu_host_path and os.path.exists(cfg.libtpu_host_path):
        spec.container_edits.mounts.append(
            cdi.Mount(
                host_path=cfg.libtpu_host_path,
                container_path=C.LIBTPU_CONTAINER_PATH,
            )
        )
        spec.container_edits.add_env(C.LIBTPU_ENV, C.LIBTPU_CONTAINER_PATH)
    for chip in inv.chips:
        annotations = {}
        if cfg.kata_annotations and chip.pci_address:
            annotations[C.ANNOTATION_BDF] = chip.pci_address
        edits = cdi.ContainerEdits(
            device_nodes=[
                cdi.DeviceNode(
                    path=_container_dev_path(chip.dev_path, cfg.dev_root),
                    host_path=chip.dev_path,
                    type="c",
                    major=chip.major,
                    minor=chip.minor,
                    permissions="rw",
                )
            ]
        )
        if chip.vfio_group:
            # Chip is vfio-bound: the guest gets the vfio node too, and Kata
            # hot-plugs the PCI function (ref annotations, device_plugin.go:62-68).
            edits.device_nodes.append(
                cdi.DeviceNode(
                    path=f"/dev/vfio/{chip.vfio_group}",
                    host_path=os.path.join(cfg.dev_root, "vfio", chip.vfio_group),
                    type="c",
                    permissions="rw",
                )
            )
            if cfg.kata_annotations:
                annotations[C.ANNOTATION_ATTACH_PCI] = "true"
        spec.add_device(
            cdi.Device(name=str(chip.index), annotations=annotations, container_edits=edits)
        )
    return spec


def build_vfio_spec(inv: VfioInventory, cfg: Config) -> cdi.Spec:
    """CDI spec for whole-VM passthrough groups: one CDI device per IOMMU
    group carrying its /dev/vfio node and Kata hot-plug annotations."""
    spec = cdi.Spec(kind=cfg.vfio_cdi_kind, cdi_version=C.CDI_VERSION)
    for group in sorted(inv.groups, key=lambda g: (len(g), g)):
        devs = inv.groups[group]
        annotations = {}
        if cfg.kata_annotations:
            annotations[C.ANNOTATION_ATTACH_PCI] = "true"
            annotations[C.ANNOTATION_BDF] = ",".join(d.address for d in devs)
        spec.add_device(
            cdi.Device(
                name=group,
                annotations=annotations,
                container_edits=cdi.ContainerEdits(
                    device_nodes=[
                        cdi.DeviceNode(
                            path=f"/dev/vfio/{group}",
                            host_path=os.path.join(cfg.dev_root, "vfio", group),
                            type="c",
                            permissions="rw",
                        )
                    ]
                ),
            )
        )
    return spec


def _container_dev_path(host_path: str, dev_root: str) -> str:
    """Map a host device path to its in-guest path (identity in production
    where dev_root is /dev; fake roots in tests still emit /dev/...)."""
    if dev_root != "/dev" and host_path.startswith(dev_root):
        return "/dev" + host_path[len(dev_root):]
    return host_path


def tpu_watched_devices(
    inv: TpuInventory, sysfs_root: str = "/sys", dev_root: str = "/dev"
) -> list[WatchedDevice]:
    """Each chip watches its /dev node AND a driver-state path (SURVEY §7
    hard part #4): the /sys/class/accel entry for natively-driven chips (a
    driver unbind removes it while the stale char device can linger), or the
    /dev/vfio/<group> node for vfio-bound chips (the accel class entry does
    not exist under vfio-pci; the kernel removes the group node on unbind).
    The same pair also backs allocate-time re-validation via
    :func:`tpu_chip_alive`."""
    return [
        WatchedDevice(
            id=str(chip.index),
            numa_node=chip.numa_node,
            watch_paths=tpu_chip_watch_paths(chip, sysfs_root, dev_root),
        )
        for chip in inv.chips
    ]


def tpu_chip_watch_paths(
    chip, sysfs_root: str = "/sys", dev_root: str = "/dev"
) -> tuple[str, str]:
    """(dev node, driver-state path) — the liveness pair for one chip."""
    if chip.vfio_group:
        driver_path = os.path.join(dev_root, "vfio", chip.vfio_group)
    else:
        driver_path = os.path.join(
            sysfs_root, ACCEL_CLASS_SUBDIR, os.path.basename(chip.dev_path)
        )
    return (chip.dev_path, driver_path)


def tpu_chip_alive(chip, sysfs_root: str = "/sys", dev_root: str = "/dev") -> bool:
    """Allocate-time liveness: the chip's dev node answers a non-blocking
    open probe (or is guest-held) AND its driver-state path still exists —
    the ref's sysfs re-validation (``generic_device_plugin.go:329-338``)
    done against the same pair the health watcher tracks, so a chip the
    watcher would flag Unhealthy can never be handed to a pod in the window
    before the next health pass."""
    return all(
        health.node_alive(p) for p in tpu_chip_watch_paths(chip, sysfs_root, dev_root)
    )


def vfio_watched_devices(
    inv: VfioInventory, groups: list[str], dev_root: str
) -> list[WatchedDevice]:
    return [
        WatchedDevice(
            id=g,
            numa_node=inv.groups[g][0].numa_node if inv.groups.get(g) else None,
            watch_paths=(os.path.join(dev_root, "vfio", g),),
        )
        for g in groups
    ]


# ----- allocation-state journal (ISSUE 10) ---------------------------------


class AllocationJournal:
    """Crash-consistent record of device→allocation assignments.

    The kubelet owns allocation truth but never replays it to a
    restarting plugin (v1beta1 has no ListAllocations), so the reference
    plugin restarts BLIND: allocations made before the restart are
    invisible, and a chip that died while the daemon was down is only
    noticed when a pod crashes on it. This journal closes that hole with
    the reconcile-from-observed-state loop the Kubernetes Network Driver
    Model argues for (PAPERS.md): every Allocate checkpoints its
    device→group assignment to disk (atomic tmp+rename), and a
    restarting daemon reconciles the journal against the devices it
    actually observes — ``alloc_reconciled`` for groups whose devices
    all still exist, ``alloc_orphaned`` (entry dropped, gauge set) for
    groups referencing vanished chips.

    Entries are keyed by device id: a chip belongs to at most one live
    allocation (the kubelet only re-hands-out freed devices), so the
    journal is bounded by chip count and a re-allocation of a device
    supersedes its old entry. A missing or corrupt file degrades to an
    empty journal — observed state is the authority, the journal is the
    hint."""

    def __init__(self, path: str):
        self.path = path
        # Allocate handlers run on the gRPC thread pool: record() calls
        # arrive concurrently, and an unguarded dict would race json.dump
        # mid-write (and two writers would fight over the same tmp file).
        self._lock = threading.Lock()
        self._devices: dict[str, dict] = {}
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
            devices = data.get("devices", {})
            if isinstance(devices, dict):
                self._devices = {
                    str(k): v for k, v in devices.items()
                    if isinstance(v, dict) and v.get("group")
                }
        except FileNotFoundError:
            pass
        except (OSError, ValueError) as e:
            LOG.warning(
                "allocation journal unreadable — starting empty",
                extra=log.kv(path=path, err=str(e)),
            )

    def _save_locked(self) -> None:
        tmp = self.path + ".tmp"
        try:
            # Sanctioned lock-held IO: concurrent Allocate handlers must
            # serialize the whole tmp+rename cycle or two writers tear
            # the same tmp file — crash consistency IS the contract here
            # (the journal is tiny; the write is bounded).
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)  # jaxguard: allow(JG203) serialized journal checkpoint
            with open(tmp, "w", encoding="utf-8") as fh:  # jaxguard: allow(JG203) serialized journal checkpoint
                json.dump({"version": 1, "devices": self._devices}, fh)  # jaxguard: allow(JG203) serialized journal checkpoint
            os.replace(tmp, self.path)  # jaxguard: allow(JG203) serialized journal checkpoint
        except OSError as e:
            # A read-only state dir must not fail Allocate — the journal
            # is a restart hint, never the allocation's source of truth.
            LOG.warning(
                "allocation journal write failed",
                extra=log.kv(path=self.path, err=str(e)),
            )

    def record(self, resource: str, device_ids: Sequence[str]) -> None:
        """Checkpoint one granted allocation (called from the Allocate
        handler via ``on_allocate``): each device maps to the full group
        it was granted with, superseding any stale entry."""
        group = sorted(str(i) for i in device_ids)
        entry = {"resource": resource, "group": group, "ts": time.time()}
        with self._lock:
            for dev_id in group:
                self._devices[dev_id] = dict(entry)
            self._save_locked()

    def allocations(self, resource: str) -> list[tuple[str, ...]]:
        """Distinct journaled device groups for ``resource``."""
        with self._lock:
            return sorted({
                tuple(ent["group"]) for ent in self._devices.values()
                if ent.get("resource") == resource
            })

    def reconcile(self, resource: str,
                  observed_ids: set[str]) -> tuple[int, int]:
        """Startup reconcile against the OBSERVED device set: emit one
        ``alloc_reconciled`` event per journaled group whose devices all
        still exist and one ``alloc_orphaned`` per group with vanished
        devices (entry dropped). Never touches device HEALTH — health is
        the watcher's job from live probes; reconcile only restores the
        assignment map, so a restart causes zero spurious Unhealthy
        flaps in the ListAndWatch stream (tested). Returns
        ``(reconciled, orphaned)`` group counts."""
        reconciled = orphaned = 0
        for group in self.allocations(resource):
            missing = [d for d in group if d not in observed_ids]
            if missing:
                orphaned += 1
                with self._lock:
                    for dev_id in group:
                        ent = self._devices.get(dev_id)
                        if ent and tuple(ent["group"]) == group:
                            del self._devices[dev_id]
                obs.emit(
                    "plugin", "alloc_orphaned",
                    resource=resource, devices=",".join(group),
                    missing=",".join(missing),
                )
                LOG.warning(
                    "journaled allocation references vanished devices",
                    extra=log.kv(
                        resource=resource, devices=",".join(group),
                        missing=",".join(missing),
                    ),
                )
            else:
                reconciled += 1
                obs.emit(
                    "plugin", "alloc_reconciled",
                    resource=resource, devices=",".join(group),
                )
        if orphaned:
            with self._lock:
                self._save_locked()
        metrics.alloc_orphaned.labels(resource=resource).set(orphaned)
        return reconciled, orphaned


# ----- guest heartbeat aggregation (ISSUE 15) -------------------------------


class HeartbeatAggregator:
    """Tail guest heartbeat streams, re-export per-allocation gauges.

    The allocator points every allocation's ``KATATPU_OBS_FILE`` at a
    per-allocation JSONL under ``--guest-events-dir`` (a host dir shared
    into the guests); this aggregator tails those files with
    ``obs.tail_events`` — incremental, rotation-safe, no whole-file
    re-reads per poll — extracts each ``serving_heartbeat`` /
    ``watchdog_alert`` / ``watchdog_clear``, and sets the
    ``utils.metrics.guest_*`` gauges keyed by (allocation, server). The
    workload-layer signal surfaces through the device layer (the
    Kubernetes Network Driver Model argument, PAPERS.md) — and is the
    per-replica occupancy/ITL feed the ROADMAP fleet-router tier
    balances on. jax-free, stdlib + obs.events only: the host daemon
    stays jax-free.

    A guest watchdog alert is additionally re-emitted on the DAEMON's
    own event stream as ``plugin/guest_alert`` (allocation, server,
    kind, the guest's dump path), so one host-side stream records every
    guest incident on the node.

    RESTART semantics: offsets are in-memory, and the stream files live
    on a hostPath that outlives the daemon pod — so after a restart the
    first poll re-reads whole files. That replay restores STATE (the
    gauges and active-alert sets take their last-written values, which
    is exactly what a fresh /metrics endpoint needs) but must not
    re-announce HISTORY: events stamped before the aggregator was
    constructed skip the ``_total`` counter increments, the
    ``guest_alert`` re-emission, and the warning log — a day of old
    incidents does not replay as a burst of new ones.

    GROWTH bound: the allocator arms the guest's FULL event stream
    (spans included), nothing in-guest rotates it, and the files live
    on a hostPath — so the aggregator is the rotator of last resort:
    once a file's consumed prefix exceeds ``max_stream_bytes`` (64 MiB
    default; 0 disables) it is truncated to zero. Safe against the
    writer: the guest sink appends with O_APPEND (the next write lands
    at the new EOF), a line torn by the race parses as the torn-tail
    case ``tail_events`` already skips, and the truncation-restart
    logic resets the offset — at worst a poll interval's telemetry is
    lost from a file that had already grown past the cap."""

    def __init__(self, events_dir: str, poll_interval_s: float = 5.0,
                 max_stream_bytes: int = 64 * 1024 * 1024):
        self.events_dir = events_dir
        self.poll_interval_s = poll_interval_s
        self.max_stream_bytes = int(max_stream_bytes)
        self._offsets: dict[str, int] = {}
        # (allocation, server) -> last heartbeat (staleness + debug).
        self._last: dict[tuple[str, str], dict] = {}
        self._active_alerts: dict[tuple[str, str], set] = {}
        # Replay horizon: guest events stamped before this are catch-up
        # state, not news (guest and daemon share the node clock).
        self._t0 = time.time()
        # snapshot() runs on the SIGUSR1 debug-report thread while
        # _consume inserts on the aggregator thread — same contract as
        # the manager's own _lock.
        self._lock = threading.Lock()

    def poll_once(self) -> int:
        """One tail pass over every stream file; returns the number of
        heartbeats consumed. Never raises — a torn file or vanished dir
        must not kill the daemon loop."""
        consumed = 0
        try:
            names = sorted(os.listdir(self.events_dir))
        except OSError:
            return 0
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.events_dir, name)
            # The offset map lives next to _last/_active_alerts under
            # this class's lock discipline — touch it under the lock,
            # with the tail-file IO outside the held region.
            with self._lock:
                last_offset = self._offsets.get(path, 0)
            try:
                events, offset = obs.tail_events(path, last_offset)
            except Exception:
                continue
            if self.max_stream_bytes and offset > self.max_stream_bytes:
                # Rotator of last resort (see the class docstring): the
                # consumed prefix outgrew the cap — drop it. The guest's
                # O_APPEND writer lands its next line at the new EOF.
                try:
                    os.truncate(path, 0)
                    offset = 0
                except OSError:
                    pass
            with self._lock:
                self._offsets[path] = offset
            # Fallback allocation identity from the allocator's file
            # naming (guest_<chips>.jsonl) for events predating the
            # heartbeat's own "chips" field.
            stem = name[:-len(".jsonl")]
            fallback = stem[len("guest_"):].replace("-", ",") if (
                stem.startswith("guest_")
            ) else stem
            for ev in events:
                if ev.get("kind") != "serving":
                    continue
                consumed += self._consume(ev, fallback)
        return consumed

    def _consume(self, ev: dict, fallback_alloc: str) -> int:
        name = ev.get("name")
        server = str(ev.get("server", "") or "unknown")
        alloc = str(ev.get("chips") or fallback_alloc or "unknown")
        key = (alloc, server)
        # Restart replay: state updates below always run; the "news"
        # surfaces (counters, guest_alert re-emission, warning log) only
        # for events from this daemon's lifetime.
        try:
            fresh = float(ev.get("ts") or 0.0) >= self._t0
        except (TypeError, ValueError):
            fresh = True
        if name == "serving_heartbeat":
            with self._lock:
                self._last[key] = ev
            labels = {"allocation": alloc, "server": server}
            metrics.guest_tokens_per_s.labels(**labels).set(
                float(ev.get("tokens_per_s") or 0.0)
            )
            metrics.guest_itl_p99_ms.labels(**labels).set(
                float(ev.get("itl_p99_ms") or 0.0)
            )
            metrics.guest_queue_depth.labels(**labels).set(
                float(ev.get("queued") or 0)
            )
            metrics.guest_batch_occupancy.labels(**labels).set(
                float(ev.get("batch_occupancy") or 0.0)
            )
            metrics.guest_kv_pool_occupancy.labels(**labels).set(
                float(ev.get("kv_pool_occupancy") or 0.0)
            )
            metrics.guest_kv_host_occupancy.labels(**labels).set(
                float(ev.get("kv_host_occupancy") or 0.0)
            )
            metrics.guest_last_heartbeat_ts.labels(**labels).set(
                float(ev.get("ts") or 0.0)
            )
            # Device ledger (ISSUE 17): omission-preserving — a gauge
            # child is created ONLY when the guest's heartbeat carries
            # the field, so a CPU guest (no memory_stats) or a disarmed
            # ledger exports nothing rather than a fake 0.
            if "mfu" in ev:
                metrics.guest_mfu.labels(**labels).set(
                    float(ev.get("mfu") or 0.0)
                )
            if "hbm_headroom_bytes" in ev:
                metrics.guest_hbm_headroom_bytes.labels(**labels).set(
                    float(ev.get("hbm_headroom_bytes") or 0.0)
                )
            if fresh:
                metrics.guest_heartbeats_total.labels(**labels).inc()
            return 1
        if name == "watchdog_alert":
            kind = str(ev.get("alert", "") or "unknown")
            with self._lock:
                active = self._active_alerts.setdefault(key, set())
                active.add(kind)
                n_active = len(active)
            metrics.guest_watchdog_active.labels(
                allocation=alloc, server=server
            ).set(n_active)
            if fresh:
                metrics.guest_alerts_total.labels(
                    allocation=alloc, server=server, kind=kind
                ).inc()
                obs.emit(
                    "plugin", "guest_alert",
                    allocation=alloc, server=server, alert=kind,
                    reason=ev.get("reason", ""), dump=ev.get("dump", ""),
                    trace=ev.get("trace", ""),
                )
                LOG.warning(
                    "guest watchdog alert",
                    extra=log.kv(
                        allocation=alloc, server=server, kind=kind,
                        reason=ev.get("reason", ""),
                    ),
                )
        elif name == "watchdog_clear":
            kind = str(ev.get("alert", "") or "unknown")
            with self._lock:
                active = self._active_alerts.setdefault(key, set())
                active.discard(kind)
                n_active = len(active)
            metrics.guest_watchdog_active.labels(
                allocation=alloc, server=server
            ).set(n_active)
        return 0

    def snapshot(self) -> dict:
        """Debug-report slice: last heartbeat per (allocation, server)."""
        with self._lock:
            return {
                f"{alloc}/{server}": {
                    "ts": hb.get("ts"),
                    "tokens_per_s": hb.get("tokens_per_s"),
                    "queued": hb.get("queued"),
                    "active_alerts": sorted(
                        self._active_alerts.get((alloc, server), ())
                    ),
                }
                for (alloc, server), hb in sorted(self._last.items())
            }


# ----- manager -------------------------------------------------------------


class PluginManager:
    """Owns discovery state and the fleet of per-resource plugin servers."""

    def __init__(self, cfg: Config, state_readonly: bool = False):
        self.cfg = cfg
        # True for one-shot introspection (the `status` subcommand): resolve
        # identity without writing/clearing the daemon's persisted state.
        self.state_readonly = state_readonly
        self._db = pciids.PciIds.load(cfg.pci_ids_path or None)
        self._lock = threading.Lock()
        self._tpu_inv: Optional[TpuInventory] = None
        self._vfio_inv: Optional[VfioInventory] = None
        self._tpu_plugin: Optional[DevicePluginServer] = None
        self._vfio_plugins: dict[tuple[str, str], DevicePluginServer] = {}
        self._watcher: Optional[HealthWatcher] = None
        self._stop = threading.Event()
        self._rescan_thread: Optional[threading.Thread] = None
        # Guest heartbeat aggregation (ISSUE 15): tails the per-
        # allocation event streams the allocator points into
        # cfg.guest_events_dir; "" disables (no env stamp, no thread).
        self._aggregator: Optional[HeartbeatAggregator] = (
            HeartbeatAggregator(
                cfg.guest_events_dir, cfg.guest_events_poll_s,
                max_stream_bytes=cfg.guest_events_max_mb * 1024 * 1024,
            )
            if cfg.guest_events_dir else None
        )
        self._aggregator_thread: Optional[threading.Thread] = None
        # Allocation-state journal (ISSUE 10): lives in the same state
        # dir as the persisted worker identity; "" disables (the daemon
        # then restarts blind, the reference behavior).
        self._journal: Optional[AllocationJournal] = (
            AllocationJournal(os.path.join(cfg.state_dir, "allocations.json"))
            if cfg.state_dir and not state_readonly else None
        )

    # -- inventory providers (allocators call these on every Allocate) ------

    def tpu_inventory(self) -> TpuInventory:
        with self._lock:
            assert self._tpu_inv is not None
            return self._tpu_inv

    def vfio_inventory(self) -> VfioInventory:
        with self._lock:
            assert self._vfio_inv is not None
            return self._vfio_inv

    # -- lifecycle ----------------------------------------------------------

    def scan(self) -> tuple[TpuInventory, VfioInventory]:
        cfg = self.cfg
        tpu_inv = scan_tpus(
            cfg.sysfs_root,
            cfg.dev_root,
            pci_ids=self._db,
            accelerator_type=cfg.accelerator_type or None,
            resolve_env_identity=False,  # _apply_membership owns identity
        )
        tpu_inv = self._apply_membership(tpu_inv)
        if cfg.vfio_vendors:
            vendors = () if cfg.vfio_vendors == ("*",) else cfg.vfio_vendors
            vfio_inv = scan_vfio(cfg.sysfs_root, vendors)
            # TPU chips already surfaced via /dev/accel are not re-advertised
            # as passthrough groups.
            tpu_groups = {c.vfio_group for c in tpu_inv.chips if c.vfio_group}
            for g in tpu_groups:
                vfio_inv.groups.pop(g, None)
            for key in list(vfio_inv.models):
                vfio_inv.models[key] = [
                    g for g in vfio_inv.models[key] if g not in tpu_groups
                ]
                if not vfio_inv.models[key]:
                    del vfio_inv.models[key]
        else:
            vfio_inv = VfioInventory()
        with self._lock:
            self._tpu_inv = tpu_inv
            self._vfio_inv = vfio_inv
        return tpu_inv, vfio_inv

    def _apply_membership(self, tpu_inv: TpuInventory) -> TpuInventory:
        """Overlay the multihost-resolved worker identity onto the scanned
        topology (SURVEY §7 stage 7). ``scan_tpus`` already honors the libtpu
        env; this adds the flag/metadata/derived sources and persistence."""
        cfg = self.cfg
        topo = tpu_inv.topology
        # The accelerator type is authoritative when pinned by flag or node
        # env. Autodetection only counts LOCAL chips — it cannot see the rest
        # of the slice, so its num_hosts=1 must neither veto a multi-host
        # membership nor invalidate persisted identity during an outage.
        authoritative = bool(cfg.accelerator_type) or bool(
            os.environ.get("TPU_ACCELERATOR_TYPE")
        )
        mem = resolve_membership(
            hostname=cfg.node_name or None,
            explicit_worker_id=cfg.worker_id,
            explicit_hostnames=cfg.worker_hostnames,
            metadata_dir=cfg.metadata_dir,
            state_dir=cfg.state_dir,
            num_hosts_hint=topo.num_hosts if authoritative else 0,
            state_readonly=self.state_readonly,
            defer_save=True,  # persist only what we ACCEPT below
        )
        if mem is None:
            return tpu_inv
        accepted = True
        if mem.num_hosts > 1 and mem.num_hosts != topo.num_hosts:
            scaled = None if authoritative else self._scale_topology(topo, mem)
            if scaled is None:
                # Writing N hostnames against mismatched host bounds would
                # hand guests a self-contradictory env; fail closed to a
                # clean SINGLE-host identity covering only the local chips
                # (a multi-host type with worker 0 everywhere and no peer
                # list would be just as contradictory).
                LOG.error(
                    "refusing %d-host membership: %s implies %d host(s) — fix "
                    "--accelerator-type or the worker hostname list",
                    mem.num_hosts,
                    topo.accelerator_type,
                    topo.num_hosts,
                )
                topo = self._standalone_topology(topo)
                accepted = False
            else:
                topo = scaled
        elif topo.num_hosts > 1 and len(mem.hostnames) != topo.num_hosts:
            # A bare worker id (pinned --worker-id, or GKE's lone
            # TPU_WORKER_ID) — or a too-short peer list — on a multi-host
            # type would hand guests TPU_HOST_BOUNDS implying N hosts with
            # a missing/short peer list: the same self-contradictory env
            # the refusal branch above exists to prevent. Fail closed the
            # same way. (A LONGER list is unreachable here: it makes
            # mem.num_hosts > 1 != topo.num_hosts, caught above.)
            LOG.error(
                "refusing membership with %d hostname(s) (worker id %d) for "
                "multi-host %s (%d hosts): supply a full --worker-hostnames /"
                " TPU_WORKER_HOSTNAMES list or a metadata dir",
                len(mem.hostnames),
                mem.worker_id,
                topo.accelerator_type,
                topo.num_hosts,
            )
            topo = self._standalone_topology(topo)
            accepted = False
        else:
            topo = dataclasses.replace(
                topo, worker_id=mem.worker_id, worker_hostnames=mem.hostnames
            )
        if not self.state_readonly and cfg.state_dir:
            if accepted:
                persist_membership(cfg.state_dir, mem)
            else:
                # A refused identity must not haunt later rescans/restarts.
                clear_state(cfg.state_dir)
        return dataclasses.replace(tpu_inv, topology=topo)

    @staticmethod
    def _standalone_topology(topo: HostTopology) -> HostTopology:
        """This host's local chips as a self-consistent single-host slice."""
        fam = topo.family
        suffix = (
            topo.local_chips * 2 if fam.suffix_counts_cores else topo.local_chips
        )
        return HostTopology.from_accelerator_type(f"{fam.name}-{suffix}")

    @staticmethod
    def _scale_topology(topo, mem) -> Optional[HostTopology]:
        """Rebuild an autodetected single-host topology at the membership's
        host count (local chips × N hosts), keeping bounds and type
        consistent with the hostnames the guests will see. Returns None when
        no valid topology exists at that host count — a partial host (e.g. 4
        chips of an 8-chip v5e machine) cannot be part of a multi-host slice."""
        fam = topo.family
        chips = topo.local_chips * mem.num_hosts
        suffix = chips * 2 if fam.suffix_counts_cores else chips
        scaled = HostTopology.from_accelerator_type(
            f"{fam.name}-{suffix}",
            worker_id=mem.worker_id,
            worker_hostnames=mem.hostnames,
        )
        if scaled.num_hosts != mem.num_hosts or scaled.local_chips != topo.local_chips:
            return None
        LOG.info(
            "scaled autodetected topology to %s for %d-host membership",
            scaled.accelerator_type,
            mem.num_hosts,
        )
        return scaled

    def write_specs(self) -> list[str]:
        cfg = self.cfg
        tpu_inv, vfio_inv = self.tpu_inventory(), self.vfio_inventory()
        paths = []
        if tpu_inv.count:
            paths.append(cdi.save(build_tpu_spec(tpu_inv, cfg), cfg.cdi_dir, cfg.cdi_format))
        if vfio_inv.groups:
            paths.append(cdi.save(build_vfio_spec(vfio_inv, cfg), cfg.cdi_dir, cfg.cdi_format))
        return paths

    def start(self, register: bool = True) -> None:
        cfg = self.cfg
        tpu_inv, vfio_inv = self.scan()
        LOG.info(
            "discovery complete",
            extra=log.kv(
                tpu_chips=tpu_inv.count,
                accelerator_type=tpu_inv.topology.accelerator_type,
                vfio_models=len(vfio_inv.models),
            ),
        )
        self.write_specs()

        # Reconcile the allocation journal against the devices this scan
        # actually OBSERVED — before any plugin serves, so the event
        # stream orders restart state ahead of new traffic. Reconcile
        # never touches health (zero spurious Unhealthy flaps in the
        # ListAndWatch stream); vanished devices surface as
        # alloc_orphaned events + the gauge, not as health churn.
        if self._journal is not None:
            self._journal.reconcile(
                cfg.tpu_resource_name,
                {str(c.index) for c in tpu_inv.chips},
            )

        if self._stop.is_set():
            return
        # The TPU plugin always runs — a 0-chip node advertises an empty list
        # (BASELINE config[0] dry run) and picks devices up on rescan.
        self._tpu_plugin = DevicePluginServer(
            resource_name=cfg.tpu_resource_name,
            state=DeviceState(tpu_watched_devices(tpu_inv, cfg.sysfs_root, cfg.dev_root)),
            allocator=TpuAllocator(
                self.tpu_inventory,
                cfg.resource_namespace,
                cfg.tpu_resource_class,
                cfg.strategies,
                libtpu_host_path=cfg.libtpu_host_path,
                revalidate=lambda chip: tpu_chip_alive(
                    chip, cfg.sysfs_root, cfg.dev_root
                ),
                compile_cache_dir=cfg.compile_cache_dir,
                prefix_cache_tokens=cfg.prefix_cache_tokens,
                kv_pool_tokens=cfg.kv_pool_tokens,
                kv_quant=cfg.kv_quant,
                kv_layout=cfg.kv_layout,
                kv_host_tokens=cfg.kv_host_tokens,
                checkpoint_rounds=cfg.checkpoint_rounds,
                fault_schedule=cfg.faults,
                sched_policy=cfg.sched_policy,
                prefill_chunk=cfg.prefill_chunk,
                itl_slo_ms=cfg.itl_slo_ms,
                decode_steps=cfg.decode_steps,
                serving_tp=cfg.serving_tp,
                serving_tp_min=cfg.serving_tp_min,
                trace_context=cfg.trace_context,
                guest_events_dir=cfg.guest_events_dir,
                heartbeat_rounds=cfg.heartbeat_rounds,
            ),
            # Journal every grant at the moment it happens (the Allocate
            # handler's on_allocate hook) — the restart reconcile's input.
            on_allocate=(
                (lambda ids: self._journal.record(cfg.tpu_resource_name, ids))
                if self._journal is not None else None
            ),
            socket_dir=cfg.kubelet_socket_dir,
            kubelet_socket=cfg.kubelet_socket,
            register_attempts=cfg.register_attempts,
            register_backoff_s=cfg.register_backoff_s,
        )
        # The plugin must be visible to request_stop() BEFORE start() blocks
        # in registration backoff, or a signal landing in between would miss
        # its stop event and wait out the full backoff.
        if self._stop.is_set():
            return
        self._tpu_plugin.start(register=register)

        for key, groups in vfio_inv.models.items():
            if self._stop.is_set():
                return
            self._spawn_vfio_plugin(key, groups, register)

        self._watcher = HealthWatcher(
            self.plugins(), poll_interval_s=cfg.health_poll_interval_s
        )
        self._watcher.start()
        if cfg.rescan_interval_s > 0:
            self._rescan_thread = threading.Thread(
                target=self._rescan_loop, name="rescan", daemon=True
            )
            self._rescan_thread.start()
        if self._aggregator is not None:
            self._aggregator_thread = threading.Thread(
                target=self._aggregator_loop, name="guest-heartbeats",
                daemon=True,
            )
            self._aggregator_thread.start()

    def _spawn_vfio_plugin(
        self, key: tuple[str, str], groups: list[str], register: bool
    ) -> None:
        cfg = self.cfg
        with self._lock:
            vfio_inv = self._vfio_inv
        suffix = vfio_inv.model_suffix(key, self._db) if vfio_inv else key[1]
        resource = f"{cfg.resource_namespace}/{suffix}"
        plugin = DevicePluginServer(
            resource_name=resource,
            state=DeviceState(
                vfio_watched_devices(self.vfio_inventory(), groups, cfg.dev_root)
            ),
            allocator=VfioAllocator(
                self.vfio_inventory,
                cfg.resource_namespace,
                key,
                revalidate=self._revalidate_group,
            ),
            socket_dir=cfg.kubelet_socket_dir,
            kubelet_socket=cfg.kubelet_socket,
            register_attempts=cfg.register_attempts,
            register_backoff_s=cfg.register_backoff_s,
        )
        # Visible to request_stop() before start() can block (see start()).
        # Locked: the signal-watcher thread iterates plugins() concurrently.
        with self._lock:
            self._vfio_plugins[key] = plugin
        if self._stop.is_set():
            return
        plugin.start(register=register)
        if self._watcher:
            self._watcher.add_plugin(plugin)

    def _revalidate_group(self, group: str) -> bool:
        """Live sysfs re-check at Allocate time (ref generic_device_plugin.go:
        329-338): every function of the group must still be vfio-bound and in
        the same group."""
        inv = self.vfio_inventory()
        devs = inv.groups.get(group, [])
        base = os.path.join(self.cfg.sysfs_root, "bus/pci/devices")
        for d in devs:
            devdir = os.path.join(base, d.address)
            if read_link_base(os.path.join(devdir, "iommu_group")) != group:
                return False
            if read_id_file(os.path.join(devdir, "vendor")) != d.vendor:
                return False
            if read_link_base(os.path.join(devdir, "driver")) != "vfio-pci":
                return False
        return True

    def plugins(self) -> list[DevicePluginServer]:
        out = []
        if self._tpu_plugin:
            out.append(self._tpu_plugin)
        with self._lock:  # rescan thread may be inserting concurrently
            out.extend(self._vfio_plugins.values())
        return out

    def debug_report(self) -> dict:
        """Snapshot of live manager state for observability (dumped on
        SIGUSR1 by the daemon — the pprof-handler equivalent the reference
        never registers, SURVEY §5 tracing row)."""
        # Runs on the SIGUSR1 debug-dump thread while the rescan thread
        # may be swapping inventories — snapshot the references under
        # the lock, format outside it.
        with self._lock:
            tpu_inv = self._tpu_inv
            vfio_inv = self._vfio_inv
        report: dict = {
            "plugins": [
                {
                    "resource": p.resource_name,
                    "serving": p.serving,
                    "stopped": p.stopped,
                    "socket": p.socket_path,
                    "devices": [
                        {"id": d.id, "health": d.health}
                        for d in p.state.snapshot()
                    ],
                }
                for p in self.plugins()
            ],
            "watcher_alive": bool(self._watcher and self._watcher.is_alive()),
            "rescan_alive": bool(
                self._rescan_thread and self._rescan_thread.is_alive()
            ),
            "aggregator_alive": bool(
                self._aggregator_thread
                and self._aggregator_thread.is_alive()
            ),
        }
        if self._aggregator is not None:
            report["guest_heartbeats"] = self._aggregator.snapshot()
        if tpu_inv is not None:
            topo = tpu_inv.topology
            report["tpu"] = {
                "chips": tpu_inv.count,
                "accelerator_type": topo.accelerator_type,
                "num_hosts": topo.num_hosts,
                "worker_id": topo.worker_id,
                "worker_hostnames": list(topo.worker_hostnames),
            }
        if vfio_inv is not None:
            report["vfio_models"] = {
                f"{v}:{d}": groups
                for (v, d), groups in sorted(vfio_inv.models.items())
            }
        return report

    def rescan_once(self) -> bool:
        """One re-discovery pass; returns True when anything changed."""
        old_tpu = self.tpu_inventory()
        old_vfio = self.vfio_inventory()
        tpu_inv, vfio_inv = self.scan()
        changed = False
        if self._tpu_plugin and (
            [c.index for c in tpu_inv.chips] != [c.index for c in old_tpu.chips]
        ):
            changed = True
            self._tpu_plugin.state.replace(
                tpu_watched_devices(tpu_inv, self.cfg.sysfs_root, self.cfg.dev_root)
            )
        if tpu_inv.topology != old_tpu.topology:
            # Worker identity can resolve after startup (metadata agent racing
            # the DaemonSet) — the spec on disk must follow it.
            changed = True
        if vfio_inv.models != old_vfio.models:
            changed = True
            # Runs on the rescan thread while gRPC handlers call
            # plugins() — snapshot the fleet under the lock (spawns
            # insert under the same lock; a key spawned below is in
            # vfio_inv.models, so the retire loop's snapshot staleness
            # is harmless).
            with self._lock:
                vfio_plugins = dict(self._vfio_plugins)
            for key, groups in vfio_inv.models.items():
                if key in vfio_plugins:
                    vfio_plugins[key].state.replace(
                        vfio_watched_devices(vfio_inv, groups, self.cfg.dev_root)
                    )
                elif not self._stop.is_set():
                    self._spawn_vfio_plugin(key, groups, register=True)
            for key in list(vfio_plugins):
                if key not in vfio_inv.models:
                    vfio_plugins[key].state.replace([])
        if changed:
            self.write_specs()
        metrics.rescans_total.labels(changed=str(changed).lower()).inc()
        return changed

    def _rescan_loop(self) -> None:
        while not self._stop.wait(self.cfg.rescan_interval_s):
            try:
                self.rescan_once()
            except Exception:
                LOG.exception("rescan failed")

    def _aggregator_loop(self) -> None:
        while not self._stop.wait(self._aggregator.poll_interval_s):
            try:
                self._aggregator.poll_once()
            except Exception:
                LOG.exception("guest heartbeat aggregation failed")

    def run_forever(self) -> None:
        """Block until stop()/request_stop() (ref ``<-stop``,
        device_plugin.go:114)."""
        self._stop.wait()

    def request_stop(self) -> None:
        """Shutdown request that takes no plugin-server locks.

        The main thread may be inside ``DevicePluginServer.start()`` holding
        the server lock (kubelet registration backoff); calling ``stop()``
        there would deadlock. This only sets events — which is also what
        wakes register's backoff waits, bounding shutdown latency — and the
        main loop falls out of :meth:`run_forever` into the real
        :meth:`stop`. Call from a normal thread (the daemon routes signals
        through a watcher thread), never directly from a signal handler.
        """
        self._stop.set()
        for plugin in self.plugins():
            plugin.request_stop()

    def stop(self) -> None:
        self._stop.set()
        if self._watcher:
            self._watcher.stop()
        for plugin in self.plugins():
            plugin.stop()
