"""Kubelet device-plugin server.

Counterpart of the reference's ``GenericDevicePlugin``
(``generic_device_plugin.go:37-386``), fixing its concurrency quirks:

- all device state behind one lock, ListAndWatch streams read snapshots
  (ref races on shared ``dpi.devs`` slices — SURVEY §Quirks 3);
- restart() reuses the plugin's single lifecycle, so a kubelet restart never
  orphans the plugin from the manager's shutdown path (Quirks 2);
- Allocate merges env instead of overwriting it (Quirks 4);
- GetPreferredAllocation is a real, injectable policy (Quirks 8).
"""
from __future__ import annotations

import os
import queue
import threading
from concurrent import futures
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol, Sequence

import grpc

from .. import obs
from ..utils import log, metrics
from .api import deviceplugin_pb2 as pb
from .api import glue

LOG = log.get("server")

SOCKET_PREFIX = "kata-tpu"


@dataclass
class WatchedDevice:
    """One schedulable unit: a TPU chip (id = host-local index) or a VFIO
    IOMMU group (id = group id)."""

    id: str
    health: str = glue.HEALTHY
    numa_node: Optional[int] = None
    # Paths whose existence gates health (/dev/accel<N>, /dev/vfio/<group>).
    watch_paths: tuple[str, ...] = ()

    def to_pb(self) -> pb.Device:
        dev = pb.Device(id=self.id, health=self.health)
        if self.numa_node is not None:
            dev.topology.nodes.add(id=self.numa_node)
        return dev


class DeviceState:
    """Thread-safe device table with change subscription (the channel pair
    ``healthy``/``unhealthy`` of the reference, generalized)."""

    def __init__(self, devices: Sequence[WatchedDevice] = ()):
        self._lock = threading.Lock()
        self._devices: dict[str, WatchedDevice] = {d.id: d for d in devices}
        self._subscribers: list[queue.SimpleQueue] = []

    def snapshot(self) -> list[WatchedDevice]:
        with self._lock:
            return [
                WatchedDevice(d.id, d.health, d.numa_node, d.watch_paths)
                for d in sorted(self._devices.values(), key=_dev_sort_key)
            ]

    def ids(self) -> list[str]:
        with self._lock:
            return sorted(self._devices, key=_id_sort_key)

    def get(self, dev_id: str) -> Optional[WatchedDevice]:
        with self._lock:
            d = self._devices.get(dev_id)
            return WatchedDevice(d.id, d.health, d.numa_node, d.watch_paths) if d else None

    def set_health(self, dev_id: str, health: str) -> bool:
        """Returns True when the device existed and its health changed."""
        with self._lock:
            dev = self._devices.get(dev_id)
            if dev is None or dev.health == health:
                return False
            dev.health = health
        self._notify()
        return True

    def replace(self, devices: Sequence[WatchedDevice]) -> bool:
        """Swap the whole table (rescan path); returns True on any change."""
        with self._lock:
            new = {d.id: d for d in devices}
            changed = {i: (d.id, d.health) for i, d in new.items()} != {
                i: (d.id, d.health) for i, d in self._devices.items()
            }
            self._devices = new
        if changed:
            self._notify()
        return changed

    def subscribe(self) -> queue.SimpleQueue:
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._lock:
            self._subscribers.append(q)
        return q

    def unsubscribe(self, q: queue.SimpleQueue) -> None:
        with self._lock:
            if q in self._subscribers:
                self._subscribers.remove(q)

    def _notify(self) -> None:
        with self._lock:
            subs = list(self._subscribers)
        for q in subs:
            q.put(None)  # wake-up token; streams re-snapshot


def _id_sort_key(i: str):
    return (0, int(i)) if i.isdigit() else (1, i)


def _dev_sort_key(d: WatchedDevice):
    return _id_sort_key(d.id)


class Allocator(Protocol):
    """Resource-specific Allocate/preferred policy, injected into the server."""

    def allocate(self, device_ids: Sequence[str]) -> pb.ContainerAllocateResponse:
        """Build one container's response; raise AllocationError to reject."""
        ...

    def preferred(
        self, available: Sequence[str], must_include: Sequence[str], size: int
    ) -> list[str]:
        ...


class AllocationError(Exception):
    pass


class DevicePluginServer(glue.DevicePluginServicer):
    """Serves one extended resource on one unix socket, registers with the
    kubelet, streams device health (ref ``Start``/``Register``/``ListAndWatch``
    lifecycle, generic_device_plugin.go:128-250)."""

    def __init__(
        self,
        resource_name: str,
        state: DeviceState,
        allocator: Allocator,
        socket_dir: str = glue.KUBELET_SOCKET_DIR,
        kubelet_socket: str = "",
        pre_start_required: bool = False,
        on_allocate: Optional[Callable[[Sequence[str]], None]] = None,
        register_attempts: int = 5,
        register_backoff_s: float = 1.0,
        register_backoff_max_s: float = 30.0,
        register_dial_timeout_s: float = 5.0,
    ):
        self.resource_name = resource_name
        self.state = state
        self.allocator = allocator
        self.socket_dir = socket_dir
        self.kubelet_socket = kubelet_socket or os.path.join(socket_dir, "kubelet.sock")
        self.pre_start_required = pre_start_required
        self.on_allocate = on_allocate
        # Registration retry policy (ISSUE 7 satellite): configurable via
        # Config (--register-attempts / --register-backoff-s) instead of
        # the old hardcoded 5 × 1 s exponential ladder that gave up for
        # good after ~31 s of kubelet downtime.
        self.register_attempts = int(register_attempts)
        self.register_backoff_s = float(register_backoff_s)
        self.register_backoff_max_s = float(register_backoff_max_s)
        self.register_dial_timeout_s = float(register_dial_timeout_s)
        self.endpoint = f"{SOCKET_PREFIX}-{resource_name.replace('/', '-')}.sock"
        self._server: Optional[grpc.Server] = None
        self._stop = threading.Event()  # one lifecycle event, never replaced
        self._serving = threading.Event()
        self._lock = threading.Lock()

    # ----- lifecycle -------------------------------------------------------

    @property
    def socket_path(self) -> str:
        return os.path.join(self.socket_dir, self.endpoint)

    def start(self, register: bool = True) -> None:
        with self._lock:
            self._start_locked(register)

    def _start_locked(self, register: bool) -> None:
        os.makedirs(self.socket_dir, exist_ok=True)
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8),
            options=(("grpc.max_receive_message_length", 16 * 1024 * 1024),),
        )
        glue.add_device_plugin_to_server(self, server)
        server.add_insecure_port(f"unix://{self.socket_path}")
        server.start()
        self._server = server
        self._wait_ready()
        self._serving.set()
        if register:
            self.register()
        LOG.info(
            "plugin serving",
            extra=log.kv(resource=self.resource_name, socket=self.socket_path),
        )

    def _wait_ready(self, timeout: float = 5.0) -> None:
        """Self-dial until our socket answers (ref waitForGrpcServer,
        generic_device_plugin.go:98-115 — without the leaked context)."""
        with grpc.insecure_channel(f"unix://{self.socket_path}") as ch:
            grpc.channel_ready_future(ch).result(timeout=timeout)

    def register(self, attempts: Optional[int] = None,
                 backoff_s: Optional[float] = None) -> None:
        """Register with retry/backoff — a restarting kubelet can take longer
        than one dial timeout to come back (the reference fails hard once,
        generic_device_plugin.go:204-209). Policy comes from the
        constructor (``Config.register_attempts`` / ``register_backoff_s``
        on the daemon path); the exponential backoff is CAPPED at
        ``register_backoff_max_s`` and JITTERED (up to +25%) so a node's
        plugins do not hammer a recovering kubelet in lockstep. Exhausting
        every attempt emits a ``registration_exhausted`` obs event before
        raising — the daemon's death is diagnosable from the event
        stream, not silent."""
        import random

        from .. import obs

        attempts = self.register_attempts if attempts is None else attempts
        backoff_s = self.register_backoff_s if backoff_s is None else backoff_s
        last: Exception | None = None
        for attempt in range(attempts):
            if self._stop.is_set():
                return
            try:
                with grpc.insecure_channel(f"unix://{self.kubelet_socket}") as ch:
                    grpc.channel_ready_future(ch).result(
                        timeout=self.register_dial_timeout_s
                    )
                    glue.RegistrationStub(ch).Register(
                        pb.RegisterRequest(
                            version=glue.DEVICE_PLUGIN_VERSION,
                            endpoint=self.endpoint,
                            resource_name=self.resource_name,
                            options=pb.DevicePluginOptions(
                                pre_start_required=self.pre_start_required,
                                get_preferred_allocation_available=True,
                            ),
                        )
                    )
                metrics.registrations_total.labels(resource=self.resource_name).inc()
                LOG.info("registered with kubelet", extra=log.kv(resource=self.resource_name))
                return
            except (grpc.RpcError, grpc.FutureTimeoutError) as e:
                last = e
                LOG.warning(
                    "kubelet registration attempt failed",
                    extra=log.kv(
                        resource=self.resource_name,
                        attempt=attempt + 1,
                        err=str(e) or type(e).__name__,
                    ),
                )
                if attempt < attempts - 1:
                    # No dead sleep after the FINAL attempt: exhaustion
                    # should surface (event + raise) immediately.
                    delay = min(
                        backoff_s * (2**attempt), self.register_backoff_max_s
                    )
                    self._stop.wait(delay * (1.0 + 0.25 * random.random()))
        assert last is not None
        obs.emit(
            "plugin", "registration_exhausted",
            resource=self.resource_name, attempts=attempts,
            err=(str(last) or type(last).__name__)[:200],
        )
        raise last

    def restart(self) -> None:
        """Kubelet restarted (our socket vanished): re-serve and re-register
        on the SAME lifecycle — the stop event is untouched, so the manager's
        shutdown still reaches us (fixes Quirks 2)."""
        with self._lock:
            if self._stop.is_set():
                return
            self._serving.clear()
            if self._server is not None:
                self._server.stop(grace=1.0).wait()
                self._server = None
            self._start_locked(register=True)

    def request_stop(self) -> None:
        """Server-lock-free stop request, usable while another thread is
        inside start()/register() holding the server lock: just flips the
        event that register's dial/backoff waits on. The real teardown must
        still follow via stop(). (Not async-signal-safe — call from a normal
        thread, e.g. the daemon's signal-watcher, never a signal handler.)"""
        self._stop.set()

    def stop(self) -> None:
        # Set the stop flag BEFORE taking the lock: a concurrent restart()
        # may hold it through register()'s retry/backoff, and the flag is
        # what makes those waits return immediately.
        self._stop.set()
        with self._lock:
            self._serving.clear()
            if self._server is not None:
                self._server.stop(grace=1.0).wait()
                self._server = None
            try:
                os.unlink(self.socket_path)
            except FileNotFoundError:
                pass

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def serving(self) -> bool:
        return self._serving.is_set()

    # ----- kubelet-facing API ---------------------------------------------

    def GetDevicePluginOptions(self, request, context) -> pb.DevicePluginOptions:
        return pb.DevicePluginOptions(
            pre_start_required=self.pre_start_required,
            get_preferred_allocation_available=True,
        )

    def ListAndWatch(self, request, context):
        """Initial device list, then a fresh snapshot on every state change
        (ref generic_device_plugin.go:222-250, without the shared-slice races)."""
        q = self.state.subscribe()
        try:
            yield self._list_response()
            while not self._stop.is_set():
                try:
                    q.get(timeout=1.0)
                except queue.Empty:
                    if not context.is_active():
                        return
                    continue
                while True:  # coalesce bursts
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
                yield self._list_response()
        finally:
            self.state.unsubscribe(q)

    def _list_response(self) -> pb.ListAndWatchResponse:
        # Snapshot build + gauge refresh latency per stream update — the
        # device-layer half of the shared telemetry pipeline (ISSUE 2).
        with obs.timer(
            "plugin.ListAndWatch_update",
            metric=metrics.grpc_handler_seconds.labels(
                method="ListAndWatch_update", resource=self.resource_name
            ),
            resource=self.resource_name,
        ) as sp:
            devices = self.state.snapshot()
            resp = pb.ListAndWatchResponse(devices=[d.to_pb() for d in devices])
            for health in (glue.HEALTHY, glue.UNHEALTHY):
                metrics.devices_total.labels(resource=self.resource_name, health=health).set(
                    sum(1 for d in devices if d.health == health)
                )
            sp.set(devices=len(devices))
        return resp

    def GetPreferredAllocation(self, request, context) -> pb.PreferredAllocationResponse:
        resp = pb.PreferredAllocationResponse()
        with obs.timer(
            "plugin.GetPreferredAllocation",
            metric=metrics.grpc_handler_seconds.labels(
                method="GetPreferredAllocation", resource=self.resource_name
            ),
            resource=self.resource_name,
        ):
            for creq in request.container_requests:
                try:
                    chosen = self.allocator.preferred(
                        list(creq.available_device_ids),
                        list(creq.must_include_device_ids),
                        creq.allocation_size,
                    )
                except Exception as e:  # advisory API: degrade, don't fail admission
                    LOG.warning(
                        "preferred allocation failed",
                        extra=log.kv(resource=self.resource_name, err=str(e)),
                    )
                    chosen = list(creq.available_device_ids)[: creq.allocation_size]
                resp.container_responses.add(device_ids=chosen)
        return resp

    def Allocate(self, request, context) -> pb.AllocateResponse:
        """Validate against live state and answer with CDI references
        (ref generic_device_plugin.go:320-355).

        Telemetry: the whole call runs inside one span whose trace id is
        carried by every log line it emits (the formatters attach it), so
        the "allocated" line — and through it the device ids — can be
        joined to the pod UID the kubelet's pod-resources API later
        reports for those ids. The AllocateRequest itself carries no pod
        identity (v1beta1 limitation); the trace id is the join key."""
        resp = pb.AllocateResponse()
        granted: list[str] = []
        with obs.span(
            "plugin.Allocate",
            resource=self.resource_name,
            containers=len(request.container_requests),
        ) as sp:
            for creq in request.container_requests:
                ids = list(creq.device_ids)
                for dev_id in ids:
                    dev = self.state.get(dev_id)
                    if dev is None:
                        metrics.allocations_total.labels(
                            resource=self.resource_name, outcome="unknown_device"
                        ).inc()
                        context.abort(
                            grpc.StatusCode.INVALID_ARGUMENT,
                            f"unknown device id {dev_id!r} for {self.resource_name}",
                        )
                    if dev.health != glue.HEALTHY:
                        metrics.allocations_total.labels(
                            resource=self.resource_name, outcome="unhealthy"
                        ).inc()
                        context.abort(
                            grpc.StatusCode.UNAVAILABLE,
                            f"device {dev_id} of {self.resource_name} is unhealthy",
                        )
                try:
                    cresp = self.allocator.allocate(ids)
                except AllocationError as e:
                    metrics.allocations_total.labels(
                        resource=self.resource_name, outcome="rejected"
                    ).inc()
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                resp.container_responses.append(cresp)
                metrics.allocations_total.labels(
                    resource=self.resource_name, outcome="ok"
                ).inc()
                metrics.allocation_chips_total.labels(resource=self.resource_name).inc(len(ids))
                if self.on_allocate:
                    self.on_allocate(ids)
                LOG.info(
                    "allocated",
                    extra=log.kv(resource=self.resource_name, devices=",".join(ids)),
                )
                # Accumulate across containers: the span event is the
                # device-ids↔pod join record for the WHOLE request.
                granted.extend(ids)
                sp.set(devices=",".join(granted))
        metrics.grpc_handler_seconds.labels(
            method="Allocate", resource=self.resource_name
        ).observe(sp.duration_s)
        return resp

    def PreStartContainer(self, request, context) -> pb.PreStartContainerResponse:
        return pb.PreStartContainerResponse()
