"""Qwen2/2.5 family configs: Llama-style pre-norm decoder (GQA, SwiGLU,
RMSNorm w·x̂, untied unembedding at 7B scale) whose one architectural
delta is additive biases on the q/k/v projections (``qkv_bias=True`` —
wo and the MLP stay bias-free). Checkpoints convert both ways via
``models.convert`` (family ``qwen2``), parity-locked against the HF
implementation in ``tests/test_hf_convert.py``.

Architecture facts from the public Qwen2 report / HF configs: 7B is
28 layers, d_model 3584, 28 q heads / 4 kv heads (head_dim 128),
d_ff 18944, rope theta 1e6, vocab 152064.
"""
from __future__ import annotations

from dataclasses import replace

from .transformer import DecoderConfig


def qwen2_7b(**overrides) -> DecoderConfig:
    cfg = DecoderConfig(
        vocab_size=152064,
        d_model=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        head_dim=128,
        d_ff=18944,
        rope_theta=1e6,
        norm_eps=1e-6,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
        qkv_bias=True,
    )
    return replace(cfg, **overrides)


def qwen2_test_config(**overrides) -> DecoderConfig:
    """Qwen2 architecture at test scale (same ratios, 8-divisible dims)."""
    cfg = DecoderConfig(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=8,
        n_kv_heads=4,
        head_dim=16,
        d_ff=256,
        rope_theta=1e6,
        norm_eps=1e-6,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
        qkv_bias=True,
    )
    return replace(cfg, **overrides)
