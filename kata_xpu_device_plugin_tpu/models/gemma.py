"""Gemma model family configs (the BASELINE north-star inference workload:
"Gemma-2B inference (MaxText) inside Kata guest" — BASELINE.json configs[3]).

Architecture facts are from the public Gemma report: MQA (1 KV head) for the
2B model, GeGLU MLP, RMSNorm with (1+scale), RoPE, embedding scaling by
sqrt(d_model), tied unembedding, vocab 256128.
"""
from __future__ import annotations

from dataclasses import replace

from .transformer import DecoderConfig


def gemma_2b(**overrides) -> DecoderConfig:
    cfg = DecoderConfig(
        vocab_size=256128,
        d_model=2048,
        n_layers=18,
        n_heads=8,
        n_kv_heads=1,  # MQA
        head_dim=256,
        d_ff=16384,
        rope_theta=10000.0,
        activation="geglu",
        scale_embeddings=True,
        tie_embeddings=True,
    )
    return replace(cfg, **overrides)


def gemma_7b(**overrides) -> DecoderConfig:
    cfg = DecoderConfig(
        vocab_size=256128,
        d_model=3072,
        n_layers=28,
        n_heads=16,
        n_kv_heads=16,
        head_dim=256,
        d_ff=24576,
        rope_theta=10000.0,
        activation="geglu",
        scale_embeddings=True,
        tie_embeddings=True,
    )
    return replace(cfg, **overrides)


def gemma_2b_bench(**overrides) -> DecoderConfig:
    """The 2B architecture with a trimmed vocabulary for single-chip
    benchmarking: the 256k embedding dominates memory/compile at no benefit
    to a throughput benchmark of random weights. Layer compute is identical
    to gemma_2b."""
    return gemma_2b(vocab_size=32128, **overrides)


def gemma2_2b(**overrides) -> DecoderConfig:
    """Gemma-2 2B (public Gemma-2 report): alternating local/global
    attention (4096-token window on even layers), pre+post RMSNorms per
    sublayer, soft-capped attention (50.0) and final (30.0) logits, GQA."""
    cfg = DecoderConfig(
        vocab_size=256128,
        d_model=2304,
        n_layers=26,
        n_heads=8,
        n_kv_heads=4,
        head_dim=256,
        d_ff=9216,
        rope_theta=10000.0,
        activation="geglu",
        scale_embeddings=True,
        tie_embeddings=True,
        logits_softcap=30.0,
        attn_logits_softcap=50.0,
        attn_windows=(4096, 0),  # even layers local, odd layers global
        post_norms=True,
    )
    return replace(cfg, **overrides)


def gemma2_9b(**overrides) -> DecoderConfig:
    """Gemma-2 9B (public Gemma-2 report): same block STRUCTURE as 2B
    (alternating windows, post-norms, both softcaps) at larger dims —
    d_model 3584, 42 layers, GQA 16/8, d_ff 14336."""
    cfg = DecoderConfig(
        vocab_size=256128,
        d_model=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        head_dim=256,
        d_ff=14336,
        rope_theta=10000.0,
        activation="geglu",
        scale_embeddings=True,
        tie_embeddings=True,
        logits_softcap=30.0,
        attn_logits_softcap=50.0,
        attn_windows=(4096, 0),
        post_norms=True,
    )
    return replace(cfg, **overrides)


def gemma2_test_config(**overrides) -> DecoderConfig:
    """Shapes-only Gemma-2-style config: a short alternating window so the
    cycle and band both engage at test lengths, post-norms, both softcaps,
    4 layers (two cycles)."""
    from .transformer import tiny_test_config

    base = tiny_test_config(
        n_layers=4,
        logits_softcap=30.0,
        attn_logits_softcap=50.0,
        attn_windows=(6, 0),
        post_norms=True,
    )
    return replace(base, **overrides)


def gemma3_4b(**overrides) -> DecoderConfig:
    """Gemma-3 4B text (public Gemma-3 report / HF config): 5:1
    local/global attention pattern (1024-token window; every 6th layer
    global, the 34-layer tail truncating the last period exactly as the
    released checkpoint's layer_types does), per-head QK-norms, dual rope
    (local layers at base 10k, global at 1M with linear factor 8),
    pre+post norms, NO logit softcaps (Gemma-3 dropped them). The
    truncated pattern has no shorter period, so the scan unrolls the full
    depth — compile cost matches an unrolled model, numerics unaffected."""
    n_layers = 34
    windows = tuple(1024 if (i + 1) % 6 else 0 for i in range(n_layers))
    cfg = DecoderConfig(
        vocab_size=262208,
        d_model=2560,
        n_layers=n_layers,
        n_heads=8,
        n_kv_heads=4,
        head_dim=256,
        d_ff=10240,
        rope_theta=1_000_000.0,
        activation="geglu",
        scale_embeddings=True,
        tie_embeddings=True,
        post_norms=True,
        qk_norm=True,
        attn_windows=windows,
        rope_theta_cycle=tuple(
            10000.0 if w else 1_000_000.0 for w in windows
        ),
        rope_linear_cycle=tuple(1.0 if w else 8.0 for w in windows),
    )
    return replace(cfg, **overrides)


def gemma3_test_config(**overrides) -> DecoderConfig:
    """Gemma-3 architecture at test scale: QK-norms, a 2:1 local/global
    cycle with dual rope and a linear factor on the global position."""
    cfg = DecoderConfig(
        vocab_size=512,
        d_model=128,
        n_layers=6,
        n_heads=8,
        n_kv_heads=4,
        head_dim=16,
        d_ff=256,
        rope_theta=100_000.0,
        activation="geglu",
        scale_embeddings=True,
        tie_embeddings=True,
        post_norms=True,
        qk_norm=True,
        attn_windows=(8, 8, 0),
        rope_theta_cycle=(10000.0, 10000.0, 100_000.0),
        rope_linear_cycle=(1.0, 1.0, 8.0),
    )
    return replace(cfg, **overrides)
