"""Llama-3 model family configs (the BASELINE multi-host training workload:
"v5p-16 multi-host slice: Llama-3-8B training" — BASELINE.json configs[4]).

Architecture facts from the public Llama 3 report: GQA with 8 KV heads,
SwiGLU MLP, RMSNorm, RoPE theta 500000, vocab 128256, untied unembedding,
no embedding scaling.
"""
from __future__ import annotations

from dataclasses import replace

from .transformer import DecoderConfig


def llama3_8b(**overrides) -> DecoderConfig:
    cfg = DecoderConfig(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        d_ff=14336,
        rope_theta=500000.0,
        norm_eps=1e-5,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
    )
    return replace(cfg, **overrides)


def llama31_8b(**overrides) -> DecoderConfig:
    """Llama-3.1 8B: the 3.0 architecture plus the llama3 per-band rope
    rescale that buys the 128k context (factor 8 over an 8192-token
    original context — the released checkpoint's rope_scaling, applied
    in :func:`transformer.rope`)."""
    cfg = llama3_8b(rope_llama3_scaling=(8.0, 1.0, 4.0, 8192.0))
    return replace(cfg, **overrides)


def llama3_train_bench(**overrides) -> DecoderConfig:
    """Llama-3 architecture at single-chip train-bench scale (~256M params,
    MXU-friendly power-of-two dims): large enough that a train step is
    matmul-dominated and an MFU number is meaningful, small enough that
    params + Adam moments + rematerialized activations fit one v5e chip
    alongside the bench's decode model. Used by bench.py's train side
    section (``train_mfu`` / ``train_flash_speedup``)."""
    cfg = DecoderConfig(
        vocab_size=32768,
        d_model=1024,
        n_layers=12,
        n_heads=16,
        n_kv_heads=8,
        head_dim=64,
        d_ff=4096,
        rope_theta=500000.0,
        norm_eps=1e-5,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
    )
    return replace(cfg, **overrides)


def llama3_train_test(**overrides) -> DecoderConfig:
    """Llama-3 architecture at test scale (same ratios, 8-divisible dims)
    for the multi-chip training dry run."""
    cfg = DecoderConfig(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=8,
        n_kv_heads=4,
        head_dim=16,
        d_ff=256,
        rope_theta=500000.0,
        norm_eps=1e-5,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
    )
    return replace(cfg, **overrides)
