"""Speculative decoding: n-gram (prompt-lookup) and draft-model drafts.

Greedy decode emits one token per full weight stream from HBM; speculative
decoding drafts ``k`` candidate tokens cheaply and verifies them in ONE
forward over ``[B, k+1]`` — when ``a`` drafts are accepted, one weight
stream yields ``a+1`` tokens. Greedy speculative decoding is LOSSLESS: the
emitted sequence is exactly the vanilla greedy sequence (tested
token-identical), only the step count changes — regardless of where the
drafts come from (draft quality moves the acceptance rate, never tokens).

Two draft sources:

- **n-gram lookup** (no draft model): the most recent prior occurrence of
  the current token in the row's own history proposes the tokens that
  followed it — free, and effective exactly when text repeats (code,
  structured output, retrieval-augmented prompts).
- **a draft model** (``draft=(draft_params, draft_cfg)``): any smaller
  decoder sharing the target's vocabulary — the production shape for
  non-repetitive text. The draft keeps its OWN KV cache at the same
  per-row positions as the target; because each round's draft decode
  starts by writing the correction token at the first rejected slot, the
  stale entries from rejected drafts are overwritten (or sit beyond the
  causal frontier) and the draft cache stays consistent with the accepted
  history without any rollback pass. :func:`self_draft` builds a
  zero-training draft by depth-truncating the target itself.

TPU-first mechanics: verification reuses the decoder's ragged multi-token
cache path (:func:`..models.transformer._cache_write_rows` — per-row
``[B, k+1]`` spans at per-row positions), so one compiled verify
executable serves every acceptance pattern; n-gram drafting is host-side
numpy (it reads tokens the host already owns), draft-model drafting is
one k-step ``lax.scan`` decode executable. Rejected drafts' cache entries
are dead until the next verify span overwrites them — the causal index
mask (``k_pos <= q_pos``) never reads past each row's accepted prefix,
the same invariant the serving arena and prefill bucketing rely on.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (
    AttnFn,
    DecoderConfig,
    Params,
    _decode_scan,
    forward,
    greedy_token,
    prefill,
)


@partial(jax.jit, static_argnames=("cfg", "attn_fn", "ring"),
         donate_argnums=(1,))
def verify_step(params: Params, caches, toks: jax.Array, pos: jax.Array,
                cfg: DecoderConfig, attn_fn: Optional[AttnFn] = None,
                ring: bool = False):
    """Forward ``toks [B, S]`` (current token + S-1 drafts) with per-row
    cache offsets ``pos [B]``; returns (greedy next-token ids [B, S],
    updated caches). Writes all S k/v spans — acceptance decides how many
    become part of each row's valid prefix (the caller advances ``pos``).
    ``caches`` is DONATED: at model scale a per-round cache copy would
    double cache memory and add a full cache read+write per round.
    ``ring=True``: ``caches`` is a ring/cycle arena whose windowed layers
    must carry ≥ S−1 slots of safety margin over their window (the
    serving side sizes arenas as window + speculative_k — see
    ``_layer``'s ring branch for the eviction argument)."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B, S = toks.shape
    positions = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    logits, caches = forward(
        params, toks, cfg, attn_fn=attn_fn, positions=positions,
        kv_caches=caches, cache_offset=pos, ring=ring,
    )
    # greedy_token, not a local argmax: the verifier and vanilla generate()
    # must pick tokens identically or losslessness breaks.
    return greedy_token(logits), caches


@partial(jax.jit, static_argnames=("cfg", "attn_fn", "ring"),
         donate_argnums=(1,))
def verify_logits_step(params: Params, caches, toks: jax.Array,
                       pos: jax.Array, cfg: DecoderConfig,
                       attn_fn: Optional[AttnFn] = None,
                       ring: bool = False):
    """:func:`verify_step`'s sampling sibling: returns the fp32 logits
    ``[B, S, V]`` themselves instead of their argmax — speculative
    SAMPLING needs the full target distribution at every span position
    for the accept/residual test. Cache semantics identical."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B, S = toks.shape
    positions = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    logits, caches = forward(
        params, toks, cfg, attn_fn=attn_fn, positions=positions,
        kv_caches=caches, cache_offset=pos, ring=ring,
    )
    return logits.astype(jnp.float32), caches


@partial(jax.jit, static_argnames=("draft_cfg", "k", "attn_fn"),
         donate_argnums=(1,))
def draft_sample_propose(draft_params: Params, draft_caches,
                         cur: jax.Array, pos: jax.Array,
                         draft_cfg: DecoderConfig, k: int,
                         temperature, key: jax.Array,
                         attn_fn: Optional[AttnFn] = None):
    """Sampling counterpart of :func:`draft_propose`: draft ``k`` tokens
    per row by SAMPLING from the draft's temperature-scaled distribution
    (the rejection-sampling proof requires proposals drawn from the
    reported ``q``), returning ``(drafts [B, k], q [B, k, V], caches)``
    where ``q[b, i]`` is the exact distribution ``drafts[b, i]`` was
    drawn from. Runs k+1 steps for the same cache-hole reason as
    :func:`draft_propose`; the k+1-th sample is discarded."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B = cur.shape[0]

    def step(carry, key_i):
        caches, tok, p = carry
        logits, caches = forward(
            draft_params, tok[:, None], draft_cfg, attn_fn=attn_fn,
            positions=p[:, None], kv_caches=caches, cache_offset=p,
        )
        lg = logits[:, -1, :].astype(jnp.float32) / temperature
        nxt = jax.random.categorical(key_i, lg, axis=-1).astype(jnp.int32)
        return (caches, nxt, p + 1), (nxt, jax.nn.softmax(lg, axis=-1))

    init = (draft_caches, cur, jnp.asarray(pos, jnp.int32))
    (caches, _tok, _p), (toks, probs) = jax.lax.scan(
        step, init, jax.random.split(key, k + 1)
    )
    # scan stacks on axis 0: [k+1, B] / [k+1, B, V] → batch-major, drop
    # the cache-hole step's sample.
    return toks[:k].T, probs[:k].transpose(1, 0, 2), caches


def sample_accept_row(drafts_row: np.ndarray, q_row: np.ndarray,
                      p_row: np.ndarray, rng: np.random.Generator) -> list:
    """Lossless speculative SAMPLING acceptance for one row (Leviathan/
    Chen rejection scheme): accept draft ``x_i`` with probability
    ``min(1, p_i(x_i)/q_i(x_i))``; on the first rejection, emit a sample
    from the residual ``normalize(max(p_i − q_i, 0))`` and stop; if all
    ``k`` drafts are accepted, emit a bonus sample from ``p_k``. The
    emitted tokens are distributed EXACTLY as ancestral sampling from
    ``p`` — draft quality moves the acceptance rate, never the
    distribution. ``q_row [k, V]``, ``p_row [k+1, V]``; returns 1..k+1
    accepted tokens (the same contract as :func:`accept_drafts`)."""
    k = len(drafts_row)
    out: list[int] = []
    for i in range(k):
        x = int(drafts_row[i])
        q_x = float(q_row[i, x])
        p_x = float(p_row[i, x])
        if q_x > 0.0 and rng.random() < min(1.0, p_x / q_x):
            out.append(x)
            continue
        resid = np.maximum(p_row[i] - q_row[i], 0.0)
        total = resid.sum()
        if total <= 0.0:  # p == q numerically: any p-sample is exact
            resid, total = p_row[i], p_row[i].sum()
        out.append(int(rng.choice(len(resid), p=resid / total)))
        return out
    p_last = p_row[k]
    out.append(int(rng.choice(len(p_last), p=p_last / p_last.sum())))
    return out


NEG_INF = -1e30


@partial(jax.jit, static_argnames=("k", "has_q"))
def sample_accept_device(drafts: jax.Array, q, logits: jax.Array,
                         temperature, key: jax.Array, k: int,
                         has_q: bool = True):
    """:func:`sample_accept_row`'s on-device twin: the same rejection
    scheme, vectorized over the batch, so each verify round transfers
    only token ids and counts to the host — never the ``[B, k+1, V]``
    target distribution (at production vocab sizes that transfer would
    dominate round latency and erase the speculative win).

    ``logits [B, k+1, V]`` are the verify round's fp32 logits; ``q
    [B, k, V]`` the draft's proposal distributions (``has_q=False``
    treats the drafts as a one-hot proposal — the n-gram case — and
    ignores ``q``). Returns ``(tokens [B, k+1], count [B])`` where row
    ``b`` emits ``tokens[b, :count[b]]``: its accepted draft prefix,
    then the residual sample (first rejection) or the bonus sample from
    ``p_k`` (full acceptance) — both unified as a categorical over
    ``max(p_at − q_at, 0)`` with ``q`` zero-padded at position k."""
    B = drafts.shape[0]
    V = logits.shape[-1]
    p = jax.nn.softmax(logits.astype(jnp.float32) / temperature, axis=-1)
    if not has_q:
        q = jax.nn.one_hot(drafts, V, dtype=jnp.float32)
    k_u, k_r = jax.random.split(key)
    u = jax.random.uniform(k_u, (B, k))
    p_x = jnp.take_along_axis(p[:, :k], drafts[..., None], axis=-1)[..., 0]
    q_x = jnp.take_along_axis(q, drafts[..., None], axis=-1)[..., 0]
    accept = (q_x > 0.0) & (u * q_x < p_x)  # u < p/q without the divide
    first = jnp.min(
        jnp.where(~accept, jnp.arange(k)[None, :], k), axis=1
    )  # [B] index of the first rejection, k when all accepted
    p_at = jnp.take_along_axis(p, first[:, None, None], axis=1)[:, 0]
    q_pad = jnp.concatenate([q, jnp.zeros((B, 1, V), jnp.float32)], axis=1)
    q_at = jnp.take_along_axis(q_pad, first[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(p_at - q_at, 0.0)
    degenerate = resid.sum(-1, keepdims=True) <= 0.0  # p == q numerically
    resid = jnp.where(degenerate, p_at, resid)
    resid_logits = jnp.where(resid > 0.0, jnp.log(resid), NEG_INF)
    correction = jax.random.categorical(k_r, resid_logits, axis=-1)
    tokens = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), drafts.dtype)], axis=1
    )
    tokens = tokens.at[jnp.arange(B), first].set(
        correction.astype(drafts.dtype)
    )
    return tokens, (first + 1).astype(jnp.int32)


def self_draft(params: Params, cfg: DecoderConfig,
               n_layers: int) -> tuple[Params, DecoderConfig]:
    """A zero-training draft model: the target's FIRST ``n_layers`` decoder
    layers with its own embedding/final-norm/unembedding. Crude (the
    truncated trunk was never trained to feed the head directly), but it
    shares the vocabulary by construction, costs ``n_layers/L`` of a target
    step to draft, and exercises the exact draft-model plumbing a trained
    draft (e.g. a distilled 2-layer companion) would use.

    Layer-stacked params slice cleanly: every ``layers.*`` leaf is
    ``[L, ...]``, and window cycles interleave in layer order, so a prefix
    that is a multiple of the cycle length stays cycle-aligned."""
    from dataclasses import replace

    if not 0 < n_layers < cfg.n_layers:
        raise ValueError(
            f"self-draft depth {n_layers} must be in (0, {cfg.n_layers})"
        )
    cycle = len(cfg.window_cycle)
    if n_layers % cycle:
        raise ValueError(
            f"self-draft depth {n_layers} must be a multiple of the "
            f"attn_windows cycle length {cycle}"
        )
    draft_params = dict(params)
    draft_params["layers"] = jax.tree.map(
        lambda a: a[:n_layers], params["layers"]
    )
    return draft_params, replace(cfg, n_layers=n_layers)


def draft_propose(draft_params: Params, draft_caches, cur: jax.Array,
                  pos: jax.Array, draft_cfg: DecoderConfig, k: int,
                  attn_fn: Optional[AttnFn] = None):
    """Draft ``k`` greedy tokens per row with the draft model: one scan
    decode at per-row positions ``pos [B]``. Returns
    ``(drafts [B, k], updated draft caches)``.

    The scan runs ``k+1`` steps (one more than the draft length) so the
    cache entries ``pos .. pos+k`` are ALL written — a k-step scan never
    writes the k/v of its last emitted token, which would leave a
    permanent hole at ``pos+k`` whenever every draft is accepted (the
    next round resumes at ``pos+k+1``). The k+1-th emitted token is
    discarded. Rejected drafts' entries self-heal: the next round's scan
    starts by overwriting the first rejected slot, and stale entries
    beyond it sit above the causal frontier until overwritten (see
    module docstring)."""
    drafts, caches, _last, _pos = _decode_scan(
        draft_params, draft_caches, cur, pos, draft_cfg, k + 1, attn_fn,
        False, 0, jnp.float32(0.0), jax.random.PRNGKey(0),
        return_state=True,
    )
    return drafts[:, :k], caches


def ngram_propose(history: np.ndarray, cur: int, k: int) -> np.ndarray:
    """Draft ``k`` tokens for one row: the tokens that followed the most
    recent prior occurrence of ``cur`` in ``history`` (which ends with the
    tokens preceding ``cur``); pads by repeating ``cur`` when the match is
    near the end or absent (bad drafts only cost their rejection)."""
    matches = np.flatnonzero(history == cur)
    out = np.full(k, cur, np.int32)
    if len(matches):
        start = matches[-1] + 1
        tail = history[start : start + k]
        out[: len(tail)] = tail
    return out


def accept_drafts(drafts_row: np.ndarray, greedy_row: np.ndarray,
                  k: int) -> list:
    """The lossless acceptance rule, shared by :func:`generate_speculative`
    and the serving integration (``guest.serving._step_speculative``) so the
    token-identity guarantee lives in ONE place: accept the longest draft
    prefix the model's own greedy choices reproduce, then the model's
    correction token. Returns the accepted token list (length 1..k+1);
    the caller advances its position by ``len(accepted)``."""
    a = 0
    while a < k and drafts_row[a] == greedy_row[a]:
        a += 1
    return list(drafts_row[:a]) + [int(greedy_row[a])]


def generate_speculative(params: Params, prompt: jax.Array,
                         cfg: DecoderConfig, steps: int, k: int = 4,
                         max_len: int = 0,
                         attn_fn: Optional[AttnFn] = None,
                         draft: Optional[tuple] = None,
                         temperature: float = 0.0,
                         seed: int = 0) -> np.ndarray:
    """Speculative generation. At ``temperature=0`` the output is
    token-identical to greedy :func:`..models.transformer.generate`; at
    ``temperature>0`` it is lossless speculative SAMPLING — the emitted
    stream is distributed exactly as ancestral sampling from the
    temperature-scaled target (:func:`sample_accept_row`), though not the
    same stream as ``generate``'s (different randomness consumption;
    ``seed`` makes it reproducible). Returns ``[B, steps]`` int32; ``k``
    is the draft length per verify round.

    ``draft=(draft_params, draft_cfg)`` switches the draft source from
    n-gram lookup to a draft model (see module docstring); the draft
    prefills its own cache over the same prompt and tracks the same
    per-row positions as the target. Sampling mode draws the drafts from
    the draft's own distribution (n-gram drafts act as a one-hot
    proposal — valid, just lower acceptance)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    sampling = temperature > 0.0
    prompt = np.asarray(prompt, np.int32)
    B, S = prompt.shape
    # Each verify round may write up to k tokens past the accepted prefix;
    # the cache needs headroom for the last round's rejected tail.
    need = S + steps + k
    if max_len == 0:
        max_len = need
    elif max_len < need:
        raise ValueError(
            f"max_len={max_len} < prompt+steps+k={need} (speculative "
            "verification needs k entries of cache headroom)"
        )
    d_key = jax.random.PRNGKey(seed)
    caches, last, pos0 = prefill(params, jnp.asarray(prompt), cfg, max_len,
                                 return_logits=sampling)
    if sampling:
        from .transformer import _next_token

        d_key, k0 = jax.random.split(d_key)
        last = np.asarray(_next_token(last, k0, True,
                                      jnp.float32(temperature), 0))
    else:
        last = np.asarray(last)
    if draft is not None:
        draft_params, draft_cfg = draft
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size} — draft tokens would be meaningless"
            )
        draft_caches, _d_last, _d_pos = prefill(
            draft_params, jnp.asarray(prompt), draft_cfg, max_len
        )

    history = [list(prompt[b]) for b in range(B)]
    out: list[list[int]] = [[int(last[b])] for b in range(B)]
    pos = np.full(B, int(pos0), np.int32)

    while min(len(o) for o in out) < steps:
        cur = np.array([o[-1] for o in out], np.int32)
        q_dev = None
        if draft is not None and sampling:
            d_key, sub = jax.random.split(d_key)
            drafts_dev, q_dev, draft_caches = draft_sample_propose(
                draft_params, draft_caches, jnp.asarray(cur),
                jnp.asarray(pos), draft_cfg, k,
                jnp.float32(temperature), sub, attn_fn=attn_fn,
            )
            drafts = np.asarray(drafts_dev)
        elif draft is not None:
            drafts, draft_caches = draft_propose(
                draft_params, draft_caches, jnp.asarray(cur),
                jnp.asarray(pos), draft_cfg, k, attn_fn=attn_fn,
            )
            drafts = np.asarray(drafts)
        else:
            drafts = np.stack([
                ngram_propose(np.asarray(history[b], np.int32), int(cur[b]), k)
                for b in range(B)
            ])
        toks = np.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
        if sampling:
            # Accept/residual runs ON DEVICE (sample_accept_device):
            # only token ids and counts cross the transport, never the
            # [B, k+1, V] target distribution.
            logits, caches = verify_logits_step(
                params, caches, jnp.asarray(toks), jnp.asarray(pos), cfg,
                attn_fn=attn_fn,
            )
            d_key, sub = jax.random.split(d_key)
            tok_acc, counts = sample_accept_device(
                jnp.asarray(drafts), q_dev, logits,
                jnp.float32(temperature), sub, k, has_q=q_dev is not None,
            )
            tok_acc, counts = np.asarray(tok_acc), np.asarray(counts)
        else:
            greedy, caches = verify_step(
                params, caches, jnp.asarray(toks), jnp.asarray(pos), cfg,
                attn_fn=attn_fn,
            )
            greedy = np.asarray(greedy)  # greedy[b, j] follows toks[b, :j+1]
        for b in range(B):
            if len(out[b]) >= steps:
                # Row already done: its verify round was padding; do not
                # advance its state (rewrites the same span next round).
                continue
            if sampling:
                accepted = tok_acc[b, : counts[b]].tolist()
            else:
                accepted = accept_drafts(drafts[b], greedy[b], k)
            history[b].extend([int(cur[b])] + accepted[:-1])
            out[b].extend(accepted)
            pos[b] += len(accepted)  # cur + accepted drafts are now cached
    return np.array([o[:steps] for o in out], np.int32)
