"""Speculative decoding: n-gram (prompt-lookup) and draft-model drafts.

Greedy decode emits one token per full weight stream from HBM; speculative
decoding drafts ``k`` candidate tokens cheaply and verifies them in ONE
forward over ``[B, k+1]`` — when ``a`` drafts are accepted, one weight
stream yields ``a+1`` tokens. Greedy speculative decoding is LOSSLESS: the
emitted sequence is exactly the vanilla greedy sequence (tested
token-identical), only the step count changes — regardless of where the
drafts come from (draft quality moves the acceptance rate, never tokens).

Two draft sources:

- **n-gram lookup** (no draft model): the most recent prior occurrence of
  the current token in the row's own history proposes the tokens that
  followed it — free, and effective exactly when text repeats (code,
  structured output, retrieval-augmented prompts).
- **a draft model** (``draft=(draft_params, draft_cfg)``): any smaller
  decoder sharing the target's vocabulary — the production shape for
  non-repetitive text. The draft keeps its OWN KV cache at the same
  per-row positions as the target; because each round's draft decode
  starts by writing the correction token at the first rejected slot, the
  stale entries from rejected drafts are overwritten (or sit beyond the
  causal frontier) and the draft cache stays consistent with the accepted
  history without any rollback pass. :func:`self_draft` builds a
  zero-training draft by depth-truncating the target itself.

TPU-first mechanics: verification reuses the decoder's ragged multi-token
cache path (:func:`..models.transformer._cache_write_rows` — per-row
``[B, k+1]`` spans at per-row positions), so one compiled verify
executable serves every acceptance pattern; n-gram drafting is host-side
numpy (it reads tokens the host already owns), draft-model drafting is
one k-step ``lax.scan`` decode executable. Rejected drafts' cache entries
are dead until the next verify span overwrites them — the causal index
mask (``k_pos <= q_pos``) never reads past each row's accepted prefix,
the same invariant the serving arena and prefill bucketing rely on.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (
    AttnFn,
    DecoderConfig,
    Params,
    _decode_scan,
    forward,
    greedy_token,
    prefill,
)


@partial(jax.jit, static_argnames=("cfg", "attn_fn", "ring"),
         donate_argnums=(1,))
def verify_step(params: Params, caches, toks: jax.Array, pos: jax.Array,
                cfg: DecoderConfig, attn_fn: Optional[AttnFn] = None,
                ring: bool = False):
    """Forward ``toks [B, S]`` (current token + S-1 drafts) with per-row
    cache offsets ``pos [B]``; returns (greedy next-token ids [B, S],
    updated caches). Writes all S k/v spans — acceptance decides how many
    become part of each row's valid prefix (the caller advances ``pos``).
    ``caches`` is DONATED: at model scale a per-round cache copy would
    double cache memory and add a full cache read+write per round.
    ``ring=True``: ``caches`` is a ring/cycle arena whose windowed layers
    must carry ≥ S−1 slots of safety margin over their window (the
    serving side sizes arenas as window + speculative_k — see
    ``_layer``'s ring branch for the eviction argument)."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B, S = toks.shape
    positions = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    logits, caches = forward(
        params, toks, cfg, attn_fn=attn_fn, positions=positions,
        kv_caches=caches, cache_offset=pos, ring=ring,
    )
    # greedy_token, not a local argmax: the verifier and vanilla generate()
    # must pick tokens identically or losslessness breaks.
    return greedy_token(logits), caches


def self_draft(params: Params, cfg: DecoderConfig,
               n_layers: int) -> tuple[Params, DecoderConfig]:
    """A zero-training draft model: the target's FIRST ``n_layers`` decoder
    layers with its own embedding/final-norm/unembedding. Crude (the
    truncated trunk was never trained to feed the head directly), but it
    shares the vocabulary by construction, costs ``n_layers/L`` of a target
    step to draft, and exercises the exact draft-model plumbing a trained
    draft (e.g. a distilled 2-layer companion) would use.

    Layer-stacked params slice cleanly: every ``layers.*`` leaf is
    ``[L, ...]``, and window cycles interleave in layer order, so a prefix
    that is a multiple of the cycle length stays cycle-aligned."""
    from dataclasses import replace

    if not 0 < n_layers < cfg.n_layers:
        raise ValueError(
            f"self-draft depth {n_layers} must be in (0, {cfg.n_layers})"
        )
    cycle = len(cfg.window_cycle)
    if n_layers % cycle:
        raise ValueError(
            f"self-draft depth {n_layers} must be a multiple of the "
            f"attn_windows cycle length {cycle}"
        )
    draft_params = dict(params)
    draft_params["layers"] = jax.tree.map(
        lambda a: a[:n_layers], params["layers"]
    )
    return draft_params, replace(cfg, n_layers=n_layers)


def draft_propose(draft_params: Params, draft_caches, cur: jax.Array,
                  pos: jax.Array, draft_cfg: DecoderConfig, k: int,
                  attn_fn: Optional[AttnFn] = None):
    """Draft ``k`` greedy tokens per row with the draft model: one scan
    decode at per-row positions ``pos [B]``. Returns
    ``(drafts [B, k], updated draft caches)``.

    The scan runs ``k+1`` steps (one more than the draft length) so the
    cache entries ``pos .. pos+k`` are ALL written — a k-step scan never
    writes the k/v of its last emitted token, which would leave a
    permanent hole at ``pos+k`` whenever every draft is accepted (the
    next round resumes at ``pos+k+1``). The k+1-th emitted token is
    discarded. Rejected drafts' entries self-heal: the next round's scan
    starts by overwriting the first rejected slot, and stale entries
    beyond it sit above the causal frontier until overwritten (see
    module docstring)."""
    drafts, caches, _last, _pos = _decode_scan(
        draft_params, draft_caches, cur, pos, draft_cfg, k + 1, attn_fn,
        False, 0, jnp.float32(0.0), jax.random.PRNGKey(0),
        return_state=True,
    )
    return drafts[:, :k], caches


def ngram_propose(history: np.ndarray, cur: int, k: int) -> np.ndarray:
    """Draft ``k`` tokens for one row: the tokens that followed the most
    recent prior occurrence of ``cur`` in ``history`` (which ends with the
    tokens preceding ``cur``); pads by repeating ``cur`` when the match is
    near the end or absent (bad drafts only cost their rejection)."""
    matches = np.flatnonzero(history == cur)
    out = np.full(k, cur, np.int32)
    if len(matches):
        start = matches[-1] + 1
        tail = history[start : start + k]
        out[: len(tail)] = tail
    return out


def accept_drafts(drafts_row: np.ndarray, greedy_row: np.ndarray,
                  k: int) -> list:
    """The lossless acceptance rule, shared by :func:`generate_speculative`
    and the serving integration (``guest.serving._step_speculative``) so the
    token-identity guarantee lives in ONE place: accept the longest draft
    prefix the model's own greedy choices reproduce, then the model's
    correction token. Returns the accepted token list (length 1..k+1);
    the caller advances its position by ``len(accepted)``."""
    a = 0
    while a < k and drafts_row[a] == greedy_row[a]:
        a += 1
    return list(drafts_row[:a]) + [int(greedy_row[a])]


def generate_speculative(params: Params, prompt: jax.Array,
                         cfg: DecoderConfig, steps: int, k: int = 4,
                         max_len: int = 0,
                         attn_fn: Optional[AttnFn] = None,
                         draft: Optional[tuple] = None) -> np.ndarray:
    """Greedy generation with speculative decoding — output is
    token-identical to :func:`..models.transformer.generate` at
    ``temperature=0``. Returns ``[B, steps]`` int32 plus nothing else;
    ``k`` is the draft length per verify round.

    ``draft=(draft_params, draft_cfg)`` switches the draft source from
    n-gram lookup to a draft model (see module docstring); the draft
    prefills its own cache over the same prompt and tracks the same
    per-row positions as the target."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    prompt = np.asarray(prompt, np.int32)
    B, S = prompt.shape
    # Each verify round may write up to k tokens past the accepted prefix;
    # the cache needs headroom for the last round's rejected tail.
    need = S + steps + k
    if max_len == 0:
        max_len = need
    elif max_len < need:
        raise ValueError(
            f"max_len={max_len} < prompt+steps+k={need} (speculative "
            "verification needs k entries of cache headroom)"
        )
    caches, last, pos0 = prefill(params, jnp.asarray(prompt), cfg, max_len)
    last = np.asarray(last)
    if draft is not None:
        draft_params, draft_cfg = draft
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size} — draft tokens would be meaningless"
            )
        draft_caches, _d_last, _d_pos = prefill(
            draft_params, jnp.asarray(prompt), draft_cfg, max_len
        )

    history = [list(prompt[b]) for b in range(B)]
    out: list[list[int]] = [[int(last[b])] for b in range(B)]
    pos = np.full(B, int(pos0), np.int32)

    while min(len(o) for o in out) < steps:
        cur = np.array([o[-1] for o in out], np.int32)
        if draft is not None:
            drafts, draft_caches = draft_propose(
                draft_params, draft_caches, jnp.asarray(cur),
                jnp.asarray(pos), draft_cfg, k, attn_fn=attn_fn,
            )
            drafts = np.asarray(drafts)
        else:
            drafts = np.stack([
                ngram_propose(np.asarray(history[b], np.int32), int(cur[b]), k)
                for b in range(B)
            ])
        toks = np.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
        greedy, caches = verify_step(
            params, caches, jnp.asarray(toks), jnp.asarray(pos), cfg,
            attn_fn=attn_fn,
        )
        greedy = np.asarray(greedy)  # greedy[b, j] follows toks[b, :j+1]
        for b in range(B):
            if len(out[b]) >= steps:
                # Row already done: its verify round was padding; do not
                # advance its state (rewrites the same span next round).
                continue
            accepted = accept_drafts(drafts[b], greedy[b], k)
            history[b].extend([int(cur[b])] + accepted[:-1])
            out[b].extend(accepted)
            pos[b] += len(accepted)  # cur + accepted drafts are now cached
    return np.array([o[:steps] for o in out], np.int32)
