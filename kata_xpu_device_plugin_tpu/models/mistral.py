"""Mistral-7B family: GQA + SwiGLU + sliding-window attention.

Architecture constants follow the public Mistral-7B-v0.1 release; the
sliding window (4096) is what distinguishes it from the Llama-3 layout —
every layer attends only to the last ``sliding_window`` positions
(``ops.attention.reference_attention``'s band mask). Reference context:
the reference ships no model code at all (SURVEY §2); model families are
guest-side capability of the TPU-first rebuild.
"""
from __future__ import annotations

from dataclasses import replace

from .transformer import DecoderConfig


def mistral_7b(**overrides) -> DecoderConfig:
    cfg = DecoderConfig(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        d_ff=14336,
        rope_theta=10000.0,
        norm_eps=1e-5,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
        sliding_window=4096,
    )
    return replace(cfg, **overrides)


def mistral_test_config(**overrides) -> DecoderConfig:
    """Shapes-only Mistral-style config (8-divisible dims, tiny window so
    the band mask actually engages at test sequence lengths)."""
    from .transformer import tiny_test_config

    base = tiny_test_config(
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
        sliding_window=8,
    )
    return replace(base, **overrides)
