"""Hugging Face checkpoint conversion: ``state_dict`` → this framework's
parameter pytree + :class:`DecoderConfig`.

The "switch to this framework" piece for weights: users of the supported
families (Llama/Mistral, Gemma, Gemma-2, Mixtral) hold checkpoints in the
HF ``transformers`` layout; this module maps them onto the stacked-layer
tree :func:`transformer.init_params` defines, so `forward`/`generate`/
`make_train_step` run them unchanged. The reverse of the usual porting
hazard applies: every convention difference is resolved HERE, once, and
locked by logit-parity tests against the canonical ``transformers`` CPU
implementations (`tests/test_hf_convert.py`) — not re-derived per model.

Convention deltas handled (cited to the HF modeling code they mirror):

- **Linear layout**: HF ``nn.Linear.weight`` is ``[out, in]``; this tree
  is input-major ``[in, out]`` → transpose every projection.
- **RMSNorm offset**: this tree's :func:`transformer.rms_norm` always
  computes ``(1 + scale) · x̂`` (the Gemma convention, matching HF
  ``Gemma*RMSNorm``); Llama-family HF norms compute ``weight · x̂`` → the
  converted scale is ``weight − 1`` for llama/mistral/mixtral.
- **Norm placement**: Llama/Gemma-1 ``post_attention_layernorm`` is the
  PRE-MLP norm (plain pre-norm blocks) → maps to ``mlp_norm``. Gemma-2
  adds true output norms: ``post_attention_layernorm`` /
  ``post_feedforward_layernorm`` norm each sublayer's output before the
  residual add → map to ``post_attn_norm`` / ``post_mlp_norm``, with
  ``pre_feedforward_layernorm`` as ``mlp_norm`` (cfg.post_norms=True).
- **Gemma-2 windows**: HF applies ``sliding_window`` on even layer
  indices (layer 0 local) → ``attn_windows=(sliding_window, 0)``.
- **Softcaps**: ``attn_logit_softcapping`` / ``final_logit_softcapping``
  → ``attn_logits_softcap`` / ``logits_softcap``.
- **Mixtral experts**: per-expert ``w1/w3/w2`` (gate/up/down) stack into
  ``moe_w_gate/moe_w_in/moe_w_out [L, E, ...]``; the router gate
  ``[E, d]`` transposes into ``router [d, E]``.

RoPE (half-split rotation, ``theta^{-2i/d}`` frequencies), embedding
scaling (``sqrt(d_model)``, Gemma only), GQA head grouping, and the
attention scale (``head_dim^{-1/2}``; Gemma-2 checkpoints use
``query_pre_attn_scalar == head_dim`` for the supported sizes) already
agree between the two implementations and need no transformation.
"""
from __future__ import annotations

from typing import Any, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from .transformer import DecoderConfig

# HF model_type → (activation, scale_embeddings, rmsnorm has the +1 baked
# in, tie_word_embeddings CLASS default). The tie default matters for raw
# config.json dicts: save_pretrained omits fields equal to the class
# default, so a tied Gemma checkpoint's dict has no tie_word_embeddings
# key at all.
_FAMILIES = {
    "llama": ("swiglu", False, False, False),
    "mistral": ("swiglu", False, False, False),
    "mixtral": ("swiglu", False, False, False),
    "qwen2": ("swiglu", False, False, False),
    "gemma": ("geglu", True, True, True),
    "gemma2": ("geglu", True, True, True),
    "gemma3_text": ("geglu", True, True, True),
}


def config_from_hf(hf_config: Any) -> DecoderConfig:
    """Map a ``transformers`` config object (or plain dict) to
    :class:`DecoderConfig`. Raises on unsupported ``model_type``."""
    get = (hf_config.get if isinstance(hf_config, Mapping)
           else lambda k, d=None: getattr(hf_config, k, d))
    model_type = get("model_type")
    if model_type not in _FAMILIES:
        raise ValueError(
            f"unsupported model_type {model_type!r}; supported: "
            f"{sorted(_FAMILIES)}"
        )
    activation, scale_embeddings, _, tie_default = _FAMILIES[model_type]
    # Fail closed on conventions this forward does not implement, so a
    # checkpoint never converts cleanly into wrong logits:
    scaling = get("rope_scaling")
    rope_llama3_scaling: tuple = ()
    gemma3_linear_factor = 1.0
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type")) or "default"
        if model_type == "gemma3_text":
            # HF applies rope_scaling to the GLOBAL rotary only (local
            # layers force rope_type=default at rope_local_base_freq), so
            # only the linear rescale maps — anything else (llama3/yarn)
            # would be applied per-layer differently than here.
            if rope_type == "linear":
                gemma3_linear_factor = float(scaling["factor"])
            elif rope_type != "default":
                raise ValueError(
                    f"rope_scaling rope_type={rope_type!r} is not "
                    "supported for gemma3_text (only the released "
                    "checkpoints' 'linear' global-layer rescale is)"
                )
        elif rope_type == "llama3":
            try:
                rope_llama3_scaling = (
                    float(scaling["factor"]),
                    float(scaling["low_freq_factor"]),
                    float(scaling["high_freq_factor"]),
                    float(scaling["original_max_position_embeddings"]),
                )
            except (KeyError, TypeError, ValueError) as bad:
                raise ValueError(
                    "rope_scaling rope_type='llama3' needs numeric "
                    "factor, low_freq_factor, high_freq_factor and "
                    f"original_max_position_embeddings fields: {bad!r}"
                ) from None
        elif rope_type != "default":
            raise ValueError(
                f"rope_scaling={scaling!r} is not supported: this forward "
                "implements plain RoPE and the llama3 per-band rescale "
                "only (yarn/linear/dynamic would convert without error "
                "but produce wrong logits at every position)"
            )
    for bias_field in ("attention_bias", "mlp_bias"):
        # qwen2's q/k/v biases ARE modeled (its branch sets qkv_bias) —
        # an attention_bias:true annotation there is accurate, not an
        # unsupported convention.
        if bias_field == "attention_bias" and model_type == "qwen2":
            continue
        if get(bias_field):
            raise ValueError(
                f"{bias_field}=True is not supported for "
                f"{model_type!r}: this family's projections are "
                "bias-free here (qwen2 is the one family whose q/k/v "
                "biases are modeled) and a silently dropped bias would "
                "corrupt the logits"
            )
    # The MLP gate nonlinearity is hardcoded per family (swiglu=silu,
    # geglu=tanh-approx gelu); a checkpoint trained with a different
    # hidden_act must not convert into silently different logits.
    allowed_acts = (
        {"silu"} if activation == "swiglu" else {"gelu_pytorch_tanh"}
    )
    for act_field in ("hidden_act", "hidden_activation"):
        act = get(act_field)
        if act is not None and act not in allowed_acts:
            raise ValueError(
                f"{act_field}={act!r} is not supported for "
                f"{model_type!r}: this forward applies "
                f"{sorted(allowed_acts)[0]!r} (exact-erf 'gelu' included "
                "— the tanh approximation here would drift from it)"
            )
    n_heads = get("num_attention_heads")
    d_model = get("hidden_size")
    head_dim = get("head_dim")
    if head_dim is None:
        # save_pretrained omits fields equal to the class default, and every
        # Gemma config class defaults head_dim=256 — which does NOT equal
        # d_model // n_heads for gemma-7b (3072/16=192), gemma2-9b (224) or
        # gemma3-4b (320). The quotient fallback is only correct for the
        # llama-family classes, which derive head_dim that way.
        head_dim = 256 if model_type.startswith("gemma") else d_model // n_heads
    kw = dict(
        vocab_size=get("vocab_size"),
        d_model=d_model,
        n_layers=get("num_hidden_layers"),
        n_heads=n_heads,
        n_kv_heads=get("num_key_value_heads") or n_heads,
        head_dim=head_dim,
        d_ff=get("intermediate_size"),
        rope_theta=float(get("rope_theta", 10000.0)),
        rope_llama3_scaling=rope_llama3_scaling,
        norm_eps=float(get("rms_norm_eps", 1e-6)),
        activation=activation,
        scale_embeddings=scale_embeddings,
        tie_embeddings=bool(get("tie_word_embeddings", tie_default)),
    )
    if model_type == "gemma2":
        kw.update(
            post_norms=True,
            # HF Gemma2Attention: even layer indices are sliding-window,
            # odd are global — layer 0 local matches cycle order.
            attn_windows=(int(get("sliding_window") or 0), 0),
            attn_logits_softcap=float(get("attn_logit_softcapping") or 0.0),
            logits_softcap=float(get("final_logit_softcapping") or 0.0),
        )
        scalar = get("query_pre_attn_scalar")
        if scalar is not None and int(scalar) != head_dim:
            raise ValueError(
                f"query_pre_attn_scalar={scalar} != head_dim={head_dim}: "
                "this forward scales attention by head_dim**-0.5 only "
                "(true for the released Gemma-2 2B/9B/27B checkpoints)"
            )
    elif model_type == "mistral":
        kw.update(sliding_window=int(get("sliding_window") or 0))
    elif model_type == "mixtral":
        kw.update(
            # Mixtral carries mistral's sliding_window field (None in the
            # released 8x7B config, set by community fine-tunes) — dropping
            # it would un-mask attention past the window.
            sliding_window=int(get("sliding_window") or 0),
            moe_num_experts=int(get("num_local_experts")),
            moe_top_k=int(get("num_experts_per_tok")),
        )
    elif model_type == "gemma3_text":
        layer_types = list(get("layer_types") or [])
        if not layer_types:
            # Raw config.json dicts saved before transformers introduced
            # layer_types carry sliding_window_pattern instead; HF's
            # Gemma3TextConfig derives the list the same way (every
            # pattern-th layer is global).
            pattern = int(get("sliding_window_pattern") or 6)
            layer_types = [
                "sliding_attention" if (i + 1) % pattern else "full_attention"
                for i in range(int(get("num_hidden_layers")))
            ]
        # Compress the per-layer attention types to their minimal period
        # (the released checkpoints repeat 5 sliding : 1 full) — the scan
        # unrolls one period, so compile cost scales with it.
        known = {"sliding_attention", "full_attention"}
        unknown = sorted(set(layer_types) - known)
        if unknown:
            raise ValueError(
                f"unknown gemma3 layer_types {unknown}: only "
                f"{sorted(known)} are modeled — an unrecognized type "
                "must not silently become full attention"
            )
        # 4096 is Gemma3TextConfig's class default — absent from raw
        # dicts saved with use_diff (save_pretrained omits defaults).
        sw = int(get("sliding_window") or 4096)
        if "sliding_attention" in layer_types and sw <= 0:
            raise ValueError(
                "gemma3_text config declares sliding_attention layers "
                f"but sliding_window={get('sliding_window')!r} — "
                "converting them to full attention would un-mask them"
            )
        n = len(layer_types)
        period = next(
            p for p in range(1, n + 1)
            if n % p == 0 and layer_types == layer_types[:p] * (n // p)
        )
        windows = tuple(
            sw if t == "sliding_attention" else 0
            for t in layer_types[:period]
        )
        theta_local = float(get("rope_local_base_freq", 10000.0))
        theta_global = kw["rope_theta"]
        kw.update(
            post_norms=True,
            qk_norm=True,
            attn_windows=windows,
            # local (windowed) layers rope at the local base frequency;
            # global layers at rope_theta, linearly rescaled on 4B+.
            rope_theta_cycle=tuple(
                theta_local if w > 0 else theta_global for w in windows
            ),
            rope_linear_cycle=(
                tuple(
                    1.0 if w > 0 else gemma3_linear_factor for w in windows
                )
                if gemma3_linear_factor != 1.0 else ()
            ),
        )
        scalar = get("query_pre_attn_scalar")
        if scalar is not None and int(scalar) != head_dim:
            raise ValueError(
                f"query_pre_attn_scalar={scalar} != head_dim={head_dim}: "
                "this forward scales attention by head_dim**-0.5 only "
                "(true for the released Gemma-3 1B/4B/12B text "
                "checkpoints; 27B scales by hidden/heads and is not "
                "supported)"
            )
    elif model_type == "qwen2":
        # Qwen2's q/k/v projections carry additive biases (wo/MLP do not).
        kw.update(qkv_bias=True)
        if get("use_sliding_window"):
            # Qwen2 gates its window per layer index (max_window_layers) —
            # different semantics from the uniform window here; the
            # released Qwen2/2.5 checkpoints ship use_sliding_window=False.
            raise ValueError(
                "use_sliding_window=True is not supported: Qwen2's "
                "layer-gated window (max_window_layers) has no equivalent "
                "here and a uniform window would attend differently"
            )
    return DecoderConfig(**kw)


def _t(x) -> np.ndarray:
    """torch tensor / array-like → float32 numpy (torch only imported if
    a tensor actually arrives, so the module works without torch)."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, dtype=np.float32)


def params_from_hf(
    state_dict: Mapping[str, Any],
    cfg: DecoderConfig,
    model_type: str,
    dtype=jnp.float32,
) -> Any:
    """Convert an HF ``state_dict`` to the stacked-layer pytree.

    ``state_dict`` keys may carry the ``model.`` prefix (ForCausalLM) or
    not (bare base model); both are accepted.
    """
    if model_type not in _FAMILIES:
        raise ValueError(f"unsupported model_type {model_type!r}")
    norm_has_plus1 = _FAMILIES[model_type][2]
    # Weights cast to the TARGET dtype per layer before stacking: staging
    # a whole [L, ...] stack in fp32 first would roughly double peak host
    # memory on a large bf16 checkpoint (Mixtral-8x7B scale).
    np_dtype = np.dtype(dtype)

    # Keep the Mapping as-is (no dict()): a lazy checkpoint view resolves
    # keys on access so the whole state_dict never materializes at once.
    sd = state_dict
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""

    def take(name):
        key = f"{prefix}{name}"
        if key not in sd:
            raise KeyError(
                f"missing {key!r} in state_dict (family {model_type})"
            )
        return _t(sd[key])

    def norm(name):
        w = take(name)
        # rms_norm computes (1 + scale)·x̂; HF llama-family computes w·x̂.
        return w if norm_has_plus1 else w - 1.0

    def stack(fn):
        return jnp.asarray(
            np.stack([np.asarray(fn(i), np_dtype)
                      for i in range(cfg.n_layers)])
        )

    L = f"layers.{{i}}."
    # Validate the projection shapes BEFORE converting anything: a config
    # whose head_dim was mis-derived (e.g. a gemma config.json re-saved
    # without its head_dim field) must fail here with the actual-vs-expected
    # shapes, not as a reshape crash deep inside the first forward. Shapes
    # are read off the raw tensors (torch or numpy both carry .shape) —
    # no _t() fp32 copy of a large projection just to look at its shape.
    for proj, expected, derivation in (
        ("q_proj", (cfg.q_dim, cfg.d_model),
         f"n_heads={cfg.n_heads} × head_dim={cfg.head_dim}"),
        ("k_proj", (cfg.kv_dim, cfg.d_model),
         f"n_kv_heads={cfg.n_kv_heads} × head_dim={cfg.head_dim}"),
    ):
        key = f"{prefix}{L.format(i=0)}self_attn.{proj}.weight"
        if key not in sd:
            raise KeyError(f"missing {key!r} in state_dict (family {model_type})")
        got = tuple(sd[key].shape)  # HF linear layout [out, in]
        if got != expected:
            raise ValueError(
                f"{proj} weight is {got} but the config derives {expected} "
                f"({derivation}, d_model={cfg.d_model}): the checkpoint and "
                "config disagree — most often a re-saved config.json "
                "missing its head_dim field"
            )
    layers = {
        "attn_norm": stack(lambda i: norm(L.format(i=i) + "input_layernorm.weight")),
        "wq": stack(lambda i: take(L.format(i=i) + "self_attn.q_proj.weight").T),
        "wk": stack(lambda i: take(L.format(i=i) + "self_attn.k_proj.weight").T),
        "wv": stack(lambda i: take(L.format(i=i) + "self_attn.v_proj.weight").T),
        "wo": stack(lambda i: take(L.format(i=i) + "self_attn.o_proj.weight").T),
    }
    if cfg.qkv_bias:  # Qwen2: additive q/k/v projection biases
        for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"),
                             ("bv", "v_proj")):
            layers[ours] = stack(
                lambda i, t=theirs: take(
                    L.format(i=i) + f"self_attn.{t}.bias"
                )
            )
    if cfg.qk_norm:  # Gemma-3: per-head QK-norms ((1+w) convention)
        layers["q_norm"] = stack(
            lambda i: norm(L.format(i=i) + "self_attn.q_norm.weight")
        )
        layers["k_norm"] = stack(
            lambda i: norm(L.format(i=i) + "self_attn.k_norm.weight")
        )
    if model_type in ("gemma2", "gemma3_text"):
        layers["post_attn_norm"] = stack(
            lambda i: norm(L.format(i=i) + "post_attention_layernorm.weight")
        )
        layers["mlp_norm"] = stack(
            lambda i: norm(L.format(i=i) + "pre_feedforward_layernorm.weight")
        )
        layers["post_mlp_norm"] = stack(
            lambda i: norm(L.format(i=i) + "post_feedforward_layernorm.weight")
        )
    else:
        # Llama/Gemma-1 "post_attention_layernorm" is the pre-MLP norm.
        layers["mlp_norm"] = stack(
            lambda i: norm(L.format(i=i) + "post_attention_layernorm.weight")
        )
    if model_type == "mixtral":
        E = cfg.moe_num_experts
        moe = L + "block_sparse_moe."
        layers["router"] = stack(
            lambda i: take(moe.format(i=i) + "gate.weight").T
        )
        layers["moe_w_gate"] = stack(lambda i: np.stack(
            [take(moe.format(i=i) + f"experts.{e}.w1.weight").T for e in range(E)]
        ))
        layers["moe_w_in"] = stack(lambda i: np.stack(
            [take(moe.format(i=i) + f"experts.{e}.w3.weight").T for e in range(E)]
        ))
        layers["moe_w_out"] = stack(lambda i: np.stack(
            [take(moe.format(i=i) + f"experts.{e}.w2.weight").T for e in range(E)]
        ))
    else:
        layers["w_gate"] = stack(
            lambda i: take(L.format(i=i) + "mlp.gate_proj.weight").T
        )
        layers["w_up"] = stack(
            lambda i: take(L.format(i=i) + "mlp.up_proj.weight").T
        )
        layers["w_down"] = stack(
            lambda i: take(L.format(i=i) + "mlp.down_proj.weight").T
        )

    params = {
        "embed": jnp.asarray(take("embed_tokens.weight"), dtype),
        "layers": layers,
        "final_norm": jnp.asarray(norm("norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" not in sd:
            raise KeyError(
                "config says untied embeddings but state_dict has no "
                "lm_head.weight"
            )
        params["unembed"] = jnp.asarray(_t(sd["lm_head.weight"]).T, dtype)
    return params


def load_hf_checkpoint(path: str, dtype=jnp.float32) -> tuple[Any, DecoderConfig]:
    """Load a locally saved HF checkpoint directory (``save_pretrained``
    layout: ``config.json`` + ``model.safetensors`` or a sharded
    ``model.safetensors.index.json``) without instantiating a torch model —
    tensors are read one at a time, on access, through a lazy Mapping
    (:class:`_LazyCheckpoint`), so peak host memory stays near the output
    tree plus one stacked weight group, never the whole checkpoint.
    ``pytorch_model.bin`` checkpoints are rejected (torch pickle
    loading pulls the whole file into memory and executes pickles; convert
    them to safetensors first)."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        hf_config = json.load(f)
    st_path = os.path.join(path, "model.safetensors")
    index_path = st_path + ".index.json"
    if os.path.exists(index_path):
        # The index's weight_map IS the key→shard mapping — no need to
        # open and list every shard just to rebuild it.
        with open(index_path) as f:
            weight_map = dict(json.load(f)["weight_map"])
    elif os.path.exists(st_path):
        from safetensors import safe_open

        with safe_open(st_path, framework="np") as f:
            weight_map = {key: "model.safetensors" for key in f.keys()}
    else:
        raise FileNotFoundError(
            f"no model.safetensors[.index.json] under {path!r} "
            "(pytorch_model.bin is not supported — convert to safetensors)"
        )
    return from_hf(_LazyCheckpoint(path, weight_map), hf_config, dtype=dtype)


class _LazyCheckpoint(Mapping):
    """Read-on-access view of a (possibly sharded) safetensors checkpoint:
    each key lookup mmap-opens its shard and copies out ONE tensor, so
    conversion peaks near the output tree plus a single stacked group
    instead of the whole checkpoint (`params_from_hf` must not dict() it —
    it takes the Mapping as-is)."""

    def __init__(self, path: str, weight_map: Mapping[str, str]):
        self._path = path
        self._weight_map = dict(weight_map)

    def __getitem__(self, key: str):
        import os

        from safetensors import safe_open

        shard = self._weight_map[key]
        with safe_open(
            os.path.join(self._path, shard), framework="np"
        ) as f:
            return f.get_tensor(key)

    def __iter__(self):
        return iter(self._weight_map)

    def __len__(self):
        return len(self._weight_map)


def from_hf(
    hf_model_or_state_dict: Any,
    hf_config: Optional[Any] = None,
    dtype=jnp.float32,
) -> tuple[Any, DecoderConfig]:
    """One-call conversion: ``(params, cfg) = from_hf(hf_model)``.

    Accepts a ``transformers`` ``*ForCausalLM``/base model (config read
    from it) or a raw ``state_dict`` plus an explicit ``hf_config``.
    """
    if hf_config is None:
        hf_config = getattr(hf_model_or_state_dict, "config", None)
        if hf_config is None:
            raise ValueError(
                "pass hf_config when converting a raw state_dict"
            )
    state_dict = (
        hf_model_or_state_dict.state_dict()
        if hasattr(hf_model_or_state_dict, "state_dict")
        else hf_model_or_state_dict
    )
    cfg = config_from_hf(hf_config)
    get = (hf_config.get if isinstance(hf_config, Mapping)
           else lambda k, d=None: getattr(hf_config, k, d))
    params = params_from_hf(state_dict, cfg, get("model_type"), dtype)
    return params, cfg


# ----- the reverse direction: export back to the HF ecosystem --------------


def hf_config_dict(
    cfg: DecoderConfig,
    model_type: str,
    max_position_embeddings: Optional[int] = None,
) -> dict:
    """Inverse of :func:`config_from_hf`: a plain ``config.json``-style
    dict for ``model_type``. Raises when the config carries features the
    family cannot express (so an export never silently drops semantics).

    ``max_position_embeddings``: trained context length to stamp into the
    exported config. Without it, unscaled llama/mistral/qwen2 exports
    inherit the HF CLASS default (LlamaConfig: 2048) and serving stacks
    that read it as the context limit cap an 8k+ model at 2k. For
    llama3-rope-scaled exports it overrides the ``factor × original`` span
    derived below (3.1 checkpoints train further and ship 131072)."""
    if model_type not in _FAMILIES:
        raise ValueError(f"unsupported model_type {model_type!r}")
    if model_type == "gemma3_text":
        raise ValueError(
            "gemma3_text is an import-only family: export would need the "
            "per-layer layer_types / dual-rope reconstruction"
        )
    if cfg.qk_norm or cfg.rope_theta_cycle or cfg.rope_linear_cycle:
        raise ValueError(
            "QK-norm / per-layer rope cycles (Gemma-3) have no exportable "
            f"representation in {model_type!r}"
        )
    activation, scale_embeddings, _, _ = _FAMILIES[model_type]
    if cfg.activation != activation:
        raise ValueError(
            f"cfg.activation={cfg.activation!r} does not match "
            f"{model_type!r} (expects {activation!r})"
        )
    if cfg.scale_embeddings != scale_embeddings:
        raise ValueError(
            f"cfg.scale_embeddings={cfg.scale_embeddings} does not match "
            f"{model_type!r}"
        )
    if cfg.moe != (model_type == "mixtral"):
        raise ValueError(
            f"MoE={cfg.moe} config cannot export as {model_type!r}"
        )
    if cfg.qkv_bias != (model_type == "qwen2"):
        raise ValueError(
            f"qkv_bias={cfg.qkv_bias} cannot export as {model_type!r}: "
            "only qwen2 carries q/k/v projection biases (a mismatch "
            "would leave the HF model's biases random-initialized or "
            "drop trained ones)"
        )
    if model_type == "qwen2" and cfg.head_dim * cfg.n_heads != cfg.d_model:
        raise ValueError(
            "qwen2 derives head_dim as hidden_size // num_heads; "
            f"head_dim={cfg.head_dim} × n_heads={cfg.n_heads} != "
            f"d_model={cfg.d_model} cannot round-trip"
        )
    out = dict(
        model_type=model_type,
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim,
        intermediate_size=cfg.d_ff,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.norm_eps,
        tie_word_embeddings=cfg.tie_embeddings,
    )
    if cfg.rope_llama3_scaling:
        if model_type != "llama":
            raise ValueError(
                f"{model_type!r} cannot express the llama3 rope rescale "
                "(only LlamaConfig takes rope_scaling rope_type='llama3')"
            )
        factor, low_f, high_f, old_len = cfg.rope_llama3_scaling
        out["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": factor,
            "low_freq_factor": low_f,
            "high_freq_factor": high_f,
            "original_max_position_embeddings": int(old_len),
        }
        # Without this, LlamaConfig's 2048 default would claim a context
        # BELOW the pre-scaling one and downstream consumers (serving
        # stacks read it as the context limit) would cap the long-context
        # model the rescale exists to enable. factor×old is the span the
        # rescale guarantees; trained-further checkpoints (3.1 ships
        # 131072) override via the explicit parameter.
        out["max_position_embeddings"] = int(factor * old_len)
    if max_position_embeddings is not None:
        out["max_position_embeddings"] = int(max_position_embeddings)
    if model_type == "gemma2":
        if not cfg.post_norms:
            raise ValueError("gemma2 export requires cfg.post_norms=True")
        cyc = cfg.attn_windows
        if len(cyc) != 2 or cyc[1] != 0 or cyc[0] <= 0:
            raise ValueError(
                f"gemma2 export needs attn_windows=(window, 0), got {cyc!r}"
            )
        out.update(
            sliding_window=cyc[0],
            query_pre_attn_scalar=cfg.head_dim,
            attn_logit_softcapping=cfg.attn_logits_softcap or None,
            final_logit_softcapping=cfg.logits_softcap or None,
        )
    else:
        if cfg.post_norms:
            raise ValueError(
                f"{model_type!r} has no post-norm slots; only gemma2 does"
            )
        if cfg.attn_logits_softcap or cfg.logits_softcap:
            raise ValueError(
                f"{model_type!r} cannot express logit softcaps"
            )
        if model_type in ("mistral", "mixtral"):
            if cfg.attn_windows:
                raise ValueError(
                    f"{model_type} expresses one uniform sliding_window; "
                    f"a per-layer attn_windows cycle {cfg.attn_windows!r} "
                    "would export to silently different attention"
                )
            out["sliding_window"] = cfg.sliding_window or None
        elif cfg.sliding_window or cfg.attn_windows:
            raise ValueError(
                f"{model_type!r} cannot express sliding windows"
            )
    if model_type == "mixtral":
        out.update(
            num_local_experts=cfg.moe_num_experts,
            num_experts_per_tok=cfg.moe_top_k,
        )
    return out


def to_hf_state_dict(
    params: Any,
    cfg: DecoderConfig,
    model_type: str,
    max_position_embeddings: Optional[int] = None,
) -> tuple[dict, dict]:
    """Export the stacked-layer pytree to an HF ``state_dict`` (numpy, the
    TREE'S dtype preserved — a bf16 tree exports bf16, half the bytes of a
    forced-fp32 export; norm offsets are computed in fp32 then cast back)
    + the matching config dict — the inverse of :func:`params_from_hf`,
    applying the same convention deltas in reverse (transpose back to
    ``[out, in]``, re-add the llama-family norm +1, unstack layers and
    Mixtral experts). The full dict is materialized in host memory (one
    tree-sized copy); there is no lazy path on the export side.

    Fused (``wqkv``), quantized (QTensor tuples) and LoRA trees are
    refused with the required preparation named: export operates on the
    plain training layout.
    """
    hf_cfg = hf_config_dict(cfg, model_type, max_position_embeddings)
    norm_has_plus1 = _FAMILIES[model_type][2]
    layers = params["layers"]
    if "wqkv" in layers:
        raise ValueError(
            "fused inference layout cannot export — convert the separate-"
            "matrix training layout (before fuse_decoder_params)"
        )
    if any(isinstance(v, tuple) for v in layers.values()):
        raise ValueError(
            "quantized/LoRA trees cannot export — dequantize or "
            "merge_lora first"
        )

    def npf(x) -> np.ndarray:
        # Native dtype preserved (bf16 trees export bf16 — safetensors'
        # numpy writer handles ml_dtypes); no forced-fp32 doubling.
        # ascontiguousarray is load-bearing even without .T: np.asarray on
        # a jax array can be a zero-copy view with device-layout strides,
        # and safetensors' numpy writer serializes the raw buffer without
        # checking contiguity — a non-contiguous view saves scrambled.
        return np.ascontiguousarray(np.asarray(x))

    def npt(x) -> np.ndarray:
        # The contiguity copy matters: ``.T`` is an F-ordered VIEW, and
        # safetensors' numpy writer serializes the raw buffer — saving a
        # non-contiguous view silently scrambles the element order.
        return np.ascontiguousarray(npf(x).T)

    def norm_out(w) -> np.ndarray:
        w = npf(w)
        if norm_has_plus1:
            return w
        # the ±1 offset in fp32, cast back to the tree's dtype
        return (w.astype(np.float32) + 1.0).astype(w.dtype)

    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": npf(params["embed"]),
        "model.norm.weight": norm_out(params["final_norm"]),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = npt(params["unembed"])

    for i in range(cfg.n_layers):
        L = f"model.layers.{i}."
        sd[L + "input_layernorm.weight"] = norm_out(layers["attn_norm"][i])
        for ours, theirs in (("wq", "q_proj"), ("wk", "k_proj"),
                             ("wv", "v_proj"), ("wo", "o_proj")):
            sd[L + f"self_attn.{theirs}.weight"] = npt(layers[ours][i])
        if cfg.qkv_bias:
            for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"),
                                 ("bv", "v_proj")):
                sd[L + f"self_attn.{theirs}.bias"] = npf(layers[ours][i])
        if model_type == "gemma2":
            sd[L + "post_attention_layernorm.weight"] = norm_out(
                layers["post_attn_norm"][i]
            )
            sd[L + "pre_feedforward_layernorm.weight"] = norm_out(
                layers["mlp_norm"][i]
            )
            sd[L + "post_feedforward_layernorm.weight"] = norm_out(
                layers["post_mlp_norm"][i]
            )
        else:
            sd[L + "post_attention_layernorm.weight"] = norm_out(
                layers["mlp_norm"][i]
            )
        if model_type == "mixtral":
            moe = L + "block_sparse_moe."
            sd[moe + "gate.weight"] = npt(layers["router"][i])
            for e in range(cfg.moe_num_experts):
                sd[moe + f"experts.{e}.w1.weight"] = npt(
                    layers["moe_w_gate"][i, e])
                sd[moe + f"experts.{e}.w3.weight"] = npt(
                    layers["moe_w_in"][i, e])
                sd[moe + f"experts.{e}.w2.weight"] = npt(
                    layers["moe_w_out"][i, e])
        else:
            sd[L + "mlp.gate_proj.weight"] = npt(layers["w_gate"][i])
            sd[L + "mlp.up_proj.weight"] = npt(layers["w_up"][i])
            sd[L + "mlp.down_proj.weight"] = npt(layers["w_down"][i])
    return sd, hf_cfg


def save_hf_checkpoint(
    params: Any,
    cfg: DecoderConfig,
    model_type: str,
    path: str,
    max_position_embeddings: Optional[int] = None,
) -> None:
    """Write a ``save_pretrained``-layout directory (``config.json`` +
    ``model.safetensors``) that ``transformers.AutoModelForCausalLM.
    from_pretrained`` — or :func:`load_hf_checkpoint` — accepts. Torch-free
    (numpy safetensors)."""
    import json
    import os

    from safetensors.numpy import save_file

    sd, hf_cfg = to_hf_state_dict(params, cfg, model_type, max_position_embeddings)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)
    save_file(sd, os.path.join(path, "model.safetensors"))
