"""Guest-side model families: the TPU-first decoder core plus Gemma (BASELINE
inference workload) and Llama-3 (BASELINE training workload) configs."""
from .gemma import (
    gemma2_2b,
    gemma3_4b,
    gemma3_test_config,
    gemma2_9b,
    gemma2_test_config,
    gemma_2b,
    gemma_2b_bench,
    gemma_7b,
)
from .convert import (
    config_from_hf,
    from_hf,
    hf_config_dict,
    load_hf_checkpoint,
    params_from_hf,
    save_hf_checkpoint,
    to_hf_state_dict,
)
from .llama import llama31_8b, llama3_8b, llama3_train_bench, llama3_train_test
from .mistral import mistral_7b, mistral_test_config
from .qwen2 import qwen2_7b, qwen2_test_config
from .mixtral import mixtral_8x7b, mixtral_test_config
from .speculative import draft_propose, generate_speculative, self_draft
from .transformer import (
    DecoderConfig,
    forward,
    generate,
    init_kv_caches,
    init_params,
    next_token_loss,
    tiny_test_config,
)

__all__ = [
    "DecoderConfig",
    "config_from_hf",
    "from_hf",
    "hf_config_dict",
    "load_hf_checkpoint",
    "save_hf_checkpoint",
    "to_hf_state_dict",
    "params_from_hf",
    "forward",
    "generate",
    "draft_propose",
    "generate_speculative",
    "self_draft",
    "init_kv_caches",
    "init_params",
    "next_token_loss",
    "tiny_test_config",
    "gemma2_2b",
    "gemma2_9b",
    "gemma2_test_config",
    "gemma3_4b",
    "gemma3_test_config",
    "gemma_2b",
    "gemma_2b_bench",
    "gemma_7b",
    "llama31_8b",
    "llama3_8b",
    "llama3_train_bench",
    "llama3_train_test",
    "mistral_7b",
    "mistral_test_config",
    "mixtral_8x7b",
    "mixtral_test_config",
    "qwen2_7b",
    "qwen2_test_config",
]
