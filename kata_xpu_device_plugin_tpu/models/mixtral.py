"""Mixtral model family configs — the MoE workload of the guest compute
stack (SURVEY §2 lists expert parallelism as a first-class component to
build; this makes it reachable from the model stack, not just a leaf op).

Architecture facts from the public Mixtral report: 8 experts, top-2 routing
with renormalized gates, otherwise the Llama architecture (GQA 8 KV heads,
SwiGLU experts, RoPE theta 1e6, vocab 32000, untied unembedding).
"""
from __future__ import annotations

from dataclasses import replace

from .transformer import DecoderConfig


def mixtral_8x7b(**overrides) -> DecoderConfig:
    cfg = DecoderConfig(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        d_ff=14336,
        rope_theta=1e6,
        norm_eps=1e-5,
        activation="swiglu",
        scale_embeddings=False,
        tie_embeddings=False,
        moe_num_experts=8,
        moe_top_k=2,
    )
    return replace(cfg, **overrides)


def mixtral_test_config(**overrides) -> DecoderConfig:
    """Shapes-only Mixtral-style config for CPU-mesh tests and the dryrun:
    4 experts (divisible by the test meshes' expert axis), ample capacity so
    nothing drops and outputs are comparable to the per-token reference."""
    from .transformer import tiny_test_config

    base = tiny_test_config(
        activation="swiglu",
        moe_num_experts=4,
        moe_top_k=2,
        moe_capacity_factor=4.0,
    )
    return replace(base, **overrides)
