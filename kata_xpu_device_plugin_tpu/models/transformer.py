"""TPU-first decoder-only transformer core.

This is the guest-side workload of the framework: the BASELINE ladder ends at
"Gemma-2B inference (MaxText) inside Kata guest" and "Llama-3-8B training"
(BASELINE.json configs[3-4]); :mod:`.gemma` and :mod:`.llama` instantiate
those families over this core.

Design choices are TPU/XLA-native, not a port of any CUDA runtime:

- pure-functional params (a pytree of arrays) + jittable apply; no framework
  Module state, so ``pjit``/``shard_map`` compose directly;
- layers stacked on a leading axis and iterated with ``lax.scan`` — one
  compiled layer body regardless of depth (fast compiles, XLA-friendly);
- bf16 compute / fp32 parameters & normalization accumulators, attention
  logits in fp32 (MXU-friendly shapes: head_dim and d_ff multiples of 128);
- attention implementation is injectable: the XLA reference from
  :mod:`..ops.attention`, the pallas flash kernel on TPU, or the ring
  variant for sequence parallelism.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..compat.jaxapi import tree_map
from ..ops.quant import (
    QTensor,
    broadcast_trailing,
    dequantize_kv,
    quantize_kv,
    weight_matmul,
)

Params = dict[str, Any]
AttnFn = Callable[..., jax.Array]  # (q, k, v, causal, q_offset) -> out


@dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    d_ff: int
    rope_theta: float = 10000.0
    # Llama-3.1-style RoPE frequency rescaling for long-context checkpoints:
    # () disables; (factor, low_freq_factor, high_freq_factor,
    # original_max_position_embeddings) applies the per-band inv_freq
    # transform (long wavelengths divided by ``factor``, short ones kept,
    # the middle band smoothly interpolated). Static tuple — resolved at
    # trace time, no runtime cost.
    rope_llama3_scaling: tuple = ()
    norm_eps: float = 1e-6
    # "geglu" (Gemma) or "swiglu" (Llama); both are gated MLPs, differing in
    # the gate nonlinearity.
    activation: str = "geglu"
    # Gemma multiplies embeddings by sqrt(d_model) and ties the unembedding.
    scale_embeddings: bool = True
    tie_embeddings: bool = True
    logits_softcap: float = 0.0  # 0 disables (Gemma-2 uses 30.0)
    # Sliding-window attention (Mistral-style): every layer sees only the
    # last `sliding_window` positions; 0 disables.
    sliding_window: int = 0
    # Per-layer attention-window CYCLE (Gemma-2 style alternation): layer i
    # uses attn_windows[i % len]. () = uniform (sliding_window everywhere).
    # The scan body unrolls one cycle, so compile cost scales with the
    # cycle length, not the layer count. n_layers % len must be 0.
    attn_windows: tuple = ()
    # Gemma-2 block shape: RMSNorm applied to each sublayer's OUTPUT as
    # well as its input (post_attn_norm / post_mlp_norm params).
    post_norms: bool = False
    # Qwen2-style additive biases on the q/k/v projections only (wo and
    # the MLP stay bias-free). Params ``bq/bk/bv`` (fused: ``bqkv``)
    # appear in the tree iff True — the same key-presence pattern as
    # post_norms, so every layout/parallelism path is tree-driven.
    qkv_bias: bool = False
    # Gemma-3-style per-head QK-norm: RMSNorm over head_dim applied to q
    # and k after the projection reshape, BEFORE rope. Params
    # ``q_norm``/``k_norm`` [L, head_dim] appear iff True.
    qk_norm: bool = False
    # Gemma-3-style per-layer rope parameters, aligned with the
    # attn_windows cycle (local layers use a different base frequency,
    # and 4B+ checkpoints linearly rescale positions on global layers):
    # rope_theta_cycle[i] overrides rope_theta for cycle position i;
    # rope_linear_cycle[i] divides the angular frequencies (HF
    # rope_type="linear" factor). () = uniform. When set, each must have
    # exactly len(window_cycle) entries.
    rope_theta_cycle: tuple = ()
    rope_linear_cycle: tuple = ()
    # Soft cap on ATTENTION logits (Gemma-2 uses 50.0); 0 disables. Capped
    # attention runs the XLA reference path (the flash kernels' blockwise
    # backward does not model the tanh).
    attn_logits_softcap: float = 0.0
    # MoE: num_experts > 0 replaces the dense FFN with a top-k MoE FFN in
    # EVERY layer (Mixtral layout; uniform layers keep the lax.scan single
    # compiled body). The silu-gated expert MLP comes from ops.moe.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01  # load-balancing loss weight
    dtype: Any = jnp.bfloat16

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def moe(self) -> bool:
        return self.moe_num_experts > 0

    def layer_window(self, i: int) -> int:
        """Attention window for layer ``i`` (0 = global)."""
        if self.attn_windows:
            return self.attn_windows[i % len(self.attn_windows)]
        return self.sliding_window

    @property
    def window_cycle(self) -> tuple:
        """The per-layer window cycle the scan unrolls (length 1 when
        uniform)."""
        return self.attn_windows or (self.sliding_window,)

    def moe_cfg(self):
        from ..ops.moe import MoEConfig

        return MoEConfig(
            d_model=self.d_model,
            d_ff=self.d_ff,
            num_experts=self.moe_num_experts,
            capacity_factor=self.moe_capacity_factor,
            top_k=self.moe_top_k,
        )

    def num_params(self) -> int:
        embed = self.vocab_size * self.d_model
        attn = self.d_model * (self.q_dim + 2 * self.kv_dim) + self.q_dim * self.d_model
        if self.qkv_bias:
            attn += self.q_dim + 2 * self.kv_dim
        if self.qk_norm:
            attn += 2 * self.head_dim
        if self.moe:
            mlp = self.d_model * self.moe_num_experts  # router
            mlp += self.moe_num_experts * 3 * self.d_model * self.d_ff
        else:
            mlp = 3 * self.d_model * self.d_ff
        norms = (4 if self.post_norms else 2) * self.d_model
        per_layer = attn + mlp + norms
        unembed = 0 if self.tie_embeddings else embed
        return embed + self.n_layers * per_layer + self.d_model + unembed


def tiny_test_config(**overrides) -> DecoderConfig:
    """A shapes-only config for CPU-mesh tests and the graft dry run: every
    sharded dimension divisible by 8 (mesh axes) and 2 KV heads for GQA."""
    base = DecoderConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=8,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
    )
    return replace(base, **overrides)


# ----- initialization ------------------------------------------------------


def init_params(key: jax.Array, cfg: DecoderConfig, dtype=jnp.float32) -> Params:
    """Stacked-layer parameter pytree (leading axis = layer, for lax.scan)."""
    k_embed, k_layers, k_unembed = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype) / jnp.sqrt(fan_in)).astype(dtype)

    L = cfg.n_layers
    if cfg.attn_windows and L % len(cfg.attn_windows) != 0:
        raise ValueError(
            f"n_layers={L} not divisible by the attn_windows cycle "
            f"{cfg.attn_windows}"
        )
    keys = jax.random.split(k_layers, 8)
    layers: Params = {
        "attn_norm": jnp.ones((L, cfg.d_model), dtype),
        "wq": dense(keys[0], (L, cfg.d_model, cfg.q_dim), cfg.d_model),
        "wk": dense(keys[1], (L, cfg.d_model, cfg.kv_dim), cfg.d_model),
        "wv": dense(keys[2], (L, cfg.d_model, cfg.kv_dim), cfg.d_model),
        "wo": dense(keys[3], (L, cfg.q_dim, cfg.d_model), cfg.q_dim),
        "mlp_norm": jnp.ones((L, cfg.d_model), dtype),
    }
    if cfg.post_norms:
        layers["post_attn_norm"] = jnp.ones((L, cfg.d_model), dtype)
        layers["post_mlp_norm"] = jnp.ones((L, cfg.d_model), dtype)
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_dim), dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_dim), dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_dim), dtype)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, cfg.head_dim), dtype)
        layers["k_norm"] = jnp.ones((L, cfg.head_dim), dtype)
    if cfg.moe:
        E, F = cfg.moe_num_experts, cfg.d_ff
        layers.update({
            "router": dense(keys[7], (L, cfg.d_model, E), cfg.d_model),
            "moe_w_gate": dense(keys[4], (L, E, cfg.d_model, F), cfg.d_model),
            "moe_w_in": dense(keys[5], (L, E, cfg.d_model, F), cfg.d_model),
            "moe_w_out": dense(keys[6], (L, E, F, cfg.d_model), F),
        })
    else:
        layers.update({
            "w_gate": dense(keys[4], (L, cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_up": dense(keys[5], (L, cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_down": dense(keys[6], (L, cfg.d_ff, cfg.d_model), cfg.d_ff),
        })
    params: Params = {
        "embed": dense(k_embed, (cfg.vocab_size, cfg.d_model), cfg.d_model),
        "layers": layers,
        "final_norm": jnp.ones((cfg.d_model,), dtype),
    }
    if not cfg.tie_embeddings:
        params["unembed"] = dense(k_unembed, (cfg.d_model, cfg.vocab_size), cfg.d_model)
    return params


def fuse_decoder_params(params: Params) -> Params:
    """Inference-layout transform: concatenate wq/wk/wv into one
    ``wqkv [L, d, q+2kv]`` and w_gate/w_up into ``w_gateup [L, d, 2f]``.

    The bandwidth-bound decode step then streams each weight group in one
    matmul instead of three/two — measured ~1% faster end-to-end decode on
    v5e (scripts/exp_decode.py). :func:`_layer` understands both layouts, so
    the same forward/generate code runs either; training keeps the separate
    layout (its sharding rules and checkpoints are keyed to it)."""
    layers = params["layers"]
    if "wqkv" in layers or "router" in layers:
        return params  # already fused, or MoE (no dense ffn to fuse)
    if any(isinstance(v, tuple) for v in layers.values()):
        raise ValueError(
            "fuse_decoder_params first: fusing concatenates raw weight "
            "matrices, not int8 QTensors or LoRA adapters — quantize/adapt "
            "after fusing (or merge_lora before)"
        )
    fused = {
        k: v for k, v in layers.items()
        if k not in ("wq", "wk", "wv", "w_gate", "w_up", "bq", "bk", "bv")
    }
    fused["wqkv"] = jnp.concatenate(
        [layers["wq"], layers["wk"], layers["wv"]], axis=2
    )
    fused["w_gateup"] = jnp.concatenate([layers["w_gate"], layers["w_up"]], axis=2)
    if "bq" in layers:  # Qwen2 qkv biases fuse along the same boundary
        fused["bqkv"] = jnp.concatenate(
            [layers["bq"], layers["bk"], layers["bv"]], axis=1
        )
    out = dict(params)
    out["layers"] = fused
    return out


# ----- building blocks -----------------------------------------------------


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    """RMSNorm with fp32 accumulation (Gemma convention: (1 + scale) * x̂)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * lax.rsqrt(var + eps)
    # Explicit trailing-dim broadcast: [D] → [1, ..., D]. Identical values,
    # but legal under jax_numpy_rank_promotion="raise" (strict mode runs
    # the serving decode window with promotion disallowed).
    scale32 = broadcast_trailing(1.0 + scale.astype(jnp.float32), x.ndim)
    return (normed * scale32).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float,
         llama3_scaling: tuple = (), linear_factor: float = 1.0) -> jax.Array:
    """Rotary position embedding. x: [B, S, H, D], positions: [B, S].

    ``llama3_scaling`` = (factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings) applies the Llama-3.1 per-band
    frequency rescale (matches HF ``_compute_llama3_parameters``):
    wavelengths longer than ``old/low`` are slowed by ``factor``, shorter
    than ``old/high`` kept, the band between linearly interpolated in
    ``old/wavelen`` space. ``linear_factor`` > 1 divides ALL angular
    frequencies (HF ``rope_type="linear"`` — Gemma-3's global layers).
    Everything is static, so the transforms fold into the compiled
    constant table."""
    d = x.shape[-1]
    freq_exponents = jnp.arange(0, d // 2, dtype=jnp.float32) * (2.0 / d)
    inv_freq = theta ** -freq_exponents  # [D/2]
    if linear_factor != 1.0:
        inv_freq = inv_freq / linear_factor
    if llama3_scaling:
        factor, low_f, high_f, old_len = (float(v) for v in llama3_scaling)
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = (old_len / wavelen - low_f) / (high_f - low_f)
        smoothed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > old_len / low_f,           # long-wavelength band
            inv_freq / factor,
            jnp.where(wavelen < old_len / high_f, inv_freq, smoothed),
        )
    # inv_freq [D/2] → [1, 1, D/2]: explicit broadcast against the
    # positions' [B, S, 1] — rank-promotion-clean under strict mode.
    angles = positions[..., None].astype(jnp.float32) * broadcast_trailing(
        inv_freq, positions.ndim + 1
    )  # [B, S, D/2]
    angles = angles[:, :, None, :]  # [B, S, 1, D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    # Half-split rotation reassembled with two pads + add, NOT with
    # split+concat or reshape/stack on the rotated dim: when x comes out of
    # a tensor-sharded projection, the 0.4.x SPMD partitioner silently
    # compiles both of those spellings to WRONG values (observed max-abs
    # errors of ~7-30 on a [B,S,2,16] GQA k — standalone for split+concat,
    # and once a KV-cache write joins the consumer set for reshape/stack).
    # Padding each rotated half to full width and adding is numerically
    # identical (disjoint supports) and partitions correctly on every
    # supported line in both patterns.
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    lo = x1 * cos - x2 * sin  # occupies [0, D/2)
    hi = x2 * cos + x1 * sin  # occupies [D/2, D)
    widths = [(0, 0)] * (x.ndim - 1)
    out = jnp.pad(lo, widths + [(0, d // 2)]) + jnp.pad(hi, widths + [(d // 2, 0)])
    return out.astype(x.dtype)


def _gate_act(x: jax.Array, kind: str) -> jax.Array:
    if kind == "geglu":
        return jax.nn.gelu(x, approximate=True)
    if kind == "swiglu":
        return jax.nn.silu(x)
    raise ValueError(f"unknown activation {kind!r}")


def embed(params: Params, tokens: jax.Array, cfg: DecoderConfig) -> jax.Array:
    """Token embedding (+ Gemma's sqrt(d_model) scaling). Shared by the
    unpipelined forward and the pipeline-parallel path so the two cannot
    drift."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.scale_embeddings:
        x = x * jnp.asarray(jnp.sqrt(cfg.d_model), cfg.dtype)
    return x


def unembed(params: Params, x: jax.Array, cfg: DecoderConfig) -> jax.Array:
    """Final norm → (tied) unembedding → fp32 logits (+ optional softcap)."""
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    proj = (params["embed"].T if cfg.tie_embeddings else params["unembed"]).astype(
        cfg.dtype
    )
    # bf16×bf16 on the MXU with fp32 accumulation — not a bf16 matmul whose
    # low bits are discarded before a separate fp32 cast.
    logits = jnp.matmul(x, proj, preferred_element_type=jnp.float32)
    if cfg.logits_softcap:
        logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
    return logits


# ----- forward pass --------------------------------------------------------


def _cache_write_full(cache, x: jax.Array, offset) -> "QTensor | jax.Array":
    """Write fresh k/v ``x [B, S, KV, D]`` into a cache at sequence offset
    ``offset`` (prefill / lockstep decode). Quantizes on the way in when the
    cache is an int8 :class:`QTensor`."""
    if isinstance(cache, QTensor):
        qt = quantize_kv(x)
        at = (0, offset, 0, 0)
        return QTensor(
            lax.dynamic_update_slice(cache.q, qt.q, at),
            lax.dynamic_update_slice(cache.scale, qt.scale, at),
        )
    return lax.dynamic_update_slice(cache, x.astype(cache.dtype), (0, offset, 0, 0))


def _cache_write_rows(cache, x: jax.Array, rows, idx,
                      wrap: int = 0) -> "QTensor | jax.Array":
    """Ragged write: row ``b``'s ``S`` k/v vectors land at its own
    positions ``idx[b] .. idx[b]+S-1``, each clamped HERE to max_len-1 (an
    over-bound serving slot scribbles the last entry, which is never read;
    multi-token callers size the cache so the clamp never engages).
    ``wrap > 0``: ring-buffer semantics instead — each position lands at
    slot ``position % wrap`` (a span crossing the wrap boundary scatters
    non-contiguously, which the positionwise ``.at[]`` write handles).
    x: [B, S, KV, D]; rows [B]; idx [B]."""
    S = x.shape[1]
    max_len = (cache.q if isinstance(cache, QTensor) else cache).shape[1]
    span = idx[:, None] + jnp.arange(S)[None, :]
    cols = span % wrap if wrap else jnp.minimum(span, max_len - 1)
    rows2 = rows[:, None]
    if isinstance(cache, QTensor):
        qt = quantize_kv(x)
        return QTensor(
            cache.q.at[rows2, cols].set(qt.q),
            cache.scale.at[rows2, cols].set(qt.scale),
        )
    return cache.at[rows2, cols].set(x.astype(cache.dtype))


# Reserved physical blocks of the paged pool — the layout contract with
# guest.kv_arena.KVPool (which re-exports these). Block 0 is ZERO: never
# written, so unmapped view entries gather the zeros a fresh dense arena
# would hold. Block 1 is SCRATCH: the block-table filler, absorbing
# writes that must not land anywhere real (dead lanes, overruns).
PAGED_ZERO_BLOCK = 0
PAGED_SCRATCH_BLOCK = 1


def _paged_write_token(cache, x: jax.Array, phys: jax.Array):
    """Paged decode write: row ``b``'s single fresh k/v vector lands at
    PHYSICAL pool row ``phys[b]`` (block_table[b, pos//bs] * bs + pos%bs,
    resolved by the caller). ``cache`` is a pool slice ``[1, NT, KV, D]``
    (or int8 QTensor pair); x: [B, 1, KV, D]. The scheduler guarantees
    live lanes map distinct physical rows; lanes with no live request aim
    at the scratch block (never read), so duplicate scatter order there
    is irrelevant."""
    if isinstance(cache, QTensor):
        qt = quantize_kv(x)
        return QTensor(
            cache.q.at[0, phys].set(qt.q[:, 0]),
            cache.scale.at[0, phys].set(qt.scale[:, 0]),
        )
    return cache.at[0, phys].set(x[:, 0].astype(cache.dtype))


def _paged_write_span(cache, x: jax.Array, phys: jax.Array):
    """Multi-token sibling of :func:`_paged_write_token` (the mixed-batch
    branch, ISSUE 13): row ``b``'s ``S`` fresh k/v vectors land at
    PHYSICAL pool rows ``phys[b, j]`` (each resolved through the row's
    block table by the caller). x: [B, S, KV, D]; phys: [B, S]. Same
    scratch-block contract as the single-token form — positions past a
    lane's span aim at SCRATCH by table-filler design."""
    if isinstance(cache, QTensor):
        qt = quantize_kv(x)
        return QTensor(
            cache.q.at[0, phys].set(qt.q),
            cache.scale.at[0, phys].set(qt.scale),
        )
    return cache.at[0, phys].set(x.astype(cache.dtype))


def _paged_view(cache, idx: jax.Array):
    """Gather each row's block-table view out of the pool:
    ``cache [1, NT, ...]`` + ``idx [B, Lm]`` physical row indices →
    ``[B, Lm, ...]`` — the same dense operand shape the fixed-slot arena
    presents to attention (unmapped entries index the zero block)."""
    if isinstance(cache, QTensor):
        return QTensor(cache.q[0][idx], cache.scale[0][idx])
    return cache[0][idx]


def _layer(
    cfg: DecoderConfig,
    attn_fn: AttnFn,
    x: jax.Array,
    layer: Params,
    positions: jax.Array,
    kv_cache: Optional[tuple[jax.Array, jax.Array]] = None,
    cache_offset: Optional[jax.Array] = None,
    prefill: bool = False,
    moe_mesh=None,
    ring: bool = False,
    window: Optional[int] = None,
    rope_theta: Optional[float] = None,
    rope_linear: float = 1.0,
    block_tables: Optional[jax.Array] = None,
    block_size: int = 0,
    paged_len: int = 0,
    decode_kernel_fn=None,
    reduce_fn=None,
):
    """One decoder block. x: [B, S, D]. Returns (x, new_kv, aux) where aux
    is the layer's MoE load-balancing loss (0.0 for dense layers).
    ``ring=True``: the cache is a ``sliding_window``-slot ring buffer
    (slot = position % window) instead of a max_len array. ``window``
    overrides ``cfg.sliding_window`` for THIS layer (the per-layer
    attn_windows cycle). ``decode_kernel_fn`` (STATIC — resolved once by
    the server, see ``ops.attention.make_decode_attn_fn``) routes the
    single-token ragged decode branches (paged AND slotted) through the
    paged-native pallas kernel instead of the gather + XLA path; None
    keeps the XLA path. ``reduce_fn`` (STATIC, resolved once per server
    — ISSUE 20) wraps the two ROW-PARALLEL projection outputs (after
    ``wo`` and after ``w_down``): under tensor-parallel serving those
    partial sums carry the layer's pending model-axis psum, and the
    server's overlap hint (``tp_serving.overlap_reduce_fn``) decomposes
    it into reduce-scatter + all-gather so the collective pipelines
    against the surrounding matmuls. Summation order per output element
    is unchanged (the same shard partials add in the same axis order),
    so greedy outputs are bit-identical with it on or off; None keeps
    the single fused psum."""
    B, S, _ = x.shape
    eff_window = cfg.sliding_window if window is None else window
    # Sliding window rides as a kwarg only when configured, so custom
    # attn_fns (ring/ulysses sequence parallelism) keep their narrower
    # signature for window-free configs.
    wkw = {"window": eff_window} if eff_window else {}
    if cfg.attn_logits_softcap:
        # Capped attention logits (Gemma-2): both the XLA reference and
        # the pallas flash kernels (forward + backward) model the tanh, so
        # softcap configs keep the fast path — the dispatchers take it as
        # a kwarg. Custom attn_fns (ring/ulysses sp wrappers) that do not
        # declare the kwarg would silently skip the cap — refuse those.
        import inspect

        from ..ops.attention import (
            best_attention,
            flash_attention,
            reference_attention,
        )

        if attn_fn not in (reference_attention, flash_attention, best_attention):
            try:
                accepts = "logits_softcap" in inspect.signature(attn_fn).parameters
            except (TypeError, ValueError):
                accepts = False
            if not accepts:
                raise ValueError(
                    "attn_logits_softcap needs an attention fn that models "
                    "the cap; this custom attn_fn does not take "
                    "logits_softcap, so the cap would be silently ignored"
                )
        attn_fn = partial(attn_fn, logits_softcap=cfg.attn_logits_softcap)
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    if "wqkv" in layer:
        # Fused projection (see fuse_decoder_params): one matmul streams the
        # q/k/v weights in a single pass — fewer kernels on the
        # bandwidth-bound decode step. weight_matmul also accepts int8
        # QTensors (ops.quant), which halve that stream again.
        qkv = weight_matmul(h, layer["wqkv"])
        if "bqkv" in layer:  # Qwen2: fused q/k/v bias, one add
            # [3D] → [1, 1, 3D]: explicit trailing-dim broadcast (legal
            # under strict mode's rank_promotion="raise").
            qkv = qkv + broadcast_trailing(
                layer["bqkv"].astype(qkv.dtype), qkv.ndim
            )
        q = qkv[..., : cfg.q_dim]
        k = qkv[..., cfg.q_dim : cfg.q_dim + cfg.kv_dim]
        v = qkv[..., cfg.q_dim + cfg.kv_dim :]
    else:
        q = weight_matmul(h, layer["wq"])
        k = weight_matmul(h, layer["wk"])
        v = weight_matmul(h, layer["wv"])
        if "bq" in layer:  # Qwen2: q/k/v projection biases
            # explicit [1, 1, D] broadcast — see the fused branch above
            q = q + broadcast_trailing(layer["bq"].astype(q.dtype), q.ndim)
            k = k + broadcast_trailing(layer["bk"].astype(k.dtype), k.ndim)
            v = v + broadcast_trailing(layer["bv"].astype(v.dtype), v.ndim)
    q = q.reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
    if "q_norm" in layer:  # Gemma-3: per-head QK-norm before rope
        q = rms_norm(q, layer["q_norm"], cfg.norm_eps)
        k = rms_norm(k, layer["k_norm"], cfg.norm_eps)
    theta = cfg.rope_theta if rope_theta is None else rope_theta
    q = rope(q, positions, theta, cfg.rope_llama3_scaling, rope_linear)
    k = rope(k, positions, theta, cfg.rope_llama3_scaling, rope_linear)

    if kv_cache is not None and prefill:
        # Prefill: the cache is empty, so attention over the FRESH k/v is
        # exact self-attention — no q_offset, no reading back the max_len
        # cache. This both skips the dead [S, max_len-S] score region and
        # makes the shapes eligible for the pallas flash kernel (which is
        # self-attention only).
        ck, cv = kv_cache
        ck = _cache_write_full(ck, k, 0)
        cv = _cache_write_full(cv, v, 0)
        attn_out = attn_fn(q, k, v, causal=True, q_offset=None, **wkw)
        new_cache = (ck, cv)
    elif kv_cache is not None and ring:
        # Ring decode: the cache holds the live window, written at slot
        # pos % W; attention consumes the slots' ABSOLUTE positions
        # (ring_positions) so the causal/validity mask is position-exact
        # even though slots are stored out of order. Memory and per-step
        # cache traffic are O(window), not O(max_len). ``cache_offset``
        # may be a lockstep scalar (generate) or a [B] vector of per-slot
        # positions — continuous batching with ragged requests keeps the
        # same O(window) arena, each row wrapping independently.
        #
        # The arena may carry MORE slots than the window (W ≥ window +
        # S − 1): speculative verification writes [B, S=k+1] spans, and
        # without the k-slot safety margin a span's later writes would
        # evict keys still inside the span's EARLIER queries' windows
        # (write at p evicts p−W ≤ pos−window only when W ≥ window+k).
        # The window band is enforced by the explicit ``window=`` mask,
        # not by the arena size.
        from ..ops.attention import reference_attention as _ref_attn

        ck, cv = kv_cache
        W = (ck.q if isinstance(ck, QTensor) else ck).shape[1]
        assert W >= eff_window + S - 1, (
            f"ring cache has {W} slots but this layer needs "
            f"window {eff_window} + span {S} - 1 — an undersized ring "
            "would evict keys still inside a live window"
        )
        if jnp.ndim(cache_offset) == 0:
            assert S == 1, "lockstep ring decode is single-token"
            ck = _cache_write_full(ck, k, cache_offset % W)
            cv = _cache_write_full(cv, v, cache_offset % W)
            k_pos = ring_positions(cache_offset, W)  # [W]
        else:
            # Ragged: row b writes its S k/v vectors at its own slots
            # (position % W — spans wrap non-contiguously, wrap= handles).
            rows = jnp.arange(B)
            ck = _cache_write_rows(ck, k, rows, cache_offset, wrap=W)
            cv = _cache_write_rows(cv, v, rows, cache_offset, wrap=W)
            k_pos = ring_positions(cache_offset[:, None] + (S - 1), W)
        attn_out = _ref_attn(
            q, dequantize_kv(ck, x.dtype), dequantize_kv(cv, x.dtype),
            causal=True, q_offset=cache_offset,
            k_positions=k_pos, window=eff_window,
            logits_softcap=cfg.attn_logits_softcap,
        )
        new_cache = (ck, cv)
    elif kv_cache is not None and block_tables is not None:
        # PAGED ragged decode: the cache pair is this layer's
        # [1, NT, KV, D] slice of the shared block pool
        # (guest.kv_arena.KVPool); ``block_tables`` [B, NB] maps row b's
        # logical block j to pool block ``block_tables[b, j]``. Write the
        # fresh k/v at its physical row, then gather each row's view back
        # into the SAME [B, paged_len] dense operand the fixed-slot arena
        # presents (mapped entries hold verbatim the rows the dense path
        # would hold, unmapped entries read the reserved zero block, and
        # the mask replaces every column > pos before softmax) — so the
        # attention math, and greedy tokens, are bit-identical to the
        # fixed-slot path. Out-of-range block indexes (a finished lane
        # overrunning its budget, same class as the dense clamp-at-
        # max_len-1) clamp to the last table entry, whose filler is the
        # scratch block — garbage lands where nothing live reads.
        # S > 1 is the MIXED-BATCH form (ISSUE 13): each row writes its
        # S-token span at its own positions (cache_offset[b] .. +S-1)
        # through its table and attends with per-row query offsets — the
        # per-lane-query-length forward fused prefill+decode dispatches
        # ride (the single-token decode scan is the S == 1 case).
        ck, cv = kv_cache
        bs = block_size
        rows = jnp.arange(B)
        if S == 1:
            blk = jnp.minimum(cache_offset // bs, block_tables.shape[1] - 1)
            phys = block_tables[rows, blk] * bs + cache_offset % bs  # [B]
            ck = _paged_write_token(ck, k, phys)
            cv = _paged_write_token(cv, v, phys)
        else:
            span = cache_offset[:, None] + jnp.arange(S)[None, :]  # [B, S]
            blk = jnp.minimum(span // bs, block_tables.shape[1] - 1)
            phys = block_tables[rows[:, None], blk] * bs + span % bs
            ck = _paged_write_span(ck, k, phys)
            cv = _paged_write_span(cv, v, phys)
        view_tables = jnp.where(
            block_tables == PAGED_SCRATCH_BLOCK, PAGED_ZERO_BLOCK,
            block_tables,
        )
        if decode_kernel_fn is not None and (
                S == 1 or getattr(decode_kernel_fn, "multi_query", False)):
            # Paged-NATIVE kernel (ISSUE 12): each lane's program walks
            # its block table in place — the dense [B, paged_len] view
            # below (a full copy of every live lane's KV through HBM,
            # every layer, every step) never materializes. int8 pools
            # dequantize in-kernel. The mask semantics are the gather
            # path's exactly (unmapped→ZERO rows, every column > pos
            # replaced before softmax), so greedy tokens match. S > 1
            # spans pass the LAST query's position + a per-lane q_lens
            # vector (ISSUE 13 — the per-lane-query-length kernel form);
            # the tp shard_map wrapper is single-token only, so sharded
            # spans keep the gather path (make_decode_attn_fn).
            if S == 1:
                attn_out = decode_kernel_fn(q, ck, cv, view_tables,
                                            cache_offset)
            else:
                attn_out = decode_kernel_fn(
                    q, ck, cv, view_tables, cache_offset + (S - 1),
                    jnp.full((B,), S, jnp.int32),
                )
        else:
            view_idx = (
                (view_tables * bs)[:, :, None]
                + jnp.arange(bs)[None, None, :]
            ).reshape(B, -1)[:, :paged_len]
            attn_out = attn_fn(
                q, dequantize_kv(_paged_view(ck, view_idx), x.dtype),
                dequantize_kv(_paged_view(cv, view_idx), x.dtype),
                causal=True, q_offset=cache_offset, **wkw,
            )
        new_cache = (ck, cv)
    elif kv_cache is not None and jnp.ndim(cache_offset) == 1:
        # Ragged decode ([B] offsets): each batch row writes its S k/v
        # vectors at its OWN positions — continuous batching (S == 1) and
        # speculative verification (S == k+1), where rows sit at different
        # lengths. Single-token writes clamp at max_len-1 (a serving slot
        # past its budget scribbles on the last entry, which the server
        # never reads); multi-token spans are bound-checked by the caller.
        ck, cv = kv_cache
        rows = jnp.arange(B)
        ck = _cache_write_rows(ck, k, rows, cache_offset)
        cv = _cache_write_rows(cv, v, rows, cache_offset)
        if decode_kernel_fn is not None and S == 1:
            # Slotted single-token decode through the SAME paged-native
            # kernel: the dense arena re-views zero-copy as a pool with
            # identity tables (ops.attention.make_decode_attn_fn,
            # paged=False). Multi-token spans (speculative verification)
            # keep the XLA path — the kernel is single-token.
            attn_out = decode_kernel_fn(q, ck, cv, None, cache_offset)
        else:
            attn_out = attn_fn(
                q, dequantize_kv(ck, x.dtype), dequantize_kv(cv, x.dtype),
                causal=True, q_offset=cache_offset, **wkw,
            )
        new_cache = (ck, cv)
    elif kv_cache is not None:
        # Decode: write new k/v at cache_offset, attend to the whole cache
        # prefix. Static shapes — XLA-friendly. dequantize_kv is a no-op on
        # bf16 caches; on int8 QTensor caches it is an elementwise producer
        # XLA fuses into the attention dots (the bf16 cache never hits HBM)
        # — true on the default XLA attention path only: the opt-in pallas
        # decode kernel (KATA_TPU_DECODE_KERNEL=1) takes materialized
        # operands, which would write the dequantized cache out each layer.
        # Don't combine the kernel opt-in with int8 caches.
        ck, cv = kv_cache
        ck = _cache_write_full(ck, k, cache_offset)
        cv = _cache_write_full(cv, v, cache_offset)
        attn_out = attn_fn(
            q, dequantize_kv(ck, x.dtype), dequantize_kv(cv, x.dtype),
            causal=True, q_offset=cache_offset, **wkw,
        )
        new_cache = (ck, cv)
    else:
        attn_out = attn_fn(q, k, v, causal=True, q_offset=None, **wkw)
        new_cache = None

    attn_out = attn_out.reshape(B, S, cfg.q_dim)
    attn_proj = weight_matmul(attn_out, layer["wo"])
    if reduce_fn is not None:  # overlap hint on the row-parallel reduce
        attn_proj = reduce_fn(attn_proj)
    if "post_attn_norm" in layer:  # Gemma-2: norm the sublayer OUTPUT too
        attn_proj = rms_norm(attn_proj, layer["post_attn_norm"], cfg.norm_eps)
    x = x + attn_proj

    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    if cfg.moe:
        from ..ops import moe as moe_mod

        moe_params = {"router": layer["router"], "w_gate": layer["moe_w_gate"],
                      "w_in": layer["moe_w_in"], "w_out": layer["moe_w_out"]}
        if moe_mesh is not None and moe_mod.dispatch_shardable(
            h.shape[:2], cfg.moe_num_experts, moe_mesh
        ):
            # Data-sharded dispatch: sort/scatter run per token shard and
            # the all-to-all carries only capacity buffers over ICI.
            y, aux = moe_mod.moe_ffn_sharded(moe_params, h, cfg.moe_cfg(), moe_mesh)
        else:
            # Indivisible token count (or no mesh): GSPMD global dispatch —
            # correct on any batch, just not dispatch-sharded.
            y, aux = moe_mod.moe_ffn(moe_params, h, cfg.moe_cfg(), mesh=moe_mesh)
        mlp_out = y.astype(x.dtype)
    elif "w_gateup" in layer:
        gu = weight_matmul(h, layer["w_gateup"])
        gate = _gate_act(gu[..., : cfg.d_ff], cfg.activation)
        mlp_out = weight_matmul(gate * gu[..., cfg.d_ff :], layer["w_down"])
        aux = jnp.float32(0.0)
    else:
        gate = _gate_act(weight_matmul(h, layer["w_gate"]), cfg.activation)
        up = weight_matmul(h, layer["w_up"])
        mlp_out = weight_matmul(gate * up, layer["w_down"])
        aux = jnp.float32(0.0)
    if reduce_fn is not None and not cfg.moe:
        # The second row-parallel site (w_down): same overlap hint; MoE
        # outputs reduce inside their own dispatch machinery.
        mlp_out = reduce_fn(mlp_out)
    if "post_mlp_norm" in layer:
        mlp_out = rms_norm(mlp_out, layer["post_mlp_norm"], cfg.norm_eps)
    x = x + mlp_out
    return x, new_cache, aux


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: DecoderConfig,
    attn_fn: Optional[AttnFn] = None,
    positions: Optional[jax.Array] = None,
    kv_caches: Optional[tuple[jax.Array, jax.Array]] = None,
    cache_offset: Optional[jax.Array] = None,
    prefill: bool = False,
    moe_mesh=None,
    return_aux: bool = False,
    remat: bool = False,
    ring: bool = False,
    block_tables: Optional[jax.Array] = None,
    block_size: int = 0,
    paged_len: int = 0,
    decode_kernel_fn=None,
    reduce_fn=None,
):
    """Full forward. tokens: [B, S] int32 → logits [B, S, vocab].

    ``block_tables`` (+ static ``block_size``/``paged_len``) switches the
    cache branch to PAGED decode: ``kv_caches`` is the shared block pool
    (``guest.kv_arena.KVPool.arena``, leaves [L, 1, NT, ...]) and each
    row reads/writes through its block table — see ``_layer``'s paged
    branch for the bit-identity argument.

    ``remat=True`` wraps each layer in ``jax.checkpoint``: the backward pass
    recomputes layer activations instead of storing all L of them — memory
    scales O(1) in depth instead of O(L), the standard TPU HBM-for-FLOPs
    trade at Llama scale (the flash kernel's custom_vjp already recomputes
    attention internally; this extends the policy to the whole block).

    With ``kv_caches`` (stacked [L, B, max_len, n_kv, D]) also returns the
    updated caches — one code path serves training, prefill and decode.
    ``prefill=True`` (static) means the caches are empty: k/v are written at
    offset 0 and attention runs over the fresh k/v only (self-attention —
    flash-kernel eligible) instead of reading back the padded cache.

    ``return_aux=True`` (static) appends the per-layer-mean MoE
    load-balancing loss to the return value (0.0 for dense configs);
    ``moe_mesh`` is the mesh whose expert axis shards the MoE buffers.
    """
    if attn_fn is None:
        from ..ops.attention import reference_attention

        attn_fn = reference_attention
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    x = embed(params, tokens, cfg)

    # The scan body covers one WINDOW CYCLE (length 1 for uniform configs):
    # Gemma-2-style alternating local/global layers unroll the cycle inside
    # the body, so compile cost scales with the cycle, not the depth.
    cycle = cfg.window_cycle
    P = len(cycle)
    if cfg.n_layers % P:
        # Checked here (not just init_params): checkpoint-loaded or
        # converted params skip init_params, and the reshape below would
        # otherwise die with an opaque error.
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by the attn_windows "
            f"cycle {cfg.attn_windows}"
        )
    # Per-cycle-position rope parameters (Gemma-3: local layers use a
    # different base frequency; global layers may linearly rescale).
    for name, c in (("rope_theta_cycle", cfg.rope_theta_cycle),
                    ("rope_linear_cycle", cfg.rope_linear_cycle)):
        if c and len(c) != P:
            raise ValueError(
                f"{name} {c!r} must have one entry per attn_windows "
                f"cycle position ({P})"
            )
    theta_cycle = cfg.rope_theta_cycle or (None,) * P
    linear_cycle = cfg.rope_linear_cycle or (1.0,) * P

    # ring + a window cycle ⇒ the CYCLE ARENA cache layout: kv_caches is a
    # tuple over cycle positions, each a [L/P, ...]-stacked cache pair of
    # its OWN length (w_i ring slots for local layers, max_len for global
    # ones — see cycle_ring_caches_from_prefill). Mixed lengths cannot live
    # in one stacked array, so the scan consumes the tuple directly.
    cycle_arena = ring and P > 1

    def one_layer(x, layer, cache, w, theta=None, linear=1.0):
        return _layer(
            cfg, attn_fn, x, layer, positions, cache, cache_offset,
            prefill=prefill, moe_mesh=moe_mesh, ring=ring and w > 0,
            window=w, rope_theta=theta, rope_linear=linear,
            block_tables=block_tables, block_size=block_size,
            paged_len=paged_len, decode_kernel_fn=decode_kernel_fn,
            reduce_fn=reduce_fn,
        )

    def body(carry, group_and_cache):
        x = carry
        group, cache_group = (
            group_and_cache if kv_caches is not None else (group_and_cache, None)
        )
        if P == 1:
            x, new_cache, aux = one_layer(
                x, group, cache_group, cycle[0],
                theta_cycle[0], linear_cycle[0],
            )
            if kv_caches is not None:
                return x, (new_cache, aux)
            return x, aux
        new_caches, auxes = [], []
        for i in range(P):
            sub_layer = tree_map(lambda a: a[i], group)
            if cache_group is None:
                sub_cache = None
            elif cycle_arena:
                sub_cache = cache_group[i]  # scan already sliced [B, len_i, ...]
            else:
                sub_cache = tree_map(lambda a: a[i], cache_group)
            x, nc, a = one_layer(
                x, sub_layer, sub_cache, cycle[i],
                theta_cycle[i], linear_cycle[i],
            )
            new_caches.append(nc)
            auxes.append(a)
        aux = jnp.mean(jnp.stack(auxes))
        if kv_caches is not None:
            if cycle_arena:  # per-position lengths differ: keep the tuple
                return x, (tuple(new_caches), aux)
            stacked = tree_map(lambda *xs: jnp.stack(xs), *new_caches)
            return x, (stacked, aux)
        return x, aux

    if remat and kv_caches is None:
        body = jax.checkpoint(body)

    def group_leaves(tree):  # [L, ...] → [L//P, P, ...] for the cycle scan
        return tree_map(
            lambda a: a.reshape((a.shape[0] // P, P) + a.shape[1:]), tree
        )

    def ungroup_leaves(tree):
        return tree_map(
            lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), tree
        )

    layers_xs = params["layers"] if P == 1 else group_leaves(params["layers"])
    if kv_caches is not None:
        if P == 1 or cycle_arena:
            caches_xs = kv_caches  # cycle arena is already [L/P, ...] per leaf
        else:
            caches_xs = group_leaves(kv_caches)
        x, (new_caches, auxes) = lax.scan(body, x, (layers_xs, caches_xs))
        if P > 1 and not cycle_arena:
            new_caches = ungroup_leaves(new_caches)
    else:
        x, auxes = lax.scan(body, x, layers_xs)
        new_caches = None
    aux = jnp.mean(auxes)  # per-layer load-balance losses

    logits = unembed(params, x, cfg)
    out = (logits, new_caches) if kv_caches is not None else (logits,)
    if return_aux:
        out = out + (aux,)
    return out[0] if len(out) == 1 else out


# ----- loss / training -----------------------------------------------------


def token_nll_sum(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Summed negative log-likelihood of ``targets`` under ``logits`` — the
    one cross-entropy body shared by the unpipelined loss and the composed
    pipeline loss (so the two cannot drift)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.sum(ll)


def next_token_loss(params: Params, tokens: jax.Array, cfg: DecoderConfig,
                    attn_fn: Optional[AttnFn] = None, moe_mesh=None,
                    remat: bool = False) -> jax.Array:
    """Mean cross-entropy of predicting tokens[:, 1:] from tokens[:, :-1],
    plus ``cfg.moe_aux_weight`` × the MoE load-balancing loss when the
    config is MoE (the aux term is what keeps the router from collapsing).

    The forward runs on the FULL sequence and the last position's logits
    are dropped — for DENSE configs the cross-entropy term is
    value-identical under causal masking to slicing the inputs first, and
    the sequence length stays unchanged so seq-sharded activations (ring
    attention over a mesh seq axis) stay evenly divisible through the
    whole step. For MoE configs the equivalence is approximate, not exact:
    the extra last token competes for finite expert-capacity slots (and
    changes the capacity ceil), which can evict earlier tokens and shift
    their logits slightly; the aux load-balancing term also counts the
    last position's routing (one more token in frac_routed/mean_prob) — a
    deliberate, slightly different regularizer, not a changed objective."""
    logits, aux = forward(
        params, tokens, cfg, attn_fn=attn_fn, moe_mesh=moe_mesh,
        return_aux=True, remat=remat,
    )
    targets = tokens[:, 1:]
    loss = token_nll_sum(logits[:, :-1], targets) / targets.size
    if cfg.moe:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


# ----- KV cache / generation ----------------------------------------------


def greedy_token(logits: jax.Array) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample_token(logits: jax.Array, key: jax.Array, temperature: jax.Array,
                 top_k: int, top_p: float = 0.0) -> jax.Array:
    """Temperature sampling from [B, vocab] fp32 logits, optionally
    truncated to the ``top_k`` most likely tokens and/or the smallest
    nucleus whose probability mass reaches ``top_p`` (the argmax token is
    always kept). ``temperature`` is a TRACED scalar — changing it between
    calls does not recompile (only the static ``top_k``/``top_p`` do)."""
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if 0.0 < top_p < 1.0:  # 1.0 keeps everything: skip the vocab sort
        order = jnp.flip(jnp.argsort(logits, axis=-1), axis=-1)
        srt = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep tokens whose cumulative mass BEFORE them is < top_p: the
        # smallest prefix reaching top_p, never empty. Scattering the
        # sorted mask back through argsort keeps EXACTLY that prefix — a
        # threshold compare would also keep tokens tied with the boundary.
        keep_sorted = (cum - probs) < top_p
        inv = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -1e30)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _next_token(logits, key, do_sample: bool, temperature, top_k: int,
                top_p: float = 0.0):
    """The one sample-vs-greedy dispatch, shared by prefill/decode/generate."""
    return (sample_token(logits, key, temperature, top_k, top_p) if do_sample
            else greedy_token(logits))


def _sampling_args(temperature, top_k, key, top_p: float = 0.0):
    """Resolve the STATIC sample-vs-greedy decision at the python wrapper
    level (so temperature itself can stay traced) and validate the args."""
    do_sample = not (isinstance(temperature, (int, float)) and temperature == 0.0)
    if do_sample and key is None:
        raise ValueError(
            "temperature > 0 requires an explicit PRNG key — a silent "
            "default would return the identical 'sample' on every call"
        )
    if not do_sample and (top_k > 0 or top_p > 0.0):
        raise ValueError(
            "top_k/top_p sampling requires temperature > 0 (greedy decoding "
            "would silently ignore them)"
        )
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1], got {top_p}")
    return do_sample, key if key is not None else jax.random.PRNGKey(0)


def _kv_stack(cfg: DecoderConfig, n_layers: int, batch: int, length: int,
              dtype, quantized: bool):
    shape = (n_layers, batch, length, cfg.n_kv_heads, cfg.head_dim)
    if quantized:
        def one():
            return QTensor(
                jnp.zeros(shape, jnp.int8),
                jnp.zeros(shape[:-1] + (1,), jnp.float32),
            )

        return one(), one()
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_kv_caches(cfg: DecoderConfig, batch: int, max_len: int,
                   dtype=None, quantized: bool = False):
    """Stacked caches [L, B, max_len, n_kv_heads, head_dim].

    ``quantized=True`` builds int8 :class:`QTensor` caches (per-vector fp32
    scales, ~2× less HBM than bf16 — the long-context serving memory hog);
    the cache write/read paths quantize/dequantize transparently."""
    return _kv_stack(cfg, cfg.n_layers, batch, max_len, dtype or cfg.dtype,
                     quantized)


def init_cycle_kv_caches(cfg: DecoderConfig, batch: int, max_len: int,
                         dtype=None, quantized: bool = False,
                         margin: int = 0):
    """The CYCLE ARENA layout for mixed local/global window cycles: a tuple
    over cycle positions, each a [L/P, B, len_i, KV, D] cache pair where
    ``len_i`` is the position's window (local) or ``max_len`` (global) —
    the decode-side counterpart of :func:`cycle_ring_caches_from_prefill`.
    ``margin`` adds safety slots to each windowed ring (speculative
    verification writes k+1-token spans; see ``_layer``'s ring branch)."""
    cycle = cfg.window_cycle
    P = len(cycle)
    return tuple(
        _kv_stack(cfg, cfg.n_layers // P, batch,
                  w + margin if w > 0 else max_len,
                  dtype or cfg.dtype, quantized)
        for w in cycle
    )


def ring_positions(pos: jax.Array, window: int) -> jax.Array:
    """Absolute position held by each slot of a ring KV buffer after
    ``pos`` tokens have been written (slot = position % window): the most
    recent position ≡ s (mod window) that is ≤ pos. Negative ⇒ unwritten
    (masked by ``reference_attention``'s ``k_positions`` path)."""
    # Explicit broadcast of the slot index against pos's leading dims
    # ([B, 1] at decode, [1]/scalar at prefill-fold) — identical values,
    # legal under strict mode's rank_promotion="raise".
    s = broadcast_trailing(jnp.arange(window, dtype=jnp.int32), pos.ndim)
    return pos - ((pos - s) % window)


@partial(jax.jit, static_argnames=("window",))
def ring_caches_from_prefill(caches, pos: jax.Array, window: int):
    """Fold a full prefill cache (entries at positions 0..pos-1) into a
    ring buffer of ``window`` slots: slot s takes the latest position
    ≡ s (mod window) below ``pos``; slots with no such position zero out
    (their ring position is negative — never attended)."""
    src = ring_positions(pos - 1, window)  # [window] absolute positions
    valid = src >= 0

    def fold(c):
        g = jnp.take(c, jnp.clip(src, 0), axis=2)  # [L, B, window, ...]
        mask = valid.reshape((1, 1, window) + (1,) * (g.ndim - 3))
        return jnp.where(mask, g, jnp.zeros_like(g))

    return tree_map(fold, caches)


@partial(jax.jit, static_argnames=("cfg", "max_len", "margin"))
def cycle_ring_caches_from_prefill(caches, pos: jax.Array,
                                   cfg: DecoderConfig, max_len: int,
                                   margin: int = 0):
    """Split a full prefill cache into the CYCLE ARENA for mixed
    local/global configs (Gemma-2's alternating ``attn_windows``): a tuple
    over the window cycle, where position ``i``'s layers (``i::P``) get a
    ``w_i``-slot ring buffer when windowed, or a ``max_len`` arena when
    global. Decode-time KV memory is then O(window) for every local layer
    — for Gemma-2's 1:1 cycle, roughly half the full-arena footprint once
    ``max_len >> window``."""
    cycle = cfg.window_cycle
    P = len(cycle)
    arena = []
    for i, w in enumerate(cycle):
        sub = tree_map(lambda a: a[i::P], caches)  # [L/P, B, S, ...]
        if w > 0:
            arena.append(ring_caches_from_prefill(sub, pos, w + margin))  # jaxguard: allow(JG104) bounded: one executable per distinct window in the static cycle (≤ len(window_cycle))
        else:
            def pad(c):
                full = jnp.zeros(c.shape[:2] + (max_len,) + c.shape[3:], c.dtype)
                return jax.lax.dynamic_update_slice(
                    full, c, (0,) * full.ndim
                )

            arena.append(tree_map(pad, sub))
    return tuple(arena)


@partial(jax.jit, static_argnames=("cfg", "max_len", "attn_fn", "return_logits",
                                   "kv_quantized"))
def prefill(params: Params, prompt: jax.Array, cfg: DecoderConfig,
            max_len: int, attn_fn: Optional[AttnFn] = None,
            return_logits: bool = False, kv_quantized: bool = False,
            true_len: Optional[jax.Array] = None):
    """Prefill the prompt into fresh KV caches (``kv_quantized=True``: int8
    caches, see :func:`init_kv_caches`). Returns
    ``(caches, next_token, pos)`` — the greedy next token and the scalar
    position where decode continues (``return_logits=True`` yields the
    last-position logits instead of the argmax token, for samplers).

    ``true_len`` (a TRACED scalar — no recompile per value) supports
    right-padded prompts: logits are taken at ``true_len - 1`` and ``pos``
    returns ``true_len``. Padding is exact, not approximate: causal
    attention already hides positions ``>= s`` from prompt token ``s``, and
    decode's index mask (``k_pos <= pos``) never reads a pad cache entry
    before the decode scan has overwritten it. One executable per BUCKET of
    prompt lengths instead of one per length.

    Separately jitted from :func:`decode` so the bench can time the
    bandwidth-bound decode loop on its own (prefill is compute-bound;
    folding it into the decode timing understates decode tok/s)."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B, S = prompt.shape
    caches = init_kv_caches(cfg, B, max_len, quantized=kv_quantized)
    logits, caches = forward(
        params, prompt, cfg, attn_fn=attn_fn, kv_caches=caches,
        cache_offset=jnp.int32(0), prefill=True,
    )
    if true_len is None:
        last, pos = logits[:, -1, :], jnp.int32(S)
    else:
        pos = jnp.asarray(true_len, jnp.int32)
        last = jax.lax.dynamic_index_in_dim(logits, pos - 1, axis=1,
                                            keepdims=False)
    if not return_logits:
        last = greedy_token(last)
    return caches, last, pos


@partial(jax.jit, static_argnames=("cfg", "attn_fn", "return_logits"))
def prefill_suffix(params: Params, suffix: jax.Array, cfg: DecoderConfig,
                   caches, offset: jax.Array,
                   attn_fn: Optional[AttnFn] = None,
                   return_logits: bool = False,
                   true_len: Optional[jax.Array] = None):
    """Suffix-only prefill: resume a prefill from PRE-POPULATED KV rows.

    ``caches`` already holds a prefix's k/v at positions ``[0, offset)``
    (e.g. gathered out of a :class:`..guest.prefix_cache.PrefixStore`);
    ``suffix [B, S]`` is the remainder of the prompt. The forward runs with
    RoPE positions shifted by ``offset`` and the causal mask spanning
    ``offset + S`` — suffix token ``i`` writes its k/v at ``offset + i``
    and attends to the cached prefix plus the fresh suffix, exactly the
    window the same token saw in a cold full-length prefill. Returns
    ``(caches, next_token_or_logits, pos)`` with the same contract as
    :func:`prefill`; for greedy decoding the resulting token stream is
    identical to the cold path (tested in ``tests/test_prefix_cache.py``).

    CHAINABLE: because ``caches`` only needs rows ``[0, offset)`` resident
    and the returned caches hold rows ``[0, offset + true_len)``, suffix
    prefills COMPOSE — calling again at ``offset + true_len`` with the
    next slice of the prompt resumes exactly where the last call stopped.
    That is the chunked-prefill contract the SLO-aware admission scheduler
    rides (``guest/scheduler.py``): a prompt split into fixed-width slices
    re-enters here per slice, and the final caches/logits — hence the
    greedy token stream — match the single-call prefill of the whole
    prompt (tested in ``tests/test_scheduler.py``).

    ``offset`` and ``true_len`` are TRACED — one executable per suffix
    SHAPE (bucket), never per prefix length. ``true_len`` supports
    right-padded suffixes the same way :func:`prefill` does: logits are
    taken at suffix index ``true_len - 1`` and ``pos`` returns
    ``offset + true_len``; pad rows land at positions decode's index mask
    never reads before overwriting. A ``[B]`` ``true_len`` vector is the
    batched-admission form (the :func:`prefill_batch` sibling): B suffixes
    sharing one matched prefix length run ONE forward, each row's logits
    gathered at its own boundary.

    The attention here reads BACK the cache (``q_offset`` path), so on TPU
    it takes the XLA reference path rather than the pallas self-attention
    kernel — the suffix is the short end of the prompt, which is the whole
    point. int8 ``QTensor`` caches work transparently: the prefix rows are
    already quantized, the fresh suffix quantizes on write, and attention
    dequantizes fused — the same numerics as every other decode-into-cache
    step."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B, S = suffix.shape
    offset = jnp.asarray(offset, jnp.int32)
    positions = offset + jnp.broadcast_to(
        jnp.arange(S, dtype=jnp.int32), (B, S)
    )
    logits, caches = forward(
        params, suffix, cfg, attn_fn=attn_fn, positions=positions,
        kv_caches=caches, cache_offset=offset,
    )
    if true_len is None:
        last, pos = logits[:, -1, :], offset + jnp.int32(S)
    else:
        tl = jnp.asarray(true_len, jnp.int32)
        pos = offset + tl
        if tl.ndim == 0:  # jaxguard: allow(JG104) bounded: scalar vs [B] true_len is one executable per admission FORM, and suffix shapes are already bucket-bound
            last = jax.lax.dynamic_index_in_dim(logits, tl - 1, axis=1,
                                                keepdims=False)
        else:  # [B] per-row boundaries (batched suffix admission)
            last = jnp.take_along_axis(
                logits, (tl - 1)[:, None, None], axis=1
            )[:, 0, :]
    if not return_logits:
        last = greedy_token(last)
    return caches, last, pos


@partial(jax.jit, static_argnames=("cfg", "max_len", "attn_fn",
                                   "return_logits", "kv_quantized"))
def prefill_batch(params: Params, prompts: jax.Array, cfg: DecoderConfig,
                  max_len: int, true_lens: jax.Array,
                  attn_fn: Optional[AttnFn] = None,
                  return_logits: bool = True, kv_quantized: bool = False):
    """Batched admission prefill: N right-padded prompts ``[N, S]`` with a
    ``[N]`` vector of true lengths run ONE forward, returning
    ``(caches, last_logits [N, vocab], pos [N])`` — the caches hold each
    row's prompt at positions ``0..true_lens[n]-1``.

    The batched sibling of :func:`prefill` (scalar ``true_len``), for
    continuous-batching servers admitting several queued requests at once:
    N sequential single-row prefills are N weight streams over the same
    bytes, while one ``[N, S]`` forward streams them once — the dominant
    TTFT cost under burst arrival. Exactness is the same ``true_len``
    argument as the scalar path: causal masking hides pad positions from
    every real token, logits are gathered per row at ``true_lens[n]-1``,
    and pad cache entries sit at positions decode's index mask never reads
    before they are overwritten. Each row's cache/logits equal its own
    single-row prefill (batching rows is independent math in every layer).

    One executable per (N, padded-length) pair — a server pairing this
    with ``prefill_buckets`` and a bounded arena keeps the compile count
    at ``len(buckets) × max_batch`` worst case, paid once per machine
    under the persistent compilation cache."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B, _S = prompts.shape
    caches = init_kv_caches(cfg, B, max_len, quantized=kv_quantized)
    logits, caches = forward(
        params, prompts, cfg, attn_fn=attn_fn, kv_caches=caches,
        cache_offset=jnp.int32(0), prefill=True,
    )
    pos = jnp.asarray(true_lens, jnp.int32)
    last = jnp.take_along_axis(
        logits, (pos - 1)[:, None, None], axis=1
    )[:, 0, :]
    if not return_logits:
        last = greedy_token(last)
    return caches, last, pos


@partial(jax.jit, static_argnames=("cfg", "steps", "attn_fn", "do_sample",
                                   "top_k", "top_p", "return_state", "ring",
                                   "block_size", "paged_len",
                                   "decode_kernel_fn", "eos_id",
                                   "reduce_fn"))
def _decode_scan(params: Params, caches, tok: jax.Array, pos: jax.Array,
                 cfg: DecoderConfig, steps: int, attn_fn: Optional[AttnFn],
                 do_sample: bool, top_k: int, temperature, key: jax.Array,
                 return_state: bool = False, ring: bool = False,
                 top_p: float = 0.0,
                 block_tables: Optional[jax.Array] = None,
                 block_size: int = 0, paged_len: int = 0,
                 decode_kernel_fn=None, eos_id: Optional[int] = None,
                 budget: Optional[jax.Array] = None, reduce_fn=None):
    """``budget`` ([B] int32, ragged callers only — ISSUE 13) arms the
    ON-DEVICE EOS/BUDGET MASK for multi-step dispatches: a lane that has
    emitted ``budget[b]`` tokens (or the static ``eos_id``) FREEZES — its
    ``tok``/``pos`` pin, so every later step recomputes the SAME k/v at
    the SAME cache position (an idempotent, value-identical rewrite: k/v
    depend only on tok + rope(pos), never on the cache) and its emitted
    token repeats the pinned one. Live lanes are untouched, so greedy
    outputs per request are bit-identical to the unmasked scan after the
    host's eos/budget trim (tested); the mask's job is bounding state —
    a frozen lane never advances past its block reservation however
    large the dispatch's step count. ``budget`` must be an UPPER bound
    on each lane's remaining tokens (freezing late is trimmed garbage;
    freezing early would drop real tokens). ``budget=None`` keeps the
    legacy carry — existing executables are untouched."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B = tok.shape[0]
    ragged = jnp.ndim(pos) == 1  # [B] per-slot positions (continuous batching)
    masked = budget is not None
    assert not masked or ragged, "budget masking is per-lane (ragged pos)"

    def step(carry, step_key):
        if masked:
            caches, tok, pos, rem = carry
            alive = rem > 0
        else:
            caches, tok, pos = carry
        positions = (pos[:, None] if ragged
                     else jnp.full((B, 1), pos, jnp.int32))
        logits, caches = forward(
            params, tok[:, None], cfg, attn_fn=attn_fn, positions=positions,
            kv_caches=caches, cache_offset=pos, ring=ring,
            block_tables=block_tables, block_size=block_size,
            paged_len=paged_len, decode_kernel_fn=decode_kernel_fn,
            reduce_fn=reduce_fn,
        )
        nxt = _next_token(logits[:, -1, :], step_key, do_sample, temperature,
                          top_k, top_p)
        if masked:
            nxt = jnp.where(alive, nxt, tok)          # frozen: pin the token
            new_pos = jnp.where(alive, pos + 1, pos)  # frozen: pin the slot
            rem = jnp.where(alive, rem - 1, rem)
            if eos_id is not None:
                rem = jnp.where(alive & (nxt == eos_id), 0, rem)
            return (caches, nxt, new_pos, rem), nxt
        return (caches, nxt, pos + 1), nxt

    init = (caches, tok, jnp.asarray(pos, jnp.int32))
    if masked:
        init = init + (jnp.asarray(budget, jnp.int32),)
    carry, out = lax.scan(step, init, jax.random.split(key, steps))
    caches, tok, pos = carry[0], carry[1], carry[2]
    return (out.T, caches, tok, pos) if return_state else out.T


@partial(jax.jit, static_argnames=("cfg", "max_steps", "attn_fn", "ring",
                                   "block_size", "paged_len",
                                   "decode_kernel_fn", "eos_id",
                                   "reduce_fn"))
def _decode_while(params: Params, caches, tok: jax.Array, pos: jax.Array,
                  budget: jax.Array, window_end: jax.Array,
                  cfg: DecoderConfig, max_steps: int,
                  attn_fn: Optional[AttnFn], ring: bool = False,
                  block_tables: Optional[jax.Array] = None,
                  block_size: int = 0, paged_len: int = 0,
                  decode_kernel_fn=None, eos_id: Optional[int] = None,
                  reduce_fn=None):
    """PERSISTENT decode rounds (ISSUE 20): a ``lax.while_loop`` whose
    body is EXACTLY :func:`_decode_scan`'s masked greedy step — same
    ``forward`` call, same :func:`greedy_token`, same frozen-lane
    tok/pos pinning (PR 13's idempotent-rewrite argument carries over
    verbatim: a frozen lane rewrites the SAME k/v at the SAME position,
    a value-identical no-op) — so each DELIVERED step is bit-identical
    to the equivalent fixed-``steps`` scan, and hence to lock-step K=1.
    The loop keeps decoding on device, host untouched, until one of
    three EXIT CONDITIONS ends the round:

    - **cap** — ``max_steps`` (static: the server's heartbeat-cadence
      step cap) delivered; the host fence is also the heartbeat/obs
      flush point, so telemetry cadence bounds device residency.
    - **done** — a lane FROZE (eos emitted or per-lane ``budget``
      spent): the lane needs host service (retire its request, refill
      the slot), so the loop returns rather than burn steps rewriting
      frozen k/v.
    - **window** — a live lane's next write position reached its
      ``window_end`` (the block-table window ``_ensure_blocks``
      pre-reserved for the whole persistent round): exit BEFORE the
      write, host re-reserves (or preempts) and re-enters.

    ``budget`` [B] int32 is REQUIRED (it is the freeze mask — lanes
    with 0 are dead slots and never gate the loop); greedy only (the
    sampling key schedule of a data-dependent step count cannot match
    the scan's pre-split keys, so persistent servers pin greedy — the
    server raises/degrades on conflict). Returns
    ``(out [B, max_steps], caches, tok, pos, delivered)`` — the host
    slices ``out[:, :delivered]`` at the fence and divides its ITL /
    ledger accounting by ``delivered``, never by the cap."""
    if attn_fn is None:
        from ..ops.attention import flash_attention

        attn_fn = flash_attention
    B = tok.shape[0]
    rem0 = jnp.asarray(budget, jnp.int32)
    window = jnp.asarray(window_end, jnp.int32)
    alive0 = rem0 > 0

    def cond(carry):
        _caches, _tok, pos, rem, _out, i = carry
        alive = rem > 0
        any_alive = jnp.any(alive)
        none_froze = jnp.all(~alive0 | alive)   # a freeze needs host service
        fits = ~jnp.any(alive & (pos >= window))  # next write must fit
        return (i < max_steps) & any_alive & none_froze & fits

    def body(carry):
        caches, tok, pos, rem, out, i = carry
        alive = rem > 0
        logits, caches = forward(
            params, tok[:, None], cfg, attn_fn=attn_fn,
            positions=pos[:, None], kv_caches=caches, cache_offset=pos,
            ring=ring, block_tables=block_tables, block_size=block_size,
            paged_len=paged_len, decode_kernel_fn=decode_kernel_fn,
            reduce_fn=reduce_fn,
        )
        nxt = greedy_token(logits[:, -1, :])
        nxt = jnp.where(alive, nxt, tok)          # frozen: pin the token
        new_pos = jnp.where(alive, pos + 1, pos)  # frozen: pin the slot
        rem = jnp.where(alive, rem - 1, rem)
        if eos_id is not None:
            rem = jnp.where(alive & (nxt == eos_id), 0, rem)
        out = lax.dynamic_update_slice_in_dim(out, nxt[:, None], i, axis=1)
        return (caches, nxt, new_pos, rem, out, i + 1)

    init = (caches, tok, jnp.asarray(pos, jnp.int32), rem0,
            jnp.zeros((B, max_steps), jnp.int32), jnp.int32(0))
    caches, tok, pos, _rem, out, delivered = lax.while_loop(cond, body, init)
    return out, caches, tok, pos, delivered


def decode(params: Params, caches, tok: jax.Array, pos: jax.Array,
           cfg: DecoderConfig, steps: int, attn_fn: Optional[AttnFn] = None,
           temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
           key: Optional[jax.Array] = None, return_state: bool = False,
           ring: bool = False, decode_kernel_fn=None):
    """Decode ``steps`` tokens after ``tok`` as one lax.scan — no per-token
    dispatch overhead. Returns [B, steps] (with ``return_state=True``:
    ``(tokens, caches, last_token, pos)`` so a server can continue later).

    ``ring=True``: ``caches`` is a ``cfg.sliding_window``-slot ring buffer
    (see :func:`ring_caches_from_prefill`); decode wraps forever in
    O(window) memory. The ring step always attends via the XLA
    ``reference_attention`` (kernels take no explicit slot positions), so
    a custom ``attn_fn`` applies to everything EXCEPT the ring reads.

    ``pos`` is either a SCALAR — the whole batch decodes in lockstep at one
    shared position — or a [B] VECTOR of per-slot positions (ragged decode:
    each row writes its k/v and masks its attention at its own position —
    the continuous-batching path, see :mod:`..guest.serving`; per-row
    writes clamp at max_len-1, the caller owns the budget). Greedy by
    default; ``temperature``/``top_k``/``key`` switch to sampling
    (:func:`sample_token`)."""
    if not ring:  # a ring buffer wraps by design — no length bound to check
        # (cycle arenas are tuples of mixed-length stacks; their global
        # layers' bound is enforced by generate()'s max_len check.)
        c0 = caches[0]
        cache_len = (c0.q if isinstance(c0, QTensor) else c0).shape[2]
        if steps > cache_len:
            raise ValueError(f"steps={steps} exceeds cache max_len={cache_len}")
        try:
            pos_concrete = int(pos) if jnp.ndim(pos) == 0 else None  # jaxguard: allow(JG101) opt-in bounds check; callers on the hot path pass a python int (bench does)
        except Exception:  # traced under an outer jit: caller owns the bound
            pos_concrete = None
        if pos_concrete is not None and pos_concrete + steps > cache_len:
            # dynamic_update_slice silently CLAMPS out-of-range writes — an
            # overrun would corrupt the last cache slot, not raise.
            raise ValueError(
                f"pos={pos_concrete} + steps={steps} overruns cache "
                f"max_len={cache_len}"
            )
    do_sample, key = _sampling_args(temperature, top_k, key, top_p)
    return _decode_scan(params, caches, tok, pos, cfg, steps, attn_fn,
                        do_sample, top_k, jnp.float32(temperature), key,
                        return_state=return_state, ring=ring, top_p=top_p,
                        decode_kernel_fn=decode_kernel_fn)


@partial(jax.jit, static_argnames=("cfg", "steps", "max_len", "attn_fn",
                                   "do_sample", "top_k", "top_p",
                                   "kv_quantized", "ring_kv"))
def _generate_impl(params, prompt, cfg, steps, max_len, attn_fn,
                   do_sample: bool, top_k: int, temperature, key,
                   kv_quantized: bool = False, ring_kv: bool = False,
                   top_p: float = 0.0):
    B, S = prompt.shape
    k_first, k_rest = jax.random.split(key)
    # Ring mode prefillls into a prompt-sized cache (transient), then folds
    # the live window into a ring buffer — steady-state KV memory and
    # per-step cache traffic are O(sliding_window), independent of steps.
    # Window-cycle configs (Gemma-2) fold into the CYCLE ARENA instead:
    # local layers get their ring, global layers a max_len arena.
    prefill_len = S if ring_kv else max_len
    caches, last_logits, pos = prefill(
        params, prompt, cfg, prefill_len, attn_fn=attn_fn, return_logits=True,
        kv_quantized=kv_quantized,
    )
    if ring_kv and len(cfg.window_cycle) > 1:
        caches = cycle_ring_caches_from_prefill(caches, pos, cfg, max_len)
    elif ring_kv:
        # Uniform window — including a length-1 attn_windows cycle, which
        # forward treats as P == 1 (no cycle arena).
        caches = ring_caches_from_prefill(caches, pos, cfg.window_cycle[0])
    last = _next_token(last_logits, k_first, do_sample, temperature, top_k,
                       top_p)
    if steps == 0:
        return jnp.zeros((B, 0), jnp.int32)
    if steps == 1:
        return last[:, None]
    out = _decode_scan(params, caches, last, pos, cfg, steps - 1, attn_fn,
                       do_sample, top_k, temperature, k_rest, ring=ring_kv,
                       top_p=top_p)
    return jnp.concatenate([last[:, None], out], axis=1)


def generate(params: Params, prompt: jax.Array, cfg: DecoderConfig,
             steps: int, max_len: int = 0, attn_fn: Optional[AttnFn] = None,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
             key: Optional[jax.Array] = None, kv_quantized: bool = False,
             ring_kv: bool = False):
    """Generation: :func:`prefill` then :func:`decode`, composed under one
    jit. Greedy by default; ``temperature``/``top_k``/``key`` sample instead
    (``temperature`` is traced — varying it does not recompile).

    ``attn_fn`` defaults to :func:`..ops.attention.flash_attention`, whose
    trace-time dispatch runs the pallas flash kernel for the prefill
    (self-attention, flash-eligible shapes on TPU) and, for the tiny-q
    decode steps, XLA's scan-fused path — the pallas fused decode kernel is
    opt-in via ``KATA_TPU_DECODE_KERNEL=1`` (it measured slower end-to-end;
    see :func:`..ops.attention.decode_eligible`)."""
    B, S = prompt.shape
    if ring_kv and not any(w > 0 for w in cfg.window_cycle):
        raise ValueError(
            "ring_kv needs a sliding-window config (cfg.sliding_window > 0 "
            "or a windowed attn_windows cycle) — a global-attention model "
            "must keep its whole prefix"
        )
    max_len = max_len or S + steps
    # Ring buffers wrap forever; the bound applies only where a max_len
    # arena actually exists — without ring_kv, or when the window cycle
    # has GLOBAL (w == 0) layers that keep their whole prefix.
    if (not ring_kv or any(w == 0 for w in cfg.window_cycle)) and (
        S + steps > max_len
    ):
        raise ValueError(
            f"prompt_len={S} + steps={steps} overruns max_len={max_len}"
        )
    do_sample, key = _sampling_args(temperature, top_k, key, top_p)
    return _generate_impl(params, prompt, cfg, steps, max_len, attn_fn,
                          do_sample, top_k, jnp.float32(temperature), key,
                          kv_quantized=kv_quantized, ring_kv=ring_kv,
                          top_p=top_p)
