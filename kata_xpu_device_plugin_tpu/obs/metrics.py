"""Metric factory over an injectable Prometheus ``CollectorRegistry``.

``utils/metrics.py``'s module-global ``Counter(...)``/``Gauge(...)`` calls
break the moment the module is imported twice (``importlib.reload``, a
second sys.path alias, plugin tests after serving tests) — prometheus's
process-global default registry raises ``Duplicated timeseries``. This
factory fixes the class of bug:

- collectors are created through :class:`MetricsRegistry`, which caches by
  (name, type, labelnames) and ADOPTS a collector the underlying registry
  already holds instead of re-registering it — creation is idempotent;
- the registry itself is injectable, so tests run against a fresh
  ``CollectorRegistry()`` instead of fighting global state;
- the default instance exports over the same ``/metrics`` endpoint the
  daemon already serves (:func:`serve`).

Also here: :class:`Rolling`, a tiny host-side summary (count/sum/min/max +
bounded reservoir for quantiles) for the ``stats()``-style dict snapshots
that prometheus histograms cannot answer client-side.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

from prometheus_client import (
    REGISTRY,
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    start_http_server,
)

# One namespace for every metric this repo exports (host daemon and guest
# stack share the pipeline — the PAPERS.md Network-Driver-Model argument).
NS = "kata_tpu"

# Latency buckets tuned for this stack's two regimes: sub-ms device steps
# (decode tokens, gRPC handlers) through multi-second compiles.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class MetricsRegistry:
    """Idempotent counter/gauge/histogram factory over one
    ``CollectorRegistry`` (default: prometheus's process-global one).

    >>> reg = MetricsRegistry(CollectorRegistry())
    >>> c = reg.counter("requests_total", "Requests", ["outcome"])
    >>> c is reg.counter("requests_total", "Requests", ["outcome"])
    True
    """

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry if registry is not None else REGISTRY
        self._lock = threading.Lock()
        self._collectors: dict[str, object] = {}

    def counter(self, name: str, doc: str, labels: Sequence[str] = ()):
        return self._get(Counter, name, doc, labels)

    def gauge(self, name: str, doc: str, labels: Sequence[str] = ()):
        return self._get(Gauge, name, doc, labels)

    def histogram(
        self,
        name: str,
        doc: str,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = LATENCY_BUCKETS,
    ):
        return self._get(Histogram, name, doc, labels, buckets=buckets)

    def _get(self, cls, name: str, doc: str, labels, **kwargs):
        with self._lock:
            cached = self._collectors.get(name)
            if cached is None:
                # A fresh MetricsRegistry over a registry that already holds
                # the collector (module reloaded, two import paths): adopt
                # it — re-registering is exactly the Duplicated-timeseries
                # crash this factory exists to remove.
                cached = self._adopt(name)
            if cached is not None:
                if not isinstance(cached, cls) or tuple(
                    getattr(cached, "_labelnames", ())
                ) != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already exists as "
                        f"{type(cached).__name__} with labels "
                        f"{tuple(getattr(cached, '_labelnames', ()))}, "
                        f"requested {cls.__name__} with {tuple(labels)}"
                    )
                self._collectors[name] = cached
                return cached
            collector = cls(
                name, doc, labelnames=tuple(labels),
                registry=self.registry, **kwargs,
            )
            self._collectors[name] = collector
            return collector

    def _adopt(self, name: str):
        # _names_to_collectors is private but stable (0.x..0.23); absence
        # just means no adoption — first registration still works.
        table = getattr(self.registry, "_names_to_collectors", None)
        if not table:
            return None
        # Counters register under name+"_total"; look up both spellings.
        return table.get(name) or table.get(f"{name}_total")


# Process-default registry: backs utils.metrics' aliases and every
# instrumented path that does not inject its own.
DEFAULT_REGISTRY = MetricsRegistry()


def counter(name: str, doc: str, labels: Sequence[str] = ()):
    return DEFAULT_REGISTRY.counter(name, doc, labels)


def gauge(name: str, doc: str, labels: Sequence[str] = ()):
    return DEFAULT_REGISTRY.gauge(name, doc, labels)


def histogram(
    name: str, doc: str, labels: Sequence[str] = (),
    buckets: Sequence[float] = LATENCY_BUCKETS,
):
    return DEFAULT_REGISTRY.histogram(name, doc, labels, buckets)


_served_port: Optional[int] = None


def serve(
    port: int, registry: Optional[CollectorRegistry] = None
) -> Optional[int]:
    """Start the /metrics HTTP endpoint; 0 disables; idempotent per
    process (a second call for the same port is a no-op — the daemon and a
    guest server can both ask). Returns the bound port."""
    global _served_port
    if not port:
        return None
    if _served_port == port:
        return port
    start_http_server(
        port, registry=registry if registry is not None else REGISTRY
    )
    _served_port = port
    return port


class Rolling:
    """Host-side summary: count/sum/min/max plus a bounded reservoir of the
    most recent values for p50/p95/p99 — the dict-snapshot complement of a
    prometheus histogram (whose quantiles only exist server-side).

    Thread-safe; ``summary()`` returns a plain-floats dict ready for
    ``stats()`` / JSON.
    """

    def __init__(self, keep: int = 512):
        self._lock = threading.Lock()
        self._keep = keep
        self._recent: list[float] = []
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self._recent.append(value)
            if len(self._recent) > self._keep:
                del self._recent[: len(self._recent) - self._keep]

    def _quantile(self, q: float) -> float:
        vals = sorted(self._recent)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))
        return vals[idx]

    def summary(self) -> dict:
        with self._lock:
            if not self.count:
                return {"count": 0}
            return {
                "count": self.count,
                "mean": round(self.total / self.count, 6),
                "min": round(self.min or 0.0, 6),
                "max": round(self.max or 0.0, 6),
                "p50": round(self._quantile(0.50), 6),
                "p95": round(self._quantile(0.95), 6),
                "p99": round(self._quantile(0.99), 6),
            }
