"""SLO-burn watchdog over serving heartbeats (ISSUE 15).

The flight recorder (:mod:`.flight`) answers "what happened" only after a
TERMINAL event; everything softer — serving got slow, the pool started
thrashing, the host tier stopped hitting — used to require an operator
staring at dashboards. This module closes that gap: the serving loop
feeds each periodic ``serving_heartbeat`` (one host dict every K rounds,
``guest/serving.py``) into :meth:`SLOBurnWatchdog.observe`, which keeps
rolling burn-rate windows over the ITL SLO budget plus a small set of
anomaly rules, and on a SUSTAINED breach turns the incident into on-disk
artifacts with zero operator action:

- one ``watchdog_alert`` event (kind, the triggering numbers, the dump
  path) on the same stream/trace as everything else;
- a flight-ring postmortem dump (``katatpu_flight_watchdog_<kind>_*``)
  — the ring is always armed, so the K heartbeats and every serving
  event leading INTO the breach are captured even with the JSONL sink
  off;
- optionally a bounded ``jax.profiler`` window (:class:`.ProfilerHook`
  over the next N heartbeats) when a profile dir is configured — the
  xplane trace of the slow period itself.

Alert kinds (``ALERT_KINDS``):

- ``slo_burn``            — the rolling fraction of heartbeats whose ITL
  p99 exceeds the SLO budget (``KATA_TPU_ITL_SLO_MS``) crossed the burn
  threshold over the window;
- ``preempt_storm``       — preemptions per heartbeat at/over the storm
  threshold (pool thrash: spill/restore churn eats the decode cadence);
- ``recovery_storm``      — supervisor recoveries per heartbeat at/over
  threshold (crash/chip-loss incidents — the chaos-gate trigger);
- ``host_hit_collapse``   — the host-RAM KV tier is armed but the
  interval prefix hit rate collapsed under real lookup traffic (the
  offload tier stopped earning its transfers);
- ``tokens_regression``   — interval tokens/s fell below
  ``regress_ratio`` × the watchdog's own healthy-period EWMA;
- ``device_idle``         — the device ledger's ``dispatch_gap_ms``
  (retire→next-dispatch host gap, ISSUE 17) grew past ``gap_ratio`` ×
  the watchdog's own healthy-period gap EWMA — the chips are waiting on
  the host, and the heartbeat's ``dispatch_gap_*`` waterfall names the
  thief;
- ``hbm_headroom_collapse`` — sustained device-memory headroom below
  ``headroom_floor_frac`` × the ledger's peak-usage watermark (the
  workload has demonstrated it needs spikes the remaining headroom can
  no longer absorb). Armed only on heartbeats that CARRY the ``hbm_*``
  fields — the ledger omits them where the backend exposes no
  ``memory_stats`` (CPU), so the rule self-disarms there.

Each rule must breach ``sustain`` CONSECUTIVE heartbeats to fire (one
slow round never pages anyone) and must be healthy ``clear`` consecutive
heartbeats to emit ``watchdog_clear`` — the recovery-clears-alert
sequence the chaos test pins. The watchdog is pure host arithmetic over
dicts the loop already built: it never touches device state, so greedy
outputs are bit-identical with it armed (tested).

jax-free at import (the profiler hook loads jax lazily, only when a
window actually opens), so offline consumers — ``tools/obs_report.py``
replaying a recorded stream through :meth:`observe` — run anywhere.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Callable, Optional

from . import events, flight
from .profiler import ProfilerHook

# Kill switch: heartbeat-armed servers run the watchdog by default
# ("serving got slow" should become an artifact with zero configuration);
# "0" disarms it without touching the heartbeat stream.
ENV_WATCHDOG = "KATA_TPU_WATCHDOG"

# Tuning knobs (all optional; the defaults are deliberately conservative
# — a breach must sustain across windows, so one GC pause or compile
# never dumps). Parsed by WatchdogConfig.from_env with the standard
# malformed-env degrade (fall back to the default, never crash a guest).
ENV_BURN_THRESHOLD = "KATA_TPU_WATCHDOG_BURN"
ENV_WINDOW = "KATA_TPU_WATCHDOG_WINDOW"
ENV_SUSTAIN = "KATA_TPU_WATCHDOG_SUSTAIN"
ENV_CLEAR = "KATA_TPU_WATCHDOG_CLEAR"
ENV_PREEMPT_STORM = "KATA_TPU_WATCHDOG_PREEMPT_STORM"
ENV_RECOVERY_STORM = "KATA_TPU_WATCHDOG_RECOVERY_STORM"
ENV_PROFILE_DIR = "KATA_TPU_WATCHDOG_PROFILE_DIR"
ENV_PROFILE_STEPS = "KATA_TPU_WATCHDOG_PROFILE_STEPS"
ENV_GAP_RATIO = "KATA_TPU_WATCHDOG_GAP_RATIO"
ENV_GAP_MIN_MS = "KATA_TPU_WATCHDOG_GAP_MIN_MS"
ENV_HEADROOM_FLOOR = "KATA_TPU_WATCHDOG_HEADROOM_FLOOR"

ALERT_SLO_BURN = "slo_burn"
ALERT_PREEMPT_STORM = "preempt_storm"
ALERT_RECOVERY_STORM = "recovery_storm"
ALERT_HOST_HIT_COLLAPSE = "host_hit_collapse"
ALERT_TOKENS_REGRESSION = "tokens_regression"
ALERT_DEVICE_IDLE = "device_idle"
ALERT_HBM_HEADROOM_COLLAPSE = "hbm_headroom_collapse"
ALERT_KINDS = (
    ALERT_SLO_BURN,
    ALERT_PREEMPT_STORM,
    ALERT_RECOVERY_STORM,
    ALERT_HOST_HIT_COLLAPSE,
    ALERT_TOKENS_REGRESSION,
    ALERT_DEVICE_IDLE,
    ALERT_HBM_HEADROOM_COLLAPSE,
)


def enabled() -> bool:
    """Is the watchdog armed (``KATA_TPU_WATCHDOG`` != "0")?"""
    return os.environ.get(ENV_WATCHDOG, "1") != "0"


@dataclass
class WatchdogConfig:
    """Rule thresholds. ``slo_ms`` is the ITL budget the burn rules
    measure against — the serving loop passes its resolved scheduler SLO
    so the watchdog and the admission policy steer by ONE number; 0
    disables the burn rule (the anomaly rules still run)."""

    slo_ms: float = 0.0
    # slo_burn: fraction of the last ``window`` heartbeats whose ITL p99
    # exceeded slo_ms before the budget counts as burning.
    burn_threshold: float = 0.5
    window: int = 6
    # Consecutive breaching heartbeats before an alert fires / healthy
    # heartbeats before an active alert clears.
    sustain: int = 2
    clear: int = 2
    # Anomaly thresholds, per heartbeat interval.
    preempt_storm: int = 8
    recovery_storm: int = 3
    # host_hit_collapse: armed only while the host tier holds tokens;
    # needs at least min_lookups interval lookups to call a collapse.
    hit_floor: float = 0.2
    min_lookups: int = 8
    # tokens_regression: current interval rate under ratio × the EWMA of
    # previously observed healthy rates (alpha-weighted, min_samples
    # heartbeats of history before the rule arms).
    regress_ratio: float = 0.5
    ewma_alpha: float = 0.2
    min_samples: int = 4
    # device_idle (ISSUE 17): the heartbeat's mean retire→next-dispatch
    # gap over gap_ratio × the healthy-period gap EWMA (same
    # ewma_alpha / min_samples discipline as tokens_regression, and the
    # same fold-healthy-only rule — a sustained idle period must not
    # become the baseline mid-incident). gap_min_ms floors the breach so
    # ratios over microsecond-noise gaps never fire.
    gap_ratio: float = 3.0
    gap_min_ms: float = 1.0
    # hbm_headroom_collapse (ISSUE 17): headroom below this fraction of
    # the ledger's peak-usage watermark.
    headroom_floor_frac: float = 0.1
    # Auto-profile window: "" disables; else a jax.profiler trace spans
    # the ``profile_steps`` heartbeats after the FIRST alert.
    profile_dir: str = ""
    profile_steps: int = 2

    @classmethod
    def from_env(cls, slo_ms: Optional[float] = None) -> "WatchdogConfig":
        """Env-tuned config with the standard degrade contract (malformed
        values fall back to the field default). ``slo_ms=None`` resolves
        the serving ITL budget env directly."""
        def _f(env: str, default: float) -> float:
            raw = os.environ.get(env, "")
            try:
                return float(raw) if raw else default
            except ValueError:
                return default

        def _i(env: str, default: int) -> int:
            raw = os.environ.get(env, "")
            try:
                return int(raw) if raw else default
            except ValueError:
                return default

        if slo_ms is None:
            slo_ms = _f("KATA_TPU_ITL_SLO_MS", 0.0)
        d = cls()
        return cls(
            slo_ms=float(slo_ms),
            burn_threshold=_f(ENV_BURN_THRESHOLD, d.burn_threshold),
            window=max(1, _i(ENV_WINDOW, d.window)),
            sustain=max(1, _i(ENV_SUSTAIN, d.sustain)),
            clear=max(1, _i(ENV_CLEAR, d.clear)),
            preempt_storm=max(1, _i(ENV_PREEMPT_STORM, d.preempt_storm)),
            recovery_storm=max(1, _i(ENV_RECOVERY_STORM, d.recovery_storm)),
            profile_dir=os.environ.get(ENV_PROFILE_DIR, ""),
            profile_steps=max(1, _i(ENV_PROFILE_STEPS, d.profile_steps)),
            gap_ratio=_f(ENV_GAP_RATIO, d.gap_ratio),
            gap_min_ms=_f(ENV_GAP_MIN_MS, d.gap_min_ms),
            headroom_floor_frac=_f(ENV_HEADROOM_FLOOR,
                                   d.headroom_floor_frac),
        )

    def as_fields(self) -> dict:
        """Flat dict for the ``watchdog_alert`` event / ``stats()``."""
        return {f.name: getattr(self, f.name) for f in fields(self)}


@dataclass
class _RuleState:
    breach_streak: int = 0
    healthy_streak: int = 0
    active: bool = False
    alerts: int = 0


class SLOBurnWatchdog:
    """Consume heartbeats, fire/clear alerts, capture artifacts.

    ``emit(name, **fields)`` is the event emitter — the serving loop
    passes its ``_emit`` so alerts carry the server label and the
    allocation trace id; standalone/offline use defaults to the
    process-wide :func:`..obs.emit` under kind ``serving`` (the consumer
    vocabulary stays one namespace). ``dump`` overrides the flight-ring
    dump callable (tests); the default dumps the process recorder with
    reason ``watchdog_<kind>``, which both names the postmortem file and
    records WHY it exists."""

    def __init__(self, config: Optional[WatchdogConfig] = None, *,
                 label: str = "", trace: str = "",
                 emit: Optional[Callable[..., None]] = None,
                 dump: Optional[Callable[[str], Optional[str]]] = None):
        self.config = config or WatchdogConfig.from_env()
        self.label = label
        self._emit_fn = emit
        self._dump_fn = dump
        self._trace = trace
        # observe() runs on whatever thread feeds heartbeats (the serving
        # loop, or an offline replay) while stats()/active serve the
        # SIGUSR1 debug-dump thread — all rolling state below is guarded.
        # Alert/clear emission and the flight dump happen OUTSIDE the
        # held region (lock-held IO would stall every emitter).
        self._lock = threading.Lock()
        self._burning: deque = deque(maxlen=self.config.window)
        self._rules = {k: _RuleState() for k in ALERT_KINDS}
        self._rate_ewma: Optional[float] = None
        self._rate_samples = 0
        self._gap_ewma: Optional[float] = None
        self._gap_samples = 0
        self._observed = 0
        self._last_dump: Optional[str] = None
        self._prof: Optional[ProfilerHook] = None
        self._prof_step = 0

    # ----- plumbing --------------------------------------------------------

    def bind(self, emit: Callable[..., None]) -> None:
        """Adopt an emitter when none was injected — the serving loop
        binds its labeled/traced ``_emit`` onto an injected watchdog so
        alerts join the server's stream; a caller-supplied emitter
        wins."""
        if self._emit_fn is None:
            self._emit_fn = emit

    def _emit(self, name: str, **f) -> None:
        if self._emit_fn is not None:
            self._emit_fn(name, **f)
            return
        if self.label:
            f.setdefault("server", self.label)
        if self._trace:
            f.setdefault("trace", self._trace)
        events.emit("serving", name, **f)

    def _dump(self, kind: str) -> Optional[str]:
        if self._dump_fn is not None:
            return self._dump_fn(f"watchdog_{kind}")
        rec = flight.recorder()
        return rec.dump(f"watchdog_{kind}") if rec is not None else None

    # ----- rule evaluation -------------------------------------------------

    def _breaches(self, hb: dict) -> dict[str, str]:
        """Which rules this heartbeat breaches: ``{kind: reason}`` with
        the triggering numbers spelled out (the reason rides the alert
        event — the runbook's first look)."""
        cfg = self.config
        out: dict[str, str] = {}
        itl_p99 = float(hb.get("itl_p99_ms") or 0.0)
        if cfg.slo_ms > 0 and hb.get("interval_rounds"):
            self._burning.append(itl_p99 > cfg.slo_ms)
            if len(self._burning) >= cfg.window:
                burn = sum(self._burning) / len(self._burning)
                if burn >= cfg.burn_threshold:
                    out[ALERT_SLO_BURN] = (
                        f"burn_rate={burn:.2f} over {len(self._burning)} "
                        f"heartbeats (itl_p99={itl_p99:.1f}ms vs "
                        f"slo={cfg.slo_ms:g}ms)"
                    )
        preempts = int(hb.get("preemptions_delta") or 0)
        if preempts >= cfg.preempt_storm:
            out[ALERT_PREEMPT_STORM] = (
                f"preemptions={preempts}/heartbeat (threshold "
                f"{cfg.preempt_storm})"
            )
        recoveries = int(hb.get("recoveries_delta") or 0)
        if recoveries >= cfg.recovery_storm:
            out[ALERT_RECOVERY_STORM] = (
                f"recoveries={recoveries}/heartbeat (threshold "
                f"{cfg.recovery_storm})"
            )
        lookups = int(hb.get("prefix_hits_delta") or 0) + int(
            hb.get("prefix_misses_delta") or 0
        )
        if (int(hb.get("kv_host_tokens") or 0) > 0
                and lookups >= cfg.min_lookups):
            rate = int(hb.get("prefix_hits_delta") or 0) / lookups
            if rate < cfg.hit_floor:
                out[ALERT_HOST_HIT_COLLAPSE] = (
                    f"hit_rate={rate:.2f} over {lookups} lookups (floor "
                    f"{cfg.hit_floor:g}, host tier armed)"
                )
        rate = float(hb.get("tokens_per_s") or 0.0)
        if int(hb.get("interval_rounds") or 0) > 0 and rate > 0:
            if (self._rate_samples >= cfg.min_samples
                    and self._rate_ewma
                    and rate < cfg.regress_ratio * self._rate_ewma):
                out[ALERT_TOKENS_REGRESSION] = (
                    f"tokens_per_s={rate:.1f} under "
                    f"{cfg.regress_ratio:g}x ewma={self._rate_ewma:.1f}"
                )
            else:
                # Fold only NON-regressing samples into the baseline: a
                # sustained slump must not drag the EWMA down until the
                # regression reads as the new normal mid-incident.
                self._rate_ewma = (
                    rate if self._rate_ewma is None
                    else self._rate_ewma
                    + cfg.ewma_alpha * (rate - self._rate_ewma)
                )
                self._rate_samples += 1
        # device_idle (ISSUE 17): heartbeats without the ledger's gap
        # fields (kill switch, pre-ledger streams) leave the rule — and
        # its baseline — untouched; intervals with no dispatches carry
        # no gap signal either.
        gap_v = hb.get("dispatch_gap_ms")
        if gap_v is not None and int(hb.get("dispatches_delta") or 0) > 0:
            gap = float(gap_v)
            if (self._gap_samples >= cfg.min_samples
                    and self._gap_ewma is not None
                    and gap >= cfg.gap_min_ms
                    and gap > cfg.gap_ratio * self._gap_ewma):
                out[ALERT_DEVICE_IDLE] = (
                    f"dispatch_gap_ms={gap:.2f} over {cfg.gap_ratio:g}x "
                    f"ewma={self._gap_ewma:.2f}ms (floor "
                    f"{cfg.gap_min_ms:g}ms)"
                )
            else:
                # Same fold-healthy-only rule as tokens_regression: a
                # sustained idle period must not become the baseline.
                self._gap_ewma = (
                    gap if self._gap_ewma is None
                    else self._gap_ewma
                    + cfg.ewma_alpha * (gap - self._gap_ewma)
                )
                self._gap_samples += 1
        # hbm_headroom_collapse (ISSUE 17): armed only when the ledger
        # supplied the memory fields — they degrade by OMISSION on
        # backends without memory_stats, so absence self-disarms.
        headroom = hb.get("hbm_headroom_bytes")
        peak = hb.get("hbm_peak_bytes")
        if headroom is not None and peak is not None and float(peak) > 0:
            floor = cfg.headroom_floor_frac * float(peak)
            if float(headroom) < floor:
                out[ALERT_HBM_HEADROOM_COLLAPSE] = (
                    f"headroom={int(headroom)}B under floor={int(floor)}B "
                    f"({cfg.headroom_floor_frac:g} x peak="
                    f"{int(float(peak))}B watermark)"
                )
        return out

    # ----- the consumer API ------------------------------------------------

    def observe(self, hb: dict) -> list[str]:
        """Feed one heartbeat; returns the alert kinds that FIRED on this
        observation (usually empty). Never raises — the watchdog is
        telemetry and must not add a failure mode to the serving loop.

        The rolling windows and rule streaks update under the lock; the
        fire/clear decisions collected there turn into events and flight
        dumps AFTER it is released."""
        fired: list[tuple[str, str]] = []
        cleared: list[tuple[str, int]] = []
        with self._lock:
            self._observed += 1
            try:
                breaches = self._breaches(hb)
            except Exception:
                return []
            for kind in ALERT_KINDS:
                st = self._rules[kind]
                if kind in breaches:
                    st.breach_streak += 1
                    st.healthy_streak = 0
                    if (not st.active
                            and st.breach_streak >= self.config.sustain):
                        st.active = True
                        st.alerts += 1
                        fired.append((kind, breaches[kind]))
                else:
                    st.healthy_streak += 1
                    st.breach_streak = 0
                    if st.active and st.healthy_streak >= self.config.clear:
                        st.active = False
                        cleared.append((kind, st.healthy_streak))
        for kind, reason in fired:
            self._fire(kind, reason, hb)
        for kind, healthy in cleared:
            self._emit(
                "watchdog_clear", alert=kind,
                healthy_heartbeats=healthy,
                round=hb.get("round"),
            )
        # Advance an open profiler window one heartbeat; the hook stops
        # itself (and emits profile/jax_trace) at the window end.
        with self._lock:
            prof = self._prof
            if prof is not None:
                self._prof_step += 1
                step = self._prof_step
        if prof is not None:
            try:
                prof.on_step(step)
            except Exception:
                with self._lock:
                    self._prof = None  # profiling must never hurt serving
        return [kind for kind, _reason in fired]

    def _fire(self, kind: str, reason: str, hb: dict) -> None:
        dump_path = None
        try:
            dump_path = self._dump(kind)
        except Exception:
            pass
        with self._lock:
            self._last_dump = dump_path or self._last_dump
            want_prof = bool(self.config.profile_dir) and self._prof is None
        self._emit(
            "watchdog_alert", alert=kind, reason=reason,
            round=hb.get("round"), dump=dump_path or "",
            tokens_per_s=hb.get("tokens_per_s"),
            itl_p99_ms=hb.get("itl_p99_ms"),
            slo_ms=self.config.slo_ms,
        )
        if want_prof:
            # One bounded window per watchdog lifetime, opened at the
            # FIRST alert: the next profile_steps heartbeats of device
            # time land in the xplane trace. (ProfilerHook._done keeps a
            # later alert from re-opening it.)
            prof = ProfilerHook(
                self.config.profile_dir, start_step=1,
                num_steps=self.config.profile_steps,
            )
            try:
                prof.on_step(0)  # opens the window now
            except Exception:
                prof = None
            if prof is not None:
                with self._lock:
                    self._prof = prof
                    self._prof_step = 0

    # ----- introspection / lifecycle ---------------------------------------

    @property
    def active(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(k for k in ALERT_KINDS if self._rules[k].active)

    def stats(self) -> dict:
        """Always-present aggregate for ``GenerationServer.stats()``;
        reads the rolling state under the lock — this runs on the
        SIGUSR1 debug-dump thread mid-serving."""
        with self._lock:
            return {
                "alerts": sum(st.alerts for st in self._rules.values()),
                "active": [
                    k for k in ALERT_KINDS if self._rules[k].active
                ],
                "observed": self._observed,
                "last_dump": self._last_dump or "",
            }

    def close(self) -> None:
        """Stop an open profiler window (idempotent); the serving loop
        calls this when the server idles out so an alert near the end of
        a run can never leave ``jax.profiler`` running."""
        with self._lock:
            prof = self._prof
            self._prof = None
        if prof is not None:
            try:
                prof.stop()
            except Exception:
                pass
