"""Device-utilization & HBM ledger (ISSUE 17).

The telemetry stack through PR 15 measures the serving loop entirely
from the host side: phase wall-times, ITL percentiles, occupancy
*counts*. This module closes the device-side gap with a per-server
ledger wired into the ONE decode dispatch site
(``guest/serving.py::_dispatch_decode``) and its retire fence:

- **Cost side** — each distinct dispatch signature (plain/fused, paged,
  steps, fused suffix width, budget shape, tp) is lowered ONCE via
  ``jax.stages`` (``fn.lower(...)``, shapes only — tracing never
  executes, so the donated arenas are untouched) and its
  ``cost_analysis()`` FLOPs/bytes-accessed cached. Where the backend
  returns nothing usable, the signature degrades with one
  ``cost_unavailable`` event and the MFU fields simply read 0 — never a
  crash, never a fake number.
- **Timing side** — per-dispatch host stamps at dispatch and at the
  retire fence give ``device_busy_frac`` (fraction of the heartbeat
  interval covered by in-flight decode rounds) and ``mfu`` (interval
  FLOPs over interval wall × public per-chip peak × tp, the portable
  utilization metric of "Exploration of TPUs for AI Applications" —
  peak table shared with bench.py). The retire→next-dispatch host gap
  is attributed to PR 15's ``_PhaseClock`` phases (admit / retire /
  host_transfer / other), so the ``dispatch_gap_*`` waterfall names the
  thief; the shares are clock-delta-derived and rescaled to sum to the
  measured gap exactly.
- **Memory side** — ``device.memory_stats()`` polled at heartbeat
  cadence. None-safe (CPU included): the ``hbm_*`` fields are *omitted*
  — never faked as 0 — with one ``hbm_stats_unavailable`` degrade event
  per server. When present, the server's own component bytes (params
  donor copy, KV arena/pool, standalone prefix store) attribute the
  usage and the ``hbm_unattributed_bytes`` residual makes leaks
  visible; the peak watermark feeds the watchdog's
  ``hbm_headroom_collapse`` floor.

The ledger is pure host arithmetic plus one trace per NEW executable
signature: it never fences, never touches device values, so greedy
outputs are bit-identical with it armed (tested, both strict modes) and
the armed cost rides under bench.py's ≤1% ``measure_obs`` bar.

``KATA_TPU_DEVLEDGER=0`` disarms the ledger without touching the
heartbeat (same kill-switch contract as ``KATA_TPU_WATCHDOG``); it is
armed by default whenever the heartbeat is. jax-free at import — jax
(and bench's peak table) load lazily, only on an armed server's first
dispatch/poll.
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Optional

from . import events

# Kill switch: heartbeat-armed servers run the ledger by default ("what
# were the chips doing" should not need configuration); "0" disarms it
# without touching the heartbeat stream or the watchdog.
ENV_DEVLEDGER = "KATA_TPU_DEVLEDGER"

# Public per-chip peak MFLOP tables, mirroring bench.py's MXU_TFLOPS —
# the ledger prefers bench's table (one source of truth when both are
# importable) and falls back to this copy where bench.py is not on the
# path (an installed guest without the repo checkout).
_MXU_TFLOPS = {
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6e": 918.0,
    "cpu": 0.1,
}

# A dispatch that raised out of the serving loop (fault injection,
# recovery replay) leaves its pending entry unretired; bound the FIFO so
# one incident can never skew attribution for the rest of the run (the
# healthy depth is 1 lock-step / 2 overlapped).
_MAX_PENDING = 4


def enabled() -> bool:
    """Is the ledger armed (``KATA_TPU_DEVLEDGER`` != "0")?"""
    return os.environ.get(ENV_DEVLEDGER, "1") != "0"


def _cost_flops(cost) -> Optional[float]:
    """Normalize a ``cost_analysis()`` result — jax returns a dict from
    ``Lowered.cost_analysis()`` and a list of per-computation dicts from
    ``Compiled.cost_analysis()`` — into total FLOPs, or None when the
    backend reported nothing usable."""
    entries = cost if isinstance(cost, (list, tuple)) else [cost]
    total = 0.0
    seen = False
    for e in entries:
        if not isinstance(e, dict):
            continue
        v = e.get("flops")
        if isinstance(v, (int, float)) and v > 0:
            total += float(v)
            seen = True
    return total if seen else None


def _cost_bytes(cost) -> float:
    entries = cost if isinstance(cost, (list, tuple)) else [cost]
    total = 0.0
    for e in entries:
        if isinstance(e, dict):
            v = e.get("bytes accessed")
            if isinstance(v, (int, float)) and v > 0:
                total += float(v)
    return total


class DeviceLedger:
    """Per-server device-utilization and memory ledger.

    The serving loop calls :meth:`on_dispatch` (cost capture + gap
    note) right before each decode executable call and
    :meth:`note_retire` at the retire fence; :meth:`heartbeat_fields`
    turns the interval accumulators into the ``serving_heartbeat``'s
    ``mfu`` / ``device_busy_frac`` / ``dispatch_gap_*`` / ``hbm_*``
    fields (memory fields present only when the backend supplies
    ``memory_stats``); :meth:`stats_fields` is the always-present
    ``stats()`` block. Disarmed, every hook is one attribute test.

    ``gap_phases`` fixes the heartbeat's gap-attribution field set (the
    serving loop passes its LOOP_PHASES) so the event schema never
    branches on what a particular interval happened to observe.
    ``clock`` is the loop's ``_PhaseClock``; ``components`` a callable
    returning the server's known device-resident byte counts (non-
    overlapping); ``device`` overrides the polled device (tests)."""

    def __init__(self, *, armed: bool = True,
                 emit: Optional[Callable[..., None]] = None,
                 clock=None, tp: int = 1,
                 gap_phases: tuple = ("other",),
                 components: Optional[Callable[[], dict]] = None,
                 device=None):
        self.armed = bool(armed)
        self._emit_fn = emit
        self._clock = clock
        self._tp = max(1, int(tp))
        self._gap_phases = tuple(gap_phases)
        if "other" not in self._gap_phases:
            self._gap_phases = self._gap_phases + ("other",)
        self._components = components
        self._device = device
        self._device_resolved = device is not None
        # Cost cache: signature key -> {"flops", "bytes_accessed"} | None
        # (None = captured but unavailable; the key never re-lowers).
        self._costs: dict = {}
        self._cost_unavailable = 0
        # In-flight dispatches (FIFO — depth 1 lock-step, 2 overlapped).
        self._pending: deque = deque()
        self._t_last_retire: Optional[float] = None
        self._snap_retire: dict = {}
        # Interval accumulators, drained by heartbeat_fields().
        self._i = self._fresh_interval()
        # Cumulative counters (stats()).
        self._dispatches = 0
        self._retired = 0
        # Memory state.
        self._peak_flops: Optional[float] = None
        self._hbm_peak = 0
        self._mem_unavailable = False
        self._mem_unavailable_emitted = False
        # Last heartbeat_fields() result — stats()' ledger snapshot.
        self._last_fields: dict = {}

    # ----- plumbing --------------------------------------------------------

    def _fresh_interval(self) -> dict:
        return {
            "dispatches": 0, "retires": 0, "busy_s": 0.0, "flops": 0.0,
            "gaps": 0, "gap_s": 0.0,
            "gap_attr": {p: 0.0 for p in self._gap_phases},
        }

    def _do_emit(self, name: str, **fields) -> None:
        try:
            if self._emit_fn is not None:
                self._emit_fn(name, **fields)
            else:
                events.emit("serving", name, **fields)
        except Exception:
            pass  # telemetry must never add a serving failure mode

    def _poll_device(self):
        if not self._device_resolved:
            self._device_resolved = True
            try:
                import jax

                devs = jax.local_devices()
                self._device = devs[0] if devs else None
            except Exception:
                self._device = None
        return self._device

    def peak_flops(self) -> float:
        """Public peak FLOP/s of the serving mesh: per-chip peak × tp.
        bench.py's table is the source of truth when importable; the
        local mirror (device_kind substring match, cpu fallback) covers
        installed guests without the repo checkout."""
        if self._peak_flops is None:
            dev = self._poll_device()
            tflops = None
            try:
                import bench

                tflops = float(bench.detect_mxu_tflops(dev))
            except Exception:
                tflops = None
            if tflops is None or tflops <= 0:
                kind = str(getattr(dev, "device_kind", "") or "").lower()
                for name, tf in _MXU_TFLOPS.items():
                    if name in kind:
                        tflops = tf
                        break
                else:
                    plat = str(getattr(dev, "platform", "") or "").lower()
                    tflops = (
                        _MXU_TFLOPS["cpu"] if plat in ("", "cpu")
                        else _MXU_TFLOPS["v5e"]
                    )
            self._peak_flops = tflops * 1e12 * self._tp
        return self._peak_flops

    # ----- cost capture (once per executable signature) --------------------

    def _capture_cost(self, key: tuple, fn, args: tuple,
                      kwargs: dict) -> None:
        """Lower ``fn`` with the dispatch's own arguments (avals only —
        tracing reads shapes/dtypes, never buffer contents, so donated
        arenas are safe) and cache its cost analysis under ``key``.
        ``Lowered.cost_analysis()`` answers without compiling on the
        backends that support it; the ``compile()`` fallback pays one
        extra compile for the signature where only the executable
        carries cost. Any failure degrades to one ``cost_unavailable``
        event for the signature — the key never re-lowers."""
        cost = None
        reason = ""
        try:
            lowered = fn.lower(*args, **kwargs)
        except Exception as exc:
            lowered = None
            reason = f"lower_failed:{type(exc).__name__}"
        if lowered is not None:
            try:
                cost = lowered.cost_analysis()
            except Exception:
                cost = None
            if _cost_flops(cost) is None:
                try:
                    cost = lowered.compile().cost_analysis()
                except Exception:
                    cost = None
            if _cost_flops(cost) is None:
                reason = reason or "no_flops"
        flops = _cost_flops(cost)
        if flops is None:
            self._costs[key] = None
            self._cost_unavailable += 1
            self._do_emit(
                "cost_unavailable", reason=reason or "no_flops",
                signature=repr(key),
            )
        else:
            self._costs[key] = {
                "flops": flops,
                "bytes_accessed": _cost_bytes(cost),
            }

    # ----- the dispatch-site hooks -----------------------------------------

    def on_dispatch(self, key: tuple, fn, args: tuple,
                    kwargs: dict, loop_cap: Optional[int] = None) -> None:
        """Called by the ONE dispatch site right before the decode
        executable call: captures the signature's cost on first sight,
        then stamps the dispatch and attributes the retire→dispatch
        host gap to the phase clock's deltas (residual → ``other``;
        shares rescaled so they sum to the gap exactly).

        ``loop_cap`` (ISSUE 20): the persistent executable's static
        while_loop step cap. ``cost_analysis`` on a while_loop body
        reports the WHOLE loop's FLOPs at the cap (trip count assumed =
        the bound), so the retire side must rescale by the round's
        actually-delivered steps — the cap rides the pending entry so
        :meth:`note_retire` can do that without re-deriving the static."""
        if not self.armed:
            return
        if key not in self._costs:
            self._capture_cost(key, fn, args, kwargs)
        now = time.perf_counter()
        if self._t_last_retire is not None:
            gap = max(now - self._t_last_retire, 0.0)
            attr: dict = {}
            if self._clock is not None:
                snap = self._clock.snapshot()
                for p, v in snap.items():
                    d = v - self._snap_retire.get(p, 0.0)
                    if d > 0:
                        attr[p] = d
            total = sum(attr.values())
            if total > gap > 0:
                # Clock deltas can overrun the gap window (a phase pop
                # lands fence time accrued outside it); rescale so the
                # shares sum to the measured gap by construction.
                scale = gap / total
                attr = {p: v * scale for p, v in attr.items()}
                total = gap
            i = self._i
            i["gaps"] += 1
            i["gap_s"] += gap
            ga = i["gap_attr"]
            for p, v in attr.items():
                ga[p if p in ga else "other"] = (
                    ga.get(p if p in ga else "other", 0.0) + v
                )
            ga["other"] += max(gap - total, 0.0)
        if len(self._pending) >= _MAX_PENDING:
            self._pending.popleft()  # abandoned by a raising dispatch
        self._pending.append((key, now, loop_cap))
        self._dispatches += 1
        self._i["dispatches"] += 1

    def note_retire(self, now: Optional[float] = None,
                    delivered_steps: Optional[int] = None) -> None:
        """Called at the retire fence: accumulates the chunk's busy time
        (retire→retire cadence at steady state — the same ``round_s``
        convention the latency metrics use) and its signature's FLOPs,
        and snapshots the phase clock as the next gap's baseline.

        ``delivered_steps`` (ISSUE 20): the persistent round's fenced
        step count. When the popped dispatch carried a ``loop_cap``, the
        signature's cached FLOPs describe a FULL ``cap``-step loop —
        credit ``delivered/cap`` of them, so an early-exit round does
        not double-count work the device never did (and MFU stays
        honest). Ignored for fixed-step dispatches."""
        if not self.armed or not self._pending:
            return
        if now is None:
            now = time.perf_counter()
        key, t_dispatch, loop_cap = self._pending.popleft()
        anchor = (
            t_dispatch if self._t_last_retire is None
            else max(t_dispatch, self._t_last_retire)
        )
        busy = max(now - anchor, 0.0)
        self._t_last_retire = now
        if self._clock is not None:
            self._snap_retire = self._clock.snapshot()
        cost = self._costs.get(key)
        if cost:
            flops = cost["flops"]
            if loop_cap and delivered_steps is not None:
                flops *= min(max(delivered_steps, 0), loop_cap) / loop_cap
            self._i["flops"] += flops
        self._i["busy_s"] += busy
        self._retired += 1
        self._i["retires"] += 1

    # ----- memory poll (heartbeat cadence) ---------------------------------

    def poll_memory(self) -> dict:
        """One ``memory_stats()`` poll plus component attribution.
        Returns the ``hbm_*`` field dict — EMPTY where the backend
        exposes no stats (CPU): the fields are omitted, never faked as
        0, and the degrade is announced once per server as
        ``hbm_stats_unavailable``."""
        if not self.armed:
            return {}
        dev = self._poll_device()
        stats = None
        try:
            stats = dev.memory_stats() if dev is not None else None
        except Exception:
            stats = None
        if not stats:
            self._mem_unavailable = True
            if not self._mem_unavailable_emitted:
                self._mem_unavailable_emitted = True
                self._do_emit(
                    "hbm_stats_unavailable",
                    reason="memory_stats_none",
                    platform=str(getattr(dev, "platform", "") or ""),
                )
            return {}
        self._mem_unavailable = False
        used = int(stats.get("bytes_in_use", 0) or 0)
        limit = int(
            stats.get("bytes_limit")
            or stats.get("bytes_reservable_limit")
            or 0
        )
        peak = int(stats.get("peak_bytes_in_use", 0) or 0)
        self._hbm_peak = max(self._hbm_peak, peak, used)
        out = {
            "hbm_used_bytes": used,
            "hbm_peak_bytes": self._hbm_peak,
        }
        if limit > 0:
            out["hbm_limit_bytes"] = limit
            out["hbm_headroom_bytes"] = max(limit - used, 0)
        comp: dict = {}
        if self._components is not None:
            try:
                comp = dict(self._components())
            except Exception:
                comp = {}
        attributed = 0
        for name, v in comp.items():
            v = int(v or 0)
            out[f"hbm_{name}_bytes"] = v
            attributed += v
        if comp:
            out["hbm_attributed_bytes"] = attributed
            # Signed on purpose: a negative residual (attribution counts
            # replicated copies the allocator shares) is as diagnostic
            # as the positive leak the field exists to expose.
            out["hbm_unattributed_bytes"] = used - attributed
        return out

    def hbm_headroom(self) -> Optional[int]:
        """Last polled headroom, None where unavailable — the dedicated
        gauge scrapes this and exports NaN rather than a fake 0."""
        v = self._last_fields.get("hbm_headroom_bytes")
        return int(v) if v is not None else None

    # ----- surfacing -------------------------------------------------------

    def heartbeat_fields(self, interval_s: float) -> dict:
        """Drain the interval accumulators into the heartbeat's ledger
        fields. Always returns the full utilization field set on an
        armed ledger (zeros before any dispatch — no schema branch);
        the ``hbm_*`` fields appear only when the backend supplies
        memory stats. Disarmed → {} (the documented kill-switch
        degrade)."""
        if not self.armed:
            return {}
        i, self._i = self._i, self._fresh_interval()
        interval_s = max(float(interval_s), 1e-9)
        gap_ms = (i["gap_s"] / i["gaps"] * 1e3) if i["gaps"] else 0.0
        fields = {
            "mfu": round(i["flops"] / (interval_s * self.peak_flops()), 6),
            "device_busy_frac": round(
                min(i["busy_s"] / interval_s, 1.0), 4
            ),
            "dispatch_gap_ms": round(gap_ms, 4),
            "dispatches_delta": i["dispatches"],
        }
        for p in self._gap_phases:
            fields[f"dispatch_gap_{p}_ms"] = round(
                (i["gap_attr"].get(p, 0.0) / i["gaps"] * 1e3)
                if i["gaps"] else 0.0,
                4,
            )
        fields.update(self.poll_memory())
        self._last_fields = fields
        return fields

    def stats_fields(self) -> dict:
        """The always-present ``stats()`` block: top-level
        ``mfu`` / ``device_busy_frac`` / ``dispatch_gap_ms`` (last
        heartbeat interval, 0.0 before the first or disarmed) plus the
        ``devledger`` detail dict. Memory fields degrade by omission
        inside the detail dict, mirroring the heartbeat."""
        last = self._last_fields
        detail = {
            "armed": int(self.armed),
            "dispatches": self._dispatches,
            "retired": self._retired,
            "cost_signatures": len(self._costs),
            "cost_unavailable": self._cost_unavailable,
            "peak_flops": self.peak_flops() if self.armed else 0.0,
            "hbm_stats_available": int(
                self.armed and not self._mem_unavailable and bool(
                    [k for k in last if k.startswith("hbm_")]
                )
            ),
        }
        detail.update(
            {k: v for k, v in last.items()
             if k.startswith(("hbm_", "dispatch_gap_"))}
        )
        return {
            "mfu": last.get("mfu", 0.0),
            "device_busy_frac": last.get("device_busy_frac", 0.0),
            "dispatch_gap_ms": last.get("dispatch_gap_ms", 0.0),
            "devledger": detail,
        }
