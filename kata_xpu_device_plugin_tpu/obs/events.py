"""JSONL event stream: the one pipeline device-layer and workload-layer
telemetry share (the Kubernetes Network Driver Model argument, PAPERS.md).

Every event is one JSON object per line::

    {"ts": 1722700000.123, "kind": "span", "name": "train.step", ...}

Producers call :func:`emit` (or pass a sink explicitly); consumers —
``bench.py``, tests, offline analysis — call :func:`read_events` and
:func:`summarize_phases`. The default sink is configured from the
environment exactly once:

- ``KATATPU_OBS=1`` (alias ``KATA_TPU_OBS=1``) enables the stream;
- ``KATATPU_OBS_FILE`` names the output path (default
  ``katatpu_events.jsonl`` in the working directory).

With the stream disabled, :func:`emit` is a dict lookup and a ``None``
check — instrumented hot paths pay nothing.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import IO, Iterable, Optional

from . import flight

_ENV_ENABLE = ("KATATPU_OBS", "KATA_TPU_OBS")
_ENV_FILE = ("KATATPU_OBS_FILE", "KATA_TPU_OBS_FILE")
_DEFAULT_FILE = "katatpu_events.jsonl"
_TRUTHY = ("1", "true", "yes", "on")


def enabled() -> bool:
    """Is the JSONL event stream switched on (``KATATPU_OBS=1``)?"""
    return any(
        os.environ.get(k, "").lower() in _TRUTHY for k in _ENV_ENABLE
    )


def events_path() -> str:
    for k in _ENV_FILE:
        v = os.environ.get(k, "")
        if v:
            return v
    return _DEFAULT_FILE


class EventSink:
    """Append-only, thread-safe JSONL writer.

    Opens lazily on first emit (an enabled-but-idle process creates no
    file); every line is flushed so a crashed or SIGKILLed worker loses at
    most the event in flight — the stream is evidence, buffered evidence
    evaporates.
    """

    def __init__(self, path: str, clock=time.time):
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        self._fh: Optional[IO[str]] = None
        self._emitted = 0

    def emit(self, kind: str, name: str, **fields) -> dict:
        event = {"ts": round(self._clock(), 6), "kind": kind, "name": name}
        event.update(fields)
        line = json.dumps(event, default=_jsonable, sort_keys=False)
        with self._lock:
            # Sanctioned lock-held IO: the lazy open + torn-line probe
            # happen ONCE per sink, and per-line append/flush is the
            # sink's whole serialization contract — emitters must not
            # interleave bytes.
            if self._fh is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)  # jaxguard: allow(JG203) one-shot lazy open
                self._fh = open(self.path, "a", encoding="utf-8")  # jaxguard: allow(JG203) one-shot lazy open
                # A previous writer killed mid-line leaves no trailing
                # newline; appending onto the torn line would corrupt THIS
                # sink's first event too. Terminate it.
                if self._fh.tell() > 0:
                    with open(self.path, "rb") as probe:  # jaxguard: allow(JG203) one-shot torn-line probe
                        probe.seek(-1, os.SEEK_END)
                        torn = probe.read(1) != b"\n"
                    if torn:
                        self._fh.write("\n")
            self._fh.write(line + "\n")
            self._fh.flush()
            self._emitted += 1
        return event

    @property
    def emitted(self) -> int:
        return self._emitted

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "EventSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _jsonable(obj):
    """Last-resort encoder: device scalars/arrays → python numbers/lists,
    everything else → str. Telemetry must never raise out of a hot path."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if fn is not None:
            try:
                return fn()
            except Exception:
                break
    return str(obj)


# -- process-default sink ----------------------------------------------------

_default: Optional[EventSink] = None
_configured = False
_lock = threading.Lock()


def configure_from_env(force: bool = False) -> Optional[EventSink]:
    """Resolve the default sink from the environment (once; ``force``
    re-reads, for tests that flip the env)."""
    global _default, _configured
    with _lock:
        if _configured and not force:
            return _default
        _configured = True
        _default = EventSink(events_path()) if enabled() else None
        return _default


def set_default_sink(sink: Optional[EventSink]) -> Optional[EventSink]:
    """Install ``sink`` as the process default (None disables); returns the
    previous sink so callers can restore it. The env default is resolved
    FIRST, so a caller that swaps and later restores hands back the
    ``KATATPU_OBS`` sink rather than erasing it."""
    global _default
    prev = configure_from_env()
    with _lock:
        _default = sink
        return prev


def default_sink() -> Optional[EventSink]:
    return configure_from_env()


def emit(kind: str, name: str, **fields) -> Optional[dict]:
    """Emit to the default sink; returns None when the sink is disabled.

    The crash FLIGHT RECORDER (:mod:`.flight`) sees every event emitted
    here regardless of the sink switch — its bounded in-memory ring is
    always armed (``KATATPU_FLIGHT=0`` disarms), so a terminal event
    (``chip_loss_fatal``, ``registration_exhausted``, a failed drain)
    can dump the recent past even when nobody enabled ``KATATPU_OBS``
    before the incident."""
    sink = default_sink()
    event: Optional[dict] = None
    if sink is not None:
        event = sink.emit(kind, name, **fields)
    rec = flight.recorder()
    if rec is not None:
        if event is None:
            event = {"ts": round(time.time(), 6), "kind": kind, "name": name}
            event.update(fields)
            rec.record(event)
            return None  # sink disabled: keep the old return contract
        rec.record(event)
    return event


# -- consumers ---------------------------------------------------------------


def read_events(path: str, offset: int = 0) -> list[dict]:
    """Parse a JSONL event file back into dicts (skipping any torn final
    line a killed writer may have left). ``offset`` skips the first N
    bytes — pass the file's size from before your run started to read
    only your own events from a shared/pinned stream (the sink appends,
    and always lands new events on a line boundary: it completes every
    line it writes and terminates any torn tail it inherits, so a
    pre-run size is always a valid resume point)."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as fh:
        if offset:
            fh.seek(offset)
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def tail_events(path: str, offset: int = 0) -> tuple[list[dict], int]:
    """Incremental, rotation-safe tail of a JSONL event file (ISSUE 15):
    parse the COMPLETE lines past byte ``offset`` and return them with
    the offset to resume from — so pollers (the SLO watchdog, the
    daemon-side heartbeat aggregator, ``bench_watch``) stop re-reading
    whole files every pass.

    Contract:

    - The returned offset always lands on a line boundary: a torn final
      line (a writer killed or caught mid-``write``) is NOT consumed —
      the next call picks it up once the writer completes it, so no
      event is ever half-parsed or skipped.
    - Rotation/truncation safe: when the file shrank below ``offset``
      (logrotate, a fresh sink truncating) the tail restarts from byte
      0 instead of silently returning nothing forever. A truncated file
      that REGREW past the old offset between polls is caught by the
      line-boundary check below (a valid resume offset always sits just
      after a newline; rewritten content almost never does) — the
      residual blind spot is a regrown file whose new content happens
      to place a newline exactly at ``offset - 1``, in which case the
      spliced lines are skipped as corrupt rather than mis-parsed.
    - A missing file returns ``([], 0)`` — the poller's steady state
      before the guest emits its first event.

    Complete-but-unparseable lines are skipped (the ``read_events``
    leniency) but their bytes ARE consumed — a corrupt line must not
    wedge the tail on every subsequent poll."""
    out: list[dict] = []
    try:
        size = os.path.getsize(path)
    except OSError:
        return out, 0
    if size < offset:
        offset = 0  # rotated/truncated under us: the stream restarted
    if size == offset:
        return out, offset
    with open(path, "rb") as fh:
        if offset:
            # Every offset this function returns lands just past a
            # newline; if that byte is no longer one, the file was
            # truncated AND regrew past the old offset between polls —
            # restart from 0 rather than splicing into the new stream.
            fh.seek(offset - 1)
            if fh.read(1) != b"\n":
                offset = 0
        fh.seek(offset)
        data = fh.read(size - offset)
    # Only complete lines are consumed; a torn tail stays unread.
    end = data.rfind(b"\n") + 1
    if end == 0:
        return out, offset
    for line in data[:end].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line.decode("utf-8")))
        except (json.JSONDecodeError, UnicodeDecodeError):
            continue
    return out, offset + end


def summarize_phases(
    events: Iterable[dict], prefix: str = ""
) -> dict[str, dict]:
    """Aggregate span events into per-phase timing: ``{phase: {count,
    total_s, min_s, max_s, mean_s}}``. ``prefix`` selects and strips a
    namespace (``prefix="bench."`` turns ``bench.decode`` into ``decode``)
    — this is how ``bench.py`` converts the stream into the per-phase
    breakdown BENCH_*.json reports."""
    acc: dict[str, list[float]] = {}
    for ev in events:
        if ev.get("kind") != "span" or "dur_s" not in ev:
            continue
        name = str(ev.get("name", ""))
        if prefix:
            if not name.startswith(prefix):
                continue
            name = name[len(prefix):]
        acc.setdefault(name, []).append(float(ev["dur_s"]))
    return {
        name: {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "min_s": round(min(durs), 6),
            "max_s": round(max(durs), 6),
            "mean_s": round(sum(durs) / len(durs), 6),
        }
        for name, durs in sorted(acc.items())
    }
