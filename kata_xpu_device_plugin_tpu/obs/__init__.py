"""Unified telemetry layer (ISSUE 2): spans, metrics, events, profiler.

The daemon side fixed the reference's "no metrics endpoint" sliver
(SURVEY §5); this package gives the whole stack — trainer, serving,
plugin gRPC — one pipeline for the signals production traffic needs:

- :mod:`.trace`    — span-based tracer with JAX-aware timing: spans FENCE
  via ``jax.block_until_ready`` on exit, so device async dispatch cannot
  fake sub-ms steps. Context-manager (:func:`span`, :func:`timer`) and
  decorator (:func:`traced`) APIs; trace/span ids ride into
  ``utils/log.py`` records automatically.
- :mod:`.metrics`  — counters/gauges/histograms created through a factory
  against an injectable ``CollectorRegistry`` (idempotent: re-import and
  double-registration cannot raise ``Duplicated timeseries``), exported
  over the same Prometheus endpoint as ``utils.metrics``.
- :mod:`.events`   — a JSONL event sink (``KATATPU_OBS=1`` +
  ``KATATPU_OBS_FILE``) every span and metric event streams into;
  ``bench.py`` parses it back into per-phase breakdowns.
- :mod:`.flight`   — the crash FLIGHT RECORDER (ISSUE 11): a bounded
  in-memory ring of the most recent events, armed even when the JSONL
  sink is off, dumped to a postmortem JSONL on terminal events
  (``chip_loss_fatal``, ``fatal_error``, ``registration_exhausted``, a
  failed drain). ``KATATPU_FLIGHT=0`` disarms.
- :mod:`.profiler` — optional ``jax.profiler`` start/stop around N
  configurable steps.
- :mod:`.watchdog` — the SLO-burn WATCHDOG (ISSUE 15): consumes the
  serving loop's periodic heartbeats, and on a sustained ITL-budget
  burn or anomaly (preemption storm, host-tier hit collapse, tokens/s
  regression, device idle growth, HBM headroom collapse) dumps the
  flight ring and opens a bounded profiler window — "serving got slow"
  becomes an on-disk artifact with zero operator action.
- :mod:`.devledger` — the DEVICE-UTILIZATION & HBM LEDGER (ISSUE 17):
  per-dispatch executable cost (once per signature via ``jax.stages``
  lowering) combined with the dispatch/retire stamps into rolling
  ``mfu`` / ``device_busy_frac`` / phase-attributed ``dispatch_gap_*``,
  plus heartbeat-cadence ``memory_stats()`` headroom with component
  attribution — fields omitted (never faked 0) where the backend
  supplies nothing.

Import discipline: NOTHING here imports jax at module level — the host
daemon (plugin/, utils/) imports this package and must stay jax-free;
jax is reached lazily, only when a span actually fences device values or
the profiler starts.
"""
from __future__ import annotations

from .devledger import DeviceLedger
from .events import (
    EventSink,
    configure_from_env,
    default_sink,
    emit,
    enabled,
    read_events,
    set_default_sink,
    summarize_phases,
    tail_events,
)
from .flight import (
    FlightRecorder,
    set_default_recorder,
)
from .metrics import (
    DEFAULT_REGISTRY,
    MetricsRegistry,
    Rolling,
    counter,
    gauge,
    histogram,
    serve,
)
from .profiler import ProfilerHook, profiler_from_env
from .watchdog import ALERT_KINDS, SLOBurnWatchdog, WatchdogConfig
from .trace import (
    DeviceFence,
    Span,
    current_span_id,
    current_trace_id,
    new_trace,
    span,
    start_span,
    timer,
    traced,
)

__all__ = [
    "DeviceLedger",
    "EventSink",
    "configure_from_env",
    "default_sink",
    "emit",
    "enabled",
    "read_events",
    "set_default_sink",
    "summarize_phases",
    "tail_events",
    "FlightRecorder",
    "set_default_recorder",
    "DEFAULT_REGISTRY",
    "MetricsRegistry",
    "Rolling",
    "counter",
    "gauge",
    "histogram",
    "serve",
    "ProfilerHook",
    "profiler_from_env",
    "ALERT_KINDS",
    "SLOBurnWatchdog",
    "WatchdogConfig",
    "DeviceFence",
    "Span",
    "current_span_id",
    "current_trace_id",
    "new_trace",
    "span",
    "start_span",
    "timer",
    "traced",
]
