"""Span-based tracer with JAX-aware timing.

The one thing a naive ``perf_counter`` pair around a jitted call measures
is Python dispatch: JAX returns futures, and the device may still be
running when the second timestamp is read (the JX004/JX005 lint rules
exist because this bug keeps recurring). Spans close that hole
structurally — a span FENCES on exit: any value registered via
``Span.fence(x)`` (or the ``fence=`` argument) is passed through
``jax.block_until_ready`` BEFORE the end timestamp is taken, so the
recorded duration covers device compute, not dispatch.

APIs:

- :func:`span` — context manager; nesting builds a parent/child tree under
  one trace id (contextvar-propagated, so it follows async tasks and
  survives thread-pool hand-off when contexts are copied)::

      with obs.span("train.step", step=i) as sp:
          state, loss = step_fn(state, batch)
          sp.fence(loss)          # block_until_ready before the end stamp
          sp.set(loss=float(loss))

- :func:`traced` — decorator; fences the wrapped function's return value
  by default.
- :func:`timer` — a plain timing context manager (same fencing) that can
  also feed a prometheus histogram child or a :class:`..obs.metrics.Rolling`.
- :func:`start_span` / :meth:`Span.end` — a DETACHED span for overlapped
  (pipelined) regions whose dispatch and completion happen in different
  call frames: open at dispatch, :meth:`Span.mark` stamps intermediate
  offsets (``<label>_s`` attributes, e.g. the dispatch→return split), and
  ``end()`` fences + emits whenever the in-flight work actually lands.
  Detached spans never become the ambient parent (the contextvar is
  untouched), so an overlapped region cannot corrupt the nesting of spans
  opened while it is in flight.
- :class:`DeviceFence` — an async device→host transfer handle: starts
  ``copy_to_host_async`` on every registered array at construction so the
  copy overlaps subsequent device work, and resolves to host numpy in
  ``wait()`` — the overlapped-serving round's token transfer.

Every closed span emits one JSONL event (kind ``"span"``) to the default
event sink; with the sink disabled the cost is two ``perf_counter`` calls
and a dict. Trace/span ids of the innermost open span ride into
``utils/log.py`` records automatically (the formatters ask
:func:`current_trace_id`/:func:`current_span_id`).

jax is imported lazily, inside the fence — host-side code (plugin/,
utils/) spans freely without pulling jax into the daemon.
"""
from __future__ import annotations

import contextvars
import functools
import os
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

from . import events

# Innermost open span for this context (None at top level). A contextvar,
# not a thread-local: gRPC handlers and asyncio tasks each get their own
# copied context, so concurrent requests cannot cross-link spans.
_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "katatpu_current_span", default=None
)


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def current_span() -> Optional["Span"]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    sp = _current.get()
    return sp.trace_id if sp is not None else None


def current_span_id() -> Optional[str]:
    sp = _current.get()
    return sp.span_id if sp is not None else None


def new_trace() -> str:
    """A fresh trace id for callers that thread one across process
    boundaries (e.g. the plugin logs it per Allocate so pod-resources
    queries can join device ids back to the handler that granted them)."""
    return _new_id(8)


def _block_until_ready(value: Any) -> None:
    """Fence: block until every device buffer in ``value`` is computed.
    Lazy jax import; a jax-free process (host daemon) no-ops — nothing
    host-side dispatches asynchronously."""
    try:
        import jax
    except Exception:
        return
    jax.block_until_ready(value)


class Span:
    """One timed region. Mutable while open: ``set()`` attaches attributes,
    ``fence()`` registers values to block on at exit. Closed spans carry
    ``duration_s`` and have been emitted to the event sink."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attrs",
        "duration_s", "_fence", "_t0", "_token",
    )

    def __init__(
        self,
        name: str,
        parent: Optional["Span"] = None,
        trace_id: Optional[str] = None,
        **attrs,
    ):
        self.name = name
        self.trace_id = (
            trace_id
            or (parent.trace_id if parent is not None else None)
            or _new_id(8)
        )
        self.span_id = _new_id(4)
        self.parent_id = parent.span_id if parent is not None else None
        self.attrs: dict = dict(attrs)
        self.duration_s: Optional[float] = None
        self._fence: list = []
        self._t0: Optional[float] = None
        self._token = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def fence(self, value: Any) -> Any:
        """Register ``value`` (any pytree) to ``block_until_ready`` at span
        exit; returns it unchanged so it drops into expressions."""
        self._fence.append(value)
        return value

    def mark(self, label: str) -> float:
        """Stamp an intermediate offset: attaches ``<label>_s`` = seconds
        since the span opened. For overlapped regions this records the
        dispatch→return split (``dispatch_s``) separately from the full
        dispatch→fence duration ``end()`` later reports as ``dur_s`` —
        honest pipelined timing without forcing a sync at dispatch."""
        offset = time.perf_counter() - (self._t0 or 0.0)
        self.attrs[f"{label}_s"] = round(offset, 6)
        return offset

    def end(self, fence: Any = None) -> "Span":
        """Close a DETACHED span (see :func:`start_span`): fences, stamps
        ``dur_s``, emits the event. ``fence`` registers one more value to
        block on first. Idempotent closes are a bug — call once."""
        if fence is not None:
            self._fence.append(fence)
        err = self._close(None)
        if err is not None:
            raise err
        return self

    def _open(self) -> "Span":
        self._token = _current.set(self)
        self._t0 = time.perf_counter()
        return self

    def _close(
        self, error: Optional[BaseException]
    ) -> Optional[BaseException]:
        # Fence BEFORE the end stamp — this ordering is the tracer's whole
        # reason to exist (async dispatch fakes sub-ms steps otherwise).
        # block_until_ready surfaces deferred device errors: when the body
        # succeeded, such an error must propagate (after bookkeeping); when
        # the body already raised, it must not mask the original.
        fence_error: Optional[BaseException] = None
        for value in self._fence:
            try:
                _block_until_ready(value)
            except BaseException as e:
                fence_error = fence_error or e
        self.duration_s = time.perf_counter() - (self._t0 or 0.0)
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        payload = dict(self.attrs)
        error = error or fence_error
        if error is not None:
            payload["error"] = f"{type(error).__name__}: {error}"[:200]
        # Derived throughput: a span that knows its token count reports
        # tokens/sec itself, so consumers never divide by an unfenced time.
        tokens = payload.get("tokens")
        if isinstance(tokens, (int, float)) and self.duration_s > 0:
            payload["tokens_per_s"] = round(tokens / self.duration_s, 2)
        events.emit(
            "span",
            self.name,
            trace=self.trace_id,
            span=self.span_id,
            parent=self.parent_id,
            dur_s=round(self.duration_s, 6),
            **payload,
        )
        return fence_error


@contextmanager
def span(
    name: str,
    fence: Any = None,
    trace_id: Optional[str] = None,
    **attrs,
):
    """Open a span named ``name``; see the module docstring for the
    contract. ``fence`` registers an up-front value (or zero-arg callable
    resolved at exit) to block on; ``Span.fence()`` registers more from
    inside the block."""
    sp = Span(name, parent=_current.get(), trace_id=trace_id, **attrs)
    sp._open()
    error: Optional[BaseException] = None
    try:
        yield sp
    except BaseException as e:
        error = e
        raise
    finally:
        # The up-front fence resolves only on the success path: after a
        # body exception its value is likely invalid, and an exception
        # from the resolver would mask the original. A raising resolver
        # must still not skip _close — the span has to unwind the context
        # stack and emit, or every later span inherits a dead parent.
        resolver_error: Optional[BaseException] = None
        if fence is not None and error is None:
            try:
                sp._fence.append(fence() if callable(fence) else fence)
            except BaseException as e:
                resolver_error = e
        fence_error = sp._close(error or resolver_error)
        if error is None:
            if resolver_error is not None:
                raise resolver_error
            if fence_error is not None:
                raise fence_error  # deferred device error surfaced by the fence


@contextmanager
def timer(name: str, metric: Any = None, fence: Any = None, **attrs):
    """Like :func:`span` but also feeds ``metric`` — a prometheus
    histogram/gauge child (``.observe``/``.set``) or a
    :class:`..obs.metrics.Rolling` — with the fenced duration."""
    with span(name, fence=fence, **attrs) as sp:
        yield sp
    if metric is not None:
        observe = getattr(metric, "observe", None) or getattr(
            metric, "set", None
        )
        if observe is not None:
            observe(sp.duration_s)


def start_span(
    name: str, trace_id: Optional[str] = None, **attrs
) -> Span:
    """Open a DETACHED span: timing starts now, but the span is NOT pushed
    onto the ambient contextvar stack — spans opened while it is in flight
    do not become its children, and closing it (from any later call frame)
    cannot unwind someone else's parent. It still records the ambient span
    at open time as its parent, so the trace tree stays joined.
    ``trace_id`` pins the span to an existing trace (e.g. the
    daemon-injected per-allocation trace context — see
    "Daemon → guest trace context" in docs/architecture.md).

    This is the API for overlapped regions — work dispatched in one call
    frame and fenced in another (the pipelined serving round)::

        sp = obs.start_span("serving.decode_chunk", tokens=n)
        toks = dispatch(...)        # returns futures immediately
        sp.mark("dispatch")         # dispatch_s: host-side dispatch cost
        ...                         # host schedules while device computes
        sp.end(fence=toks)          # dur_s: dispatch → results ready
    """
    sp = Span(name, parent=_current.get(), trace_id=trace_id, **attrs)
    sp._t0 = time.perf_counter()  # open without touching the contextvar
    return sp


class DeviceFence:
    """In-flight device→host transfer handle for overlapped scheduling.

    Construction starts ``copy_to_host_async`` on every registered array —
    the D2H copy is enqueued behind the producing computation and overlaps
    whatever the device (and host) do next. :meth:`wait` resolves to host
    numpy arrays, blocking only until the copies land; by the time an
    overlapped consumer calls it, the data is typically already resident
    and the wait is the honest fence for the producing chunk.

    Arrays without ``copy_to_host_async`` (plain numpy, older backends)
    degrade gracefully: ``wait()`` falls back to a synchronous transfer.
    """

    __slots__ = ("_arrays",)

    def __init__(self, **arrays: Any):
        self._arrays = arrays
        for a in arrays.values():
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:  # pragma: no cover - backend quirk
                    pass  # wait() still resolves synchronously

    def wait(self) -> dict:
        """Block until every array's host copy is ready; returns
        ``{name: np.ndarray}``.

        This is strict mode's SANCTIONED retire point: under
        ``compat.jaxapi.strict_mode`` (``KATA_TPU_STRICT=1``) the
        overlapped round runs with ``jax.transfer_guard("disallow")``,
        and the one legal device→host read is this wait on the async
        copy — so it passes through the ``allow_transfer`` hatch. Lazy,
        guarded import: a jax-free host daemon (or an old JAX without
        the guard) degrades to the plain transfer."""
        import numpy as np

        try:
            from ..compat.jaxapi import allow_transfer
        except Exception:  # pragma: no cover - jax-free host process
            return {k: np.asarray(v) for k, v in self._arrays.items()}
        with allow_transfer("DeviceFence retire — the async copy lands here"):
            return {k: np.asarray(v) for k, v in self._arrays.items()}


def traced(
    name: Optional[str] = None, fence_result: bool = True
) -> Callable:
    """Decorator form: the whole call is one span; the return value is
    fenced before the end stamp unless ``fence_result=False``."""

    def deco(fn: Callable) -> Callable:
        span_name = name or f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(span_name) as sp:
                result = fn(*args, **kwargs)
                if fence_result:
                    sp.fence(result)
                return result

        return wrapper

    return deco
